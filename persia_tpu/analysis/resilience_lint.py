"""Resilience-policy lint: no retry/backoff/timeout outside the engine.

PR 3's invariant — every deadline, backoff sleep, and breaker decision in
the service plane flows through ``service/resilience.py`` — is what makes
the chaos suite's bit-identical replay argument sound: a hand-rolled
``time.sleep(0.3)`` poll loop is an unseeded, unbudgeted side channel the
Deadline cannot cap and the soak cannot replay. These rules mechanically
protect the invariant inside ``persia_tpu/service/`` and
``persia_tpu/serving/`` (``resilience.py`` itself is the one exempt file —
it IS the engine):

- RES001 ``time.sleep`` with a constant delay — backoff must come from
         ``RetryPolicy.backoff`` (seeded jitter) capped by a ``Deadline``
- RES002 a constant socket timeout (``settimeout(0.5)``,
         ``create_connection(..., timeout=2)``) — per-attempt timeouts
         must be budget-capped (``Deadline.cap``) or config-driven
- RES003 an ad-hoc retry/poll loop: a ``while``/``for`` whose body both
         swallows exceptions and sleeps, without referencing the policy
         engine (``backoff``/``Deadline``/``RetryPolicy``/``poll_until``/
         ``breaker``) — duplicated backoff is exactly what PR 3 deleted
- RES004 a manual wall-clock deadline (``time.time() + timeout``) driving
         a sleep loop — use ``resilience.Deadline`` (monotonic, propagates
         through nested calls)
- RES005 a loop whose broad ``except Exception`` handler swallows with
         ONLY a log line — no metric increment, no re-raise. A watcher
         that can fail forever while exporting nothing is invisible to
         alerting; every swallow-and-continue loop must count its
         failures (``counter.inc()``) so the failure rate is observable
- RES006 a liveness decision from ONE failed probe: an ``except``
         handler around a probe/health call that directly fires an
         evict-class mutator (``quarantine``/``evict``/``deregister``/
         ``remove_replica``/``replace_replica``/``kill_ps``/
         ``mark_dead``), with no miss accounting (consecutive-miss
         streaks, lease/verdict state, thresholds, breaker) anywhere in
         the enclosing function. One dropped packet must never evict a
         replica — eviction belongs downstream of an N-consecutive-miss
         failure detector (service/failure_detector.py)
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Sequence

from persia_tpu.analysis.common import Finding, REPO_ROOT, read_text, rel

_SCOPE_DIRS = (
    os.path.join("persia_tpu", "service"),
    os.path.join("persia_tpu", "serving"),
)
_EXEMPT_BASENAMES = ("resilience.py",)

# Tokens that prove the loop runs ON the engine. Note "deadline." /
# "deadline(" (method call / construction) rather than the bare word: a
# hand-rolled `deadline = time.time() + t` variable must NOT whitelist its
# own loop.
_POLICY_TOKENS = (
    "backoff", "retrypolicy", "deadline.", "deadline(", "poll_until",
    "breaker", "policy", ".remaining(", ".cap(",
)


def _src(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover
        return ""


def _is_const_number(node: ast.expr) -> bool:
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)):
        return True
    if isinstance(node, ast.UnaryOp) and isinstance(node.operand, ast.Constant):
        return isinstance(node.operand.value, (int, float))
    return False


def _swallows_exceptions(loop: ast.AST) -> bool:
    for node in ast.walk(loop):
        if isinstance(node, ast.ExceptHandler):
            return True
    return False


def _sleeps(loop: ast.AST) -> Optional[int]:
    for node in ast.walk(loop):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "sleep"
        ):
            return node.lineno
    return None


def _mentions_policy(loop: ast.AST) -> bool:
    return any(tok in _src(loop).lower() for tok in _POLICY_TOKENS)


def _is_broad_handler(h: ast.ExceptHandler) -> bool:
    """Bare ``except:`` or ``except (Base)Exception`` (incl. in a tuple)."""
    if h.type is None:
        return True
    types = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
    for t in types:
        if isinstance(t, ast.Name) and t.id in ("Exception", "BaseException"):
            return True
    return False


def _log_only_swallow(h: ast.ExceptHandler) -> bool:
    """True when the handler body is nothing but logging/pass/continue —
    no metric ``.inc(``, no ``raise``, no state change the loop can act on."""
    src = _src(h).lower()
    if ".inc(" in src or "raise" in src:
        return False
    for stmt in h.body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            f = stmt.value.func
            base = ""
            if isinstance(f, ast.Attribute):
                v = f.value
                base = str(getattr(v, "id", getattr(v, "attr", "")))
            elif isinstance(f, ast.Name):
                base = f.id
            if "log" in base.lower() or base == "print":
                continue
        return False
    return True


# RES006: probe calls whose failure must feed a counter, not a verdict
_PROBE_TOKENS = ("healthz", "health(", "probe", "wait_ready", "ping(",
                 "replica_info")
# mutators that remove a replica from service — the "eviction class"
_EVICT_TOKENS = ("quarantine", "evict", "deregister", "remove_replica",
                 "replace_replica", "kill_ps", "mark_dead")
# evidence the enclosing function keeps miss ACCOUNTING between probes —
# any of these and the eviction is a thresholded decision, not a reflex
_MISS_TOKENS = ("miss", "consecutive", "streak", "strikes", "lease",
                "verdict", "threshold", "breaker", "fail_count", "failures")


def _res006_findings(fn: ast.AST, path: str) -> List[Finding]:
    """Single-probe evictions inside one function: an ``except`` handler
    whose guarded try-body probes a replica and whose handler body fires
    an evict-class mutator, in a function with no miss accounting."""
    fn_src = _src(fn).lower()
    if any(tok in fn_src for tok in _MISS_TOKENS):
        return []
    out: List[Finding] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Try):
            continue
        tried = " ".join(_src(s) for s in node.body).lower()
        if not any(tok in tried for tok in _PROBE_TOKENS):
            continue
        for h in node.handlers:
            hsrc = _src(h).lower()
            hit = next((tok for tok in _EVICT_TOKENS if tok + "(" in hsrc
                        or "." + tok in hsrc), None)
            if hit is not None:
                out.append(Finding(
                    "RES006", path, h.lineno,
                    f"liveness decision from a single failed probe — the "
                    f"handler calls {hit}() directly; one dropped packet "
                    "must never evict a replica. Count the miss and let an "
                    "N-consecutive-miss detector "
                    "(service/failure_detector.py) decide",
                ))
    return out


def check_source(text: str, path: str) -> List[Finding]:
    findings: List[Finding] = []
    tree = ast.parse(text, filename=path)

    for fn in ast.walk(tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            findings.extend(_res006_findings(fn, path))

    for node in ast.walk(tree):
        # RES001: constant sleep
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "sleep"
            and node.args
            and _is_const_number(node.args[0])
        ):
            findings.append(Finding(
                "RES001", path, node.lineno,
                f"{_src(node.func)}({_src(node.args[0])}) — constant backoff "
                "bypasses resilience.RetryPolicy (unseeded, un-budgeted; the "
                "chaos replay cannot reproduce it)",
            ))
        # RES002: constant socket timeouts
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr == "settimeout" and node.args and _is_const_number(node.args[0]):
                findings.append(Finding(
                    "RES002", path, node.lineno,
                    f"settimeout({_src(node.args[0])}) — constant socket "
                    "timeout bypasses Deadline.cap / config",
                ))
            if node.func.attr in ("create_connection", "connect_ex"):
                for kw in node.keywords:
                    if kw.arg == "timeout" and _is_const_number(kw.value):
                        findings.append(Finding(
                            "RES002", path, node.lineno,
                            f"create_connection(timeout={_src(kw.value)}) — "
                            "constant socket timeout bypasses Deadline.cap",
                        ))
        # RES005: swallow-without-metric loops (failure invisible forever)
        if isinstance(node, (ast.While, ast.For)):
            for inner in ast.walk(node):
                if (
                    isinstance(inner, ast.ExceptHandler)
                    and _is_broad_handler(inner)
                    and _log_only_swallow(inner)
                ):
                    findings.append(Finding(
                        "RES005", path, inner.lineno,
                        "loop swallows Exception with only a log line — "
                        "count the failure (counter.inc()) or re-raise; an "
                        "un-metered retry loop can fail forever invisibly",
                    ))
        # RES003 / RES004: ad-hoc retry/poll loops
        if isinstance(node, (ast.While, ast.For)):
            sleep_line = _sleeps(node)
            if sleep_line is None:
                continue
            if _swallows_exceptions(node) and not _mentions_policy(node):
                findings.append(Finding(
                    "RES003", path, node.lineno,
                    "ad-hoc retry loop (swallows exceptions + sleeps) — "
                    "route it through resilience.poll_until / RetryPolicy",
                ))
            loop_src = _src(node)
            if not _mentions_policy(node) and (
                "time.time() +" in loop_src or "time.monotonic() +" in loop_src
            ):
                findings.append(Finding(
                    "RES004", path, node.lineno,
                    "manual wall-clock deadline driving a sleep loop — use "
                    "resilience.Deadline (monotonic, propagates)",
                ))

    # RES004 also fires when the deadline is computed just before the loop
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        body = fn.body
        for i, stmt in enumerate(body):
            if not isinstance(stmt, ast.Assign):
                continue
            ssrc = _src(stmt.value)
            if not ("time.time() +" in ssrc or "_time.time() +" in ssrc):
                continue
            for later in body[i + 1:]:
                if isinstance(later, (ast.While, ast.For)) and _sleeps(later) is not None \
                        and not _mentions_policy(later):
                    findings.append(Finding(
                        "RES004", path, stmt.lineno,
                        "manual wall-clock deadline driving the sleep loop "
                        f"at line {later.lineno} — use resilience.Deadline",
                    ))
                    break
    # dedupe (a loop can be reached by both RES004 paths)
    seen = set()
    out: List[Finding] = []
    for f in findings:
        key = (f.rule, f.path, f.line)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out


def in_scope(path: str) -> bool:
    p = path.replace("/", os.sep)
    if os.path.basename(p) in _EXEMPT_BASENAMES:
        return False
    return any(d in p for d in _SCOPE_DIRS)


def check(root: str = REPO_ROOT, files: Optional[Sequence[str]] = None) -> List[Finding]:
    from persia_tpu.analysis.common import python_files

    paths = list(files) if files is not None else python_files(root)
    findings: List[Finding] = []
    for p in paths:
        abspath = p if os.path.isabs(p) else os.path.join(root, p)
        rp = rel(abspath)
        if files is None and not in_scope(rp):
            continue
        findings.extend(check_source(read_text(abspath), rp))
    return findings

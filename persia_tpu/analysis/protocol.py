"""persia-proto: static protocol extraction over the journaled state machines.

The repo's exactly-once story rests on five journaled two-phase protocols
(jobstate fences, elastic reshard phases, autopilot drives, healer
decisions, scrub/replication records). This pass recovers their shape
statically — manifest-write sites, phase-name string constants, journal
record/probe sites, ``resume()`` re-entry arms, :func:`crashcheck.reach`
crash points — and enforces the construction rules the protocols depend
on:

- **PROTO001** — a checkpoint-class artifact written through a helper
  whose raw ``open(..., "w")`` hides behind a parameter. DUR001 is
  lexical: it only fires when the artifact name appears in the ``open``
  target expression itself, so ``_put(os.path.join(d, "MANIFEST.json"),
  data)`` delegating to ``def _put(path, data): open(path, "wb")`` is
  invisible to it. This rule propagates artifact-ness of arguments
  through resolved call edges to raw-write helpers.
- **PROTO002** — a journal id minted by raw bit arithmetic (shifts /
  or-ing constants) at a journal sink instead of through the registered
  constructors in ``jobstate.py``/``health/scrub.py`` — and, from the
  namespace prover below, any two registered constructors whose bit
  layouts can collide over their declared domains.
- **PROTO003** — a phase string committed by a protocol's two-phase
  writer with no matching re-entry arm in the corresponding ``resume()``
  path: a phase the actuator can durably record but the resume path
  silently falls through is a crash window that loses work (or worse,
  skips it).
- **PROTO004** — a ``journal_record`` apply site with no
  ``journal_probe`` on its path (same function or a module-local
  callee): recording without probing double-applies on replay.
- **PROTO005** — a topology mutator (``reshard_ps`` / ``replace_replica``
  / ``swap_topology`` / ``apply_migration``) reachable outside a
  drained-fence / fence-callback / resume context.
- **PROTO006** — a statically extracted crash transition (a
  ``reach("...")`` site) absent from the committed ``PROTO_COVERAGE.json``
  or recorded there with zero kills: the exhaustive crash matrix
  (tests/test_protocol.py) must kill every transition at least once.
- **PROTO007** — an abort arm that escapes the crash matrices. Any
  phase commit whose name starts with ``abort`` (the journaled
  preemption arms: ``aborting``/``aborted``) must sit in a module that
  also wires an ``abort`` crash site into :func:`crashcheck.reach`, and
  every such abort site must be recorded in ``PROTO_COVERAGE.json``
  with at least one kill. Preemption rollback releases partially
  imported ring ranges exactly-once through the abort journal-id
  family; an abort arm the matrices never SIGKILL is an unproven
  rollback path.

**Journal-id namespace prover.** Every id constructor is compiled from
its AST (pure ints, no imports) and bit-probed over its declared domain:
``f(0)`` gives the fixed-one bits, single-bit probes give the varying
bits, and an all-ones probe verifies the constructor is bit-affine (no
carries) so the analysis is exact, not sampled. Two families are proven
disjoint when some bit is fixed-one in one and fixed-zero in the other;
the witness bit is part of the result (and pinned in tests). Declared
domains: job_epoch < 2^24, fence/train step < 2^30 (step bits 30-31 are
namespace tags: handoff 00, scrub 01, replication 10, abort 11),
replica/op < 2^7.

Pure stdlib (ast only) like every pass here; never lints ``analysis/``
itself. Suppress with ``# persia-lint: disable=PROTO00x`` on the line.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from persia_tpu.analysis.common import Finding, REPO_ROOT, read_text, rel
from persia_tpu.analysis.durability import _ARTIFACT_RE, _ATOMIC_RE, _WRITE_MODES

# journal-consuming sinks: the id argument must come from a constructor
_JOURNAL_SINKS = frozenset({
    "journal_record", "journal_probe",
    "import_range_journaled", "delete_range_journaled",
})

# the registered id constructors (jobstate.py + health/scrub.py); their
# bodies are the one place raw bit arithmetic on ids is legal
CONSTRUCTOR_NAMES = frozenset({
    "make_journal_id", "journal_shard_id", "handoff_journal_id",
    "replication_journal_id", "scrub_journal_id", "abort_journal_id",
})

_MUTATORS = frozenset({
    "reshard_ps", "replace_replica", "swap_topology", "apply_migration",
})

# Enclosing-function names that ARE a drained-fence / fence-callback
# context by construction (each entry documented; grep confirms the
# contract at the definition site):
# - enable_autopilot / enable_self_heal: actuator lambdas wired there run
#   only inside the controller/healer two-phase drive, which the stream
#   fence (train_stream(fence_callback=...)) or the heal contract gates.
# - heal_promote: ServiceCtx promotion — the router swap inside it is the
#   atomic replacement step of a heal that the healer drives at its fence.
# - _ring_swapper: builds the on_imported callback the elastic engine
#   fires at the "imported" boundary, inside the reshard fence.
FENCE_CONTEXTS = frozenset({
    "enable_autopilot", "enable_self_heal", "heal_promote", "_ring_swapper",
})

# phases that terminate a protocol: a resume path never needs an arm for
# a state that means "nothing left to do" — "done" (completed) and
# "aborted" (preemption rollback fully released; terminal by the same
# contract)
TERMINAL_PHASES = frozenset({"done", "aborted"})

COVERAGE_FILE = "PROTO_COVERAGE.json"

_U64 = (1 << 64) - 1


# ------------------------------------------------------------ module scan


@dataclass
class _Func:
    qual: str
    name: str
    lineno: int
    end: int
    args: List[str]
    stack: Tuple[str, ...]  # enclosing function names, outermost first
    src: str
    calls: List["_Call"] = field(default_factory=list)
    callee_names: Set[str] = field(default_factory=set)


@dataclass
class _Call:
    name: str  # simple callee name (attr for method calls)
    node: ast.Call
    line: int


@dataclass
class _RawWriter:
    """A function that raw-writes (open w-mode / np.savez) to a target
    naming one of its parameters, with no atomic machinery in scope."""

    qual: str
    path: str
    pos: int  # self-adjusted positional index of the written parameter
    line: int


@dataclass
class _PhaseWriter:
    qual: str
    name: str
    pos: int  # self-adjusted positional index of the phase parameter
    line: int


@dataclass
class _ModuleScan:
    path: str
    funcs: Dict[str, _Func] = field(default_factory=dict)
    by_name: Dict[str, List[str]] = field(default_factory=dict)
    raw_writers: List[_RawWriter] = field(default_factory=list)
    phase_writers: List[_PhaseWriter] = field(default_factory=list)
    # (writer simple name, phase string, line)
    phase_sites: List[Tuple[str, str, int]] = field(default_factory=list)
    reach_sites: List[Tuple[str, int]] = field(default_factory=list)
    module_calls: List[_Call] = field(default_factory=list)


def _self_offset(args: List[str]) -> int:
    return 1 if args and args[0] in ("self", "cls") else 0


def _src(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover
        return ""


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _is_open_write(node: ast.Call) -> bool:
    f = node.func
    is_open = (isinstance(f, ast.Name) and f.id == "open") or (
        isinstance(f, ast.Attribute) and f.attr == "open"
        and isinstance(f.value, ast.Name) and f.value.id == "io"
    )
    if not is_open:
        if isinstance(f, ast.Attribute) and f.attr in ("savez", "savez_compressed"):
            return bool(node.args)
        return False
    mode: Optional[ast.expr] = node.args[1] if len(node.args) >= 2 else None
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    return (
        bool(node.args)
        and isinstance(mode, ast.Constant)
        and isinstance(mode.value, str)
        and mode.value in _WRITE_MODES
    )


def _scan_module(text: str, path: str) -> Optional[_ModuleScan]:
    try:
        tree = ast.parse(text, filename=path)
    except SyntaxError:
        return None
    scan = _ModuleScan(path=path)
    lines = text.splitlines()

    def segment(node) -> str:
        end = getattr(node, "end_lineno", node.lineno) or node.lineno
        return "\n".join(lines[node.lineno - 1:end])

    def walk_func(node, cls_prefix: str, stack: Tuple[str, ...]) -> None:
        qual = f"{cls_prefix}{node.name}"
        args = [a.arg for a in node.args.posonlyargs + node.args.args]
        fn = _Func(
            qual=qual, name=node.name, lineno=node.lineno,
            end=getattr(node, "end_lineno", node.lineno) or node.lineno,
            args=args, stack=stack, src=segment(node),
        )
        scan.funcs[qual] = fn
        scan.by_name.setdefault(node.name, []).append(qual)
        body_stack = stack + (node.name,)
        for sub in ast.walk(node):
            if sub is node:
                continue
            if isinstance(sub, ast.Call):
                nm = _call_name(sub)
                if nm:
                    fn.calls.append(_Call(nm, sub, sub.lineno))
                    fn.callee_names.add(nm)
        _collect_raw_writer(scan, fn)
        _collect_phase_writer(scan, fn)
        # nested defs get their own _Func entries (with the stack)
        for sub in node.body:
            _walk_stmt_defs(sub, cls_prefix, body_stack)

    def _walk_stmt_defs(st, cls_prefix: str, stack: Tuple[str, ...]) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            walk_func(st, cls_prefix, stack)
            return
        if isinstance(st, ast.ClassDef):
            for sub in st.body:
                _walk_stmt_defs(sub, f"{st.name}.", stack)
            return
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.stmt):
                _walk_stmt_defs(child, cls_prefix, stack)

    for st in tree.body:
        _walk_stmt_defs(st, "", ())

    # module-level calls (outside any function) + reach sites everywhere
    func_spans = [(f.lineno, f.end) for f in scan.funcs.values()]

    def in_func(line: int) -> bool:
        return any(lo <= line <= hi for lo, hi in func_spans)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        nm = _call_name(node)
        if nm == "reach" and node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            scan.reach_sites.append((node.args[0].value, node.lineno))
        if nm and not in_func(node.lineno):
            scan.module_calls.append(_Call(nm, node, node.lineno))

    # phase write sites: calls to a phase writer with a string constant
    writer_by_name = {w.name: w for w in scan.phase_writers}
    for fn in scan.funcs.values():
        for call in fn.calls:
            w = writer_by_name.get(call.name)
            if w is None or w.pos >= len(call.node.args):
                continue
            arg = call.node.args[w.pos]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                scan.phase_sites.append((w.name, arg.value, call.line))
    return scan


def _collect_raw_writer(scan: _ModuleScan, fn: _Func) -> None:
    if _ATOMIC_RE.search(fn.src):
        return
    off = _self_offset(fn.args)
    for call in fn.calls:
        if not _is_open_write(call.node):
            continue
        tsrc = _src(call.node.args[0])
        for i, a in enumerate(fn.args[off:]):
            # the parameter must appear in the target expression
            if a in tsrc.replace(".", " ").replace("(", " ").replace(")", " ") \
                    .replace(",", " ").replace("[", " ").replace("]", " ").split() \
                    or tsrc == a:
                scan.raw_writers.append(
                    _RawWriter(fn.qual, scan.path, i, call.line)
                )
                return


def _collect_phase_writer(scan: _ModuleScan, fn: _Func) -> None:
    """A two-phase writer: a function whose body commits a dict carrying a
    literal ``"phase"`` key whose value is one of its own parameters."""
    off = _self_offset(fn.args)
    for call in fn.calls:
        for node in ast.walk(call.node):
            if not isinstance(node, ast.Dict):
                continue
            for k, v in zip(node.keys, node.values):
                if (
                    isinstance(k, ast.Constant) and k.value == "phase"
                    and isinstance(v, ast.Name) and v.id in fn.args[off:]
                ):
                    scan.phase_writers.append(_PhaseWriter(
                        fn.qual, fn.name, fn.args.index(v.id) - off, fn.lineno,
                    ))
                    return


# ------------------------------------------------------------------- rules


def _rule_proto001(scans: List[_ModuleScan]) -> List[Finding]:
    """Artifact-named argument flowing into a raw-write helper."""
    writers: Dict[str, _RawWriter] = {}
    ambiguous: Set[str] = set()
    for scan in scans:
        for w in scan.raw_writers:
            simple = w.qual.rsplit(".", 1)[-1]
            if simple in writers:
                ambiguous.add(simple)
            writers[simple] = w
    findings: List[Finding] = []
    for scan in scans:
        all_calls = [(fn, c) for fn in scan.funcs.values() for c in fn.calls]
        all_calls += [(None, c) for c in scan.module_calls]
        for fn, call in all_calls:
            w = writers.get(call.name)
            if w is None or call.name in ambiguous:
                continue
            if fn is not None and fn.qual == w.qual and scan.path == w.path:
                continue  # the writer's own recursive mention
            if w.pos >= len(call.node.args):
                continue
            argsrc = _src(call.node.args[w.pos])
            if not _ARTIFACT_RE.search(argsrc):
                continue
            if fn is not None and _ATOMIC_RE.search(fn.src):
                continue  # caller participates in an atomic publish dance
            findings.append(Finding(
                "PROTO001", scan.path, call.line,
                f"checkpoint artifact {argsrc!r} written through "
                f"{call.name}() whose open() has no temp+fsync+rename — "
                "interprocedural DUR001: the helper publishes a torn file "
                "under the final name on crash (use "
                "jobstate.fsync_write_bytes / storage.write_bytes)",
            ))
    return findings


def _raw_mint(node: ast.expr) -> bool:
    """True when the expression builds an id by raw bit arithmetic: a
    shift, or or-ing an integer constant — with no registered constructor
    call anywhere inside it."""
    has_bits = False
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            nm = _call_name(sub)
            if nm in CONSTRUCTOR_NAMES or nm.endswith("_journal_id"):
                return False
        if isinstance(sub, ast.BinOp):
            if isinstance(sub.op, ast.LShift):
                has_bits = True
            elif isinstance(sub.op, ast.BitOr):
                for side in (sub.left, sub.right):
                    if isinstance(side, ast.Constant) and isinstance(side.value, int):
                        has_bits = True
    return has_bits


def _rule_proto002(scan: _ModuleScan) -> List[Finding]:
    findings: List[Finding] = []
    for fn in scan.funcs.values():
        if fn.name in CONSTRUCTOR_NAMES:
            continue  # the registered constructors own the bit layout
        # last-assignment map: name -> RHS exprs within this function
        assigns: Dict[str, List[ast.expr]] = {}
        body_calls = []
        for call in fn.calls:
            body_calls.append(call)
        # re-walk for assignments (calls were collected already)
        # fn.src re-parse is wasteful; use the stored call nodes' parents
        # instead: walk assignments from the function's source segment
        try:
            seg = ast.parse(_dedent(fn.src))
        except SyntaxError:
            seg = None
        if seg is not None:
            for node in ast.walk(seg):
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            assigns.setdefault(tgt.id, []).append(node.value)
                elif isinstance(node, ast.AugAssign) and isinstance(node.target, ast.Name):
                    assigns.setdefault(node.target.id, []).append(node.value)
        for call in fn.calls:
            if call.name not in _JOURNAL_SINKS or not call.node.args:
                continue
            idarg = call.node.args[0]
            raw = _raw_mint(idarg)
            if not raw and isinstance(idarg, ast.Name):
                raw = any(_raw_mint(r) for r in assigns.get(idarg.id, ()))
            if raw:
                findings.append(Finding(
                    "PROTO002", scan.path, call.line,
                    f"journal id reaching {call.name}() is minted by raw bit "
                    "arithmetic — ids must come from the registered "
                    "constructors in jobstate.py (make_journal_id / "
                    "journal_shard_id / handoff_journal_id / "
                    "replication_journal_id / scrub_journal_id) so the "
                    "namespace prover can see the layout",
                ))
    return findings


def _dedent(src: str) -> str:
    import textwrap

    return textwrap.dedent(src)


def _rule_proto003(scan: _ModuleScan) -> List[Finding]:
    if not scan.phase_sites:
        return []
    # resume-reachable closure over module-local simple-name call edges
    roots = [q for q, f in scan.funcs.items() if f.name.startswith("resume")]
    reachable: Set[str] = set()
    work = list(roots)
    while work:
        q = work.pop()
        if q in reachable:
            continue
        reachable.add(q)
        for callee in scan.funcs[q].callee_names:
            for target in scan.by_name.get(callee, ()):
                if target not in reachable:
                    work.append(target)
    arms: Set[str] = set()
    for q in reachable:
        fn = scan.funcs[q]
        try:
            seg = ast.parse(_dedent(fn.src))
        except SyntaxError:
            continue
        for node in ast.walk(seg):
            if not isinstance(node, ast.Compare):
                continue
            involved = _src(node.left) + "".join(_src(c) for c in node.comparators)
            if "phase" not in involved:
                continue
            for expr in [node.left] + list(node.comparators):
                for sub in ast.walk(expr):
                    if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                        arms.add(sub.value)
    findings: List[Finding] = []
    for writer, phase, line in scan.phase_sites:
        if phase in TERMINAL_PHASES or phase in arms:
            continue
        findings.append(Finding(
            "PROTO003", scan.path, line,
            f"phase {phase!r} is committed by {writer}() but no resume path "
            f"in this module compares against it (arms seen: "
            f"{sorted(arms) or 'none'}) — a crash after this commit leaves "
            "a durable state the re-entry logic silently falls through",
        ))
    return findings


def _rule_proto004(scan: _ModuleScan) -> List[Finding]:
    findings: List[Finding] = []
    for fn in scan.funcs.values():
        if fn.name == "journal_record":
            continue  # the journal primitive itself
        for call in fn.calls:
            if call.name != "journal_record":
                continue
            if _probes_on_path(scan, fn.qual, set()):
                continue
            findings.append(Finding(
                "PROTO004", scan.path, call.line,
                "journal_record() with no journal_probe on its path — an "
                "apply site that records without probing re-applies its "
                "payload on every resume replay (exactly-once requires "
                "probe-before-record)",
            ))
    return findings


def _probes_on_path(scan: _ModuleScan, qual: str, seen: Set[str]) -> bool:
    if qual in seen:
        return False
    seen.add(qual)
    fn = scan.funcs[qual]
    if "journal_probe" in fn.callee_names:
        return True
    for callee in fn.callee_names:
        for target in scan.by_name.get(callee, ()):
            if _probes_on_path(scan, target, seen):
                return True
    return False


def _rule_proto005(scan: _ModuleScan) -> List[Finding]:
    findings: List[Finding] = []

    def exempt(mutator: str, chain: Sequence[str]) -> bool:
        for name in chain:
            if name == mutator:
                return True  # a delegating wrapper IS the guarded surface
            if name.startswith("resume"):
                return True  # re-entry arms run inside the recovery fence
            if "fence" in name or "drain" in name:
                return True
            if name in FENCE_CONTEXTS:
                return True
        return False

    for fn in scan.funcs.values():
        chain = list(fn.stack) + [fn.name]
        for call in fn.calls:
            if call.name not in _MUTATORS:
                continue
            if exempt(call.name, chain):
                continue
            findings.append(Finding(
                "PROTO005", scan.path, call.line,
                f"topology mutator {call.name}() reachable outside a "
                f"drained-fence / fence_callback / resume context (enclosing "
                f"chain: {' -> '.join(chain)}) — topology may only change "
                "inside the one window the stream fence guarantees quiescent",
            ))
    for call in scan.module_calls:
        if call.name in _MUTATORS:
            findings.append(Finding(
                "PROTO005", scan.path, call.line,
                f"topology mutator {call.name}() invoked at module scope — "
                "topology may only change inside a drained-fence context",
            ))
    return findings


def _rule_proto007(scan: _ModuleScan) -> List[Finding]:
    """Abort arms must be wired into crashcheck.reach: any module that
    commits a phase starting with ``abort`` (the journaled preemption
    arms) must also declare at least one ``abort`` reach site, or the
    rollback's crash transitions escape the exhaustive kill matrices."""
    abort_commits = [
        (writer, phase, line)
        for writer, phase, line in scan.phase_sites
        if phase.startswith("abort")
    ]
    if not abort_commits:
        return []
    if any("abort" in site for site, _ in scan.reach_sites):
        return []
    return [
        Finding(
            "PROTO007", scan.path, line,
            f"abort arm: phase {phase!r} is committed by {writer}() but "
            "this module wires no abort crash site into crashcheck.reach — "
            "the preemption rollback's transitions are invisible to the "
            "exhaustive kill matrices (add reach(\"<proto>.phase.abort...\") "
            "at the commit boundary)",
        )
        for writer, phase, line in abort_commits
    ]


def _abort_coverage_findings(
    root: str, sites: Dict[str, List[Tuple[str, int]]],
) -> List[Finding]:
    """check()-level half of PROTO007: every abort reach site must carry
    at least one recorded kill in the committed coverage artifact — an
    abort transition the matrices never SIGKILL is an unproven rollback."""
    from persia_tpu.analysis import crashcheck

    abort_sites = sorted(s for s in sites if "abort" in s)
    if not abort_sites:
        return []
    cov_path = os.path.join(root, COVERAGE_FILE)
    try:
        recorded = crashcheck.load_coverage(cov_path).get("sites", {})
    except (OSError, ValueError):
        recorded = {}  # missing/unreadable artifact: PROTO006 already fires
    findings: List[Finding] = []
    for site in abort_sites:
        kills = int(recorded.get(site, {}).get("kills", 0))
        if kills < 1:
            findings.append(Finding(
                "PROTO007", COVERAGE_FILE, 1,
                f"abort transition {site!r} has no recorded kill — every "
                "journaled preemption arm must be SIGKILLed at least once "
                "by the crash matrices (python tests/test_protocol.py "
                "--write-coverage after adding the schedule)",
            ))
    return findings


# --------------------------------------------------- namespace prover


@dataclass
class BitPattern:
    fixed_one: int
    fixed_zero: int
    affine: bool

    @property
    def varying(self) -> int:
        return _U64 & ~(self.fixed_one | self.fixed_zero)


def probe_bits(fn, widths: Sequence[int]) -> BitPattern:
    """Exact bit analysis of a bit-routing constructor over its declared
    domain: ``f(0)`` = fixed-one bits; single-bit probes accumulate the
    varying mask; the all-ones probe certifies there are no carries (the
    function is bit-affine), making the fixed masks exact, not sampled."""
    zeros = [0] * len(widths)
    base = fn(*zeros) & _U64
    union = 0
    for i, w in enumerate(widths):
        for b in range(w):
            args = list(zeros)
            args[i] = 1 << b
            union |= (fn(*args) ^ base) & _U64
    maxes = [(1 << w) - 1 for w in widths]
    affine = (fn(*maxes) & _U64) == (base | union)
    return BitPattern(
        fixed_one=base, fixed_zero=_U64 & ~(base | union), affine=affine,
    )


def disjoint_witness(a: BitPattern, b: BitPattern) -> Optional[int]:
    """Lowest bit proving the two id spaces can never collide (fixed-one
    in one, fixed-zero in the other), or None when no such bit exists."""
    m = (a.fixed_one & b.fixed_zero) | (b.fixed_one & a.fixed_zero)
    if m == 0:
        return None
    return (m & -m).bit_length() - 1


# name-keyed declared domains (bit widths). Fence/train steps are < 2^30
# BY CONTRACT: step bits 30-31 are namespace subspace tags (handoff 00,
# scrub 01, replication 10, abort 11) — see jobstate.py / health/scrub.py.
_DOMAIN_BITS = {
    "job_epoch": 24, "epoch": 24, "step": 30,
    "op": 7, "op_index": 7, "replica": 7, "replica_index": 7, "r": 7,
}
_DEFAULT_DOMAIN = 24

# the five shipped id families over the compiled constructor namespace
_FAMILIES: List[Tuple[str, Sequence[int]]] = [
    ("gradient", (24, 30, 7)),
    ("handoff", (24, 30, 7)),
    ("replication", (24, 30, 7)),
    ("scrub", (24, 30, 7)),
    ("abort", (24, 30, 7)),
]


def _family_fns(ns: Dict) -> Dict[str, object]:
    return {
        "gradient": lambda e, s, r: ns["journal_shard_id"](
            ns["make_journal_id"](e, s), r),
        "handoff": lambda e, s, op: ns["handoff_journal_id"](
            ns["make_journal_id"](e, s), op),
        "replication": lambda e, s, op: ns["replication_journal_id"](e, s, op),
        "scrub": lambda e, s, r: ns["scrub_journal_id"](e, s, r),
        "abort": lambda e, s, op: ns["abort_journal_id"](e, s, op),
    }


_CONST_EXPR_NODES = (
    ast.Constant, ast.BinOp, ast.UnaryOp, ast.Name,
    ast.operator, ast.unaryop, ast.expr_context,
)


def _is_const_assign(node: ast.stmt) -> bool:
    return (
        isinstance(node, ast.Assign)
        and len(node.targets) == 1
        and isinstance(node.targets[0], ast.Name)
        and all(isinstance(s, _CONST_EXPR_NODES) for s in ast.walk(node.value))
    )


def _compile_constructors(root: str) -> Tuple[Dict, Dict[str, Tuple[str, int]]]:
    """exec the registered constructor FunctionDefs (plus the constant
    assigns they reference) into one shared namespace. Returns (namespace,
    {name: (repo-relative path, def line)})."""
    ns: Dict = {}
    where: Dict[str, Tuple[str, int]] = {}
    for relpath in ("persia_tpu/jobstate.py", "persia_tpu/health/scrub.py"):
        path = os.path.join(root, relpath)
        if not os.path.exists(path):
            continue
        tree = ast.parse(read_text(path), filename=path)
        picked: List[ast.stmt] = []
        for node in tree.body:
            if _is_const_assign(node):
                picked.append(node)
            elif isinstance(node, ast.FunctionDef) and node.name in CONSTRUCTOR_NAMES:
                where[node.name] = (relpath, node.lineno)
                picked.append(node)
        mod = ast.Module(body=picked, type_ignores=[])
        ast.fix_missing_locations(mod)
        try:
            exec(compile(mod, path, "exec"), ns)  # noqa: S102 - own repo source
        except Exception:
            continue
    return ns, where


def prove_namespaces(root: str = REPO_ROOT) -> Dict:
    """Bit-prove pairwise disjointness of the shipped journal-id families.
    Returns ``{"patterns": {family: BitPattern}, "pairs": {(a, b): witness
    bit or None}, "where": {constructor: (path, line)}}``."""
    ns, where = _compile_constructors(root)
    fns = _family_fns(ns)
    patterns: Dict[str, BitPattern] = {}
    for fam, widths in _FAMILIES:
        fn = fns[fam]
        try:
            patterns[fam] = probe_bits(fn, widths)
        except Exception:
            continue  # constructor missing under this root
    pairs: Dict[Tuple[str, str], Optional[int]] = {}
    fams = [f for f, _ in _FAMILIES if f in patterns]
    for i, a in enumerate(fams):
        for b in fams[i + 1:]:
            pairs[(a, b)] = disjoint_witness(patterns[a], patterns[b])
    return {"patterns": patterns, "pairs": pairs, "where": where}


def _prover_findings(root: str) -> List[Finding]:
    proof = prove_namespaces(root)
    if not proof["patterns"]:
        return []
    findings: List[Finding] = []
    for fam, pat in sorted(proof["patterns"].items()):
        if not pat.affine:
            findings.append(Finding(
                "PROTO002", "persia_tpu/jobstate.py", 1,
                f"journal-id family {fam!r} is not bit-affine over its "
                "declared domain — the namespace prover cannot certify its "
                "layout (avoid arithmetic with carries in id constructors)",
            ))
    for (a, b), witness in sorted(proof["pairs"].items()):
        if witness is None:
            findings.append(Finding(
                "PROTO002", "persia_tpu/jobstate.py", 1,
                f"journal-id namespaces {a!r} and {b!r} OVERLAP: no bit is "
                "fixed-one in one and fixed-zero in the other over the "
                "declared domains — a collision dedupes one protocol's op "
                "against the other's record (crc mismatch => hard error at "
                "the apply site)",
            ))
    return findings


def _fixture_prover_findings(scan: _ModuleScan, text: str) -> List[Finding]:
    """check_source path: prove any ``*_journal_id`` constructors defined
    in this single module against each other (fixtures for the prover)."""
    tree = ast.parse(text)
    ctors: List[ast.FunctionDef] = [
        n for n in tree.body
        if isinstance(n, ast.FunctionDef) and n.name.endswith("_journal_id")
    ]
    if len(ctors) < 2:
        return []
    ns: Dict = {}
    picked: List[ast.stmt] = [
        n for n in tree.body if _is_const_assign(n)
    ] + list(ctors)
    mod = ast.Module(body=picked, type_ignores=[])
    ast.fix_missing_locations(mod)
    try:
        exec(compile(mod, scan.path, "exec"), ns)  # noqa: S102 - test fixture
    except Exception:
        return []
    pats: Dict[str, Tuple[BitPattern, int]] = {}
    for c in ctors:
        widths = [
            _DOMAIN_BITS.get(a.arg, _DEFAULT_DOMAIN)
            for a in c.args.posonlyargs + c.args.args
        ]
        try:
            pats[c.name] = (probe_bits(ns[c.name], widths), c.lineno)
        except Exception:
            continue
    names = sorted(pats)
    findings: List[Finding] = []
    for i, a in enumerate(names):
        for b in names[i + 1:]:
            if disjoint_witness(pats[a][0], pats[b][0]) is None:
                findings.append(Finding(
                    "PROTO002", scan.path, pats[b][1],
                    f"journal-id namespaces {a!r} and {b!r} OVERLAP over "
                    "their declared domains — no fixed bit separates them",
                ))
    return findings


# ----------------------------------------------------------- reach sites


def reach_sites(
    root: str = REPO_ROOT, files: Optional[Sequence[str]] = None,
) -> Dict[str, List[Tuple[str, int]]]:
    """site name -> [(repo-relative path, line)] for every
    ``reach("...")`` crash point in the tree — the statically extracted
    transition set the crash matrices must cover 100%."""
    from persia_tpu.analysis.common import python_files

    paths = list(files) if files is not None else python_files(root)
    out: Dict[str, List[Tuple[str, int]]] = {}
    for p in paths:
        abspath = p if os.path.isabs(p) else os.path.join(root, p)
        if (os.sep + "analysis" + os.sep) in abspath:
            continue
        scan = _scan_module(read_text(abspath), rel(abspath))
        if scan is None:
            continue
        for site, line in scan.reach_sites:
            out.setdefault(site, []).append((rel(abspath), line))
    return out


def _coverage_findings(root: str, sites: Dict[str, List[Tuple[str, int]]]) -> List[Finding]:
    from persia_tpu.analysis import crashcheck

    cov_path = os.path.join(root, COVERAGE_FILE)
    if not sites:
        return []
    if not os.path.exists(cov_path):
        return [Finding(
            "PROTO006", COVERAGE_FILE, 1,
            f"{len(sites)} reach() crash transitions extracted but no "
            f"{COVERAGE_FILE} committed — run the full crash matrix "
            "(python tests/test_protocol.py --write-coverage)",
        )]
    try:
        data = crashcheck.load_coverage(cov_path)
    except (OSError, ValueError):
        return [Finding("PROTO006", COVERAGE_FILE, 1,
                        f"{COVERAGE_FILE} is unreadable or not JSON")]
    return [
        Finding("PROTO006", COVERAGE_FILE, 1,
                p + " — every statically extracted transition must be "
                "killed at least once by tests/test_protocol.py")
        for p in crashcheck.validate_coverage(data, sites)
    ]


# --------------------------------------------------------------------- API


def check_source(text: str, path: str) -> List[Finding]:
    """Single-module entry point (fixtures): every rule evaluated with
    module-local resolution only, plus the fixture namespace prover."""
    scan = _scan_module(text, path)
    if scan is None:
        return []
    findings = _rule_proto001([scan])
    findings += _rule_proto002(scan)
    findings += _rule_proto003(scan)
    findings += _rule_proto004(scan)
    findings += _rule_proto005(scan)
    findings += _rule_proto007(scan)
    findings += _fixture_prover_findings(scan, text)
    return findings


def check(
    root: str = REPO_ROOT, files: Optional[Sequence[str]] = None,
) -> Tuple[List[Finding], Dict[str, object]]:
    from persia_tpu.analysis.common import python_files

    paths = list(files) if files is not None else python_files(root)
    scans: List[_ModuleScan] = []
    texts = 0
    for p in paths:
        abspath = p if os.path.isabs(p) else os.path.join(root, p)
        if (os.sep + "analysis" + os.sep) in abspath:
            continue  # the lint does not lint itself
        scan = _scan_module(read_text(abspath), rel(abspath))
        if scan is None:
            continue
        scans.append(scan)
        texts += 1
    findings = _rule_proto001(scans)
    for scan in scans:
        findings += _rule_proto002(scan)
        findings += _rule_proto003(scan)
        findings += _rule_proto004(scan)
        findings += _rule_proto005(scan)
        findings += _rule_proto007(scan)
    findings += _prover_findings(root)
    sites = {}
    for scan in scans:
        for site, line in scan.reach_sites:
            sites.setdefault(site, []).append((scan.path, line))
    findings += _coverage_findings(root, sites)
    findings += _abort_coverage_findings(root, sites)
    proof = prove_namespaces(root)
    coverage = {
        "files": texts,
        "phase_writers": sum(len(s.phase_writers) for s in scans),
        "phase_sites": sum(len(s.phase_sites) for s in scans),
        "reach_sites": len(sites),
        "families_proven": sorted(proof["patterns"].keys()),
        "pairs_disjoint": sum(
            1 for w in proof["pairs"].values() if w is not None
        ),
        "pairs_total": len(proof["pairs"]),
    }
    return findings, coverage

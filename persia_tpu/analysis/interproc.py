"""Interprocedural concurrency analysis: whole-program lock discipline.

The lexical pass (:mod:`persia_tpu.analysis.concurrency`) sees one
function at a time, so a blocking call reached THROUGH a helper —
``with self._lock: self._flush()`` where ``_flush`` does the native
call — is invisible to CONC003, and a lock acquired inside a callee is
invisible to CONC004. This pass builds a module-level call graph over
the whole package, propagates held-lock sets through call edges, and
re-issues those rules as whole-program checks:

- CONC005 **transitive blocking-call-under-lock**: a call made while
  holding a lock whose callee (transitively, through any number of
  resolved call edges) reaches a blocking call — ``time.sleep``, socket
  I/O, subprocess, or a ctypes call into a native core. Reported at the
  call site under the lock (that is the line that owns the decision to
  hold the lock across the call), with the full call chain in the
  message. Direct blocking in the same function stays CONC003's job.
- CONC006 **cross-function lock-order inversion**: a call made while
  holding a ranked lock whose callee transitively acquires a lock that
  ranks ABOVE (outer-than) the held one per
  :mod:`persia_tpu.analysis.lock_order`. CONC004 catches the lexically
  nested ``with``; this catches the same deadlock built out of two
  functions.
- CONC007 **unranked lock**: any lock-ish attribute/variable created via
  ``threading.Lock/RLock/Condition`` whose terminal name has no entry in
  ``lock_order.LOCK_RANKS``. Unranked locks are invisible to CONC004 and
  CONC006 — the registry must be complete for the order checks to mean
  anything.

Call resolution is deliberately conservative (a missed edge is a missed
finding, never a false one): ``self.m()`` resolves within the enclosing
class; bare ``f()`` to the module's own functions, then ``from``-imports,
then a package-wide UNIQUE module-level name; ``mod.f()`` through import
aliases; ``obj.m()`` only when exactly one class in the package defines
``m``. Suppress a finding with ``# persia-lint: disable=CONC005`` (or 006)
**on the call site under the lock** — the leaf that eventually blocks may
be shared by many callers, each of which must justify holding ITS lock
across the call. Like every pass here: pure stdlib, never lints
``analysis/`` itself.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from persia_tpu.analysis.common import Finding, REPO_ROOT, read_text, rel
from persia_tpu.analysis.concurrency import (
    _expr_name,
    _is_lockish,
    _is_semish,
    blocking_call_detail,
)
from persia_tpu.analysis.lock_order import rank_of

_LOCK_CTORS = {"Lock", "RLock", "Condition"}

# Method names NEVER resolved through the unique-name fallback: they are
# (also) methods of builtin containers / str / files / hashlib / queues /
# threading primitives, and the receiver's type is unknown — ``h.update()``
# on a hashlib object must not resolve to the one CLASS in the package that
# happens to define ``update``. Conservative by design: a genuine repo
# method with one of these names just loses its fallback edge (exact
# ``self.``/module-alias resolution still works).
_FALLBACK_DENY = frozenset({
    # dict / set / list / deque
    "update", "get", "pop", "popitem", "setdefault", "keys", "values",
    "items", "clear", "copy", "append", "appendleft", "extend",
    "extendleft", "insert", "remove", "sort", "reverse", "index", "count",
    "add", "discard", "union", "intersection", "difference",
    # str / bytes
    "join", "split", "rsplit", "splitlines", "strip", "lstrip", "rstrip",
    "startswith", "endswith", "replace", "format", "encode", "decode",
    "lower", "upper", "zfill",
    # files / buffers
    "read", "readline", "readlines", "write", "writelines", "seek",
    "tell", "flush", "close", "fileno",
    # hashlib / re
    "digest", "hexdigest", "group", "groups", "search", "match", "sub",
    "findall", "finditer",
    # threading / queue / futures (lock semantics differ per receiver —
    # Condition.wait_for RELEASES the lock, so attributing it to some
    # repo method named wait_for inverts the rule's meaning)
    "wait", "wait_for", "notify", "notify_all", "acquire", "release",
    "locked", "set", "is_set", "put", "put_nowait", "get_nowait", "qsize",
    "empty", "full", "task_done", "start", "cancel", "result", "done",
    "submit", "shutdown",
    # numpy scalars/arrays
    "item", "tolist", "tobytes", "astype", "reshape", "fill", "mean",
    "sum", "min", "max", "all", "any",
})

# held-lock entry: (lock name, rank or None, with-stmt line)
_Held = Tuple[str, Optional[int], int]


@dataclass
class _CallSite:
    kind: str  # "local" | "self" | "modattr" | "method"
    owner: str  # alias before the dot for modattr; "" otherwise
    name: str  # callee function/method name
    line: int
    held: Tuple[_Held, ...]
    resolved: Optional[str] = None  # function key, filled by _resolve_all


@dataclass
class _FuncInfo:
    key: str  # "<module>::<qualname>"
    path: str  # repo-relative
    module: str
    cls: str  # "" for module-level functions
    name: str
    blocking: List[Tuple[int, str]] = field(default_factory=list)
    acquires: List[Tuple[str, Optional[int], int]] = field(default_factory=list)
    calls: List[_CallSite] = field(default_factory=list)


@dataclass
class _ModuleInfo:
    module: str  # dotted name
    path: str
    imports: Dict[str, str] = field(default_factory=dict)  # alias -> dotted target
    lock_creations: List[Tuple[str, int]] = field(default_factory=list)


class _Index:
    def __init__(self) -> None:
        self.funcs: Dict[str, _FuncInfo] = {}
        self.modules: Dict[str, _ModuleInfo] = {}
        # fallback tables for unique-name resolution
        self.funcs_by_name: Dict[str, List[str]] = {}
        self.methods_by_name: Dict[str, List[str]] = {}

    def add_func(self, fi: _FuncInfo) -> None:
        self.funcs[fi.key] = fi
        table = self.methods_by_name if fi.cls else self.funcs_by_name
        table.setdefault(fi.name, []).append(fi.key)


def _dotted(path: str) -> str:
    p = rel(path) if os.path.isabs(path) else path
    p = p[:-3] if p.endswith(".py") else p
    parts = [x for x in p.split(os.sep) if x]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


# --------------------------------------------------------------- indexing


class _ModuleIndexer:
    """One pass over a module's AST collecting per-function facts: direct
    blocking calls, lock acquisitions, call sites with the held-lock
    stack at that point, plus the module's imports and lock creations."""

    def __init__(self, index: _Index, text: str, path: str, module: str):
        self.index = index
        self.path = path
        self.module = module
        self.mi = _ModuleInfo(module=module, path=path)
        index.modules[module] = self.mi
        self.tree = ast.parse(text, filename=path)

    def run(self) -> None:
        self._imports(self.tree)
        self._lock_creations(self.tree)
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._index_func(node, cls="")
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self._index_func(sub, cls=node.name)

    def _imports(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    alias = a.asname or a.name.split(".")[0]
                    # `import a.b.c` binds `a`; `import a.b.c as d` binds d->a.b.c
                    self.mi.imports[alias] = a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:  # relative: resolve against this module's package
                    pkg = self.module.split(".")
                    pkg = pkg[: len(pkg) - node.level]
                    base = ".".join(pkg + ([node.module] if node.module else []))
                for a in node.names:
                    if a.name == "*":
                        continue
                    alias = a.asname or a.name
                    self.mi.imports[alias] = f"{base}.{a.name}" if base else a.name

    def _lock_creations(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = node.value
            if not (isinstance(value, ast.Call) and self._is_lock_ctor(value.func)):
                continue
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for tgt in targets:
                name = _expr_name(tgt)
                if name and _is_lockish(name):
                    self.mi.lock_creations.append((name, node.lineno))

    def _is_lock_ctor(self, func: ast.expr) -> bool:
        if isinstance(func, ast.Attribute):
            return func.attr in _LOCK_CTORS and _expr_name(func.value) == "threading"
        if isinstance(func, ast.Name):
            return (
                func.id in _LOCK_CTORS
                and self.mi.imports.get(func.id, "") == f"threading.{func.id}"
            )
        return False

    # ------------------------------------------------------------ functions

    def _index_func(self, node, cls: str) -> None:
        qual = f"{cls}.{node.name}" if cls else node.name
        fi = _FuncInfo(
            key=f"{self.module}::{qual}",
            path=self.path, module=self.module, cls=cls, name=node.name,
        )
        self._walk_stmts(fi, node.body, held=[])
        self.index.add_func(fi)

    def _walk_stmts(self, fi: _FuncInfo, stmts: Sequence[ast.stmt], held: List[_Held]) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue  # nested scopes do not execute inline
            if isinstance(st, (ast.With, ast.AsyncWith)):
                entered: List[_Held] = []
                for item in st.items:
                    self._scan_expr(fi, item.context_expr, held)
                    name = _expr_name(item.context_expr)
                    if name and _is_lockish(name) and not _is_semish(name):
                        entry = (name, rank_of(name), st.lineno)
                        entered.append(entry)
                        fi.acquires.append(entry)
                held.extend(entered)
                self._walk_stmts(fi, st.body, held)
                for _ in entered:
                    held.pop()
                continue
            # the statement's own (header) expressions
            for fname, value in ast.iter_fields(st):
                if fname in ("body", "orelse", "finalbody", "handlers"):
                    continue
                for expr in value if isinstance(value, list) else [value]:
                    if isinstance(expr, ast.AST):
                        self._scan_expr(fi, expr, held)
            for fname in ("body", "orelse", "finalbody"):
                sub = getattr(st, fname, None)
                if sub:
                    self._walk_stmts(fi, sub, held)
            for h in getattr(st, "handlers", ()):
                self._walk_stmts(fi, h.body, held)

    def _scan_expr(self, fi: _FuncInfo, expr: ast.AST, held: List[_Held]) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            detail = blocking_call_detail(node)
            if detail is not None:
                fi.blocking.append((node.lineno, detail))
                continue
            site = self._call_site(node, tuple(held))
            if site is not None:
                fi.calls.append(site)

    def _call_site(self, node: ast.Call, held: Tuple[_Held, ...]) -> Optional[_CallSite]:
        f = node.func
        if isinstance(f, ast.Name):
            return _CallSite("local", "", f.id, node.lineno, held)
        if isinstance(f, ast.Attribute):
            value = f.value
            if isinstance(value, ast.Name):
                if value.id in ("self", "cls"):
                    return _CallSite("self", "", f.attr, node.lineno, held)
                return _CallSite("modattr", value.id, f.attr, node.lineno, held)
            return _CallSite("method", "", f.attr, node.lineno, held)
        return None


# -------------------------------------------------------------- resolution


def _resolve_all(index: _Index) -> int:
    edges = 0
    for fi in index.funcs.values():
        mi = index.modules[fi.module]
        for site in fi.calls:
            site.resolved = _resolve(index, mi, fi, site)
            if site.resolved is not None:
                edges += 1
    return edges


def _resolve(index: _Index, mi: _ModuleInfo, fi: _FuncInfo, site: _CallSite) -> Optional[str]:
    if site.kind == "self":
        key = f"{fi.module}::{fi.cls}.{site.name}"
        if key in index.funcs:
            return key
        return _unique(index.methods_by_name, site.name)
    if site.kind == "local":
        key = f"{fi.module}::{site.name}"
        if key in index.funcs:
            return key
        tgt = mi.imports.get(site.name)
        if tgt and "." in tgt:
            owner, leaf = tgt.rsplit(".", 1)
            key = f"{owner}::{leaf}"
            if key in index.funcs:
                return key
        return _unique(index.funcs_by_name, site.name)
    if site.kind == "modattr":
        tgt = mi.imports.get(site.owner)
        if tgt:
            key = f"{tgt}::{site.name}"
            if key in index.funcs:
                return key
        # not a module alias (or not ours): treat as a method receiver
        return _unique(index.methods_by_name, site.name)
    if site.kind == "method":
        return _unique(index.methods_by_name, site.name)
    return None


def _unique(table: Dict[str, List[str]], name: str) -> Optional[str]:
    if name in _FALLBACK_DENY:
        return None
    hits = table.get(name, ())
    return hits[0] if len(hits) == 1 else None


# --------------------------------------------------------------- summaries


def _blocking_path(
    index: _Index, key: str,
    memo: Dict[str, Optional[Tuple[Tuple[str, ...], str, int]]],
    stack: Set[str],
) -> Optional[Tuple[Tuple[str, ...], str, int]]:
    """(call chain of keys, blocking detail, leaf line) if ``key``
    transitively reaches a blocking call, else None. Cycles break to None
    for the in-progress member (a cycle adds no new blocking leaf)."""
    if key in memo:
        return memo[key]
    if key in stack:
        return None
    fi = index.funcs[key]
    if fi.blocking:
        line, detail = min(fi.blocking)
        memo[key] = ((key,), detail, line)
        return memo[key]
    stack.add(key)
    found = None
    for site in fi.calls:
        if site.resolved is None:
            continue
        sub = _blocking_path(index, site.resolved, memo, stack)
        if sub is not None:
            found = ((key,) + sub[0], sub[1], sub[2])
            break
    stack.discard(key)
    memo[key] = found
    return found


def _transitive_acquires(
    index: _Index, key: str,
    memo: Dict[str, Dict[str, Tuple[Optional[int], Tuple[str, ...], int]]],
    stack: Set[str],
) -> Dict[str, Tuple[Optional[int], Tuple[str, ...], int]]:
    """lock name -> (rank, example call chain, acquire line) for every
    lock ``key`` acquires itself or through resolved callees."""
    if key in memo:
        return memo[key]
    if key in stack:
        return {}
    fi = index.funcs[key]
    out: Dict[str, Tuple[Optional[int], Tuple[str, ...], int]] = {}
    for name, rank, line in fi.acquires:
        out.setdefault(name, (rank, (key,), line))
    stack.add(key)
    for site in fi.calls:
        if site.resolved is None:
            continue
        for name, (rank, path, line) in _transitive_acquires(
            index, site.resolved, memo, stack
        ).items():
            out.setdefault(name, (rank, (key,) + path, line))
    stack.discard(key)
    memo[key] = out
    return out


def _chain(keys: Sequence[str]) -> str:
    return " -> ".join(k.split("::", 1)[1] for k in keys)


# ------------------------------------------------------------------- rules


def _apply_rules(index: _Index) -> List[Finding]:
    findings: List[Finding] = []
    bmemo: Dict[str, Optional[Tuple[Tuple[str, ...], str, int]]] = {}
    amemo: Dict[str, Dict[str, Tuple[Optional[int], Tuple[str, ...], int]]] = {}

    for fi in index.funcs.values():
        for site in fi.calls:
            if not site.held or site.resolved is None:
                continue
            held_names = [h[0] for h in site.held]
            # CONC005: callee transitively blocks while we hold a lock
            bp = _blocking_path(index, site.resolved, bmemo, set())
            if bp is not None:
                path_keys, detail, leaf_line = bp
                leaf = index.funcs[path_keys[-1]]
                findings.append(Finding(
                    "CONC005", fi.path, site.line,
                    f"call under {', '.join(held_names)} reaches blocking "
                    f"{detail} via {_chain((fi.key,) + path_keys)} "
                    f"(at {leaf.path}:{leaf_line}) — every sibling thread "
                    "wanting the lock stalls behind the whole chain",
                ))
            # CONC006: callee transitively acquires an outer-ranked lock
            acq = _transitive_acquires(index, site.resolved, amemo, set())
            for name, (rank, path_keys, line) in sorted(acq.items()):
                if rank is None:
                    continue
                for held_name, held_rank, _ in site.held:
                    if held_rank is None or name == held_name:
                        continue
                    if rank < held_rank:
                        findings.append(Finding(
                            "CONC006", fi.path, site.line,
                            f"cross-function lock-order inversion: call under "
                            f"{held_name} (rank {held_rank}) acquires {name} "
                            f"(rank {rank}) via {_chain((fi.key,) + path_keys)} "
                            f"(at {index.funcs[path_keys[-1]].path}:{line}) — "
                            "declared order in analysis/lock_order.py says "
                            f"{name} is outermost",
                        ))

    # CONC007: lock created but absent from the ranking registry
    for mi in index.modules.values():
        for name, line in mi.lock_creations:
            if rank_of(name) is None:
                findings.append(Finding(
                    "CONC007", mi.path, line,
                    f"unranked lock '{name}' — absent from "
                    "analysis/lock_order.LOCK_RANKS, so CONC004/CONC006 "
                    "cannot order it; register a rank for it",
                ))

    # a call site under two locks (or one reached twice) reports once
    seen: Set[Tuple[str, str, int]] = set()
    out: List[Finding] = []
    for f in findings:
        k = (f.rule, f.path, f.line)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out


# --------------------------------------------------------------------- API


def build_index(
    root: str = REPO_ROOT, files: Optional[Sequence[str]] = None,
) -> Tuple[_Index, Dict[str, object]]:
    from persia_tpu.analysis.common import python_files

    paths = list(files) if files is not None else python_files(root)
    index = _Index()
    n_files = 0
    for p in paths:
        abspath = p if os.path.isabs(p) else os.path.join(root, p)
        if (os.sep + "analysis" + os.sep) in abspath:
            continue  # the lint does not lint itself
        try:
            _ModuleIndexer(index, read_text(abspath), rel(abspath), _dotted(abspath)).run()
        except SyntaxError:
            continue  # the style passes own broken-file reporting
        n_files += 1
    edges = _resolve_all(index)
    coverage = {
        "files": n_files,
        "functions": len(index.funcs),
        "edges": edges,
    }
    return index, coverage


def check_source(text: str, path: str) -> List[Finding]:
    """Single-module entry point (fixtures): the call graph spans just
    this module, so only self/local/unique-name edges resolve."""
    index = _Index()
    _ModuleIndexer(index, text, path, _dotted(path)).run()
    _resolve_all(index)
    return _apply_rules(index)


def check(
    root: str = REPO_ROOT, files: Optional[Sequence[str]] = None,
) -> Tuple[List[Finding], Dict[str, object]]:
    index, coverage = build_index(root, files)
    return _apply_rules(index), coverage

"""Concurrency lints for the feeder / write-back / RPC thread plane.

The ~26 locks guarding the training plane are invisible to the type
system; these AST rules mechanize the conventions the code already relies
on:

- CONC001 a mutex acquired with a bare ``.acquire()`` instead of ``with``
          (locks named ``*lock*``/``*mutex*``; semaphores are exempt — a
          permit legitimately crosses function/thread boundaries, CONC002
          covers their exception safety instead)
- CONC002 an ``.acquire()``/ring-span ``reserve()`` whose very next
          executed statement is not a ``try`` releasing it on the
          exception path — any statement in the gap (even a log call) can
          raise and leak the permit/span forever
- CONC003 a blocking call made while holding a lock: ``time.sleep``,
          socket connect/recv/send/accept, subprocess, or a ctypes call
          into a native core (``lib.*`` / ``*_lib.*`` — native calls can
          take the core's own mutex and block every sibling thread that
          wants the Python lock). ``Condition.wait`` is exempt (it
          releases the lock)
- CONC004 lexically nested ``with`` acquisitions of two registry locks in
          an order that inverts ``lock_order.LOCK_RANKS``
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Sequence, Tuple

from persia_tpu.analysis.common import Finding, REPO_ROOT, read_text, rel
from persia_tpu.analysis.lock_order import rank_of

_LOCKISH = ("lock", "mutex", "_mu")
_SEMISH = ("sem",)
_ACQUIRE_METHODS = ("acquire",)
_RESERVE_METHODS = ("reserve", "reserve_span")

# blocking calls flagged under a held lock: (qualifier substring, attr name)
_BLOCKING_ATTRS = {
    "sleep", "recv", "recv_into", "send", "sendall", "accept", "connect",
    "create_connection", "getaddrinfo", "check_call", "check_output", "run",
    "wait_for", "urlopen",
}
_BLOCKING_MODULES = ("time", "_time", "socket", "subprocess")


def _expr_name(node: ast.expr) -> str:
    """Terminal name of an attribute chain: self._deg_lock -> _deg_lock."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _expr_source(node: ast.expr) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover — unparse of synthetic nodes
        return ""


def _is_lockish(name: str) -> bool:
    low = name.lower()
    return (
        any(t in low for t in _LOCKISH)
        or low in ("cv", "cond")
        or low.endswith("cond")
        or low.endswith("_cv")
    )


def _is_semish(name: str) -> bool:
    return any(t in name.lower() for t in _SEMISH)


def blocking_call_detail(node: ast.Call) -> Optional[str]:
    """Human-readable description when ``node`` is a call this pass treats
    as blocking (sleep/socket/subprocess or a ctypes call into a native
    core), else None. Shared with the interprocedural pass (CONC005) so
    the two rules can never disagree about what "blocking" means."""
    f = node.func
    if not isinstance(f, ast.Attribute):
        return None
    attr = f.attr
    qual = _expr_source(f.value)
    qlow = qual.lower()
    if attr in _BLOCKING_ATTRS and (
        qual in _BLOCKING_MODULES
        or qlow.startswith("socket")
        or qlow.startswith("subprocess")
        or qlow.endswith("sock")
        or ".sock" in qlow
    ):
        return f"{qual}.{attr}()"
    if (
        (qlow == "lib" or qlow.endswith("_lib") or qlow.endswith("._lib"))
        and not attr.startswith("_")
    ):
        return f"native call {qual}.{attr}()"
    return None


def _releases(node: ast.AST, target_src: str) -> bool:
    """Does this subtree call <target>.release(...) (or ``_release``-ish
    cleanup naming the same object)?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
            if sub.func.attr.startswith("release") and _expr_source(sub.func.value) == target_src:
                return True
    return False


class _FuncChecker:
    def __init__(self, path: str, findings: List[Finding]):
        self.path = path
        self.findings = findings

    # ---------------------------------------------------------------- body
    def check_body(
        self,
        body: Sequence[ast.stmt],
        held: List[Tuple[str, int, int]],
        cont: Optional[ast.stmt] = None,
    ) -> None:
        """Walk a statement list; ``held`` is the stack of (lock name,
        rank-or-None, line) currently held via ``with``. ``cont`` is the
        statement that executes after this list runs off its end (so an
        acquire that is the LAST statement of an if-branch is judged
        against the statement following the whole if)."""
        for idx, stmt in enumerate(body):
            self._check_stmt(stmt, body, idx, held, cont)

    def _check_stmt(self, stmt, body, idx, held, cont=None) -> None:
        nxt_stmt = body[idx + 1] if idx + 1 < len(body) else cont
        if isinstance(stmt, ast.With) or isinstance(stmt, ast.AsyncWith):
            entered: List[Tuple[str, Optional[int], int]] = []
            for item in stmt.items:
                ctx = item.context_expr
                name = _expr_name(ctx)
                if _is_lockish(name) or _is_semish(name):
                    rank = rank_of(name)
                    # CONC004: nested with against the declared order
                    for outer_name, outer_rank, outer_line in held:
                        if (
                            rank is not None
                            and outer_rank is not None
                            and rank < outer_rank
                        ):
                            self.findings.append(Finding(
                                "CONC004", self.path, stmt.lineno,
                                f"lock-order inversion: {name} (rank {rank}) "
                                f"acquired while holding {outer_name} (rank "
                                f"{outer_rank}, line {outer_line}) — declared "
                                "order in analysis/lock_order.py says "
                                f"{name} is outermost",
                            ))
                    entered.append((name, rank, stmt.lineno))
            held.extend(entered)
            # CONC003 inside the with body (only when a lock was entered)
            if entered:
                self._check_blocking(stmt.body, [e[0] for e in held if e[0]], stmt)
            self.check_body(stmt.body, held, nxt_stmt)
            for _ in entered:
                held.pop()
            return

        # CONC001 / CONC002: bare acquire()/reserve() statements
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            if isinstance(call.func, ast.Attribute):
                attr = call.func.attr
                target = call.func.value
                tname = _expr_name(target)
                tsrc = _expr_source(target)
                if attr in _ACQUIRE_METHODS and _is_lockish(tname):
                    self.findings.append(Finding(
                        "CONC001", self.path, stmt.lineno,
                        f"{tsrc}.acquire() outside `with` — use `with {tsrc}:` "
                        "so every exit path releases the lock",
                    ))
                elif attr in _ACQUIRE_METHODS and _is_semish(tname):
                    self._check_release_follows(stmt, nxt_stmt, tsrc, "permit")
                elif attr in _RESERVE_METHODS and any(
                    t in tname.lower() for t in ("ring", "span", "ledger")
                ):
                    self._check_release_follows(stmt, nxt_stmt, tsrc, "span")

        # recurse into compound statements
        for sub_body in _sub_bodies(stmt):
            self.check_body(sub_body, held, nxt_stmt)

    # ------------------------------------------------------------ CONC002
    def _check_release_follows(self, stmt, nxt, tsrc: str, what: str) -> None:
        """The statement executing after an acquire/reserve must be a try
        that releases on the exception path (except or finally)."""
        ok = False
        if isinstance(nxt, ast.Try):
            for h in nxt.handlers:
                if _releases(h, tsrc):
                    ok = True
            for fstmt in nxt.finalbody:
                if _releases(fstmt, tsrc):
                    ok = True
        if not ok:
            self.findings.append(Finding(
                "CONC002", self.path, stmt.lineno,
                f"{tsrc} {what} acquired but the next statement is not a "
                "try releasing it on the exception path — anything raising "
                f"in the gap leaks the {what} forever",
            ))

    # ------------------------------------------------------------ CONC003
    def _check_blocking(self, body: Sequence[ast.stmt], held_names: List[str], with_stmt) -> None:
        for stmt in body:
            for node in ast.walk(stmt):
                # nested with over the same analysis happens via check_body;
                # here only flag direct blocking calls
                if not isinstance(node, ast.Call):
                    continue
                detail = blocking_call_detail(node)
                if detail is not None:
                    self.findings.append(Finding(
                        "CONC003", self.path, node.lineno,
                        f"blocking {detail} while holding "
                        f"{', '.join(held_names)} (with at line "
                        f"{with_stmt.lineno}) — every sibling thread wanting "
                        "the lock stalls behind it",
                    ))


def _sub_bodies(stmt: ast.stmt):
    for field_name in ("body", "orelse", "finalbody"):
        sub = getattr(stmt, field_name, None)
        if isinstance(stmt, (ast.With, ast.AsyncWith)) and field_name == "body":
            continue  # handled by the with path
        if sub:
            yield sub
    for h in getattr(stmt, "handlers", ()):
        yield h.body


def check_source(text: str, path: str) -> List[Finding]:
    findings: List[Finding] = []
    tree = ast.parse(text, filename=path)
    checker = _FuncChecker(path, findings)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            checker.check_body(node.body, [])
    # nested withs are visited from every enclosing level — dedupe by site
    seen = set()
    out: List[Finding] = []
    for f in findings:
        key = (f.rule, f.path, f.line)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out


def check(root: str = REPO_ROOT, files: Optional[Sequence[str]] = None) -> List[Finding]:
    from persia_tpu.analysis.common import python_files

    paths = list(files) if files is not None else python_files(root)
    findings: List[Finding] = []
    for p in paths:
        abspath = p if os.path.isabs(p) else os.path.join(root, p)
        if (os.sep + "analysis" + os.sep) in abspath:
            continue  # the lint does not lint itself
        findings.extend(check_source(read_text(abspath), rel(abspath)))
    return findings

"""Observability lint: one metric namespace, one stage-timing mechanism.

The telemetry plane (persia_tpu/tracing.py + metrics.py) only composes
into one fleet view if every process follows two mechanical conventions:

- OBS001 a metric registered (``.counter(`` / ``.gauge(`` /
         ``.histogram(``) with a literal name OUTSIDE the
         ``persia_tpu_`` / ``persia_`` namespace — the fleet scraper
         aggregates by prefix, and an off-namespace series silently
         drops out of every dashboard and bench artifact
- OBS002 a hand-rolled ``t0 = time.time()`` / ``time.perf_counter()``
         stage timer in a pipeline module whose result feeds a
         subtraction, in a function with no ``tracing.span`` /
         ``stage_span`` / metric ``.time(`` in sight — the duration is
         measured but invisible to both the live stage histogram and the
         merged trace; use :func:`persia_tpu.tracing.stage_span`

OBS002 scope: the hot pipeline modules (``embedding/hbm_cache/``,
``serving/``, ``data_loader.py``, ``incremental.py``) — a stage duration
there IS an observability artifact. ``tracing.py``/``metrics.py`` are the
mechanism and exempt; deadline arithmetic on ``time.monotonic()`` is the
resilience engine's business (RES004), not flagged here.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Sequence

from persia_tpu.analysis.common import Finding, REPO_ROOT, read_text, rel

_METRIC_METHODS = ("counter", "gauge", "histogram")
_NAME_PREFIXES = ("persia_tpu_", "persia_")

_TIMER_SCOPE_DIRS = (
    os.path.join("persia_tpu", "embedding", "hbm_cache"),
    os.path.join("persia_tpu", "serving"),
)
_TIMER_SCOPE_FILES = (
    os.path.join("persia_tpu", "data_loader.py"),
    os.path.join("persia_tpu", "incremental.py"),
    # the elastic reshard engine: fence/handoff/release durations are
    # recovery-time evidence and must flow through spans, and its
    # reshard.* flight events ride the same OBS001 namespace rule
    os.path.join("persia_tpu", "elastic.py"),
)
# the mechanism itself may hold raw clocks
_EXEMPT_BASENAMES = ("tracing.py", "metrics.py")

# what proves the enclosing function already times through the sanctioned
# machinery: a tracing span (span/stage_span), or a metric timer context
_SANCTIONED_TOKENS = ("span(", ".time(")

_CLOCK_FUNCS = ("time", "perf_counter")


def _src(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover
        return ""


def _is_clock_call(node: ast.expr) -> bool:
    """``time.time()`` / ``time.perf_counter()`` (module aliased ``_time``
    too, the stream module's idiom)."""
    if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
        return False
    f = node.func
    return (
        f.attr in _CLOCK_FUNCS
        and isinstance(f.value, ast.Name)
        and f.value.id in ("time", "_time")
    )


def _metric_name_findings(tree: ast.AST, path: str) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_METHODS):
            continue
        if not node.args:
            continue
        name = node.args[0]
        if not (isinstance(name, ast.Constant) and isinstance(name.value, str)):
            continue  # computed names are the registry's own business
        if name.value.startswith(_NAME_PREFIXES):
            continue
        findings.append(Finding(
            "OBS001", path, node.lineno,
            f".{node.func.attr}({name.value!r}) registers a metric outside "
            "the persia_tpu_/persia_ namespace — the fleet scraper "
            "aggregates by prefix, so this series drops out of every "
            "dashboard and bench artifact",
        ))
    return findings


def _timer_findings(tree: ast.AST, text: str, path: str) -> List[Finding]:
    findings: List[Finding] = []
    scopes = [
        n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    for fn in scopes:
        # nested defs belong to the inner scope: judge each function only
        # on its OWN direct statements' clock assignments, but whitelist
        # on the full source (a closure timing into an outer span is fine)
        fn_src = _src(fn)
        if any(tok in fn_src for tok in _SANCTIONED_TOKENS):
            continue
        assigns = {}
        for node in ast.walk(fn):
            if (isinstance(node, ast.Assign) and _is_clock_call(node.value)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                assigns[node.targets[0].id] = node.lineno
        if not assigns:
            continue
        for node in ast.walk(fn):
            if (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub)
                    and isinstance(node.right, ast.Name)
                    and node.right.id in assigns):
                var = node.right.id
                findings.append(Finding(
                    "OBS002", path, assigns.pop(var),
                    f"hand-rolled stage timer ({var} = time.{_CLOCK_FUNCS[0]}"
                    f"()/perf_counter() ... X - {var}) in a pipeline module "
                    "— the duration never reaches the stage histogram or "
                    "the trace; wrap the stage in tracing.stage_span(...)",
                ))
    return findings


def _timer_in_scope(path: str) -> bool:
    p = rel(path)
    if os.path.basename(p) in _EXEMPT_BASENAMES:
        return False
    if p in _TIMER_SCOPE_FILES:
        return True
    return any(p.startswith(d + os.sep) for d in _TIMER_SCOPE_DIRS)


def check_source(text: str, path: str,
                 timer_scope: Optional[bool] = None) -> List[Finding]:
    """Lint one file. ``timer_scope`` forces OBS002 on/off (fixtures);
    None = decide from the path."""
    tree = ast.parse(text, filename=path)
    findings = _metric_name_findings(tree, path)
    if timer_scope if timer_scope is not None else _timer_in_scope(path):
        findings.extend(_timer_findings(tree, text, path))
    return findings


def check(root: str = REPO_ROOT,
          files: Optional[Sequence[str]] = None) -> List[Finding]:
    from persia_tpu.analysis.common import python_files

    paths = list(files) if files is not None else python_files(root)
    findings: List[Finding] = []
    for p in paths:
        abspath = p if os.path.isabs(p) else os.path.join(root, p)
        findings.extend(check_source(read_text(abspath), rel(abspath)))
    return findings

"""Shared plumbing for the persia-lint passes.

A finding is (rule, file, line, message). Every pass returns a list of
findings; the CLI exits nonzero when any survive suppression. Suppression
is inline and per-line in both languages::

    something_flagged()  # persia-lint: disable=RES001
    do_native_call();    // persia-lint: disable=ABI006
    risky()              # persia-lint: disable=all

The passes are pure stdlib (ast + re) by design: the lint must run on a
toolchain-less host in well under a second, so it can gate every commit
(scripts/round_preflight.sh) without jax, numpy, or clang anywhere near it.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Sequence, Set

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_SUPPRESS_RE = re.compile(r"persia-lint:\s*disable=([A-Za-z0-9_,\s]+|all)")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


def rel(path: str) -> str:
    """Repo-relative display path (keeps absolute paths out of findings so
    fixture-based tests compare stable strings)."""
    try:
        return os.path.relpath(path, REPO_ROOT)
    except ValueError:  # different drive (never on POSIX)
        return path


def read_text(path: str) -> str:
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return f.read()


def suppressed_lines(text: str) -> Dict[int, Set[str]]:
    """line (1-based) -> set of rule ids disabled on that line ("all" wins)."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(text.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        spec = m.group(1).strip()
        if spec == "all":
            out[i] = {"all"}
        else:
            out[i] = {r.strip().upper() for r in spec.split(",") if r.strip()}
    return out


def apply_suppressions(findings: Iterable[Finding], texts: Dict[str, str]) -> List[Finding]:
    """Drop findings whose line carries a matching inline disable. ``texts``
    maps repo-relative path -> raw source."""
    cache: Dict[str, Dict[int, Set[str]]] = {}
    kept: List[Finding] = []
    for f in findings:
        text = texts.get(f.path)
        if text is not None:
            if f.path not in cache:
                cache[f.path] = suppressed_lines(text)
            rules = cache[f.path].get(f.line, set())
            if "all" in rules or f.rule.upper() in rules:
                continue
        kept.append(f)
    return kept


def python_files(root: str, subdirs: Sequence[str] = ("persia_tpu",)) -> List[str]:
    out: List[str] = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            out.extend(
                os.path.join(dirpath, f) for f in sorted(filenames) if f.endswith(".py")
            )
    return sorted(out)


# ------------------------------------------------------------------ registry
#
# The five native libraries and the binding files that speak to them. The
# ABI pass discovers bindings by parsing ctypes.CDLL call sites, but the
# registry is the completeness oracle: a lib listed here with zero parsed
# exports, or a binding file that stops parsing, is itself a finding
# (silent coverage loss is how drift sneaks back in).

NATIVE_LIBS: Dict[str, List[str]] = {
    "libpersia_ps.so": ["native/ps.cpp"],
    "libpersia_worker.so": ["native/worker.cpp"],
    "libpersia_cache.so": ["native/cache.cpp"],
    "libpersia_codec.so": ["native/codec.cpp"],
    "libpersia_net.so": ["native/server.cpp", "native/codec.cpp"],
}

# Files expected to declare ctypes bindings against the libs above.
BINDING_FILES: List[str] = [
    "persia_tpu/embedding/hbm_cache/directory.py",
    "persia_tpu/embedding/native_store.py",
    "persia_tpu/embedding/native_worker.py",
    "persia_tpu/embedding/tiering/native.py",
    "persia_tpu/service/codec.py",
    "persia_tpu/service/native_rpc.py",
]

# Every file that touches ctypes at all (bindings above + raw-pointer call
# sites riding a lib loaded elsewhere). The ABI pass asserts it scanned all
# of them so "covers all ctypes files" stays true as the set grows.
CTYPES_FILES: List[str] = BINDING_FILES + [
    "persia_tpu/embedding/build_native.py",
    "persia_tpu/embedding/hbm_cache/ctx.py",
    "persia_tpu/embedding/hbm_cache/groups.py",
    "persia_tpu/embedding/hbm_cache/step.py",
    "persia_tpu/embedding/hbm_cache/stream.py",
    "persia_tpu/embedding/hbm_cache/tier.py",
]


def ctypes_loader_files(root: str = REPO_ROOT) -> List[str]:
    """Repo-relative persia_tpu/ files that load a native library via
    ``ctypes.CDLL``. The ABI pass (ABI009) diffs this against CTYPES_FILES:
    a loader the registry does not know about is a binding surface the
    drift checker silently skips. AST-based so comments, docstrings, and
    the lint passes' own string literals never count as call sites."""
    out: List[str] = []
    for path in python_files(root):
        text = read_text(path)
        if "CDLL" not in text:  # cheap pre-filter before parsing
            continue
        try:
            tree = ast.parse(text, filename=path)
        except SyntaxError:
            continue  # the style passes own broken-file reporting
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                f = node.func
                name = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else "")
                if name == "CDLL":
                    out.append(os.path.relpath(path, root))
                    break
    return sorted(out)

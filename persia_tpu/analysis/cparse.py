"""Lightweight ``extern "C"`` declaration parser for the native sources.

Deliberately not a C++ parser: the exported surface of the five native
libraries is plain-C by construction (pointer/integer/float scalars only —
anything fancier would not be ctypes-bindable in the first place), so a
comment-stripping brace walker that reads declarations at the top level of
each ``extern "C"`` block is complete for this codebase and needs no clang.

Canonical type descriptors (shared with the Python side in abi.py):

    ("void",)                      C void return
    ("int", width, signed)         integer scalar, width in bits
    ("float", width)               float (32) / double (64)
    ("ptr", inner)                 pointer; inner is a descriptor or
                                   ("void",) for void* / unknown pointees
    ("funcptr",)                   function-pointer typedef
    ("opaque", token)              unrecognized token (matched leniently,
                                   but surfaced in the parse report)
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

TypeDesc = Tuple  # canonical descriptor tuples, see module docstring


@dataclass
class CFunc:
    name: str
    ret: TypeDesc
    params: List[TypeDesc]
    line: int  # 1-based line of the declaration in the source file
    path: str  # repo-relative source path


_SCALARS: Dict[str, TypeDesc] = {
    "void": ("void",),
    "bool": ("int", 8, False),
    "char": ("int", 8, True),
    "int8_t": ("int", 8, True),
    "uint8_t": ("int", 8, False),
    "int16_t": ("int", 16, True),
    "short": ("int", 16, True),
    "uint16_t": ("int", 16, False),
    "int": ("int", 32, True),
    "int32_t": ("int", 32, True),
    "unsigned": ("int", 32, False),
    "uint32_t": ("int", 32, False),
    "long": ("int", 64, True),
    "int64_t": ("int", 64, True),
    "uint64_t": ("int", 64, False),
    "size_t": ("int", 64, False),
    "ssize_t": ("int", 64, True),
    "float": ("float", 32),
    "double": ("float", 64),
}

_FUNCPTR_TYPEDEF_RE = re.compile(r"typedef\s+[^;{]*\(\s*\*\s*(\w+)\s*\)\s*\(")


def _strip_comments(text: str) -> str:
    """Replace comments with spaces (newlines preserved so line numbers
    survive)."""
    out: List[str] = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == "/" and i + 1 < n and text[i + 1] == "/":
            j = text.find("\n", i)
            j = n if j < 0 else j
            out.append(" " * (j - i))
            i = j
        elif c == "/" and i + 1 < n and text[i + 1] == "*":
            j = text.find("*/", i + 2)
            j = n - 2 if j < 0 else j
            seg = text[i:j + 2]
            out.append("".join("\n" if ch == "\n" else " " for ch in seg))
            i = j + 2
        elif c == '"' or c == "'":
            q = c
            j = i + 1
            while j < n and text[j] != q:
                j = j + 2 if text[j] == "\\" else j + 1
            out.append(c + " " * max(j - i - 1, 0) + (q if j < n else ""))
            i = j + 1
        else:
            out.append(c)
            i += 1
    return "".join(out)


def parse_c_type(tok: str, funcptr_typedefs=()) -> TypeDesc:
    """Canonicalize one C parameter/return type string (name already
    removed)."""
    t = tok.strip()
    # drop qualifiers that do not affect the call ABI
    t = re.sub(r"\b(const|volatile|restrict|struct|enum)\b", " ", t)
    t = re.sub(r"\s+", " ", t).strip()
    if t.endswith("*"):
        inner = parse_c_type(t[:-1], funcptr_typedefs)
        return ("ptr", inner)
    # collapse multi-word scalars
    if t in ("unsigned int",):
        t = "unsigned"
    if t in ("long long", "long int", "long long int"):
        t = "long"
    if t in ("unsigned long", "unsigned long long", "unsigned long long int"):
        return ("int", 64, False)
    if t in ("unsigned char",):
        return ("int", 8, False)
    if t in ("signed char",):
        return ("int", 8, True)
    if t in _SCALARS:
        return _SCALARS[t]
    if t in funcptr_typedefs:
        return ("funcptr",)
    return ("opaque", t)


def _split_params(paramstr: str) -> List[str]:
    """Split a parameter list on top-level commas."""
    parts: List[str] = []
    depth = 0
    cur: List[str] = []
    for ch in paramstr:
        if ch in "(<[":
            depth += 1
        elif ch in ")>]":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur and "".join(cur).strip():
        parts.append("".join(cur))
    return parts


def _strip_param_name(param: str) -> str:
    """Remove the trailing parameter name, keeping its type. Handles
    ``const uint64_t* const* ids`` and bare types (``int64_t``)."""
    p = param.strip()
    if not p or p == "void" or p == "...":
        return p if p == "void" else p
    m = re.match(r"^(.*?)([A-Za-z_]\w*)\s*(\[\s*\d*\s*\])?$", p, re.S)
    if not m:
        return p
    head, last, arr = m.group(1).strip(), m.group(2), m.group(3)
    if not head:
        return last  # a bare type like "void" or a typedef with no name
    if arr:
        head += "*"  # T name[] decays to T*
    return head


_KEYWORD_HEADS = ("namespace", "struct", "class", "union", "enum", "typedef",
                  "using", "template", "static_assert", "extern")


def parse_extern_c(text: str, path: str = "<src>") -> Tuple[List[CFunc], List[str]]:
    """Parse every declaration at the TOP LEVEL of each ``extern "C"``
    block. Returns (functions, parse_warnings). Nested bodies (function
    definitions, interior namespaces) are brace-skipped, so calls inside
    bodies are never mistaken for declarations."""
    raw = text
    text = _strip_comments(text)
    funcptr_typedefs = set(_FUNCPTR_TYPEDEF_RE.findall(text))
    funcs: List[CFunc] = []
    warnings: List[str] = []
    seen: Dict[str, CFunc] = {}

    pos = 0
    while True:
        # NB: _strip_comments blanks string-literal contents, so the "C" in
        # the source reads back as a one-space string here
        m = re.search(r'extern\s*"[^"\n]*"\s*\{', text[pos:])
        if not m:
            break
        block_start = pos + m.end()
        # find the matching close brace for the extern block
        depth = 1
        i = block_start
        n = len(text)
        decl_start = i
        while i < n and depth > 0:
            c = text[i]
            if c == "{":
                if depth == 1:
                    # a declaration ending in a body: parse the signature,
                    # then skip the balanced body
                    _consume_decl(text, decl_start, i, path, funcptr_typedefs,
                                  funcs, seen, warnings)
                    body_depth = 1
                    i += 1
                    while i < n and body_depth > 0:
                        if text[i] == "{":
                            body_depth += 1
                        elif text[i] == "}":
                            body_depth -= 1
                        i += 1
                    decl_start = i
                    continue
                depth += 1
            elif c == "}":
                depth -= 1
                if depth == 0:
                    break
            elif c == ";" and depth == 1:
                _consume_decl(text, decl_start, i, path, funcptr_typedefs,
                              funcs, seen, warnings)
                decl_start = i + 1
            i += 1
        pos = i + 1
    if not funcs and 'extern "C"' in raw:
        warnings.append(f"{path}: extern \"C\" block parsed to zero declarations")
    return funcs, warnings


def _consume_decl(text, start, end, path, funcptr_typedefs, funcs, seen, warnings):
    decl = text[start:end].strip()
    if not decl or "(" not in decl:
        return
    head = decl.split("(", 1)[0].strip()
    first_word = head.split()[0] if head.split() else ""
    if first_word in _KEYWORD_HEADS:
        return
    if "static" in head.split():
        return  # internal linkage — never in the dynamic symbol table
    line = text.count("\n", 0, start + (len(text[start:end]) - len(text[start:end].lstrip()))) + 1
    # signature: everything up to the matching close paren of the first open
    open_idx = decl.index("(")
    depth = 0
    close_idx = -1
    for j in range(open_idx, len(decl)):
        if decl[j] == "(":
            depth += 1
        elif decl[j] == ")":
            depth -= 1
            if depth == 0:
                close_idx = j
                break
    if close_idx < 0:
        warnings.append(f"{path}:{line}: unterminated declaration {decl[:60]!r}")
        return
    paramstr = decl[open_idx + 1:close_idx]
    mh = re.match(r"^(.*?)([A-Za-z_]\w*)$", head, re.S)
    if not mh:
        warnings.append(f"{path}:{line}: unparseable declaration head {head!r}")
        return
    ret_str, name = mh.group(1).strip(), mh.group(2)
    if not ret_str:
        return  # constructor-ish / macro — not a C export
    ret = parse_c_type(ret_str, funcptr_typedefs)
    params: List[TypeDesc] = []
    raw_params = _split_params(paramstr)
    if not (len(raw_params) == 1 and raw_params[0].strip() in ("void", "")):
        for prm in raw_params:
            params.append(parse_c_type(_strip_param_name(prm), funcptr_typedefs))
    fn = CFunc(name=name, ret=ret, params=params, line=line, path=path)
    prev = seen.get(name)
    if prev is not None:
        # re-declaration (e.g. server.cpp forward-declares the codec fns):
        # signatures must agree or the lib itself is internally drifted
        if (prev.ret, prev.params) != (fn.ret, fn.params):
            warnings.append(
                f"{path}:{line}: conflicting re-declaration of {name} "
                f"(first at {prev.path}:{prev.line})"
            )
        return
    seen[name] = fn
    funcs.append(fn)


def describe(desc: TypeDesc) -> str:
    """Human-readable descriptor for findings."""
    kind = desc[0]
    if kind == "void":
        return "void"
    if kind == "int":
        return f"{'' if desc[2] else 'u'}int{desc[1]}"
    if kind == "float":
        return {32: "float", 64: "double"}[desc[1]]
    if kind == "ptr":
        return describe(desc[1]) + "*"
    if kind == "funcptr":
        return "<funcptr>"
    return f"<{desc[1]}>"

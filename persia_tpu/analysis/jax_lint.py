"""JAX trace-discipline lints: the jit boundary as a checkable contract.

The pjit/TPU scaling work (PAPERS.md, arxiv 2204.06514) shows step-time
regressions on the training plane are dominated not by kernels but by
boundary mistakes: an accidental host sync serializing the dispatch
pipeline, a retrace storm from a Python-value branch inside a jitted
function, a donated buffer read after the callee already aliased it, and
benchmarks that read the wall clock before the device finished. Four
rules, each mechanizing one of those:

- JAX001 **host sync on a jit output in a hot path**: a value produced by
  a jitted callable consumed on the host (``.item()``, ``float(...)``,
  ``np.asarray``/``np.array``) inside ``parallel/`` or
  ``embedding/hbm_cache/`` without a sentinel-style guard in the function
  (tokens: sentinel / isfinite / isnan / nonfinite / block_until_ready —
  the deliberate-sync idioms the health plane already uses). Each such
  sync drains the dispatch queue; per-step it serializes host and device.
- JAX002 **retrace hazard**: a jitted function branching (``if``/
  ``while``/``for _ in range(...)``) on a parameter not marked static via
  ``static_argnums``/``static_argnames``. Branching on a traced value
  either raises at trace time or — when callers pass Python scalars —
  silently retraces per distinct value. ``x is None`` / ``x is not None``
  and shape/dtype attribute probes (``x.shape``, ``x.ndim``, ``x.dtype``)
  are static under trace and exempt.
- JAX003 **donated-buffer reuse**: an argument passed in a donated
  position (``donate_argnums``) of a jitted callable and then read again
  before being rebound. XLA may alias the donated buffer into the output;
  the read observes garbage — or silently stale data on backends that
  copy. The loop idiom ``state, loss = step(state, batch)`` rebinds and
  is clean.
- JAX004 **un-synced benchmark timing** (``bench.py`` + ``benchmarks/``):
  a ``t0 = time.perf_counter()`` … ``x - t0`` window that calls a
  device-producing function (jitted, or a package function touching
  jax/jnp — resolved through imports, whole-program like CONC005) with no
  ``block_until_ready`` inside the window. The window then measures
  dispatch, not execution. Host-orchestrated loops (ctx methods that sync
  internally) stay silent — only resolvable device-producing callees
  count.

Suppress with ``# persia-lint: disable=JAX00n`` on the reported line.
Pure stdlib; jax itself is never imported.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from persia_tpu.analysis.common import Finding, REPO_ROOT, read_text, rel

# JAX001 hot-path scope
_SYNC_SCOPE_DIRS = (
    os.path.join("persia_tpu", "parallel"),
    os.path.join("persia_tpu", "embedding", "hbm_cache"),
)
# JAX004 bench scope
_BENCH_SCOPE_FILES = ("bench.py",)
_BENCH_SCOPE_DIRS = ("benchmarks",)

_GUARD_TOKENS = ("sentinel", "isfinite", "isnan", "nonfinite", "block_until_ready")
_CLOCK_FUNCS = ("perf_counter", "monotonic", "time")


@dataclass
class _JitInfo:
    jitted: bool = False
    donate: Tuple[int, ...] = ()
    static_nums: Tuple[int, ...] = ()
    static_names: Tuple[str, ...] = ()
    device: bool = False  # produces device values (jitted or touches jax/jnp)
    def_node: Optional[ast.AST] = None


def _int_tuple(node: Optional[ast.expr]) -> Tuple[int, ...]:
    out: List[int] = []
    for sub in ast.walk(node) if node is not None else ():
        if isinstance(sub, ast.Constant) and isinstance(sub.value, int):
            out.append(sub.value)
    return tuple(out)


def _str_tuple(node: Optional[ast.expr]) -> Tuple[str, ...]:
    out: List[str] = []
    for sub in ast.walk(node) if node is not None else ():
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            out.append(sub.value)
    return tuple(out)


def _is_jit_ref(node: ast.expr) -> bool:
    """``jax.jit`` / bare ``jit`` / ``pjit``."""
    if isinstance(node, ast.Attribute):
        return node.attr in ("jit", "pjit")
    return isinstance(node, ast.Name) and node.id in ("jit", "pjit")


def _jit_call_opts(call: ast.Call) -> Optional[_JitInfo]:
    """Options when ``call`` is ``jax.jit(...)`` / ``partial(jax.jit, ...)``,
    else None."""
    f = call.func
    if _is_jit_ref(f):
        info = _JitInfo(jitted=True, device=True)
    elif (
        (isinstance(f, ast.Attribute) and f.attr == "partial")
        or (isinstance(f, ast.Name) and f.id.lstrip("_") == "partial")
    ) and call.args and _is_jit_ref(call.args[0]):
        info = _JitInfo(jitted=True, device=True)
    else:
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            info.donate = _int_tuple(kw.value)
        elif kw.arg == "static_argnums":
            info.static_nums = _int_tuple(kw.value)
        elif kw.arg == "static_argnames":
            info.static_names = _str_tuple(kw.value)
    return info


def _decorated_jit(node) -> Optional[_JitInfo]:
    for dec in node.decorator_list:
        if _is_jit_ref(dec):
            return _JitInfo(jitted=True, device=True, def_node=node)
        if isinstance(dec, ast.Call):
            info = _jit_call_opts(dec)
            if info is not None:
                info.def_node = node
                return info
    return None


def _root_name(node: ast.expr) -> str:
    """Leftmost Name of an Attribute/Subscript chain: m["loss"].x -> m."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else ""


def _target_names(tgt: ast.expr) -> List[str]:
    out: List[str] = []
    for sub in ast.walk(tgt):
        if isinstance(sub, ast.Name):
            out.append(sub.id)
    return out


def _uses_jax(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in ("jax", "jnp"):
            return True
    return False


def _own_nodes(fn) -> List[ast.AST]:
    """All nodes of ``fn``'s body except nested function/class scopes."""
    out: List[ast.AST] = []
    stack: List[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


# ---------------------------------------------------------------- module scan


class _Module:
    """One file's jit surface: imports, jitted/device-producing defs,
    jitted assignments (``step = jax.jit(f, ...)``, incl. self-attrs)."""

    def __init__(self, text: str, path: str):
        self.path = path
        p = path[:-3] if path.endswith(".py") else path
        parts = [x for x in p.split(os.sep) if x]
        if parts and parts[-1] == "__init__":
            parts = parts[:-1]
        self.dotted = ".".join(parts)
        self.tree = ast.parse(text, filename=path)
        self.imports: Dict[str, str] = {}
        self.defs: Dict[str, _JitInfo] = {}  # module-level def name -> info
        self.assigned: Dict[str, _JitInfo] = {}  # name or attr-source -> info
        self._scan()

    def _scan(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.imports[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:  # relative: resolve against this package
                    pkg = self.dotted.split(".")
                    pkg = pkg[: len(pkg) - node.level]
                    base = ".".join(pkg + ([node.module] if node.module else []))
                if not base:
                    continue
                for a in node.names:
                    if a.name != "*":
                        self.imports[a.asname or a.name] = f"{base}.{a.name}"
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = _decorated_jit(node)
                if info is None:
                    info = _JitInfo(device=_uses_jax(node), def_node=node)
                self.defs.setdefault(node.name, info)
            elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                info = _jit_call_opts(node.value)
                if info is None:
                    continue
                # wrapped local def: jax.jit(step, ...) — attach the def so
                # JAX002 can check its params against the static sets
                if node.value.args and isinstance(node.value.args[0], ast.Name):
                    wrapped = node.value.args[0].id
                    if wrapped in self.defs:
                        info.def_node = self.defs[wrapped].def_node
                for tgt in node.targets:
                    try:
                        self.assigned[ast.unparse(tgt)] = info
                    except Exception:  # pragma: no cover — synthetic nodes
                        pass

    def jit_info_for_call(self, call: ast.Call, registry: Dict[str, _JitInfo]) -> Optional[_JitInfo]:
        """Resolve a call's target to its jit info: local assignment
        (``step(...)`` / ``self._kstep_jit(...)``), module-level def,
        from-imported name via the package registry."""
        f = call.func
        try:
            src = ast.unparse(f)
        except Exception:  # pragma: no cover
            src = ""
        if src in self.assigned:
            return self.assigned[src]
        if isinstance(f, ast.Name):
            if f.id in self.defs:
                return self.defs[f.id]
            tgt = self.imports.get(f.id)
            if tgt and tgt in registry:
                return registry[tgt]
        return None


def _registry_from(paths: Sequence[str], root: str) -> Dict[str, _JitInfo]:
    """dotted.module.func -> jit info for every module-level def in the
    package (the JAX004/JAX003 whole-program half: an imported callee's
    jit/donation/device facts travel to the caller's module)."""
    registry: Dict[str, _JitInfo] = {}
    for p in paths:
        abspath = p if os.path.isabs(p) else os.path.join(root, p)
        rp = rel(abspath)
        dotted = rp[:-3].replace(os.sep, ".") if rp.endswith(".py") else rp
        try:
            mod = _Module(read_text(abspath), rp)
        except SyntaxError:
            continue
        for name, info in mod.defs.items():
            registry[f"{dotted}.{name}"] = info
        for name, info in mod.assigned.items():
            if "." not in name:  # module-level simple names only
                registry[f"{dotted}.{name}"] = info
    return registry


# -------------------------------------------------------------------- JAX001


def _jax001(mod: _Module, registry: Dict[str, _JitInfo], findings: List[Finding]) -> None:
    for fn in ast.walk(mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        try:
            fn_src = ast.unparse(fn)
        except Exception:  # pragma: no cover
            fn_src = ""
        if any(tok in fn_src for tok in _GUARD_TOKENS):
            continue  # the function syncs deliberately, guard-style
        nodes = _own_nodes(fn)
        tracked: Set[str] = set()
        for node in nodes:
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                info = mod.jit_info_for_call(node.value, registry)
                if info is not None and info.jitted:
                    for tgt in node.targets:
                        tracked.update(_target_names(tgt))
        if not tracked:
            continue
        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            hit = ""
            if isinstance(f, ast.Name) and f.id == "float" and node.args:
                if _root_name(node.args[0]) in tracked:
                    hit = f"float({ast.unparse(node.args[0])})"
            elif isinstance(f, ast.Attribute) and f.attr == "item":
                if _root_name(f.value) in tracked:
                    hit = f"{ast.unparse(f.value)}.item()"
            elif (
                isinstance(f, ast.Attribute)
                and f.attr in ("asarray", "array")
                and isinstance(f.value, ast.Name)
                and f.value.id in ("np", "numpy")
                and node.args
            ):
                if _root_name(node.args[0]) in tracked:
                    hit = f"np.{f.attr}({ast.unparse(node.args[0])})"
            if hit:
                findings.append(Finding(
                    "JAX001", mod.path, node.lineno,
                    f"host sync {hit} on a jit output in a hot path — "
                    "drains the dispatch queue every step; batch the read "
                    "behind a sentinel/guard or move it off the step path",
                ))


# -------------------------------------------------------------------- JAX002


def _static_params(fn, info: _JitInfo) -> Set[str]:
    params = [a.arg for a in fn.args.args]
    static = {params[i] for i in info.static_nums if i < len(params)}
    static.update(n for n in info.static_names if n in params)
    return static


def _bare_names(expr: ast.expr) -> Set[str]:
    """Names whose VALUE the expression branches on. Exempt as static
    under trace: ``x is None`` / ``x is not None``; ``key in x``
    membership (dict/pytree KEY structure, not data); and any name under
    an Attribute/Subscript (``state.batch_stats`` truthiness probes pytree
    structure, ``x.shape``/``x.ndim`` are static metadata)."""
    out: Set[str] = set()
    skip: Set[int] = set()
    for node in ast.walk(expr):
        if isinstance(node, (ast.Attribute, ast.Subscript)):
            for sub in ast.walk(node.value):
                skip.add(id(sub))
        elif isinstance(node, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
        ):
            for sub in ast.walk(node):
                skip.add(id(sub))
        elif isinstance(node, ast.Compare) and all(
            isinstance(op, (ast.In, ast.NotIn)) for op in node.ops
        ):
            for comp in node.comparators:  # container side only
                for sub in ast.walk(comp):
                    skip.add(id(sub))
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and id(node) not in skip:
            out.add(node.id)
    return out


def _jax002(mod: _Module, findings: List[Finding]) -> None:
    checked: Set[int] = set()
    for info in list(mod.defs.values()) + list(mod.assigned.values()):
        fn = info.def_node
        if not info.jitted or fn is None or id(fn) in checked:
            continue
        checked.add(id(fn))
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        traced = {a.arg for a in fn.args.args} - _static_params(fn, info) - {"self"}
        if not traced:
            continue
        for node in _own_nodes(fn):
            bad: Set[str] = set()
            where = ""
            if isinstance(node, (ast.If, ast.While)):
                bad = _bare_names(node.test) & traced
                where = "branches on"
            elif (
                isinstance(node, ast.For)
                and isinstance(node.iter, ast.Call)
                and isinstance(node.iter.func, ast.Name)
                and node.iter.func.id == "range"
            ):
                bad = set()
                for arg in node.iter.args:
                    bad |= _bare_names(arg) & traced
                where = "sizes a range() loop with"
            if bad:
                findings.append(Finding(
                    "JAX002", mod.path, node.lineno,
                    f"jitted function {fn.name!r} {where} traced "
                    f"argument(s) {', '.join(sorted(bad))} — raises at "
                    "trace time for arrays, retraces per distinct value "
                    "for Python scalars; mark static via static_argnums/"
                    "static_argnames or branch with jnp.where",
                ))


# -------------------------------------------------------------------- JAX003


def _jax003(mod: _Module, registry: Dict[str, _JitInfo], findings: List[Finding]) -> None:
    for fn in ast.walk(mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if _decorated_jit(fn) is not None:
            # inside a jit trace the callee inlines — its donate_argnums
            # are ignored, so "reuse" there is not a donation hazard
            continue
        nodes = _own_nodes(fn)
        for node in nodes:
            if not (isinstance(node, ast.Assign) or isinstance(node, ast.Expr)):
                continue
            value = node.value
            if not isinstance(value, ast.Call):
                continue
            info = mod.jit_info_for_call(value, registry)
            if info is None or not info.donate:
                continue
            rebound: Set[str] = set()
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    rebound.update(_target_names(tgt))
            for i in info.donate:
                if i >= len(value.args) or not isinstance(value.args[i], ast.Name):
                    continue
                donated = value.args[i].id
                if donated in rebound:
                    continue  # state, loss = step(state, ...) — clean
                reuse = _first_read_after(
                    nodes, donated, getattr(node, "end_lineno", node.lineno)
                )
                if reuse is not None:
                    findings.append(Finding(
                        "JAX003", mod.path, reuse,
                        f"donated buffer {donated!r} read after being "
                        f"passed in donate_argnums position {i} at line "
                        f"{value.lineno} — XLA may alias it into the "
                        "output; rebind the result or copy before the call",
                    ))


def _first_read_after(nodes: Sequence[ast.AST], name: str, call_line: int) -> Optional[int]:
    """Line of the first Load of ``name`` after ``call_line``, unless a
    rebind (Store) intervenes."""
    events: List[Tuple[int, str]] = []
    for node in nodes:
        if isinstance(node, ast.Name) and node.id == name:
            kind = "store" if isinstance(node.ctx, (ast.Store, ast.Del)) else "load"
            events.append((node.lineno, kind))
    for line, kind in sorted(events):
        if line <= call_line:
            continue
        if kind == "store":
            return None
        return line
    return None


# -------------------------------------------------------------------- JAX004


def _jax004(mod: _Module, registry: Dict[str, _JitInfo], findings: List[Finding]) -> None:
    for fn in ast.walk(mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        nodes = _own_nodes(fn)
        # clock-var assignments and elapsed reads, in line order
        assigns: List[Tuple[int, str]] = []
        elapsed: List[Tuple[int, str]] = []
        for node in nodes:
            if (
                isinstance(node, ast.Assign)
                and _is_clock(node.value)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                assigns.append((node.lineno, node.targets[0].id))
            elif (
                isinstance(node, ast.BinOp)
                and isinstance(node.op, ast.Sub)
                and isinstance(node.right, ast.Name)
            ):
                elapsed.append((node.lineno, node.right.id))
        if not elapsed:
            continue
        # block_until_ready, plus d2h conversions — np.asarray/.item()
        # force completion, and roundtrip benches time them on purpose
        syncs = [
            n.lineno for n in nodes
            if isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and (
                n.func.attr in ("block_until_ready", "item")
                or (
                    n.func.attr in ("asarray", "array")
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id in ("np", "numpy")
                )
            )
        ]
        for end_line, var in elapsed:
            starts = [ln for ln, v in assigns if v == var and ln < end_line]
            if not starts:
                continue
            start_line = max(starts)
            window_calls = [
                n for n in nodes
                if isinstance(n, ast.Call) and start_line < n.lineno <= end_line
            ]
            device_call = None
            for call in window_calls:
                info = mod.jit_info_for_call(call, registry)
                if info is not None and info.device:
                    device_call = call
                    break
            if device_call is None:
                continue
            if any(start_line <= ln <= end_line for ln in syncs):
                continue
            try:
                callee = ast.unparse(device_call.func)
            except Exception:  # pragma: no cover
                callee = "<call>"
            findings.append(Finding(
                "JAX004", mod.path, end_line,
                f"timer window (t0 at line {start_line}) calls "
                f"device-producing {callee}() but reads the clock with no "
                "block_until_ready in the window — this measures dispatch, "
                "not execution",
            ))


def _is_clock(node: ast.expr) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr in _CLOCK_FUNCS
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id in ("time", "_time")
    )


# --------------------------------------------------------------------- scope


def _in_sync_scope(path: str) -> bool:
    p = rel(path) if os.path.isabs(path) else path
    return any(p.startswith(d + os.sep) for d in _SYNC_SCOPE_DIRS)


def _in_bench_scope(path: str) -> bool:
    p = rel(path) if os.path.isabs(path) else path
    return p in _BENCH_SCOPE_FILES or any(
        p.startswith(d + os.sep) for d in _BENCH_SCOPE_DIRS
    )


# ----------------------------------------------------------------------- API


def check_source(
    text: str, path: str,
    sync_scope: Optional[bool] = None,
    bench_scope: Optional[bool] = None,
    registry: Optional[Dict[str, _JitInfo]] = None,
) -> List[Finding]:
    """Lint one module. Scope flags default from the path (fixtures pass
    explicit True); ``registry`` carries cross-module jit facts."""
    registry = registry or {}
    findings: List[Finding] = []
    mod = _Module(text, path)
    if sync_scope if sync_scope is not None else _in_sync_scope(path):
        _jax001(mod, registry, findings)
    _jax002(mod, findings)
    _jax003(mod, registry, findings)
    if bench_scope if bench_scope is not None else _in_bench_scope(path):
        _jax004(mod, registry, findings)
    # dedupe by site (a tuple target tracked twice reports once)
    seen: Set[Tuple[str, int, str]] = set()
    out: List[Finding] = []
    for f in findings:
        k = (f.rule, f.line, f.message)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out


def check(root: str = REPO_ROOT, files: Optional[Sequence[str]] = None) -> List[Finding]:
    from persia_tpu.analysis.common import python_files

    pkg = python_files(root)
    # bench scope rides along the package scan
    extra = [
        os.path.join(root, p) for p in _BENCH_SCOPE_FILES
        if os.path.exists(os.path.join(root, p))
    ]
    bench_dirs = [os.path.join(root, d) for d in _BENCH_SCOPE_DIRS]
    for d in bench_dirs:
        if os.path.isdir(d):
            extra.extend(
                os.path.join(d, f) for f in sorted(os.listdir(d))
                if f.endswith(".py")
            )
    paths = list(files) if files is not None else pkg + extra
    registry = _registry_from(pkg, root)
    findings: List[Finding] = []
    for p in paths:
        abspath = p if os.path.isabs(p) else os.path.join(root, p)
        if (os.sep + "analysis" + os.sep) in abspath:
            continue  # the lint does not lint itself
        try:
            findings.extend(
                check_source(read_text(abspath), rel(abspath), registry=registry)
            )
        except SyntaxError:
            continue
    return findings

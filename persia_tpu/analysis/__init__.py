"""persia-lint: static correctness tooling for the hybrid training plane.

``python -m persia_tpu.analysis`` runs three passes and exits nonzero on
any finding:

- **ABI drift** (ABI000–ABI008): every ctypes binding in the repo
  cross-checked against the ``extern "C"`` surface of the five native
  libraries — arity, int-width/pointer-class agreement, missing/mismatched
  ``restype``, bindings to non-exported symbols, exports with no binding,
  untyped foreign calls. See :mod:`persia_tpu.analysis.abi`.
- **Concurrency** (CONC001–CONC004): bare ``acquire`` outside ``with``,
  permits/ring-spans not released on exception paths, blocking calls made
  under a lock, lock-order inversions against the declared registry
  (:mod:`persia_tpu.analysis.lock_order`).
- **Interprocedural concurrency** (CONC005–CONC007): a module-level call
  graph over the whole package with held-lock sets propagated through
  call edges — transitive blocking-call-under-lock, cross-function
  lock-order inversion, and locks created but absent from the ranking
  registry (:mod:`persia_tpu.analysis.interproc`).
- **JAX trace discipline** (JAX001–JAX004): host syncs on jit outputs in
  hot paths, retrace hazards from traced-argument branches, donated-buffer
  reuse after ``donate_argnums``, and benchmark timer windows that read
  the clock without ``block_until_ready``
  (:mod:`persia_tpu.analysis.jax_lint`).
- **Resilience policy** (RES001–RES005): raw sleeps, constant socket
  timeouts, ad-hoc retry loops, manual wall-clock deadlines, and
  swallow-without-metric ``except Exception`` loops in
  ``service/``+``serving/`` that bypass ``service/resilience.py`` or
  fail invisibly.
- **Durability** (DUR001): checkpoint/manifest artifacts written with a
  plain ``open(..., "w")`` (or direct ``np.savez``) instead of the
  temp + fsync + atomic-rename publish the crash-consistency layer
  (persia_tpu.jobstate / checkpoint.py) requires.
- **Observability** (OBS001–OBS002): metrics registered outside the
  ``persia_tpu_``/``persia_`` namespace, and hand-rolled
  ``t0 = time.time()`` stage timers in pipeline modules that bypass
  ``tracing.stage_span`` (:mod:`persia_tpu.analysis.observability_lint`).
- **Numerical health** (NUM001): train-plane code consuming loss/grad
  scalars on the host (``.item()``, ``float(...)``, ``np.asarray``)
  with no finite guard in the function — a blind spot in the health
  escalation ladder (:mod:`persia_tpu.analysis.numeric_lint`).
- **Control loops** (CTRL001–CTRL002): a loop mutating fleet topology
  (``reshard_ps`` / ``swap_topology`` / replica add-remove) with no
  hysteresis/dwell guard on the decision path — an unguarded control
  loop is a flap machine — and any direct topology actuation from
  control-plane code that bypasses the arbiter's single actuation lease
  (:mod:`persia_tpu.analysis.control_lint`).
- **Protocol verification** (PROTO001–PROTO007): the journaled two-phase
  state machines extracted statically — interprocedural raw-write of
  checkpoint artifacts, journal ids minted outside the registered
  constructors (plus an exact bitmask prover of pairwise namespace
  disjointness), committed phases with no resume() re-entry arm,
  journal_record sites with no journal_probe on their path, topology
  mutators reachable outside a drained-fence context, crash
  transitions missing from ``PROTO_COVERAGE.json``, and abort arms
  (journaled preemption) not wired into the crash matrices or never
  killed (:mod:`persia_tpu.analysis.protocol` +
  :mod:`persia_tpu.analysis.crashcheck`).

Suppress a finding inline with ``# persia-lint: disable=RULE`` (or
``disable=all``) on the offending line; C sources use the same token in a
``//`` comment. Pure stdlib — no jax, numpy, or toolchain required.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from persia_tpu.analysis.common import (
    BINDING_FILES,
    CTYPES_FILES,
    NATIVE_LIBS,
    REPO_ROOT,
    Finding,
    apply_suppressions,
    python_files,
    read_text,
    rel,
)

__all__ = [
    "Finding",
    "run_all",
    "BINDING_FILES",
    "CTYPES_FILES",
    "NATIVE_LIBS",
]

_PASS_PREFIXES = ("ABI", "CONC", "RES", "DUR", "OBS", "NUM", "JAX", "CTRL",
                  "PROTO")


def run_all(
    root: str = REPO_ROOT, rules: Optional[Sequence[str]] = None,
) -> Tuple[List[Finding], Dict[str, object]]:
    """Run every pass over the repo. Returns (findings after suppression,
    coverage report). ``rules`` filters by rule-id prefix (e.g. ["ABI"])."""
    from persia_tpu.analysis import (
        abi,
        concurrency,
        control_lint,
        durability,
        interproc,
        jax_lint,
        numeric_lint,
        observability_lint,
        protocol,
        resilience_lint,
    )

    wanted = tuple(r.upper() for r in rules) if rules else _PASS_PREFIXES
    findings: List[Finding] = []
    coverage: Dict[str, object] = {}

    if any(w.startswith("ABI") for w in wanted):
        abi_findings, abi_cov = abi.check(root)
        findings.extend(abi_findings)
        coverage["abi"] = abi_cov
    py_files = python_files(root)
    if any(w.startswith("CONC") for w in wanted):
        findings.extend(concurrency.check(root, py_files))
        ip_findings, ip_cov = interproc.check(root, py_files)
        findings.extend(ip_findings)
        coverage["callgraph"] = ip_cov
    if any(w.startswith("JAX") for w in wanted):
        findings.extend(jax_lint.check(root))
    if any(w.startswith("RES") for w in wanted):
        findings.extend(resilience_lint.check(root))
    if any(w.startswith("DUR") for w in wanted):
        findings.extend(durability.check(root, py_files))
    if any(w.startswith("OBS") for w in wanted):
        findings.extend(observability_lint.check(root, py_files))
    if any(w.startswith("NUM") for w in wanted):
        findings.extend(numeric_lint.check(root, py_files))
    if any(w.startswith("CTRL") for w in wanted):
        findings.extend(control_lint.check(root, py_files))
    if any(w.startswith("PROTO") for w in wanted):
        p_findings, p_cov = protocol.check(root, py_files)
        findings.extend(p_findings)
        coverage["protocol"] = p_cov
    coverage["python_files_scanned"] = len(py_files)
    coverage["ctypes_files"] = [p for p in CTYPES_FILES
                                if any(rel(f) == p for f in py_files)]

    # rule-id filter (exact ids also allowed, e.g. --rules RES001)
    findings = [
        f for f in findings
        if any(f.rule.startswith(w) or f.rule == w for w in wanted)
    ]

    texts: Dict[str, str] = {}
    for f in findings:
        if f.path not in texts:
            import os

            abspath = f.path if os.path.isabs(f.path) else os.path.join(root, f.path)
            try:
                texts[f.path] = read_text(abspath)
            except OSError:
                texts[f.path] = ""
    findings = apply_suppressions(findings, texts)
    # stable RULE-sorted order: the --json output is diffed against a
    # committed baseline in CI, and rule-major ordering keeps a new file
    # from reshuffling every other rule's block of the diff
    findings.sort(key=lambda f: (f.rule, f.path, f.line))
    return findings, coverage

"""CLI: ``python -m persia_tpu.analysis`` — exit nonzero on findings."""

from __future__ import annotations

import argparse
import json
import sys

from persia_tpu.analysis import run_all
from persia_tpu.analysis.common import BINDING_FILES, NATIVE_LIBS, REPO_ROOT

_RULE_DOC = {
    "ABI000": "native source unparseable / registry broken (coverage lost)",
    "ABI001": "ctypes argtypes arity differs from the C parameter list",
    "ABI002": "ctypes argument type mismatch (width / kind / pointer class)",
    "ABI003": "missing restype (c_int default truncates 64-bit/pointer returns)",
    "ABI004": "declared restype disagrees with the C return type",
    "ABI005": "binding targets a symbol the library does not export",
    "ABI006": "exported symbol with no ctypes binding anywhere",
    "ABI007": "bound symbol never declares argtypes",
    "ABI008": "call through a CDLL handle with no argtypes in that file",
    "CONC001": "lock acquired with bare .acquire() instead of `with`",
    "CONC002": "permit/ring-span not released on the exception path",
    "CONC003": "blocking call (sleep/socket/native) while holding a lock",
    "CONC004": "lock-order inversion vs analysis/lock_order.py registry",
    "RES001": "constant time.sleep bypassing resilience.RetryPolicy",
    "RES002": "constant socket timeout bypassing resilience.Deadline.cap",
    "RES003": "ad-hoc retry loop outside resilience (swallow+sleep)",
    "RES004": "manual wall-clock deadline instead of resilience.Deadline",
    "DUR001": "checkpoint/manifest artifact written without temp+fsync+rename",
    "OBS001": "metric registered outside the persia_tpu_/persia_ namespace",
    "OBS002": "hand-rolled stage timer bypassing tracing.stage_span",
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m persia_tpu.analysis",
        description="persia-lint: ABI drift + concurrency + resilience checks",
    )
    ap.add_argument("--rules", help="comma-separated rule ids or prefixes "
                    "(e.g. ABI or RES001); default: all")
    ap.add_argument("--root", default=REPO_ROOT, help="repo root to scan")
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, doc in _RULE_DOC.items():
            print(f"{rid}  {doc}")
        return 0

    rules = [r.strip() for r in args.rules.split(",")] if args.rules else None
    findings, coverage = run_all(args.root, rules)

    if args.json:
        print(json.dumps({
            "findings": [f.__dict__ for f in findings],
            "coverage": coverage,
        }, indent=2))
    else:
        for f in findings:
            print(f.format())
        from persia_tpu.analysis.common import CTYPES_FILES

        abi_cov = coverage.get("abi", {})
        lib_counts = abi_cov.get("libs", {}) if isinstance(abi_cov, dict) else {}
        print(
            f"persia-lint: {len(findings)} finding(s); "
            f"{len(lib_counts)}/{len(NATIVE_LIBS)} native libs "
            f"({sum(lib_counts.values())} exports), "
            f"{len(abi_cov.get('binding_files', [])) if isinstance(abi_cov, dict) else 0}"
            f"/{len(BINDING_FILES)} binding files, "
            f"{len(coverage.get('ctypes_files', []))}/{len(CTYPES_FILES)} "
            f"ctypes files, "
            f"{coverage.get('python_files_scanned', 0)} python files scanned"
        )
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())

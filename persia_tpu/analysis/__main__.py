"""CLI: ``python -m persia_tpu.analysis`` — the persia-verify entry point.

Exit contract (what CI and ``round_preflight.sh`` rely on):

- **0**: no findings survived suppression (with ``--baseline``: no finding
  absent from the baseline).
- **1**: at least one (new) finding. Findings are printed in stable
  rule-sorted order — ``(rule, path, line)`` — so two runs over the same
  tree diff cleanly.
- **2**: argparse usage errors (argparse's own convention).

``--json`` emits ``{"findings": [{rule, path, line, message}...],
"coverage": {...}}`` with the same ordering, for machine diffing.
``--write-baseline FILE`` records the current findings;
``--baseline FILE`` fails only on findings NOT in that record, so a
legacy finding can be grandfathered without an inline suppression while
still gating new ones. Baselines match on (rule, path, message) — line
numbers drift with unrelated edits; regenerate with ``--write-baseline``
when a recorded finding moves enough that its message changes.
"""

from __future__ import annotations

import argparse
import json
import sys

from persia_tpu.analysis import run_all
from persia_tpu.analysis.common import BINDING_FILES, NATIVE_LIBS, REPO_ROOT

_RULE_DOC = {
    "ABI000": "native source unparseable / registry broken (coverage lost)",
    "ABI001": "ctypes argtypes arity differs from the C parameter list",
    "ABI002": "ctypes argument type mismatch (width / kind / pointer class)",
    "ABI003": "missing restype (c_int default truncates 64-bit/pointer returns)",
    "ABI004": "declared restype disagrees with the C return type",
    "ABI005": "binding targets a symbol the library does not export",
    "ABI006": "exported symbol with no ctypes binding anywhere",
    "ABI007": "bound symbol never declares argtypes",
    "ABI008": "call through a CDLL handle with no argtypes in that file",
    "CONC001": "lock acquired with bare .acquire() instead of `with`",
    "CONC002": "permit/ring-span not released on the exception path",
    "CONC003": "blocking call (sleep/socket/native) while holding a lock",
    "CONC004": "lock-order inversion vs analysis/lock_order.py registry",
    "CONC005": "transitive blocking call under a lock through the call graph",
    "CONC006": "cross-function lock-order inversion (callee acquires outer lock)",
    "CONC007": "lock created but absent from the lock_order.py ranking registry",
    "RES001": "constant time.sleep bypassing resilience.RetryPolicy",
    "RES002": "constant socket timeout bypassing resilience.Deadline.cap",
    "RES003": "ad-hoc retry loop outside resilience (swallow+sleep)",
    "RES004": "manual wall-clock deadline instead of resilience.Deadline",
    "DUR001": "checkpoint/manifest artifact written without temp+fsync+rename",
    "OBS001": "metric registered outside the persia_tpu_/persia_ namespace",
    "OBS002": "hand-rolled stage timer bypassing tracing.stage_span",
    "NUM001": "host consumption of loss/grad scalars with no finite guard",
    "JAX001": "host sync on jit output in a hot path without a guard rationale",
    "JAX002": "branch on a traced argument inside jit (retrace/ConcretizationError)",
    "JAX003": "donated buffer read after the donating call",
    "JAX004": "benchmark timer window reads the clock without block_until_ready",
    "PROTO001": "manifest/pointer artifact written raw through an interprocedural helper",
    "PROTO002": "raw-minted journal id at a sink, or id-family namespace overlap",
    "PROTO003": "committed phase value no resume arm ever compares against",
    "PROTO004": "journal_record with no journal_probe on the apply path",
    "PROTO005": "topology mutator reachable outside a drained-fence/resume context",
    "PROTO006": "PROTO_COVERAGE.json missing/stale vs extracted crash transitions",
}


def _baseline_key(f) -> tuple:
    # (rule, path, message) — deliberately NOT line: unrelated edits shift
    # line numbers and would un-grandfather every recorded finding below them
    return (f["rule"], f["path"], f["message"]) if isinstance(f, dict) \
        else (f.rule, f.path, f.message)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m persia_tpu.analysis",
        description="persia-verify: ABI drift + (interprocedural) concurrency "
        "+ JAX trace-discipline + resilience checks",
        epilog="exit status: 0 = clean (with --baseline: no NEW finding), "
        "1 = findings, 2 = usage error. Output is stable rule-sorted "
        "(rule, path, line) so runs diff cleanly.",
    )
    ap.add_argument("--rules", help="comma-separated rule ids or prefixes "
                    "(e.g. ABI or RES001); default: all")
    ap.add_argument("--root", default=REPO_ROOT, help="repo root to scan")
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--baseline", metavar="FILE",
                    help="JSON findings file (from --write-baseline); exit "
                    "nonzero only on findings not recorded there")
    ap.add_argument("--write-baseline", metavar="FILE",
                    help="record current findings to FILE and exit 0")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, doc in _RULE_DOC.items():
            print(f"{rid}  {doc}")
        return 0

    rules = [r.strip() for r in args.rules.split(",")] if args.rules else None
    findings, coverage = run_all(args.root, rules)

    if args.write_baseline:
        with open(args.write_baseline, "w") as fh:
            json.dump({"findings": [f.__dict__ for f in findings]}, fh, indent=2)
            fh.write("\n")
        print(f"persia-lint: baseline written "
              f"({len(findings)} finding(s)) -> {args.write_baseline}")
        return 0

    if args.baseline:
        with open(args.baseline) as fh:
            recorded = {_baseline_key(f)
                        for f in json.load(fh).get("findings", [])}
        new = [f for f in findings if _baseline_key(f) not in recorded]
        grandfathered = len(findings) - len(new)
        findings = new
        if grandfathered:
            print(f"persia-lint: {grandfathered} baseline finding(s) "
                  f"grandfathered ({args.baseline})", file=sys.stderr)

    if args.json:
        print(json.dumps({
            "findings": [f.__dict__ for f in findings],
            "coverage": coverage,
        }, indent=2))
    else:
        for f in findings:
            print(f.format())
        from persia_tpu.analysis.common import CTYPES_FILES

        abi_cov = coverage.get("abi", {})
        lib_counts = abi_cov.get("libs", {}) if isinstance(abi_cov, dict) else {}
        print(
            f"persia-lint: {len(findings)} finding(s); "
            f"{len(lib_counts)}/{len(NATIVE_LIBS)} native libs "
            f"({sum(lib_counts.values())} exports), "
            f"{len(abi_cov.get('binding_files', [])) if isinstance(abi_cov, dict) else 0}"
            f"/{len(BINDING_FILES)} binding files, "
            f"{len(coverage.get('ctypes_files', []))}/{len(CTYPES_FILES)} "
            f"ctypes files, "
            f"{coverage.get('python_files_scanned', 0)} python files scanned"
        )
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())

"""ABI drift checker: ctypes bindings vs the native ``extern "C"`` surface.

The hybrid protocol's correctness rests on hand-maintained ctypes
signatures: a drifted ``argtypes`` reads the wrong registers, a missing
``restype`` silently defaults to ``c_int`` and truncates 64-bit returns
(pointers become garbage handles on the NEXT call, not this one), and a
binding to a renamed symbol only explodes at call time on whatever host
first takes that code path. This pass cross-checks every binding without
importing the bound modules (no jax, no .so load, no toolchain):

- the C side comes from :mod:`persia_tpu.analysis.cparse` over each lib's
  sources (registry: ``common.NATIVE_LIBS``);
- the Python side comes from an AST walk that tracks ``ctypes.CDLL``
  handles, resolves the ``_SO``/``_SRC`` module constants to a lib, builds
  a symbolic ctypes-type environment (including tuple assigns like
  ``u64, u32 = ctypes.c_uint64, ctypes.c_uint32`` and ``POINTER`` /
  ``CFUNCTYPE`` aliases), and records every ``lib.sym.argtypes`` /
  ``lib.sym.restype`` assignment and every ``lib.sym(...)`` call site.

Rules:

- ABI001 arity mismatch between argtypes and the C parameter list
- ABI002 argument type mismatch (int width / float-vs-int / pointer class)
- ABI003 missing restype (c_int default: truncates 64-bit/pointer returns;
         void functions must declare ``restype = None`` so a later C-side
         return-type change cannot hide behind the default)
- ABI004 declared restype disagrees with the C return type
- ABI005 binding targets a symbol the library does not export
- ABI006 exported symbol with no ctypes binding anywhere
- ABI007 bound symbol never declares argtypes (declare ``[]`` for
         zero-argument functions)
- ABI008 call through a CDLL handle to a symbol with no argtypes in that
         file (untyped foreign call — every argument silently becomes the
         ctypes default conversion)
- ABI009 a persia_tpu/ file calls ctypes.CDLL but is absent from the
         ``common.CTYPES_FILES`` registry — a binding surface the drift
         checker silently skips (registry completeness)
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from persia_tpu.analysis import cparse
from persia_tpu.analysis.common import (
    BINDING_FILES,
    CTYPES_FILES,
    NATIVE_LIBS,
    REPO_ROOT,
    Finding,
    ctypes_loader_files,
    read_text,
    rel,
)

TypeDesc = cparse.TypeDesc

# ctypes primitive name -> canonical descriptor
_CTYPES_MAP: Dict[str, TypeDesc] = {
    "c_void_p": ("ptr", ("void",)),
    "c_char_p": ("ptr", ("int", 8, True)),
    "c_bool": ("int", 8, False),
    "c_int8": ("int", 8, True),
    "c_uint8": ("int", 8, False),
    "c_byte": ("int", 8, True),
    "c_ubyte": ("int", 8, False),
    "c_char": ("int", 8, True),
    "c_int16": ("int", 16, True),
    "c_uint16": ("int", 16, False),
    "c_short": ("int", 16, True),
    "c_ushort": ("int", 16, False),
    "c_int": ("int", 32, True),
    "c_uint": ("int", 32, False),
    "c_int32": ("int", 32, True),
    "c_uint32": ("int", 32, False),
    "c_long": ("int", 64, True),
    "c_ulong": ("int", 64, False),
    "c_int64": ("int", 64, True),
    "c_uint64": ("int", 64, False),
    "c_longlong": ("int", 64, True),
    "c_ulonglong": ("int", 64, False),
    "c_size_t": ("int", 64, False),
    "c_ssize_t": ("int", 64, True),
    "c_float": ("float", 32),
    "c_double": ("float", 64),
}


@dataclass
class Binding:
    symbol: str
    lib: str  # lib key (e.g. "libpersia_ps.so")
    path: str  # repo-relative binding file
    restype: Optional[TypeDesc] = None  # ("void",) means explicit None
    restype_line: int = 0
    argtypes: Optional[List[TypeDesc]] = None
    argtypes_computed: bool = False  # non-literal argtypes expr (flagged)
    argtypes_line: int = 0
    first_line: int = 0


@dataclass
class FileScan:
    path: str
    libs: Set[str] = field(default_factory=set)
    bindings: Dict[Tuple[str, str], Binding] = field(default_factory=dict)
    foreign_declared: Set[str] = field(default_factory=set)  # typed syms on
    # non-registry handles (libc etc.) — exempt from ABI008, not cross-checked
    findings: List[Finding] = field(default_factory=list)


class _TypeEnv:
    """Best-effort symbolic evaluation of ctypes type expressions."""

    def __init__(self):
        self.names: Dict[str, TypeDesc] = {}

    def assign(self, target: ast.expr, value: ast.expr) -> None:
        if isinstance(target, ast.Name):
            desc = self.eval(value)
            if desc is not None:
                self.names[target.id] = desc
        elif isinstance(target, ast.Tuple) and isinstance(value, ast.Tuple):
            if len(target.elts) == len(value.elts):
                for t, v in zip(target.elts, value.elts):
                    self.assign(t, v)

    def eval(self, node: ast.expr) -> Optional[TypeDesc]:
        if isinstance(node, ast.Constant) and node.value is None:
            return ("void",)
        if isinstance(node, ast.Name):
            if node.id in self.names:
                return self.names[node.id]
            return _CTYPES_MAP.get(node.id)
        if isinstance(node, ast.Attribute):
            return _CTYPES_MAP.get(node.attr)
        if isinstance(node, ast.Call):
            fname = _call_name(node)
            if fname == "POINTER" and node.args:
                inner = self.eval(node.args[0])
                return ("ptr", inner if inner is not None else ("void",))
            if fname in ("CFUNCTYPE", "PYFUNCTYPE", "WINFUNCTYPE"):
                return ("funcptr",)
        return None


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _expr_str_value(node: ast.expr, consts: Dict[str, object]):
    """Resolve a string-ish expression: literal, Name of a tracked module
    constant, os.path.join(...) (last string component wins), list of the
    above."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    if isinstance(node, ast.Call) and _call_name(node) == "join":
        parts = [_expr_str_value(a, consts) for a in node.args]
        strs = [p for p in parts if isinstance(p, str)]
        return strs[-1] if strs else None
    if isinstance(node, (ast.List, ast.Tuple)):
        return [_expr_str_value(e, consts) for e in node.elts]
    return None


def _basename_lib(value, known: Optional[Dict[str, List[str]]] = None) -> Optional[str]:
    """Map a resolved _SO-ish string to a registry lib key."""
    if not isinstance(value, str):
        return None
    base = os.path.basename(value)
    return base if base in (NATIVE_LIBS if known is None else known) else None


class _BindingVisitor(ast.NodeVisitor):
    """One pass over a binding file: CDLL handle tracking + binding
    assignment extraction + untyped-call detection (ABI008)."""

    def __init__(self, path: str, known_libs: Optional[Dict[str, List[str]]] = None):
        self.path = path
        self.env = _TypeEnv()
        self.consts: Dict[str, object] = {}
        self.handles: Dict[str, Optional[str]] = {}  # var name -> lib key (None = foreign/libc)
        self.known_libs = known_libs
        self.scan = FileScan(path=path)
        self.calls: List[Tuple[str, str, int]] = []  # (handle var, symbol, line)

    # -- assignments ------------------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        value = node.value
        for target in node.targets:
            # lib = ctypes.CDLL(...)
            if (
                isinstance(target, ast.Name)
                and isinstance(value, ast.Call)
                and _call_name(value) == "CDLL"
            ):
                libkey = None
                explicit_foreign = False
                if value.args:
                    arg = value.args[0]
                    if isinstance(arg, ast.Constant) and arg.value is None:
                        explicit_foreign = True  # CDLL(None) == libc
                    resolved = _expr_str_value(arg, self.consts)
                    libkey = _basename_lib(resolved, self.known_libs)
                if libkey is None and not explicit_foreign:
                    # the loaders CDLL the path build_so() RETURNS (so the
                    # sanitizer variant takes effect); the argument is then
                    # a local var the tracker cannot evaluate. Fall back to
                    # the file's unique known-lib module constant (_SO).
                    libs = {
                        bk
                        for v in self.consts.values()
                        if (bk := _basename_lib(v, self.known_libs)) is not None
                    }
                    if len(libs) == 1:
                        libkey = libs.pop()
                self.handles[target.id] = libkey
            # module-ish constants (also picked up inside functions: the
            # loader files assign _SO at module level, tests may not)
            elif isinstance(target, ast.Name):
                resolved = _expr_str_value(value, self.consts)
                if resolved is None and isinstance(value, ast.Call):
                    # so_path = build_so(_SRCS, _SO, ...): the build returns
                    # a (possibly variant-suffixed) path to the lib named in
                    # its arguments — propagate that lib through the var
                    for a in value.args:
                        cand = _basename_lib(_expr_str_value(a, self.consts), self.known_libs)
                        if cand is not None:
                            resolved = cand
                            break
                if resolved is not None:
                    self.consts[target.id] = resolved
                self.env.assign(target, value)
            elif isinstance(target, ast.Tuple):
                self.env.assign(target, value)
            # lib.sym.restype / lib.sym.argtypes
            if (
                isinstance(target, ast.Attribute)
                and target.attr in ("restype", "argtypes")
                and isinstance(target.value, ast.Attribute)
                and isinstance(target.value.value, ast.Name)
            ):
                handle = target.value.value.id
                if handle not in self.handles:
                    continue
                libkey = self.handles[handle]
                symbol = target.value.attr
                if libkey is None:
                    # foreign lib (libc etc.): typing it satisfies ABI008,
                    # but there is no C surface to cross-check against
                    self.scan.foreign_declared.add(symbol)
                    continue
                b = self.scan.bindings.setdefault(
                    (libkey, symbol),
                    Binding(symbol=symbol, lib=libkey, path=self.path,
                            first_line=node.lineno),
                )
                if target.attr == "restype":
                    desc = self.env.eval(value)
                    b.restype = desc if desc is not None else ("opaque", ast.dump(value)[:40])
                    b.restype_line = node.lineno
                else:
                    if isinstance(value, (ast.List, ast.Tuple)):
                        descs: List[TypeDesc] = []
                        for elt in value.elts:
                            d = self.env.eval(elt)
                            descs.append(d if d is not None else ("opaque", ast.unparse(elt)[:40]))
                        b.argtypes = descs
                    else:
                        b.argtypes_computed = True  # arity unverifiable
                        self.scan.findings.append(Finding(
                            "ABI002", self.path, node.lineno,
                            f"argtypes for {symbol} is not a literal list — "
                            "the checker (and the reader) cannot verify it",
                        ))
                    b.argtypes_line = node.lineno
        self.generic_visit(node)

    # -- calls ------------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id in self.handles
        ):
            self.calls.append((f.value.id, f.attr, node.lineno))
        self.generic_visit(node)


def _int_compatible(py: TypeDesc, c: TypeDesc) -> bool:
    # width must agree; signedness is ABI-neutral on every supported target
    return py[1] == c[1]


def _ptr_compatible(py: TypeDesc, c: TypeDesc) -> bool:
    pin, cin = py[1], c[1]
    if pin == ("void",) or cin == ("void",):
        return True  # void* matches any object pointer
    if pin[0] == "ptr" or cin[0] == "ptr":
        # pointer-to-pointer: both sides must be pointers (inner void matches)
        return pin[0] == cin[0] or pin == ("void",) or cin == ("void",)
    if pin[0] == "opaque" or cin[0] == "opaque":
        return True
    if pin[0] == "int" and cin[0] == "int":
        return pin[1] == cin[1]
    return pin == cin


def _compatible(py: TypeDesc, c: TypeDesc) -> bool:
    if py[0] == "opaque" or c[0] == "opaque":
        return True  # lenient: surfaced via parse warnings, not per-arg noise
    if py[0] == "funcptr":
        return c[0] in ("funcptr", "ptr")
    if c[0] == "funcptr":
        return py[0] in ("funcptr", "ptr") or py == ("ptr", ("void",))
    if py[0] == "ptr" and c[0] == "ptr":
        return _ptr_compatible(py, c)
    if py[0] == "int" and c[0] == "int":
        return _int_compatible(py, c)
    return py[0] == c[0] and py[1:2] == c[1:2]


def load_native_surface(
    root: str = REPO_ROOT, libs: Optional[Dict[str, List[str]]] = None,
) -> Tuple[Dict[str, Dict[str, cparse.CFunc]], List[Finding]]:
    """Parse every registered lib's sources. Returns
    ({lib: {symbol: CFunc}}, findings-for-parse-problems)."""
    libs = NATIVE_LIBS if libs is None else libs
    surface: Dict[str, Dict[str, cparse.CFunc]] = {}
    findings: List[Finding] = []
    parsed_cache: Dict[str, Tuple[List[cparse.CFunc], List[str]]] = {}
    for lib, sources in libs.items():
        exports: Dict[str, cparse.CFunc] = {}
        for src in sources:
            path = os.path.join(root, src)
            if src not in parsed_cache:
                if not os.path.exists(path):
                    findings.append(Finding(
                        "ABI000", src, 1, "registered native source is missing"))
                    parsed_cache[src] = ([], [])
                else:
                    parsed_cache[src] = cparse.parse_extern_c(read_text(path), src)
            funcs, warns = parsed_cache[src]
            for w in warns:
                wpath, _, rest = w.partition(":")
                lineno = 1
                msg = rest
                head, _, tail = rest.partition(":")
                if head.strip().isdigit():
                    lineno, msg = int(head), tail.strip()
                findings.append(Finding("ABI000", wpath, lineno, msg.strip()))
            for fn in funcs:
                prev = exports.get(fn.name)
                if prev is not None and (prev.ret, prev.params) != (fn.ret, fn.params):
                    findings.append(Finding(
                        "ABI000", fn.path, fn.line,
                        f"{fn.name} declared with a different signature in "
                        f"{prev.path}:{prev.line} (same library {lib})",
                    ))
                exports.setdefault(fn.name, fn)
        if not exports:
            findings.append(Finding(
                "ABI000", sources[0] if sources else lib, 1,
                f"{lib}: parsed zero extern \"C\" exports — coverage lost"))
        surface[lib] = exports
    return surface, findings


def scan_binding_file(
    path: str, known_libs: Optional[Dict[str, List[str]]] = None,
) -> Tuple[FileScan, List[Tuple[str, str, int]]]:
    abspath = path if os.path.isabs(path) else os.path.join(REPO_ROOT, path)
    text = read_text(abspath)
    tree = ast.parse(text, filename=path)
    visitor = _BindingVisitor(rel(abspath), known_libs)
    visitor.visit(tree)
    visitor.scan.libs = {lk for lk in visitor.handles.values() if lk}
    return visitor.scan, visitor.calls


def check(
    root: str = REPO_ROOT,
    binding_files: Optional[Sequence[str]] = None,
    libs: Optional[Dict[str, List[str]]] = None,
) -> Tuple[List[Finding], Dict[str, object]]:
    """Run the full ABI cross-check. Returns (findings, coverage report)."""
    binding_files = list(BINDING_FILES if binding_files is None else binding_files)
    surface, findings = load_native_surface(root, libs)

    scans: List[FileScan] = []
    all_calls: Dict[str, List[Tuple[str, str, int]]] = {}
    for bf in binding_files:
        abspath = bf if os.path.isabs(bf) else os.path.join(root, bf)
        if not os.path.exists(abspath):
            findings.append(Finding("ABI000", bf, 1, "registered binding file is missing"))
            continue
        scan, calls = scan_binding_file(abspath, libs)
        scans.append(scan)
        all_calls[scan.path] = calls
        findings.extend(scan.findings)

    bound_symbols: Set[str] = set()
    for scan in scans:
        for (libkey, symbol), b in sorted(scan.bindings.items()):
            exports = surface.get(libkey, {})
            fn = exports.get(symbol)
            anchor = b.argtypes_line or b.restype_line or b.first_line
            if fn is None:
                findings.append(Finding(
                    "ABI005", scan.path, anchor,
                    f"{symbol} is not exported by {libkey} "
                    f"(sources: {', '.join((libs or NATIVE_LIBS)[libkey])})",
                ))
                continue
            bound_symbols.add(symbol)
            # restype
            if b.restype is None:
                want = cparse.describe(fn.ret)
                hazard = (
                    "truncates the 64-bit return to c_int"
                    if fn.ret[0] == "ptr" or (fn.ret[0] == "int" and fn.ret[1] == 64)
                    else "defaults to c_int"
                    if fn.ret != ("void",)
                    else "declare restype = None so a future C return-type "
                    "change cannot hide behind the c_int default"
                )
                findings.append(Finding(
                    "ABI003", scan.path, anchor,
                    f"{symbol}: missing restype — C returns {want}; {hazard}",
                ))
            elif fn.ret == ("void",):
                if b.restype != ("void",):
                    findings.append(Finding(
                        "ABI004", scan.path, b.restype_line or anchor,
                        f"{symbol}: restype {cparse.describe(b.restype)} but C "
                        "returns void (use restype = None)",
                    ))
            elif b.restype == ("void",) or not _compatible(b.restype, fn.ret):
                findings.append(Finding(
                    "ABI004", scan.path, b.restype_line or anchor,
                    f"{symbol}: restype {cparse.describe(b.restype)} but C "
                    f"returns {cparse.describe(fn.ret)}",
                ))
            # argtypes
            if b.argtypes is None:
                if not b.argtypes_computed:  # computed → already ABI002
                    findings.append(Finding(
                        "ABI007", scan.path, anchor,
                        f"{symbol}: no argtypes declared (C takes "
                        f"{len(fn.params)} args — declare [] if zero)",
                    ))
                continue
            if len(b.argtypes) != len(fn.params):
                findings.append(Finding(
                    "ABI001", scan.path, b.argtypes_line or anchor,
                    f"{symbol}: argtypes has {len(b.argtypes)} entries but C "
                    f"takes {len(fn.params)}",
                ))
            else:
                for i, (py, c) in enumerate(zip(b.argtypes, fn.params)):
                    if not _compatible(py, c):
                        findings.append(Finding(
                            "ABI002", scan.path, b.argtypes_line or anchor,
                            f"{symbol}: arg {i} is {cparse.describe(py)} but C "
                            f"takes {cparse.describe(c)}",
                        ))

    # ABI006: exported but never bound anywhere
    for libkey in sorted(surface):
        for symbol, fn in sorted(surface[libkey].items()):
            if symbol not in bound_symbols:
                findings.append(Finding(
                    "ABI006", fn.path, fn.line,
                    f"{symbol} is exported by {libkey} but has no ctypes "
                    "binding in any registered binding file",
                ))

    # ABI009: registry completeness — every CDLL loader under persia_tpu/
    # must be listed in CTYPES_FILES (the superset containing BINDING_FILES),
    # else its bindings never reach this cross-check. Only enforced against
    # the real registry: fixture-driven tests pass a custom binding_files
    # list whose synthetic trees have no registry to be complete against.
    if binding_files == list(BINDING_FILES) and libs is None:
        registered = set(CTYPES_FILES)
        for loader in ctypes_loader_files(root):
            if loader not in registered:
                findings.append(Finding(
                    "ABI009", loader, 1,
                    "file calls ctypes.CDLL but is not registered in "
                    "common.CTYPES_FILES — the ABI drift checker is "
                    "silently skipping its bindings",
                ))

    # ABI008: untyped calls through a CDLL handle
    for scan in scans:
        declared = {sym for (_lk, sym) in scan.bindings} | scan.foreign_declared
        for handle, symbol, line in all_calls.get(scan.path, ()):
            if symbol in declared or symbol in ("restype", "argtypes"):
                continue
            findings.append(Finding(
                "ABI008", scan.path, line,
                f"call to {symbol} through CDLL handle {handle!r} with no "
                "argtypes/restype declared in this file (untyped foreign call)",
            ))

    coverage = {
        "libs": {lk: len(surface.get(lk, {})) for lk in (libs or NATIVE_LIBS)},
        "binding_files": [s.path for s in scans],
        "bindings": sum(len(s.bindings) for s in scans),
    }
    return findings, coverage

"""Crash-point registry: exhaustive SIGKILL-schedule enumeration in-process.

The journaled two-phase protocols (jobstate fences, elastic reshard
phases, autopilot drives, healer decisions, scrub records) all promise
"SIGKILL anywhere resumes bit-identical" — but until PR 19 that promise
was pinned by a handful of hand-seeded ``fault_hook`` kill points. This
module closes the gap between the static protocol model
(:mod:`persia_tpu.analysis.protocol`) and the chaos suite:

- Production protocol code marks every manifest-commit and journal-record
  boundary with :func:`reach` — a module-level no-op (one dict read) when
  disarmed, so the hooks cost nothing on the hot path and need no test
  plumbing threaded through call signatures.
- A test records one uninterrupted protocol run under :func:`recording`
  to enumerate the ordered ``(site, occurrence)`` crash points it passes.
- For every enumerated point, the test re-runs the protocol fresh under
  :func:`crash_at`, which raises :class:`SimulatedCrash` exactly there
  (and disarms itself, so the resume path runs clean), then asserts the
  resumed end state equals the uninterrupted run's.
- :class:`Coverage` accumulates kills per site across matrices and
  serializes ``PROTO_COVERAGE.json``; :func:`validate_coverage` diffs it
  against the statically extracted site set, so a protocol arm added
  without a kill schedule is a lint finding (PROTO006), not a silent gap.

``SimulatedCrash`` derives from ``BaseException`` on purpose: a protocol
that swallows it behind ``except Exception`` would be hiding a window
where a real SIGKILL loses state, and the matrix must see that as a
failure, not a pass. Pure stdlib — importable from jobstate/elastic
without cycles or heavyweight deps.
"""

from __future__ import annotations

import json
from contextlib import contextmanager
from typing import Dict, Iterable, List, Optional, Tuple


class SimulatedCrash(BaseException):
    """Raised at an armed crash point. BaseException so production
    ``except Exception`` recovery paths cannot absorb the simulated kill."""


class _State:
    __slots__ = ("mode", "sites", "target", "counts")

    def __init__(self) -> None:
        self.mode: Optional[str] = None  # None | "record" | "crash"
        self.sites: List[str] = []
        self.target: Optional[Tuple[str, int]] = None
        self.counts: Dict[str, int] = {}


_STATE = _State()


def reach(site: str) -> None:
    """Mark a protocol transition boundary. Disarmed (the default, and
    always in production) this is a single attribute read."""
    mode = _STATE.mode
    if mode is None:
        return
    if mode == "record":
        _STATE.sites.append(site)
        return
    occ = _STATE.counts.get(site, 0)
    _STATE.counts[site] = occ + 1
    if (site, occ) == _STATE.target:
        _STATE.mode = None  # disarm: the resume path must run uninterrupted
        raise SimulatedCrash(f"simulated kill at {site}#{occ}")


def disarm() -> None:
    _STATE.mode = None
    _STATE.target = None
    _STATE.sites = []
    _STATE.counts = {}


@contextmanager
def recording():
    """Collect the ordered crash points one uninterrupted run passes.
    Yields the live list (ordered, with repeats — occurrence numbering is
    derived by :func:`enumerate_points`)."""
    disarm()
    _STATE.mode = "record"
    try:
        yield _STATE.sites
    finally:
        _STATE.mode = None


@contextmanager
def crash_at(site: str, occurrence: int = 0):
    """Arm one crash point: the ``occurrence``-th time ``site`` is reached,
    :class:`SimulatedCrash` raises and the registry disarms itself."""
    disarm()
    _STATE.target = (site, int(occurrence))
    _STATE.mode = "crash"
    try:
        yield
    finally:
        disarm()


def enumerate_points(sites: Iterable[str]) -> List[Tuple[str, int]]:
    """Ordered (site, occurrence) pairs from a recording — the full crash
    schedule of one protocol run."""
    counts: Dict[str, int] = {}
    out: List[Tuple[str, int]] = []
    for s in sites:
        k = counts.get(s, 0)
        counts[s] = k + 1
        out.append((s, k))
    return out


# ------------------------------------------------------------------ coverage


class Coverage:
    """Kill counts per site, accumulated across protocol matrices, and the
    PROTO_COVERAGE.json (de)serializer the committed artifact uses."""

    def __init__(self) -> None:
        self.kills: Dict[str, int] = {}
        self.matrices: Dict[str, Dict[str, int]] = {}

    def add_kill(self, matrix: str, site: str) -> None:
        self.kills[site] = self.kills.get(site, 0) + 1
        per = self.matrices.setdefault(matrix, {})
        per[site] = per.get(site, 0) + 1

    def merge(self, other: "Coverage") -> None:
        for site, n in other.kills.items():
            self.kills[site] = self.kills.get(site, 0) + n
        for matrix, per in other.matrices.items():
            mine = self.matrices.setdefault(matrix, {})
            for site, n in per.items():
                mine[site] = mine.get(site, 0) + n

    def to_json(self) -> Dict:
        return {
            "sites": {s: {"kills": n} for s, n in sorted(self.kills.items())},
            "matrices": {
                m: dict(sorted(per.items()))
                for m, per in sorted(self.matrices.items())
            },
        }

    def write(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump(self.to_json(), fh, indent=2, sort_keys=True)
            fh.write("\n")


def load_coverage(path: str) -> Dict:
    with open(path) as fh:
        return json.load(fh)


def validate_coverage(data: Dict, static_sites: Iterable[str]) -> List[str]:
    """Problems in a PROTO_COVERAGE record vs the statically extracted
    transition set: sites never killed, or absent from the record. A
    recorded site the static pass no longer sees is also flagged — stale
    coverage reads as proof of something that no longer exists."""
    recorded = data.get("sites", {})
    problems: List[str] = []
    static = set(static_sites)
    for site in sorted(static):
        entry = recorded.get(site)
        if entry is None:
            problems.append(f"transition {site!r} has no crash coverage record")
        elif int(entry.get("kills", 0)) < 1:
            problems.append(f"transition {site!r} recorded but never killed")
    for site in sorted(recorded):
        if site not in static:
            problems.append(
                f"coverage records {site!r} but no reach() site declares it"
            )
    return problems

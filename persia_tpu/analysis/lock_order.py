"""Declared lock-order registry for the feeder / write-back / stream threads.

The stream pipeline (hbm_cache/stream.py) runs three cooperating threads —
feeder prep, host→device staging, and the write-back flusher — plus the
RPC client threads underneath them. Deadlock-freedom rests on every thread
acquiring locks in ONE global order; this registry makes that order a
checkable artifact instead of tribal knowledge. CONC004 flags any lexically
nested ``with``-acquisition whose inner lock ranks ABOVE (outer-than) the
outer lock.

Ranks are matched by attribute-name suffix (the lock's field name), which
is how the code names them everywhere; a lock field not listed here simply
does not participate in the check — add it when it starts nesting.

Order (outermost first):

1. ``cv``            — the stream pipeline condition (hbm_cache/stream.py);
                       guards heads/tails/alloc queue/sign map. Nothing may
                       be held when taking it.
2. ``_pipe_cv``      — stage-graph window condition
                       (parallel/stage_graph.py); guards the in-flight
                       feed window, lane accounting, and abort flag.
                       Leaf-ish: only the metrics/tracing leaves
                       (``_flight_lock``, ``_REGISTRY_LOCK``) are ever
                       taken under it
3. ``_cv``           — data-loader prefetch pipeline condition; same
                       contract as ``cv`` for the loader threads
3. ``_cond``         — RPC response-waiter / serving-batcher queue
                       conditions; taken first by their worker threads
4. ``_buf_lock``     — embedding worker forward-buffer table
5. ``_grad_lock``    — embedding worker gradient-state table
6. ``_deg_lock``     — degraded-lookup bookkeeping (worker + cache tier)
7. ``_ring_lock``    — ShardedLookup versioned-topology swap latch
                       (embedding/worker.py): guards the atomic publish of
                       the (replicas, ring, version) tuple during an
                       elastic reshard / replica replacement. Held for the
                       tuple swap only — every side effect (gauge, breaker
                       reset, degraded purge, flight event) runs after
                       release, so nothing is ever nested under it
8. ``_swap_lock``    — serving engine model-swap latch
9. ``_state_lock``   — CachedTrainCtx device-state mutex (hbm_cache/ctx.py):
                       serializes the stager thread's feed dispatch against
                       the main thread's dense dispatch in pipelined
                       streams (every read-modify-replace of ``self.state``
                       / ``self._ev_rings``). Never nested with ``cv`` or
                       ``_pipe_cv``; only generic leaves below may be taken
                       under it
10. ``_lock``/``lock``— generic leaf locks (breakers, caches, registries,
                       checkpoint shard fan-out); must never wrap a
                       ranked-above lock
11. ``_flight_lock``  — tracing flight-recorder ring (leaf; appends only)
12. ``_rng_lock``    — RetryPolicy jitter RNG (innermost; held for one
                       random() call only)
13. ``_DEFAULT_LOCK``— resilience default-policy registry (leaf)
14. ``_PROC_LOCK``   — native-build serializer (_native_build.py): a LAZY
                       first-use build can trigger under any lock above,
                       and nothing ranked is ever taken under it (only the
                       compile subprocess + flock), so it is a leaf despite
                       being held the longest
15. ``_REGISTRY_LOCK``— metrics registry (innermost leaf)

Native mutexes (native/cache.cpp) live below every Python lock: a ctypes
call can run under any ``with`` above (CONC005 audits which ones), and the
native side never calls back into Python. ``NATIVE_LOCK_RANKS`` records
the round-14 sharded-feeder order so the TSan harness and reviewers have
one artifact to check the C++ against. The discipline is deliberately
**never-nested**: a feed walker releases each mutex before taking the
next — FeedShard::mu for the admit passes, then AccessSketch::mu for the
fused observe apply, then PendingMap::mu for the ledger probe — and
ShardedCache::pool_mu is only ever held around the dispatch/teardown
handshake, never across a shard walk. The ranks therefore encode the
SEQUENCE of a walker's acquisitions, not a nesting tree; any future change
that nests two of them must follow this order (and will face TSan's
deadlock detector in scripts/race_native.sh either way). Stats-plane
readers (probe/len/snapshot/shard_sizes) take one FeedShard::mu at a time.

Round 17 (SIMD probe layout + walker affinity) adds NO new mutexes: the
tag array and probe_mode flag mutate only under the owning shard's
FeedShard::mu (so scalar<->simd flips are legal from any thread), the
stall gauge is a relaxed atomic beside busy_ns, and affinity_mode rides
pool_mu with the same join-outside-the-lock respawn shape as set_threads.
"""

from __future__ import annotations

from typing import Dict, Optional

# native/cache.cpp mutex order (outermost / first-acquired first). These
# are C++ fields, invisible to the AST lints above — the registry is the
# documented contract the TSan gate exercises.
NATIVE_LOCK_RANKS: Dict[str, int] = {
    "pool_mu": 0,   # ShardedCache walker-pool handshake (dispatch only)
    "mu@FeedShard": 10,    # per-shard directory + LRU + result buffers
    "mu@AccessSketch": 20,  # count-min/bitmap/top-K (observe vs fence)
    "mu@PendingMap": 30,   # hazard ledger (feeder probe vs write-back)
}

# attribute-name suffix -> rank (lower = must be taken first / outermost)
LOCK_RANKS: Dict[str, int] = {
    "cv": 0,
    "_pipe_cv": 1,
    "_cv": 2,
    "_cond": 6,
    "_buf_lock": 10,
    "_grad_lock": 20,
    "_deg_lock": 30,
    "_ring_lock": 35,
    "_swap_lock": 40,
    "_state_lock": 45,
    "_lock": 50,
    "lock": 50,
    "_flight_lock": 55,
    "_rng_lock": 60,
    "_DEFAULT_LOCK": 65,
    "_PROC_LOCK": 68,
    "_REGISTRY_LOCK": 70,
}


def rank_of(name: str) -> Optional[int]:
    """Rank for a lock-ish expression's terminal attribute/variable name,
    or None when the name is not registered."""
    if name in LOCK_RANKS:
        return LOCK_RANKS[name]
    return None

"""Declared lock-order registry for the feeder / write-back / stream threads.

The stream pipeline (hbm_cache/stream.py) runs three cooperating threads —
feeder prep, host→device staging, and the write-back flusher — plus the
RPC client threads underneath them. Deadlock-freedom rests on every thread
acquiring locks in ONE global order; this registry makes that order a
checkable artifact instead of tribal knowledge. CONC004 flags any lexically
nested ``with``-acquisition whose inner lock ranks ABOVE (outer-than) the
outer lock.

Ranks are matched by attribute-name suffix (the lock's field name), which
is how the code names them everywhere; a lock field not listed here simply
does not participate in the check — add it when it starts nesting.

Order (outermost first):

1. ``cv``            — the stream pipeline condition (hbm_cache/stream.py);
                       guards heads/tails/alloc queue/sign map. Nothing may
                       be held when taking it.
2. ``_buf_lock``     — embedding worker forward-buffer table
3. ``_grad_lock``    — embedding worker gradient-state table
4. ``_deg_lock``     — degraded-lookup bookkeeping (worker + cache tier)
5. ``_swap_lock``    — serving engine model-swap latch
6. ``_lock``         — generic leaf locks (breakers, caches, registries);
                       must never wrap a ranked-above lock
7. ``_rng_lock``     — RetryPolicy jitter RNG (innermost; held for one
                       random() call only)
8. ``_REGISTRY_LOCK``— metrics registry (innermost leaf)
"""

from __future__ import annotations

from typing import Dict, Optional

# attribute-name suffix -> rank (lower = must be taken first / outermost)
LOCK_RANKS: Dict[str, int] = {
    "cv": 0,
    "_buf_lock": 10,
    "_grad_lock": 20,
    "_deg_lock": 30,
    "_swap_lock": 40,
    "_lock": 50,
    "_rng_lock": 60,
    "_REGISTRY_LOCK": 70,
}


def rank_of(name: str) -> Optional[int]:
    """Rank for a lock-ish expression's terminal attribute/variable name,
    or None when the name is not registered."""
    if name in LOCK_RANKS:
        return LOCK_RANKS[name]
    return None

"""Durability lint: checkpoint/manifest artifacts must publish atomically.

The crash-consistency story (persia_tpu.jobstate + checkpoint.py) rests on
one mechanical invariant: no checkpoint-class artifact — shard files,
manifests, done-markers, dense state, job-state pointers — is ever written
with a plain ``open(path, "w")`` (or a direct ``np.savez``), because a
crash mid-write leaves a torn file under the FINAL name that a later load
happily reads. Durable writes go temp + fsync + atomic rename
(``jobstate.fsync_write_bytes`` / ``storage.DiskPath.write_bytes``).

- DUR001: a plain ``open(..., "w"/"wb"/"a"/"ab")`` (or ``np.savez[_
  compressed]``) whose target expression names a checkpoint artifact
  (manifest / ckpt / checkpoint / shard / snapshot / .emb / done-marker /
  last_good / fused_state), inside a function with no atomic-publish
  machinery (mkstemp / NamedTemporaryFile / os.replace / rename / fsync /
  write_bytes) anywhere in it.

Scope: the whole ``persia_tpu`` tree — durability holes do not respect
module boundaries the way the resilience rules' service-plane scope does.
"""

from __future__ import annotations

import ast
import os
import re
from typing import List, Optional, Sequence

from persia_tpu.analysis.common import Finding, REPO_ROOT, read_text, rel

# what makes a write target a checkpoint-class artifact
_ARTIFACT_RE = re.compile(
    r"manifest|ckpt|checkpoint|shard|snapshot|\.emb|done_marker|done-marker"
    r"|last_good|fused_state",
    re.IGNORECASE,
)

# what proves the enclosing function publishes atomically
_ATOMIC_RE = re.compile(
    r"mkstemp|NamedTemporaryFile|os\.replace|\brename\b|fsync|write_bytes"
    r"|fsync_write_bytes|add_blob|storage_path",
)

_WRITE_MODES = {"w", "wb", "a", "ab", "w+", "wb+", "a+", "ab+"}


def _src(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover
        return ""


def _open_write_mode(call: ast.Call) -> bool:
    """True when this is ``open(target, <write mode>)`` (positional or kw)."""
    mode: Optional[ast.expr] = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    return (
        isinstance(mode, ast.Constant)
        and isinstance(mode.value, str)
        and mode.value in _WRITE_MODES
    )


def _is_open(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name) and f.id == "open":
        return True
    return (
        isinstance(f, ast.Attribute) and f.attr == "open"
        and isinstance(f.value, ast.Name) and f.value.id == "io"
    )


def _is_savez(call: ast.Call) -> bool:
    f = call.func
    return isinstance(f, ast.Attribute) and f.attr in ("savez", "savez_compressed")


def check_source(text: str, path: str) -> List[Finding]:
    findings: List[Finding] = []
    tree = ast.parse(text, filename=path)

    # map every write call to its enclosing function (module level counts as
    # its own scope) so the atomicity whitelist is judged function-locally —
    # a helper that mkstemps in one function must not whitelist another
    scopes: List[ast.AST] = [
        n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]

    def enclosing(call: ast.Call) -> Optional[ast.AST]:
        best = None
        for fn in scopes:
            if fn.lineno <= call.lineno <= max(
                getattr(fn, "end_lineno", fn.lineno), fn.lineno
            ):
                if best is None or fn.lineno > best.lineno:  # innermost
                    best = fn
        return best

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        target: Optional[ast.expr] = None
        what = None
        if _is_open(node) and _open_write_mode(node) and node.args:
            target, what = node.args[0], "open"
        elif _is_savez(node) and node.args:
            target, what = node.args[0], _src(node.func)
        if target is None:
            continue
        tsrc = _src(target)
        if not _ARTIFACT_RE.search(tsrc):
            continue
        fn = enclosing(node)
        scope_src = _src(fn) if fn is not None else text
        if _ATOMIC_RE.search(scope_src):
            continue
        findings.append(Finding(
            "DUR001", path, node.lineno,
            f"{what}({tsrc!r}, <write>) publishes a checkpoint artifact "
            "without temp + fsync + atomic rename — a crash mid-write "
            "leaves a torn file under the final name (use "
            "jobstate.fsync_write_bytes / storage.write_bytes)",
        ))
    return findings


def check(root: str = REPO_ROOT, files: Optional[Sequence[str]] = None) -> List[Finding]:
    from persia_tpu.analysis.common import python_files

    paths = list(files) if files is not None else python_files(root)
    findings: List[Finding] = []
    for p in paths:
        abspath = p if os.path.isabs(p) else os.path.join(root, p)
        findings.extend(check_source(read_text(abspath), rel(abspath)))
    return findings

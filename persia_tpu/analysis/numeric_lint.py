"""Numerical-health lint: loss/grad scalars must cross the host boundary
through a finite guard.

The train plane's escalation ladder (persia_tpu/health) only works if
every point where a loss or gradient statistic becomes a HOST scalar —
``.item()``, ``float(...)``, ``np.asarray(...)`` on a device value — can
see a NaN/Inf when one arrives. A decode site that converts and consumes
the number without any finite check is a blind spot: the poisoned value
flows into logs, EMAs, or LR schedules and the sentinel never hears
about it.

- NUM001 a function in a train-plane module converts a loss/grad-named
         value to a host scalar with no finite-guard token
         (``isfinite`` / ``isnan`` / ``nonfinite``) anywhere in the
         function — route the value through a guard such as
         ``parallel.train_step._note_nonfinite_loss`` or check it
         inline before consuming it

Scope: the modules that decode device step results or publish training
stats (``embedding/hbm_cache/``, ``parallel/``, ``data_loader.py``,
``topology.py``). The health package itself is the guard mechanism and
exempt. A function-level whitelist (rather than expression-level
dataflow) keeps the pass stdlib-pure and fast; the guard token must
live in the SAME function so the check stays local and reviewable.
"""

from __future__ import annotations

import ast
import os
import re
from typing import List, Optional, Sequence

from persia_tpu.analysis.common import Finding, REPO_ROOT, read_text, rel

_SCOPE_DIRS = (
    os.path.join("persia_tpu", "embedding", "hbm_cache"),
    os.path.join("persia_tpu", "parallel"),
)
_SCOPE_FILES = (
    os.path.join("persia_tpu", "data_loader.py"),
    os.path.join("persia_tpu", "topology.py"),
)
# the guard mechanism itself may convert unguarded
_EXEMPT_DIRS = (os.path.join("persia_tpu", "health"),)

# a conversion site is loss/grad-plane when the converted expression or
# its assignment target carries one of these name stems
_VALUE_RE = re.compile(r"(?:^|[^a-z])(loss|grad|gnorm)", re.IGNORECASE)

# what proves the enclosing function already guards: any finite check or
# a call into the nonfinite-note helper
_GUARD_TOKENS = ("isfinite", "isnan", "nonfinite")


def _src(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover
        return ""


def _conversion(node: ast.expr) -> Optional[str]:
    """Return the converted sub-expression's source when ``node`` is a
    host-scalar conversion (``float(x)``, ``x.item()``,
    ``np.asarray(x)`` / ``np.array(x)``), else None."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Name) and f.id == "float" and len(node.args) == 1:
        return _src(node.args[0])
    if isinstance(f, ast.Attribute):
        if f.attr == "item" and not node.args:
            return _src(f.value)
        # np.asarray is the host sync; jnp.asarray is device-ward and
        # never materializes the value on the host — not a crossing
        if (f.attr in ("asarray", "array") and node.args
                and isinstance(f.value, ast.Name)
                and f.value.id in ("np", "numpy")):
            return _src(node.args[0])
    return None


def _own_nodes(fn: ast.AST):
    """Walk a function's body WITHOUT descending into nested defs — each
    function is judged (and whitelisted) on its own source only."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _function_findings(fn: ast.AST, path: str) -> List[Finding]:
    fn_src = _src(fn)
    if any(tok in fn_src for tok in _GUARD_TOKENS):
        return []
    findings: List[Finding] = []
    for node in _own_nodes(fn):
        targets = ""
        expr = node
        if isinstance(node, ast.Assign):
            targets = " ".join(_src(t) for t in node.targets)
            expr = node.value
        for sub in ast.walk(expr):
            conv = _conversion(sub)
            if conv is None:
                continue
            if not (_VALUE_RE.search(conv) or _VALUE_RE.search(targets)):
                continue
            findings.append(Finding(
                "NUM001", path, sub.lineno,
                f"loss/grad scalar crosses to host unguarded ({_src(sub)}) "
                "— a NaN/Inf here flows into stats/schedules invisibly; "
                "check np.isfinite (or route through "
                "parallel.train_step._note_nonfinite_loss) in this "
                "function before consuming it",
            ))
    # one finding per line: a chained conversion (float(x.item())) is one
    # blind spot, not two
    seen = set()
    out = []
    for f in findings:
        if (f.path, f.line) not in seen:
            seen.add((f.path, f.line))
            out.append(f)
    return out


def _in_scope(path: str) -> bool:
    p = rel(path)
    if any(p.startswith(d + os.sep) for d in _EXEMPT_DIRS):
        return False
    if p in _SCOPE_FILES:
        return True
    return any(p.startswith(d + os.sep) for d in _SCOPE_DIRS)


def check_source(text: str, path: str) -> List[Finding]:
    """Lint one file (no scope filter — fixtures call this directly)."""
    tree = ast.parse(text, filename=path)
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            findings.extend(_function_findings(node, path))
    # nested defs are walked twice (outer pass sees them inside the
    # enclosing function's walk); dedupe keeps one finding per site
    seen = set()
    out = []
    for f in findings:
        if (f.path, f.line) not in seen:
            seen.add((f.path, f.line))
            out.append(f)
    return out


def check(root: str = REPO_ROOT,
          files: Optional[Sequence[str]] = None) -> List[Finding]:
    from persia_tpu.analysis.common import python_files

    paths = list(files) if files is not None else python_files(root)
    findings: List[Finding] = []
    for p in paths:
        abspath = p if os.path.isabs(p) else os.path.join(root, p)
        if not _in_scope(abspath):
            continue
        findings.extend(check_source(read_text(abspath), rel(abspath)))
    return findings

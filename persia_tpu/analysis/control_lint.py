"""Control-loop lint: topology mutation must sit behind a flap guard.

- CTRL001 a ``while`` loop whose body calls a topology-mutating
         actuator — ``reshard_ps`` / ``swap_topology`` / ``add_replica``
         / ``remove_replica`` / ``restart_replica`` / ``kill_replica`` /
         ``scale_serving`` — from a function (or module scope) whose
         source shows no hysteresis/dwell/cooldown guard token anywhere
         on the decision path. An unguarded control loop is a flap
         machine: two states trading places every round thrash the
         exactly-once handoff journal, churn the gateway's breaker
         history, and turn every sensor blip into a fleet mutation. Route
         the decision through a guarded policy
         (:class:`persia_tpu.autopilot.PolicyEngine`,
         :class:`~persia_tpu.embedding.tiering.shard_planner.ShardPlanner`)
         or put the margin + dwell check next to the loop.
- CTRL002 a DIRECT call to a topology actuator — ``reshard_ps`` /
         ``heal_promote`` / ``heal_drain_gray`` / ``apply_migration`` /
         ``replace_replica`` / ``swap_topology`` — from control-plane
         code whose enclosing function shows no arbiter/lease evidence.
         Since PR 20 the fleet holds ONE topology-actuation lease
         (:mod:`persia_tpu.autopilot.arbiter`): four loops submit
         intents and the arbiter serializes them, preempts in-flight
         lower-priority protocols, and suppresses cross-loop flaps. A
         call site that bypasses the lease reopens the
         concurrent-mutation hole the arbiter closed. Files that
         IMPLEMENT an actuator (helper.py, topology.py, the cache ctx,
         the worker) are the mechanism layer below the lease and are
         exempt wholesale — actuator-to-actuator delegation inside the
         drained window is their job.

Scope notes: only ``while`` loops are control loops here — a bounded
``for`` over a static membership list (gateway bootstrap, a probe sweep)
applies a decision, it doesn't make one. A mutator call outside any loop
is fine too (a one-shot reshard is an operator action) for CTRL001;
CTRL002 still wants the lease token (or an explicit inline disable, as
the launcher's setup-time operator reshard carries). The guard search
covers the whole enclosing function's source — comments and docstrings
count, so an actuator whose guard genuinely lives one call up can say so
(``# dwell/hysteresis guard in PolicyEngine.decide_*``) and the reader
gets the pointer the lint wanted. Test files exercise flap paths on
purpose and are exempt.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Sequence

from persia_tpu.analysis.common import Finding, REPO_ROOT, read_text, rel

# actuators that change fleet topology when called
_MUTATORS = (
    "reshard_ps",
    "swap_topology",
    "add_replica",
    "remove_replica",
    "restart_replica",
    "kill_replica",
    "scale_serving",
)

# evidence of a flap guard on the decision path
_GUARD_TOKENS = ("hysteresis", "dwell", "cooldown")

# actuators that must route through the control-plane arbiter's topology
# lease (CTRL002) when called from control-plane code
_LEASED_ACTUATORS = (
    "reshard_ps",
    "heal_promote",
    "heal_drain_gray",
    "apply_migration",
    "replace_replica",
    "swap_topology",
)

# evidence that the call site sits under (or wires up) the arbiter lease;
# the lookbehind keeps "release"/"released" from counting as "lease"
import re as _re

_LEASE_RE = _re.compile(r"arbiter|(?<![a-z])lease")


def _called_mutators(loop: ast.AST) -> List[ast.Call]:
    """Mutator calls anywhere inside the loop body (method or bare name)."""
    out: List[ast.Call] = []
    for node in ast.walk(loop):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = (f.attr if isinstance(f, ast.Attribute)
                else f.id if isinstance(f, ast.Name) else None)
        if name in _MUTATORS:
            out.append(node)
    return out


def _guarded(scope_src: str) -> bool:
    low = scope_src.lower()
    return any(tok in low for tok in _GUARD_TOKENS)


def _scope_source(text: str, scope: Optional[ast.AST]) -> str:
    if scope is None:
        return text  # module-level loop: the whole file is the scope
    seg = ast.get_source_segment(text, scope)
    return seg if seg is not None else text


def check_source(text: str, path: str) -> List[Finding]:
    """Lint one file for CTRL001."""
    tree = ast.parse(text, filename=path)
    findings: List[Finding] = []
    # map every loop to its innermost enclosing function scope
    scopes: List[tuple] = []  # (loop, enclosing function or None)

    def walk(node: ast.AST, func: Optional[ast.AST]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk(child, child)
            else:
                if isinstance(child, ast.While):
                    scopes.append((child, func))
                walk(child, func)

    walk(tree, None)
    for loop, func in scopes:
        calls = _called_mutators(loop)
        if not calls:
            continue
        if _guarded(_scope_source(text, func)):
            continue
        for call in calls:
            f = call.func
            name = (f.attr if isinstance(f, ast.Attribute)
                    else getattr(f, "id", "?"))
            where = (f"function {func.name!r}" if func is not None
                     else "module scope")
            findings.append(Finding(
                "CTRL001", path, call.lineno,
                f"control loop in {where} mutates topology ({name}) with "
                f"no hysteresis/dwell guard on the decision path — flap "
                f"risk; gate it through a guarded policy "
                f"(autopilot.PolicyEngine / tiering.ShardPlanner)",
            ))
    return findings


def check_source_lease(text: str, path: str) -> List[Finding]:
    """Lint one file for CTRL002 (unleased topology actuation)."""
    tree = ast.parse(text, filename=path)
    defined = {
        n.name for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    if defined & set(_LEASED_ACTUATORS):
        # mechanism layer: this file IMPLEMENTS an actuator, so its
        # internal delegation runs below the lease by construction
        return []
    findings: List[Finding] = []

    def has_lease(chain: List[ast.AST]) -> bool:
        # evidence anywhere in the enclosing-function CHAIN counts: the
        # leased wrapper pattern puts the arbiter submit in the outer
        # function and the actuator call in an inner closure
        if not chain:
            return _LEASE_RE.search(text.lower()) is not None
        return any(
            _LEASE_RE.search(_scope_source(text, fn).lower()) is not None
            for fn in chain
        )

    def walk(node: ast.AST, chain: List[ast.AST]) -> None:
        for child in ast.iter_child_nodes(node):
            inner = (chain + [child] if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)) else chain)
            if isinstance(child, ast.Call):
                f = child.func
                name = (f.attr if isinstance(f, ast.Attribute)
                        else f.id if isinstance(f, ast.Name) else None)
                if name in _LEASED_ACTUATORS and not has_lease(chain):
                    where = (f"function {chain[-1].name!r}" if chain
                             else "module scope")
                    findings.append(Finding(
                        "CTRL002", path, child.lineno,
                        f"direct topology actuation ({name}) in {where} "
                        f"with no arbiter lease on the call path — submit "
                        f"an Intent through autopilot.arbiter.Arbiter.run "
                        f"(or carry the lease evidence/token) so the "
                        f"single-mutation + preemption + flap-suppression "
                        f"guarantees hold",
                    ))
            walk(child, inner)

    walk(tree, [])
    return findings


def check(root: str = REPO_ROOT,
          files: Optional[Sequence[str]] = None) -> List[Finding]:
    from persia_tpu.analysis.common import python_files

    paths = list(files) if files is not None else python_files(root)
    findings: List[Finding] = []
    for p in paths:
        abspath = p if os.path.isabs(p) else os.path.join(root, p)
        rp = rel(abspath)
        base = os.path.basename(rp)
        # tests exercise flap paths on purpose
        if base.startswith("test_") or rp.startswith("tests" + os.sep):
            continue
        text = read_text(abspath)
        findings.extend(check_source(text, rp))
        findings.extend(check_source_lease(text, rp))
    return findings

"""Control-loop lint: topology mutation must sit behind a flap guard.

- CTRL001 a ``while`` loop whose body calls a topology-mutating
         actuator — ``reshard_ps`` / ``swap_topology`` / ``add_replica``
         / ``remove_replica`` / ``restart_replica`` / ``kill_replica`` /
         ``scale_serving`` — from a function (or module scope) whose
         source shows no hysteresis/dwell/cooldown guard token anywhere
         on the decision path. An unguarded control loop is a flap
         machine: two states trading places every round thrash the
         exactly-once handoff journal, churn the gateway's breaker
         history, and turn every sensor blip into a fleet mutation. Route
         the decision through a guarded policy
         (:class:`persia_tpu.autopilot.PolicyEngine`,
         :class:`~persia_tpu.embedding.tiering.shard_planner.ShardPlanner`)
         or put the margin + dwell check next to the loop.

Scope notes: only ``while`` loops are control loops here — a bounded
``for`` over a static membership list (gateway bootstrap, a probe sweep)
applies a decision, it doesn't make one. A mutator call outside any loop
is fine too (a one-shot reshard is an operator action). The guard search
covers the whole enclosing function's source — comments and docstrings
count, so an actuator whose guard genuinely lives one call up can say so
(``# dwell/hysteresis guard in PolicyEngine.decide_*``) and the reader
gets the pointer the lint wanted. Test files exercise flap paths on
purpose and are exempt.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Sequence

from persia_tpu.analysis.common import Finding, REPO_ROOT, read_text, rel

# actuators that change fleet topology when called
_MUTATORS = (
    "reshard_ps",
    "swap_topology",
    "add_replica",
    "remove_replica",
    "restart_replica",
    "kill_replica",
    "scale_serving",
)

# evidence of a flap guard on the decision path
_GUARD_TOKENS = ("hysteresis", "dwell", "cooldown")


def _called_mutators(loop: ast.AST) -> List[ast.Call]:
    """Mutator calls anywhere inside the loop body (method or bare name)."""
    out: List[ast.Call] = []
    for node in ast.walk(loop):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = (f.attr if isinstance(f, ast.Attribute)
                else f.id if isinstance(f, ast.Name) else None)
        if name in _MUTATORS:
            out.append(node)
    return out


def _guarded(scope_src: str) -> bool:
    low = scope_src.lower()
    return any(tok in low for tok in _GUARD_TOKENS)


def _scope_source(text: str, scope: Optional[ast.AST]) -> str:
    if scope is None:
        return text  # module-level loop: the whole file is the scope
    seg = ast.get_source_segment(text, scope)
    return seg if seg is not None else text


def check_source(text: str, path: str) -> List[Finding]:
    """Lint one file for CTRL001."""
    tree = ast.parse(text, filename=path)
    findings: List[Finding] = []
    # map every loop to its innermost enclosing function scope
    scopes: List[tuple] = []  # (loop, enclosing function or None)

    def walk(node: ast.AST, func: Optional[ast.AST]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk(child, child)
            else:
                if isinstance(child, ast.While):
                    scopes.append((child, func))
                walk(child, func)

    walk(tree, None)
    for loop, func in scopes:
        calls = _called_mutators(loop)
        if not calls:
            continue
        if _guarded(_scope_source(text, func)):
            continue
        for call in calls:
            f = call.func
            name = (f.attr if isinstance(f, ast.Attribute)
                    else getattr(f, "id", "?"))
            where = (f"function {func.name!r}" if func is not None
                     else "module scope")
            findings.append(Finding(
                "CTRL001", path, call.lineno,
                f"control loop in {where} mutates topology ({name}) with "
                f"no hysteresis/dwell guard on the decision path — flap "
                f"risk; gate it through a guarded policy "
                f"(autopilot.PolicyEngine / tiering.ShardPlanner)",
            ))
    return findings


def check(root: str = REPO_ROOT,
          files: Optional[Sequence[str]] = None) -> List[Finding]:
    from persia_tpu.analysis.common import python_files

    paths = list(files) if files is not None else python_files(root)
    findings: List[Finding] = []
    for p in paths:
        abspath = p if os.path.isabs(p) else os.path.join(root, p)
        rp = rel(abspath)
        base = os.path.basename(rp)
        # tests exercise flap paths on purpose
        if base.startswith("test_") or rp.startswith("tests" + os.sep):
            continue
        findings.extend(check_source(read_text(abspath), rp))
    return findings

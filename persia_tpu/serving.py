"""HTTP model serving.

Parity target: the reference's TorchServe deployment
(`examples/src/adult-income/serve_handler.py` — handler builds an InferCtx
over embedding-worker RPC addresses, `serve_client.py` — posts
``PersiaBatch.to_bytes()`` payloads and checks AUC > 0.8927).

Here the model server is part of the framework: ``InferenceServer`` wraps an
``InferCtx`` (jitted eval step on the TPU/host + embedding lookups with
zeros-on-miss) behind a thin HTTP API:

- ``POST /predict``  body = ``PersiaBatch.to_bytes()`` → ``.npy`` scores
- ``GET  /healthz``  liveness + model metadata
- ``GET  /metrics``  Prometheus text (the process registry)

``InferenceClient`` is the matching urllib client. Incremental updates reach
the PS tier independently (persia_tpu/incremental.py), so a long-running
server picks up online deltas without restarts.
"""

from __future__ import annotations

import io
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib import request as urlrequest

import numpy as np

from persia_tpu.data import PersiaBatch
from persia_tpu.logger import get_default_logger

logger = get_default_logger("persia_tpu.serving")


class InferenceServer:
    """Serve an ``InferCtx`` over HTTP. ``port=0`` picks a free port."""

    def __init__(self, infer_ctx, port: int = 0, host: str = "0.0.0.0"):
        self.ctx = infer_ctx
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    meta = {
                        "status": "ok",
                        "model": type(outer.ctx.model).__name__,
                        "requests": outer.request_count,
                    }
                    self._send(200, json.dumps(meta).encode(), "application/json")
                elif self.path == "/metrics":
                    from persia_tpu.metrics import get_metrics

                    self._send(200, get_metrics().render().encode(), "text/plain")
                else:
                    self._send(404, b"not found", "text/plain")

            def do_POST(self):
                if self.path != "/predict":
                    self._send(404, b"not found", "text/plain")
                    return
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    raw = self.rfile.read(n)
                    scores = outer.ctx.predict_from_bytes(raw)
                    outer.request_count += 1
                    buf = io.BytesIO()
                    np.save(buf, np.asarray(scores, dtype=np.float32))
                    self._send(200, buf.getvalue(), "application/octet-stream")
                except Exception as e:  # noqa: BLE001 — app error crosses the wire
                    logger.exception("predict failed")
                    self._send(400, repr(e).encode(), "text/plain")

            def log_message(self, *a):
                pass

        self.request_count = 0
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "InferenceServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="persia-infer-http")
        self._thread.start()
        logger.info("inference server on port %d", self.port)
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)


class InferenceClient:
    """Blocking HTTP client for :class:`InferenceServer`."""

    def __init__(self, addr: str, timeout_s: float = 30.0):
        self.base = addr if addr.startswith("http") else f"http://{addr}"
        self.timeout_s = timeout_s

    def predict(self, batch: PersiaBatch) -> np.ndarray:
        return self.predict_bytes(batch.to_bytes())

    def predict_bytes(self, raw: bytes) -> np.ndarray:
        req = urlrequest.Request(f"{self.base}/predict", data=raw, method="POST",
                                 headers={"Content-Type": "application/octet-stream"})
        with urlrequest.urlopen(req, timeout=self.timeout_s) as resp:
            return np.load(io.BytesIO(resp.read()))

    def health(self) -> dict:
        with urlrequest.urlopen(f"{self.base}/healthz", timeout=self.timeout_s) as resp:
            return json.loads(resp.read())

    def metrics_text(self) -> str:
        with urlrequest.urlopen(f"{self.base}/metrics", timeout=self.timeout_s) as resp:
            return resp.read().decode()

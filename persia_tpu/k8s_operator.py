"""Kubernetes operator tier: reconcile loop + REST scheduler.

Parity target: the reference's kube-runtime Controller
(`k8s/src/bin/operator.rs:55-100` — create → apply resources with a
finalizer, delete → teardown) and the actix-web REST scheduler
(`k8s/src/bin/server.rs:1-229` — /apply, /delete, list/log endpoints).

Design: a level-triggered poll-reconcile loop (no watch streams — the
convergence property is the same: each cycle diffs DESIRED state, derived
from the ``PersiaTpuJob`` custom resources via
``persia_tpu.k8s.generate_manifests``, against ACTUAL labeled resources,
then creates what's missing, deletes what's orphaned, and replaces failed
pods). The cluster API is behind the small ``KubeApi`` interface:
``KubectlApi`` shells out to kubectl for real clusters; tests inject an
in-memory fake, so the controller logic is covered without a cluster
(the reference needs a live cluster for `k8s/src/bin/e2e.rs`).
"""

from __future__ import annotations

import json
import subprocess
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from persia_tpu.k8s import (
    GROUP,
    JOB_LABEL,
    KIND,
    PLURAL,
    VERSION,
    generate_manifests,
    job_from_custom_resource,
)
from persia_tpu.logger import get_default_logger

logger = get_default_logger("persia_tpu.k8s_operator")

_FINALIZER = f"{GROUP}/teardown"


def _obj_key(obj: Dict[str, Any]) -> Tuple[str, str, str]:
    return (
        obj.get("kind", ""),
        obj.get("metadata", {}).get("namespace", "default"),
        obj.get("metadata", {}).get("name", ""),
    )


class KubeApi:
    """Minimal cluster surface the reconciler needs."""

    def list_jobs(self) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def list_labeled(self, namespace: Optional[str]) -> Optional[List[Dict[str, Any]]]:
        """All framework-labeled Pods/Services/Deployments; ``namespace=None``
        means every namespace (the reconciler's observation scope — it must
        survive restarts, so it cannot rely on remembering namespaces). A
        cluster-wide listing that FAILS (e.g. RBAC) returns ``None``, never
        an empty-looking partial view."""
        raise NotImplementedError

    def create(self, obj: Dict[str, Any]) -> None:
        raise NotImplementedError

    def delete(self, kind: str, namespace: str, name: str) -> None:
        raise NotImplementedError

    def pod_phase(self, obj: Dict[str, Any]) -> str:
        return obj.get("status", {}).get("phase", "Unknown")

    def set_finalizers(self, namespace: str, name: str,
                       finalizers: List[str]) -> None:
        """Replace the finalizer list on a ``PersiaTpuJob`` CR (ref:
        k8s/src/finalizer.rs — add on reconcile, remove once children are
        confirmed gone, so the API server holds the CR until teardown is
        ordered). Default no-op keeps finalizer-unaware backends working."""


class KubectlApi(KubeApi):
    """Real-cluster backend (kubectl JSON shell-outs; the framework image
    does not vendor a kube client library)."""

    def __init__(self, kubectl: str = "kubectl"):
        self.kubectl = kubectl

    def _run_json(self, args: List[str]) -> Dict[str, Any]:
        out = subprocess.run(
            [self.kubectl] + args + ["-o", "json"],
            capture_output=True, text=True, check=True,
        )
        return json.loads(out.stdout)

    def list_jobs(self) -> List[Dict[str, Any]]:
        try:
            return self._run_json(
                ["get", f"{PLURAL}.{GROUP}", "--all-namespaces"]
            ).get("items", [])
        except subprocess.CalledProcessError:
            return []

    def list_labeled(self, namespace: Optional[str]) -> Optional[List[Dict[str, Any]]]:
        """Per the KubeApi contract: returns ``None`` when ANY of the
        listings FAILED, cluster-wide or namespaced — the reconciler must
        distinguish 'access denied / API down' from 'no resources exist' or
        it would sweep/re-apply against a partial view (and, on the
        namespaced fallback, re-issue create for every desired object each
        tick against an empty view)."""
        scope = ["--all-namespaces"] if namespace is None else ["-n", namespace]
        objs: List[Dict[str, Any]] = []
        for kind in ("pods", "services", "deployments"):
            try:
                objs.extend(
                    self._run_json(
                        ["get", kind, *scope, "-l", JOB_LABEL]
                    ).get("items", [])
                )
            except subprocess.CalledProcessError as e:
                logger.warning(
                    "kubectl get %s %s failed: %s", kind, " ".join(scope),
                    (e.stderr or b"").strip() if isinstance(e.stderr, (bytes, str))
                    else e,
                )
                return None
        return objs

    def create(self, obj: Dict[str, Any]) -> None:
        subprocess.run(
            [self.kubectl, "apply", "-f", "-"],
            input=json.dumps(obj), text=True, check=True, capture_output=True,
        )

    def delete(self, kind: str, namespace: str, name: str) -> None:
        # --wait=false ONLY for the CR kind: a finalized CR parks on
        # deletionTimestamp until a LATER reconcile cycle releases the
        # finalizer, so a blocking delete from the reconciler's own thread
        # would deadlock on itself. Child deletes stay synchronous — the
        # finalizer-release check and the e2e leftovers check both rely on
        # swept children actually being gone when the next listing runs.
        wait = [] if kind != KIND else ["--wait=false"]
        subprocess.run(
            [self.kubectl, "delete", kind.lower(), name, "-n", namespace,
             "--ignore-not-found", *wait],
            check=True, capture_output=True,
        )

    def set_finalizers(self, namespace: str, name: str,
                       finalizers: List[str]) -> None:
        subprocess.run(
            [self.kubectl, "patch", f"{PLURAL}.{GROUP}", name, "-n", namespace,
             "--type", "merge", "-p",
             json.dumps({"metadata": {"finalizers": finalizers}})],
            check=True, capture_output=True,
        )


class Reconciler:
    """Level-triggered controller: converge labeled resources to the
    ``PersiaTpuJob`` CRs every cycle (ref: reconcile,
    k8s/src/bin/operator.rs:55-100)."""

    def __init__(self, api: KubeApi, namespace: str = "default"):
        self.api = api
        # observation is cluster-wide; this is only the RBAC fallback scope
        # (see reconcile_once) and the REST tier's default
        self.namespace = namespace
        self._stop = threading.Event()
        # consecutive cycles with NO usable observation (API unreachable):
        # drives run()'s backoff and the alert counter — a chronically
        # unreachable API must not degrade into silent pod leakage
        self.observe_failures = 0
        self._m_unreachable = None

    def _observe_failed(self) -> None:
        self.observe_failures += 1
        if self._m_unreachable is None:
            try:
                from persia_tpu.metrics import get_metrics

                self._m_unreachable = get_metrics().counter(
                    "persia_operator_observe_failures_total",
                    "reconcile cycles skipped: cluster API unreachable",
                )
            except Exception:  # noqa: BLE001
                self._m_unreachable = False
        if self._m_unreachable:
            self._m_unreachable.inc()
        logger.error(
            "cluster observation unavailable (%d consecutive) — skipping "
            "reconcile cycle, backing off", self.observe_failures,
        )

    def reconcile_once(self) -> Dict[str, int]:
        """One convergence pass. Returns action counts (for tests/metrics).

        Two-phase teardown via a finalizer (ref: k8s/src/finalizer.rs):
        every live CR gets ``{GROUP}/teardown`` appended, so deleting the CR
        — even while the operator is down — parks it with a
        ``deletionTimestamp`` instead of vanishing. A deleting CR's children
        leave the desired set (→ swept as orphans); only a cycle that
        OBSERVES zero remaining children releases the finalizer, so the CR
        cannot disappear before its resources do.
        """
        stats = {"created": 0, "deleted": 0, "restarted": 0, "skipped": 0,
                 "finalized": 0, "released": 0}
        desired: Dict[Tuple[str, str, str], Dict[str, Any]] = {}
        deleting: List[Tuple[str, str, List[str]]] = []  # (ns, name, finalizers)
        for cr in self.api.list_jobs():
            meta = cr.get("metadata", {})
            ns = meta.get("namespace", "default")
            name = meta.get("name", "")
            finalizers = list(meta.get("finalizers", []))
            if meta.get("deletionTimestamp"):
                if _FINALIZER in finalizers:
                    deleting.append((ns, name, finalizers))
                continue  # children intentionally absent from desired
            try:
                spec = job_from_custom_resource(cr)
            except Exception as e:  # noqa: BLE001 — one bad CR must not wedge the loop
                logger.error("bad %s %s: %r", KIND, name, e)
                continue
            if _FINALIZER not in finalizers:
                try:
                    self.api.set_finalizers(ns, name, finalizers + [_FINALIZER])
                    stats["finalized"] += 1
                except Exception:  # noqa: BLE001 — converge children anyway
                    logger.exception("adding finalizer to %s/%s failed", ns, name)
            for obj in generate_manifests(spec):
                desired[_obj_key(obj)] = obj

        # observe CLUSTER-WIDE, matching the cluster-wide CR listing: a
        # deleted cross-namespace CR's leftovers must be swept even after an
        # operator restart, so the observation scope cannot depend on any
        # remembered state. Under namespace-scoped RBAC the cluster-wide
        # list FAILS (None — distinct from 'no resources'); fall back to the
        # operator's own namespace so convergence works within the granted
        # scope. If THAT also fails there is no usable view: skip the cycle
        # (acting on a blind view would re-create everything / sweep
        # nothing) and let run() back off.
        listed = self.api.list_labeled(None)
        cluster_wide_view = listed is not None
        if listed is None:
            listed = self.api.list_labeled(self.namespace)
        if listed is None:
            self._observe_failed()
            stats["skipped"] = 1
            return stats
        self.observe_failures = 0
        actual = {_obj_key(o): o for o in listed}

        # replace failed pods first (restartPolicy at the controller level)
        for key, obj in list(actual.items()):
            kind, ns, name = key
            if kind == "Pod" and key in desired and self.api.pod_phase(obj) == "Failed":
                logger.warning("restarting failed pod %s/%s", ns, name)
                self.api.delete(kind, ns, name)
                del actual[key]
                stats["restarted"] += 1

        for key, obj in desired.items():
            if key not in actual:
                self.api.create(obj)
                stats["created"] += 1
        for key, obj in actual.items():
            if key not in desired:
                if obj.get("metadata", {}).get("ownerReferences"):
                    # controller-managed child (e.g. a Deployment's
                    # ReplicaSet pods): its owner is the desired object;
                    # deleting it here would fight that controller forever
                    continue
                kind, ns, name = key
                logger.info("tearing down orphan %s %s/%s", kind, ns, name)
                self.api.delete(kind, ns, name)
                stats["deleted"] += 1

        # finalizer release: only when THIS cycle's observation shows no
        # children left for the deleting CR (deletes just issued may be
        # async — those CRs release on a later cycle, after the listing
        # confirms the sweep landed)
        for ns, name, finalizers in deleting:
            if not cluster_wide_view and ns != self.namespace:
                # the fallback view cannot see this CR's namespace —
                # releasing on zero VISIBLE children would break the
                # ordered-teardown guarantee; hold until a cycle with scope
                continue
            children = [
                o for o in listed
                if o.get("metadata", {}).get("labels", {}).get(JOB_LABEL) == name
                and o.get("metadata", {}).get("namespace", "default") == ns
            ]
            if not children:
                try:
                    self.api.set_finalizers(
                        ns, name, [f for f in finalizers if f != _FINALIZER]
                    )
                    stats["released"] += 1
                    logger.info("released finalizer on %s/%s", ns, name)
                except Exception:  # noqa: BLE001
                    logger.exception("releasing finalizer on %s/%s failed", ns, name)
        return stats

    def backoff_s(self, interval_s: float, max_s: float = 60.0) -> float:
        """Next sleep: exponential in consecutive observation failures,
        capped — an unreachable API is polled gently, not hammered."""
        if not self.observe_failures:
            return interval_s
        return min(interval_s * (2.0 ** self.observe_failures), max_s)

    def run(self, interval_s: float = 2.0) -> None:
        logger.info("operator reconciling every %.1fs", interval_s)
        while not self._stop.wait(self.backoff_s(interval_s)):
            try:
                self.reconcile_once()
            except Exception:  # noqa: BLE001 — the loop must survive API hiccups
                logger.exception("reconcile cycle failed")

    def stop(self) -> None:
        self._stop.set()


# --------------------------------------------------------------- REST tier


class OperatorHttpServer:
    """REST scheduler (ref: k8s/src/bin/server.rs): POST /apply with a
    PersiaTpuJob CR, POST /delete?name=..., GET /jobs, GET /status — thin
    HTTP wrappers over the same KubeApi the reconciler converges."""

    def __init__(self, api: KubeApi, port: int = 0, namespace: str = "default"):
        import http.server

        operator_self = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _reply(self, code: int, payload: Dict[str, Any]) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path.startswith("/jobs"):
                    names = [
                        cr.get("metadata", {}).get("name")
                        for cr in operator_self.api.list_jobs()
                    ]
                    self._reply(200, {"jobs": names})
                elif self.path.startswith("/status"):
                    objs = operator_self.api.list_labeled(namespace)
                    if objs is None:  # listing failed — observation unavailable
                        self._reply(503, {"error": "cluster API unavailable"})
                        return
                    pods = {
                        o["metadata"]["name"]: operator_self.api.pod_phase(o)
                        for o in objs if o.get("kind") == "Pod"
                    }
                    self._reply(200, {"pods": pods})
                else:
                    self._reply(404, {"error": "unknown path"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n) if n else b""
                if self.path.startswith("/apply"):
                    try:
                        cr = json.loads(raw)
                        if cr.get("kind") != KIND:  # not assert: must survive -O
                            raise ValueError(f"kind must be {KIND}")
                        job_from_custom_resource(cr)  # validate
                        operator_self.api.create(cr)
                        self._reply(200, {"applied": cr["metadata"]["name"]})
                    except Exception as e:  # noqa: BLE001
                        self._reply(400, {"error": repr(e)})
                elif self.path.startswith("/delete"):
                    from urllib.parse import parse_qs, urlparse

                    q = parse_qs(urlparse(self.path).query)
                    name = (q.get("name") or [None])[0]
                    if not name:
                        self._reply(400, {"error": "name required"})
                        return
                    operator_self.api.delete(KIND, namespace, name)
                    self._reply(200, {"deleted": name})
                else:
                    self._reply(404, {"error": "unknown path"})

        self.api = api
        self._httpd = http.server.ThreadingHTTPServer(("0.0.0.0", port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "OperatorHttpServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()


def main(argv: Optional[List[str]] = None) -> None:
    import argparse

    ap = argparse.ArgumentParser("persia-tpu-k8s-operator")
    ap.add_argument("--namespace", default="default")
    ap.add_argument("--interval-s", type=float, default=2.0)
    ap.add_argument("--rest-port", type=int, default=0,
                    help="also serve the REST scheduler (0 = off)")
    args = ap.parse_args(argv)
    api = KubectlApi()
    rec = Reconciler(api, namespace=args.namespace)
    if args.rest_port:
        srv = OperatorHttpServer(api, port=args.rest_port, namespace=args.namespace)
        srv.start()
        logger.info("REST scheduler on :%d", srv.port)
    rec.run(interval_s=args.interval_s)


if __name__ == "__main__":
    main()

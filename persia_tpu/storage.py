"""Storage abstraction: one path API over local disk and remote filesystems.

Parity target: ``persia-storage`` (`/root/reference/rust/persia-storage/src/lib.rs`):
``PersiaPath`` enum-dispatches create/read/write/list/append over Disk and
HDFS, where HDFS is a shell-out to ``hdfs dfs`` / ``hadoop fs`` (`lib.rs:173-391`).

TPU-first differences: the scheme set is disk + ``hdfs://`` + ``gs://`` (GCS
is the natural object store next to TPU pods; shell-out to ``gsutil``).
Remote backends are *gated*: constructing a path is always allowed, but the
first operation raises ``StorageUnavailableError`` when the CLI tool is not
installed, so import never fails on a laptop without the Hadoop/Cloud SDK.
"""

from __future__ import annotations

import os
import posixpath
import shutil
import subprocess
import tempfile
import uuid
from typing import List, Optional, Union

# sampled once at import: os.umask() is process-wide and briefly setting it to
# 0 per write would race concurrent writers (checkpoint IO is multithreaded)
_UMASK = os.umask(0)
os.umask(_UMASK)


class StorageError(RuntimeError):
    pass


class StorageUnavailableError(StorageError):
    """The backing CLI tool (``hdfs``/``gsutil``) is not installed."""


def _run(cmd: List[str], input_bytes: Optional[bytes] = None) -> bytes:
    proc = subprocess.run(
        cmd, input=input_bytes, stdout=subprocess.PIPE, stderr=subprocess.PIPE
    )
    if proc.returncode != 0:
        raise StorageError(
            f"{' '.join(cmd[:3])}... failed ({proc.returncode}): "
            f"{proc.stderr.decode(errors='replace')[:500]}"
        )
    return proc.stdout


class StoragePath:
    """Base path handle. Use :func:`storage_path` to construct one."""

    scheme = ""

    def __init__(self, uri: str):
        self.uri = uri

    # -- navigation ---------------------------------------------------------
    def join(self, *parts: str) -> "StoragePath":
        return storage_path(posixpath.join(self.uri, *parts))

    @property
    def name(self) -> str:
        return posixpath.basename(self.uri.rstrip("/"))

    @property
    def parent(self) -> "StoragePath":
        return storage_path(posixpath.dirname(self.uri.rstrip("/")))

    def __str__(self) -> str:
        return self.uri

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.uri!r})"

    # -- operations (implemented per backend) -------------------------------
    def exists(self) -> bool:
        raise NotImplementedError

    def makedirs(self) -> None:
        raise NotImplementedError

    def read_bytes(self) -> bytes:
        raise NotImplementedError

    def write_bytes(self, data: bytes) -> None:
        """Atomic publish: readers never observe a partial file."""
        raise NotImplementedError

    def append_bytes(self, data: bytes) -> None:
        raise NotImplementedError

    def list(self) -> List[str]:
        """Basenames of directory children."""
        raise NotImplementedError

    def remove(self) -> None:
        raise NotImplementedError

    # -- shared conveniences -------------------------------------------------
    def read_text(self) -> str:
        return self.read_bytes().decode()

    def write_text(self, text: str) -> None:
        self.write_bytes(text.encode())


class DiskPath(StoragePath):
    scheme = "file"

    def exists(self) -> bool:
        return os.path.exists(self.uri)

    def makedirs(self) -> None:
        os.makedirs(self.uri, exist_ok=True)

    def read_bytes(self) -> bytes:
        with open(self.uri, "rb") as f:
            return f.read()

    def write_bytes(self, data: bytes) -> None:
        d = os.path.dirname(self.uri) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp_" + os.path.basename(self.uri))
        try:
            # mkstemp creates 0600; restore normal umask-derived permissions so
            # checkpoint dirs stay readable by other users/jobs
            os.fchmod(fd, 0o666 & ~_UMASK)
            with os.fdopen(fd, "wb") as f:
                f.write(data)
                # fsync BEFORE the rename: without it a power cut after the
                # replace can publish a zero-length file under the final
                # name (the rename is durable before the data is) — the
                # torn-checkpoint hole the durability layer exists to close
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.uri)
            try:
                dfd = os.open(d, os.O_RDONLY)
            except OSError:
                dfd = -1  # directory fsync unsupported — rename still atomic
            if dfd >= 0:
                try:
                    os.fsync(dfd)
                except OSError:
                    pass
                finally:
                    os.close(dfd)
        except BaseException:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise

    def append_bytes(self, data: bytes) -> None:
        with open(self.uri, "ab") as f:
            f.write(data)

    def list(self) -> List[str]:
        return sorted(os.listdir(self.uri))

    def remove(self) -> None:
        if os.path.isdir(self.uri):
            shutil.rmtree(self.uri)
        elif os.path.exists(self.uri):
            os.remove(self.uri)


class HdfsPath(StoragePath):
    """Shell-out to the Hadoop CLI, like the reference (`lib.rs:173-391`).

    The binary is resolved once per process: ``hdfs dfs`` preferred,
    ``hadoop fs`` fallback (the reference uses both spellings)."""

    scheme = "hdfs"
    _cli: Optional[List[str]] = None

    @classmethod
    def cli(cls) -> List[str]:
        if cls._cli is None:
            if shutil.which("hdfs"):
                cls._cli = ["hdfs", "dfs"]
            elif shutil.which("hadoop"):
                cls._cli = ["hadoop", "fs"]
            else:
                raise StorageUnavailableError(
                    "hdfs:// path used but neither `hdfs` nor `hadoop` is on PATH"
                )
        return cls._cli

    def exists(self) -> bool:
        proc = subprocess.run(
            self.cli() + ["-test", "-e", self.uri],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        return proc.returncode == 0

    def makedirs(self) -> None:
        _run(self.cli() + ["-mkdir", "-p", self.uri])

    def read_bytes(self) -> bytes:
        return _run(self.cli() + ["-cat", self.uri])

    def write_bytes(self, data: bytes) -> None:
        # stage locally, put to a tmp name, rename — atomic for a fresh
        # destination. HDFS `-mv` refuses to overwrite, so replacing an
        # existing file needs rm+mv; that window is unavoidable through the
        # CLI and is only entered when the destination verifiably exists.
        # unique per writer: replicas publishing the same path (e.g. the
        # shared done-marker) must not collide on the staging name
        tmp_remote = f"{self.uri}.tmp_put.{os.getpid()}_{uuid.uuid4().hex[:8]}"
        try:
            with tempfile.NamedTemporaryFile() as f:
                f.write(data)
                f.flush()
                _run(self.cli() + ["-put", "-f", f.name, tmp_remote])
            proc = subprocess.run(
                self.cli() + ["-mv", tmp_remote, self.uri],
                stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE,
            )
            if proc.returncode != 0:
                if not self.exists():
                    # transient failure, not an overwrite refusal — don't
                    # touch the destination
                    raise StorageError(
                        f"hdfs mv {tmp_remote} -> {self.uri} failed: "
                        f"{proc.stderr.decode(errors='replace')[:500]}"
                    )
                _run(self.cli() + ["-rm", "-f", self.uri])
                _run(self.cli() + ["-mv", tmp_remote, self.uri])
        except BaseException:
            # the unique staging name is never reclaimed by later writes —
            # sweep it so retry loops can't litter the directory
            subprocess.run(
                self.cli() + ["-rm", "-f", tmp_remote],
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            )
            raise

    def append_bytes(self, data: bytes) -> None:
        _run(self.cli() + ["-appendToFile", "-", self.uri], input_bytes=data)

    def list(self) -> List[str]:
        out = _run(self.cli() + ["-ls", self.uri]).decode()
        names = []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) >= 8 and parts[-1].startswith(("hdfs://", "/")):
                names.append(posixpath.basename(parts[-1]))
        return sorted(names)

    def remove(self) -> None:
        _run(self.cli() + ["-rm", "-r", "-f", self.uri])


class GcsPath(StoragePath):
    """Shell-out to ``gsutil`` for ``gs://`` object paths. Objects have no
    real directories: ``makedirs`` is a no-op, ``list`` globs the prefix."""

    scheme = "gs"
    _cli: Optional[str] = None

    @classmethod
    def cli(cls) -> str:
        if cls._cli is None:
            cls._cli = shutil.which("gsutil") or ""
        if not cls._cli:
            raise StorageUnavailableError("gs:// path used but `gsutil` is not on PATH")
        return cls._cli

    def exists(self) -> bool:
        proc = subprocess.run(
            [self.cli(), "-q", "stat", self.uri],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        if proc.returncode == 0:
            return True
        # maybe a "directory" (prefix with children)
        proc = subprocess.run(
            [self.cli(), "ls", self.uri.rstrip("/") + "/"],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        return proc.returncode == 0

    def makedirs(self) -> None:
        pass

    def read_bytes(self) -> bytes:
        return _run([self.cli(), "cp", self.uri, "-"])

    def write_bytes(self, data: bytes) -> None:
        # GCS object writes are already atomic (visible only on completion)
        _run([self.cli(), "cp", "-", self.uri], input_bytes=data)

    def append_bytes(self, data: bytes) -> None:
        # objects are immutable: read-modify-write (compose would need two objects)
        old = self.read_bytes() if self.exists() else b""
        self.write_bytes(old + data)

    @staticmethod
    def _is_no_match(stderr: bytes) -> bool:
        return b"matched no objects" in stderr or b"No URLs matched" in stderr

    def list(self) -> List[str]:
        proc = subprocess.run(
            [self.cli(), "ls", self.uri.rstrip("/") + "/"],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
        )
        if proc.returncode != 0:
            if self._is_no_match(proc.stderr):
                return []  # empty prefix — a fresh "directory"
            raise StorageError(
                f"gsutil ls {self.uri} failed: "
                f"{proc.stderr.decode(errors='replace')[:500]}"
            )
        return sorted(
            posixpath.basename(line.rstrip("/"))
            for line in proc.stdout.decode().splitlines()
            if line.strip()
        )

    def remove(self) -> None:
        proc = subprocess.run(
            [self.cli(), "-m", "rm", "-r", "-f", self.uri],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE,
        )
        # not-found is fine (remove is idempotent); real failures must raise —
        # dump_store relies on remove() to invalidate a stale done-marker
        if proc.returncode != 0 and not self._is_no_match(proc.stderr):
            raise StorageError(
                f"gsutil rm {self.uri} failed: "
                f"{proc.stderr.decode(errors='replace')[:500]}"
            )


def storage_path(uri: Union[str, StoragePath]) -> StoragePath:
    """Factory: dispatch a URI to its backend (ref: PersiaPath enum dispatch,
    persia-storage/src/lib.rs:12-69)."""
    if isinstance(uri, StoragePath):
        return uri
    if uri.startswith("hdfs://"):
        return HdfsPath(uri)
    if uri.startswith("gs://"):
        return GcsPath(uri)
    if uri.startswith("file://"):
        return DiskPath(uri[len("file://"):])
    return DiskPath(uri)

"""One-command local train-to-serve topology.

The pieces of the online continuous-learning loop — trainer, incremental
delta channel, checkpoint rollover, serving replicas, gateway — each run
standalone, but bringing them up together used to take a page of glue.
This module is that glue, in three layers:

- **role entries** (``python -m persia_tpu.topology trainer|replica ...``):
  a demo trainer (synthetic zipf-skewed click stream, in-process embedding
  store, jobstate fences for crash-consistent auto-resume, incremental
  packets + periodic checkpoints published on a cadence) and a demo
  serving replica (ServingServer: micro-batcher, hot cache, rollover
  watcher + live delta consumption, freshness export). Both build the
  SAME deterministic model spec, so a replica can deserialize any
  trainer checkpoint;
- :class:`LocalTopology` — spawns K trainers + R replica subprocesses
  (optionally a ServiceCtx PS/worker tier as the discovery fabric),
  fronts the replicas with a staleness-aware :class:`ReplicaGateway`,
  auto-restarts crashed trainers (the jobstate resume path), and exposes
  the fault hooks the chaos soak drives (kill/restart any component,
  per-replica delta-channel faults via ``chaos.DeltaChannelChaos``);
- the ``persia-tpu-launcher local`` subcommand (persia_tpu/launcher.py)
  wraps :class:`LocalTopology` for the README quickstart;
  ``benchmarks/online_bench.py`` drives the same class under chaos for
  the flagship artifact.

Everything is CPU-host friendly (``JAX_PLATFORMS=cpu`` is forced into
children) — the point is the topology, not the chip.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from persia_tpu.logger import get_default_logger

logger = get_default_logger("persia_tpu.topology")

# demo model spec — shared by every role so checkpoints deserialize anywhere
N_SLOTS = 4
EMB_DIM = 8
N_DENSE = 4
READY_LINE = "TOPOLOGY_REPLICA_READY"


def build_demo_ctx(seed: int = 7, capacity: int = 1 << 16):
    """Deterministic (TrainCtx, EmbeddingConfig) every topology role shares."""
    import optax

    from persia_tpu.config import EmbeddingConfig, SlotConfig
    from persia_tpu.ctx import TrainCtx
    from persia_tpu.embedding.optim import Adagrad
    from persia_tpu.embedding.store import EmbeddingStore
    from persia_tpu.embedding.worker import EmbeddingWorker
    from persia_tpu.models import DNN

    cfg = EmbeddingConfig(
        slots_config={f"cat_{i}": SlotConfig(dim=EMB_DIM) for i in range(N_SLOTS)},
        feature_index_prefix_bit=8,
    )
    store = EmbeddingStore(capacity=capacity, num_internal_shards=4,
                           optimizer=Adagrad(lr=0.1).config, seed=seed)
    worker = EmbeddingWorker(cfg, [store])
    ctx = TrainCtx(
        model=DNN(dense_mlp_size=16, sparse_mlp_size=32, hidden_sizes=(32,)),
        dense_optimizer=optax.adam(3e-3),
        embedding_optimizer=Adagrad(lr=0.1),
        worker=worker,
        embedding_config=cfg,
    )
    return ctx, cfg


def demo_batch(step: int, rows: int, vocab: int, seed: int = 0,
               publisher: int = 0, requires_grad: bool = True):
    """One deterministic zipf-skewed training batch: the stream regenerates
    identically after a trainer crash-resume (batch N is a pure function of
    N), and publisher ``k`` owns the id range ``[k*vocab, (k+1)*vocab)`` so
    multiple trainers partition the user space instead of fighting over it."""
    from persia_tpu.data import (
        IDTypeFeatureWithSingleID,
        Label,
        NonIDTypeFeature,
        PersiaBatch,
    )

    rng = np.random.default_rng(seed * 1_000_003 + step * 2 + publisher * 977)
    base = np.uint64(publisher * vocab)
    ids = [
        IDTypeFeatureWithSingleID(
            f"cat_{i}",
            base + ((rng.zipf(1.2, rows).astype(np.uint64)
                     + np.uint64(i * 1000)) % vocab),
        )
        for i in range(N_SLOTS)
    ]
    return PersiaBatch(
        ids,
        non_id_type_features=[NonIDTypeFeature(
            rng.normal(size=(rows, N_DENSE)).astype(np.float32))],
        labels=[Label(rng.integers(0, 2, (rows, 1)).astype(np.float32))],
        requires_grad=requires_grad,
    )


def _arm_telemetry(role: str, trace_dir: Optional[str] = None) -> Optional[int]:
    """Arm a role's telemetry plane when the parent asked for one
    (``PERSIA_TRACE_DIR`` in the child env, or an explicit ``trace_dir``):
    enable tracing tagged with ``role``, serve ``/metrics`` + ``/spans`` +
    ``/flight`` on a loopback port advertised through an atomic
    ``<role>.endpoint`` file in the trace dir, arm the flight recorder,
    and export the span ring on exit. Returns the bound port (None when
    telemetry is off)."""
    trace_dir = trace_dir or os.environ.get("PERSIA_TRACE_DIR")
    if not trace_dir:
        return None
    from persia_tpu import tracing
    from persia_tpu.metrics import get_metrics

    os.makedirs(trace_dir, exist_ok=True)
    tracing.enable(True)
    tracing.set_role(role)
    tracing.install_flight_recorder(
        os.path.join(trace_dir, f"{role}.flight.json")
    )
    port = get_metrics().serve_http(0, host="127.0.0.1")
    ep = os.path.join(trace_dir, f"{role}.endpoint")
    tmp = f"{ep}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"role": role, "pid": os.getpid(), "port": port}, f)
    os.replace(tmp, ep)  # atomic: the collector never reads a torn file
    tracing.arm_trace_export(os.path.join(trace_dir, f"{role}.trace.json"))
    logger.info("telemetry armed for %s on 127.0.0.1:%d", role, port)
    return port


def _annotate_checkpoint_step(ckpt_dir: str, step: int) -> None:
    """Stamp the trainer's committed step onto the checkpoint done-marker:
    a replica resyncing from this checkpoint reports the step as its
    freshness floor (serving/rollover.py reads ``train_step``)."""
    from persia_tpu.checkpoint import DONE_MARKER as CKPT_DONE
    from persia_tpu.storage import StorageError, storage_path

    try:
        p = storage_path(ckpt_dir).join(CKPT_DONE)
        info = json.loads(p.read_text())
        info["train_step"] = int(step)
        p.write_text(json.dumps(info))
    except (StorageError, OSError, ValueError) as e:
        logger.warning("could not annotate checkpoint step: %s", e)


# ------------------------------------------------------------ trainer role


def trainer_main(argv: Optional[List[str]] = None) -> int:
    """Demo online trainer: train the synthetic stream, publish incremental
    packets every ``--flush-every`` steps, a full checkpoint every
    ``--ckpt-every``, a jobstate fence every ``--snapshot-every`` — and
    resume all three exactly where a crash left them."""
    ap = argparse.ArgumentParser("persia-topology-trainer")
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--inc-dir", required=True)
    ap.add_argument("--job-state-dir", default=None)
    ap.add_argument("--progress-file", default=None,
                    help="per-step beacon for external killers (chaos.py)")
    ap.add_argument("--steps", type=int, default=1000)
    ap.add_argument("--rows", type=int, default=32)
    ap.add_argument("--vocab", type=int, default=100_000)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--publisher-index", type=int, default=0)
    ap.add_argument("--flush-every", type=int, default=5)
    ap.add_argument("--ckpt-every", type=int, default=200,
                    help="0 = this trainer never dumps full checkpoints")
    ap.add_argument("--snapshot-every", type=int, default=50)
    ap.add_argument("--step-ms", type=float, default=0.0,
                    help="pace the loop (an online trainer is rate-driven)")
    args = ap.parse_args(argv)

    from persia_tpu.chaos import write_progress
    from persia_tpu.health import health_enabled
    from persia_tpu.health.scrub import scrub_router
    from persia_tpu.incremental import attach_incremental
    from persia_tpu.parallel.train_step import _note_nonfinite_loss

    _arm_telemetry(f"trainer{args.publisher_index}")
    ctx, _cfg = build_demo_ctx(seed=args.seed)
    store = ctx.worker.lookup_router.replicas[0]
    with ctx:
        if args.job_state_dir:
            manifest = ctx.resume(args.job_state_dir, restore_ps=True)
            if manifest is not None:
                logger.info("trainer resumed at step %d", ctx._global_step)
        mgr = attach_incremental(
            store, args.inc_dir, replica_index=args.publisher_index,
            flush_interval_sec=3600.0,  # cadence is step-driven below
        )
        mgr.note_step(ctx._global_step)
        start = ctx._global_step
        sentinel_armed = health_enabled()
        for step in range(start, args.steps):
            out = ctx.train_step(demo_batch(step, args.rows, args.vocab,
                                            seed=args.seed,
                                            publisher=args.publisher_index))
            if sentinel_armed and isinstance(out, dict) and "loss" in out:
                _note_nonfinite_loss(float(out["loss"]))
            done = step + 1
            mgr.note_step(done)
            if args.progress_file:
                write_progress(args.progress_file, done)
            if args.flush_every and done % args.flush_every == 0:
                mgr.flush()
            if args.snapshot_every and args.job_state_dir and \
                    done % args.snapshot_every == 0:
                if sentinel_armed:
                    # fence-point scrub: repair any non-finite PS row
                    # BEFORE it can be captured into LAST_GOOD
                    scrub_router(ctx.worker.lookup_router,
                                 getattr(ctx, "_job_epoch", 0) or 0, done)
                ctx.snapshot_job(args.job_state_dir)
            if args.ckpt_every and args.ckpt_dir and done % args.ckpt_every == 0:
                ctx.dump_checkpoint(args.ckpt_dir)
                _annotate_checkpoint_step(args.ckpt_dir, done)
                mgr.flush()
            if args.step_ms > 0:
                time.sleep(args.step_ms / 1e3)
        mgr.stop(final_flush=True)
        if args.ckpt_dir:
            ctx.dump_checkpoint(args.ckpt_dir)
            _annotate_checkpoint_step(args.ckpt_dir, ctx._global_step)
        if args.job_state_dir:
            ctx.snapshot_job(args.job_state_dir)
    return 0


# ------------------------------------------------------------ replica role


def replica_main(argv: Optional[List[str]] = None) -> int:
    """Demo serving replica: ServingServer with the hot cache, the rollover
    watcher, and the live delta channel armed; registers with a coordinator
    when one is given and prints a READY line with its port."""
    ap = argparse.ArgumentParser("persia-topology-replica")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--inc-dir", default=None)
    ap.add_argument("--replica-index", type=int, default=0)
    ap.add_argument("--coordinator", default=None)
    ap.add_argument("--cache-rows", type=int, default=1 << 15)
    ap.add_argument("--poll-s", type=float, default=0.2)
    ap.add_argument("--vocab", type=int, default=100_000)
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args(argv)

    import jax

    from persia_tpu.ctx import InferCtx
    from persia_tpu.serving import ServingServer

    _arm_telemetry(f"replica{args.replica_index}")
    train_ctx, cfg = build_demo_ctx(seed=args.seed)
    # initialize dense shapes off one sample batch; the rollover watcher
    # overlays real weights the moment a checkpoint marker lands
    sample = demo_batch(0, 8, args.vocab, seed=args.seed, requires_grad=False)
    emb = train_ctx.worker.forward_directly(sample, train=False)
    device_batch, _ = train_ctx.prepare_features(sample, emb)
    train_ctx.init_state(jax.random.PRNGKey(0), device_batch)

    ctx = InferCtx(model=train_ctx.model, state=train_ctx.state,
                   worker=train_ctx.worker, embedding_config=cfg)
    srv = ServingServer(
        ctx,
        port=args.port,
        max_batch=256,
        max_wait_ms=2.0,
        cache_rows=args.cache_rows,
        ckpt_dir=args.ckpt_dir,
        inc_dir=args.inc_dir,
        rollover_poll_s=args.poll_s,
        coordinator=args.coordinator,
        replica_index=args.replica_index,
    ).start()
    print(f"{READY_LINE} port={srv.port}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    try:
        while not stop.wait(0.5):
            pass
    except KeyboardInterrupt:
        pass
    srv.stop()
    return 0


# ------------------------------------------------------------- the topology


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class LocalTopology:
    """One-command local cluster: K trainers + R serving replicas + a
    staleness-aware gateway (+ an optional ServiceCtx PS/worker tier as the
    discovery fabric). Every component is a real subprocess, so the chaos
    hooks (:meth:`kill_trainer` / :meth:`kill_replica` / the delta relay)
    inject the same faults production sees.

    ``delta_chaos`` (a ``chaos.ChaosConfig`` or True) routes each replica's
    delta channel through a :class:`~persia_tpu.chaos.DeltaChannelChaos`
    relay — per-replica corrupt/torn/drop faults and blackhole windows;
    without it all replicas scan the trainer's packet dir directly.
    """

    def __init__(
        self,
        ps: int = 0,
        workers: int = 0,
        trainers: int = 1,
        replicas: int = 2,
        base_dir: Optional[str] = None,
        steps: int = 2000,
        rows: int = 32,
        vocab: int = 100_000,
        step_ms: float = 5.0,
        flush_every: int = 5,
        ckpt_every: int = 200,
        snapshot_every: int = 50,
        cache_rows: int = 1 << 15,
        replica_poll_s: float = 0.2,
        max_staleness_steps: Optional[int] = None,
        max_staleness_s: Optional[float] = None,
        health_interval_s: float = 0.5,
        auto_resume: bool = True,
        max_restarts: int = 10,
        delta_chaos=None,
        seed: int = 7,
        startup_timeout_s: float = 120.0,
        trace_dir: Optional[str] = None,
    ):
        import tempfile

        self.n_ps, self.n_workers = ps, workers
        self.n_trainers, self.n_replicas = max(1, trainers), max(1, replicas)
        self.base_dir = base_dir or tempfile.mkdtemp(prefix="persia_local_")
        self.ckpt_dir = os.path.join(self.base_dir, "ckpt")
        self.inc_dir = os.path.join(self.base_dir, "inc")
        self.jobstate_dir = os.path.join(self.base_dir, "jobstate")
        for d in (self.ckpt_dir, self.inc_dir, self.jobstate_dir):
            os.makedirs(d, exist_ok=True)
        self.steps, self.rows, self.vocab = steps, rows, vocab
        self.step_ms = step_ms
        self.flush_every, self.ckpt_every = flush_every, ckpt_every
        self.snapshot_every = snapshot_every
        self.cache_rows, self.replica_poll_s = cache_rows, replica_poll_s
        self.max_staleness_steps = max_staleness_steps
        self.max_staleness_s = max_staleness_s
        self.health_interval_s = health_interval_s
        self.auto_resume, self.max_restarts = auto_resume, max_restarts
        self.seed = seed
        self.startup_timeout_s = startup_timeout_s
        self.svc = None
        self.gateway = None
        self.delta_chaos = None
        self._delta_cfg = delta_chaos
        self._trainer_procs: List[subprocess.Popen] = []
        self._replica_procs: List[Optional[subprocess.Popen]] = []
        self.replica_ports: List[int] = []
        self.trainer_restarts = 0
        self._expected_dead: set = set()
        self._watch_stop = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None
        self.autopilot = None
        self._ap_stop = threading.Event()
        self._ap_thread: Optional[threading.Thread] = None
        self.healer = None
        self._env = dict(os.environ, JAX_PLATFORMS="cpu")
        self._env["PYTHONPATH"] = (
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            + os.pathsep + self._env.get("PYTHONPATH", "")
        )
        self.trace_dir = trace_dir
        if self.trace_dir:
            os.makedirs(self.trace_dir, exist_ok=True)
            # children inherit the telemetry contract through the env:
            # every role arms tracing + its /spans endpoint on boot
            self._env["PERSIA_TRACE"] = "1"
            self._env["PERSIA_TRACE_DIR"] = self.trace_dir
        # data-plane health sentinel armed by default in the demo fleet:
        # health.* events (scrub at fences, anomalies) land in the merged
        # trace alongside the fence/rollover events they correlate with
        self._env.setdefault("PERSIA_HEALTH", "1")

    # -------------------------------------------------------------- lifecycle

    def __enter__(self) -> "LocalTopology":
        try:
            return self._enter_impl()
        except BaseException:
            self.stop()
            raise

    def _enter_impl(self) -> "LocalTopology":
        from persia_tpu.serving import InferenceClient, ReplicaGateway
        from persia_tpu.service.resilience import poll_until

        if self.trace_dir:
            # the parent process hosts the gateway (and the delta relay
            # under chaos): its spans and flight events join the fleet too
            _arm_telemetry("gateway", self.trace_dir)
        coordinator = None
        if self.n_ps > 0:
            from persia_tpu.helper import ServiceCtx

            self.svc = ServiceCtx(
                num_parameter_servers=self.n_ps,
                num_embedding_workers=self.n_workers,
                startup_timeout_s=self.startup_timeout_s,
            ).__enter__()
            coordinator = f"127.0.0.1:{self.svc.coordinator.port}"
        if self._delta_cfg:
            from persia_tpu.chaos import ChaosConfig, DeltaChannelChaos

            cfg = (self._delta_cfg if not isinstance(self._delta_cfg, bool)
                   else ChaosConfig())
            self.delta_chaos = DeltaChannelChaos(
                self.inc_dir, os.path.join(self.base_dir, "delta"),
                self.n_replicas, cfg=cfg, seed=self.seed,
            ).start(interval_s=min(0.2, self.replica_poll_s))
        for k in range(self.n_trainers):
            self._trainer_procs.append(self._spawn_trainer(k))
        for i in range(self.n_replicas):
            self.replica_ports.append(_free_port())
            self._replica_procs.append(
                self._spawn_replica(i, coordinator=coordinator)
            )
        # wait for every replica's health endpoint before fronting them
        for i, port in enumerate(self.replica_ports):
            cli = InferenceClient(f"127.0.0.1:{port}", timeout_s=5.0)
            poll_until(
                lambda c=cli: c.health().get("status") == "ok",
                timeout_s=self.startup_timeout_s,
                what=f"replica {i} health",
            )
        from persia_tpu.incremental import read_head

        self.gateway = ReplicaGateway(
            replicas=[f"127.0.0.1:{p}" for p in self.replica_ports],
            health_interval_s=self.health_interval_s,
            max_staleness_steps=self.max_staleness_steps,
            max_staleness_s=self.max_staleness_s,
            # the durable source dir is the head oracle: a partition that
            # freezes every replica's delta channel cannot also freeze the
            # staleness measurement
            head_source=lambda: read_head(self.inc_dir),
        ).start()
        if self.auto_resume:
            self._watch_thread = threading.Thread(
                target=self._watch, daemon=True, name="topology-watch"
            )
            self._watch_thread.start()
        return self

    def _spawn_trainer(self, k: int) -> subprocess.Popen:
        cmd = [
            sys.executable, "-m", "persia_tpu.topology", "trainer",
            "--inc-dir", self.inc_dir,
            "--job-state-dir", os.path.join(self.jobstate_dir, f"t{k}"),
            "--progress-file", self.progress_file(k),
            "--steps", str(self.steps), "--rows", str(self.rows),
            "--vocab", str(self.vocab), "--seed", str(self.seed),
            "--publisher-index", str(k),
            "--flush-every", str(self.flush_every),
            "--snapshot-every", str(self.snapshot_every),
            "--step-ms", str(self.step_ms),
            # only publisher 0 dumps full checkpoints: one writer per dir
            "--ckpt-every", str(self.ckpt_every if k == 0 else 0),
        ]
        if k == 0:
            cmd += ["--ckpt-dir", self.ckpt_dir]
        return subprocess.Popen(cmd, env=self._env)

    def _spawn_replica(self, i: int, coordinator=None) -> subprocess.Popen:
        inc = (self.delta_chaos.inc_dir(i) if self.delta_chaos is not None
               else self.inc_dir)
        cmd = [
            sys.executable, "-m", "persia_tpu.topology", "replica",
            "--port", str(self.replica_ports[i]),
            "--ckpt-dir", self.ckpt_dir, "--inc-dir", inc,
            "--replica-index", str(i),
            "--cache-rows", str(self.cache_rows),
            "--poll-s", str(self.replica_poll_s),
            "--vocab", str(self.vocab), "--seed", str(self.seed),
        ]
        if coordinator:
            cmd += ["--coordinator", coordinator]
        return subprocess.Popen(cmd, env=self._env)

    def progress_file(self, k: int = 0) -> str:
        return os.path.join(self.base_dir, f"progress_{k}")

    # ----------------------------------------------------------- chaos hooks

    def kill_trainer(self, k: int = 0) -> None:
        """SIGKILL trainer ``k`` mid-step; the watcher (auto_resume) brings
        it back through the jobstate resume path."""
        p = self._trainer_procs[k]
        p.kill()
        p.wait(timeout=30)

    def kill_replica(self, i: int) -> None:
        """SIGKILL replica ``i`` (possibly mid-packet-apply). Marked
        expected so the watcher leaves it down until restart_replica."""
        p = self._replica_procs[i]
        if p is not None:
            self._expected_dead.add(p.pid)
            p.kill()
            p.wait(timeout=30)

    def restart_replica(self, i: int) -> None:
        """Respawn replica ``i`` on its ORIGINAL port: it boots from the
        newest checkpoint, replays the retained delta tail, and the gateway
        heals it back into rotation when its breaker re-closes."""
        self._replica_procs[i] = self._spawn_replica(i)

    # ------------------------------------------------------------- autopilot

    def live_serving(self) -> List[int]:
        """Indices of serving replicas whose process is currently alive."""
        return [i for i, p in enumerate(self._replica_procs)
                if p is not None and p.poll() is None]

    def scale_serving(self, target: int) -> int:
        """Grow/shrink the live serving replica set to ``target`` — the
        autopilot's scale actuator. Shrink drains from the highest live
        index (kill + ``gateway.remove_replica``, so no new requests route
        there); grow reuses dead slots' original ports first (the healed
        replica boots from the newest checkpoint + delta tail) before
        allocating fresh ones, waits for ``/healthz``, then folds the
        address into the gateway's balance set. Idempotent: re-driving the
        same target converges without churn. This is a pure ACTUATOR —
        the flap guards (hysteresis margin + min-dwell, CTRL001) live
        upstream in ``autopilot.PolicyEngine.decide_scale``, which decides
        ``target``; nothing here re-decides. Returns the live count."""
        from persia_tpu.serving import InferenceClient
        from persia_tpu.service.resilience import poll_until

        target = max(1, int(target))
        coordinator = (f"127.0.0.1:{self.svc.coordinator.port}"
                       if self.svc is not None else None)
        live = self.live_serving()
        while len(live) > target:
            i = live.pop()
            addr = f"127.0.0.1:{self.replica_ports[i]}"
            logger.info("autopilot scale: draining serving replica %d", i)
            self.kill_replica(i)
            self._replica_procs[i] = None
            if self.gateway is not None:
                self.gateway.remove_replica(addr)
        while len(live) < target:
            dead = [i for i in range(len(self._replica_procs))
                    if i not in live]
            if dead:
                i = dead[0]
            else:
                i = len(self._replica_procs)
                self._replica_procs.append(None)
                self.replica_ports.append(_free_port())
            logger.info("autopilot scale: spawning serving replica %d", i)
            self._replica_procs[i] = self._spawn_replica(
                i, coordinator=coordinator
            )
            addr = f"127.0.0.1:{self.replica_ports[i]}"
            cli = InferenceClient(addr, timeout_s=5.0)
            poll_until(
                lambda c=cli: c.health().get("status") == "ok",
                timeout_s=self.startup_timeout_s,
                what=f"replica {i} health",
            )
            if self.gateway is not None:
                self.gateway.add_replica(addr)
            live.append(i)
        return len(live)

    def start_autopilot(self, interval_s: float = 2.0, config=None):
        """Arm the parent-side serving autopilot: a timer thread sensing
        the gateway (QPS, quarantine pressure) and actuating
        :meth:`scale_serving`, every decision two-phase-journaled under
        ``base_dir/autopilot`` and resumed on re-arm. The PS-reshard and
        hot-replication actuators are fence-driven and live INSIDE the
        trainer (``train_stream(fence_callback=pilot.on_fence)``, see
        persia_tpu/autopilot) — this thread covers the serving plane,
        whose control loop has no fence to ride. All flap suppression
        (hysteresis margin + min-dwell) happens in the shared
        :class:`~persia_tpu.autopilot.PolicyEngine` on the decision path,
        never here."""
        from persia_tpu.autopilot import (
            Autopilot, PolicyConfig, PolicyEngine, gateway_sensors,
        )

        self.autopilot = Autopilot(
            os.path.join(self.base_dir, "autopilot", "decisions"),
            policy=PolicyEngine(config or PolicyConfig()),
            scale_to=self.scale_serving,
            serving_sensors=gateway_sensors(self.gateway),
        )
        self.autopilot.resume()

        def _loop() -> None:
            tick = 0
            while not self._ap_stop.wait(interval_s):
                tick += 1
                try:
                    self.autopilot.on_tick(tick)
                except Exception:
                    logger.exception("autopilot tick %d failed", tick)

        self._ap_thread = threading.Thread(
            target=_loop, daemon=True, name="autopilot"
        )
        self._ap_thread.start()
        return self.autopilot

    def start_self_heal(self, interval_s: float = 0.5, **kw):
        """Arm the self-healing control plane over the PS tier (needs
        ``ps > 0``): a lease+probe :class:`FailureDetector` feeding a
        :class:`~persia_tpu.autopilot.Healer` whose decisions journal
        under ``base_dir/selfheal`` — a SIGKILLed PS is detected, a warm
        standby promoted from the last fence snapshot, and the fleet
        registration re-pointed, with no operator in the loop. Any heal
        interrupted by a parent crash is re-driven by ``resume()`` on
        re-arm. Extra ``**kw`` forwards to
        :func:`~persia_tpu.autopilot.enable_self_heal` (router, configs,
        sensors...)."""
        from persia_tpu.autopilot import enable_self_heal

        if self.svc is None:
            raise RuntimeError("start_self_heal needs a PS tier (ps > 0)")
        if self.healer is None:
            state = os.path.join(self.base_dir, "selfheal")
            os.makedirs(state, exist_ok=True)
            self.healer = enable_self_heal(self.svc, state, **kw)
            self.healer.resume()
            self.healer.start(interval_s)
        return self.healer

    def reshard_ps(self, n_new: int, **kw) -> Dict:
        """Live-reshard the PS tier to ``n_new`` replicas (needs ``ps > 0``):
        delegates to :meth:`ServiceCtx.reshard_ps` with a journal dir under
        this topology's base_dir, so an interrupted reshard resumes through
        ``self.svc.resume_reshard`` against the same manifests. Accepts the
        same keyword knobs (``planner``/``profiler``/``router``/
        ``fault_hook``/...)."""
        if self.svc is None:
            raise RuntimeError("reshard_ps needs a PS tier (ps > 0)")
        js = os.path.join(self.base_dir, "reshard_js")
        os.makedirs(js, exist_ok=True)
        return self.svc.reshard_ps(n_new, js, **kw)

    def _watch(self) -> None:
        while not self._watch_stop.wait(0.3):
            for k, p in enumerate(self._trainer_procs):
                rc = p.poll()
                if rc is not None and rc != 0 and p.pid not in self._expected_dead:
                    if self.trainer_restarts >= self.max_restarts:
                        logger.error("trainer %d dead (rc=%s); restart budget "
                                     "exhausted", k, rc)
                        self._expected_dead.add(p.pid)
                        continue
                    self.trainer_restarts += 1
                    logger.warning(
                        "trainer %d died (rc=%s); auto-resume %d/%d",
                        k, rc, self.trainer_restarts, self.max_restarts,
                    )
                    self._trainer_procs[k] = self._spawn_trainer(k)

    # ----------------------------------------------------------------- state

    def trainer_running(self) -> bool:
        return any(p.poll() is None for p in self._trainer_procs)

    def trainer_step(self, k: int = 0) -> int:
        from persia_tpu.chaos import read_progress

        return read_progress(self.progress_file(k))

    def stats(self) -> Dict:
        out = {
            "trainer_steps": [self.trainer_step(k)
                              for k in range(self.n_trainers)],
            "trainer_restarts": self.trainer_restarts,
            "replica_ports": list(self.replica_ports),
        }
        if self.gateway is not None:
            out["gateway"] = self.gateway.stats()
        if self.delta_chaos is not None:
            out["delta_channel"] = dict(self.delta_chaos.counts)
        if self.autopilot is not None:
            out["autopilot_rounds"] = self.autopilot.rounds
        if self.healer is not None:
            out["heal_verdicts"] = self.healer.detector.verdicts()
            out["heal_mttr_s"] = list(self.healer.mttr_s)
        if self.svc is not None:
            out["n_ps"] = self.svc.n_ps
            if self.svc.ps_ring is not None:
                out["ps_ring"] = [int(x) for x in self.svc.ps_ring]
        return out

    # ------------------------------------------------------------- telemetry

    def telemetry_endpoints(self) -> Dict[str, Dict]:
        """``role -> {pid, port}`` read from the atomic ``<role>.endpoint``
        files every armed role writes on boot (empty when tracing is off)."""
        out: Dict[str, Dict] = {}
        if not self.trace_dir:
            return out
        for fn in sorted(os.listdir(self.trace_dir)):
            if not fn.endswith(".endpoint"):
                continue
            try:
                with open(os.path.join(self.trace_dir, fn)) as f:
                    info = json.load(f)
                out[str(info["role"])] = {
                    "pid": int(info["pid"]), "port": int(info["port"]),
                }
            except (OSError, ValueError, KeyError):
                continue
        return out

    @staticmethod
    def _scrape(port: int, path: str, drain: bool = False):
        """GET one telemetry endpoint; returns ``(doc, offset_us)`` where
        ``offset_us`` is the remote clock minus the local clock, estimated
        from the remote ``now_us`` sample against the local midpoint of the
        request (the classic NTP-style half-RTT handshake)."""
        import urllib.request

        url = f"http://127.0.0.1:{port}{path}" + ("?drain=1" if drain else "")
        t0 = time.time()
        with urllib.request.urlopen(url, timeout=5) as resp:
            doc = json.loads(resp.read())
        t1 = time.time()
        offset_us = float(doc.get("now_us", 0.0)) - (t0 + t1) / 2.0 * 1e6
        return doc, offset_us

    def _role_events(self, role: str, info: Dict, kind: str, drain: bool):
        """One role's span (or flight) events, clock-aligned into THIS
        process's wall clock. A dead role falls back to the trace file its
        atexit export left behind (offset 0 — same host, same clock)."""
        try:
            doc, offset_us = self._scrape(
                info["port"], f"/{kind}", drain=drain
            )
            return doc.get("spans" if kind == "spans" else "events", []), \
                offset_us
        except (OSError, ValueError):
            if kind != "spans":
                # a finished role's flight ring lives in its atexit dump
                # (trainers exit long before the merge; their health.* /
                # fence events must still make the ledger)
                path = os.path.join(self.trace_dir, f"{role}.flight.json")
                try:
                    with open(path) as f:
                        return json.load(f).get("events", []), 0.0
                except (OSError, ValueError):
                    return [], 0.0
            path = os.path.join(self.trace_dir, f"{role}.trace.json")
            try:
                with open(path) as f:
                    return json.load(f).get("traceEvents", []), 0.0
            except (OSError, ValueError):
                return [], 0.0

    def merge_traces(self, out_path: Optional[str] = None,
                     drain: bool = False) -> Optional[str]:
        """Fleet aggregation: scrape every role's ``/spans`` ring, align
        clocks via the offset handshake, and write ONE Perfetto-loadable
        timeline (plus a merged flight-event ledger) into the trace dir.
        Returns the merged trace path (None when tracing is off)."""
        from persia_tpu import tracing

        if not self.trace_dir:
            return None
        merged: List[Dict] = []
        flight: List[Dict] = []
        meta: List[Dict] = []
        offsets: Dict[str, float] = {}
        for role, info in sorted(self.telemetry_endpoints().items()):
            events, offset_us = self._role_events(role, info, "spans", drain)
            offsets[role] = offset_us
            for ev in events:
                ev = dict(ev)
                ev["ts"] = float(ev.get("ts", 0.0)) - offset_us
                merged.append(ev)
            fl, f_off = self._role_events(role, info, "flight", drain)
            for ev in fl:
                ev = dict(ev)
                ev["ts_us"] = float(ev.get("ts_us", 0.0)) - f_off
                ev["role"] = role
                flight.append(ev)
            # Perfetto names each process track after its role
            meta.append({
                "name": "process_name", "ph": "M", "pid": info["pid"],
                "args": {"name": role},
            })
        merged.sort(key=lambda ev: ev.get("ts", 0.0))
        flight.sort(key=lambda ev: ev.get("ts_us", 0.0))
        doc = {
            "traceEvents": meta + merged,
            "displayTimeUnit": "ms",
            "metadata": {
                "merged_by_pid": os.getpid(),
                "clock_offsets_us": offsets,
                "roles": sorted(offsets),
            },
        }
        out = out_path or os.path.join(self.trace_dir, "merged_trace.json")
        tracing._atomic_write_json(out, doc)
        tracing._atomic_write_json(
            os.path.join(self.trace_dir, "merged_flight.json"),
            {"events": flight},
        )
        logger.info("merged %d spans + %d flight events from %d roles -> %s",
                    len(merged), len(flight), len(offsets), out)
        return out

    def stop(self) -> None:
        if self.healer is not None:
            self.healer.stop()
            self.healer.detector.close()
            self.healer = None
        self._ap_stop.set()
        if self._ap_thread is not None:
            self._ap_thread.join(timeout=5)
        self._watch_stop.set()
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=5)
        if self.gateway is not None:
            self.gateway.stop()
        if self.delta_chaos is not None:
            self.delta_chaos.stop()
        procs = [p for p in self._trainer_procs if p is not None]
        procs += [p for p in self._replica_procs if p is not None]
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + 10
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
        if self.svc is not None:
            self.svc.__exit__(None, None, None)
            self.svc = None

    def __exit__(self, *exc):
        self.stop()
        return False


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print("usage: python -m persia_tpu.topology {trainer|replica} ...",
              file=sys.stderr)
        return 2
    role, rest = argv[0], argv[1:]
    if role == "trainer":
        return trainer_main(rest)
    if role == "replica":
        return replica_main(rest)
    print(f"unknown topology role {role!r}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())

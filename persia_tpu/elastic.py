"""Elastic PS tier: live shard resharding with exactly-once handoff.

``ServiceCtx.reshard_ps(n)`` (helper.py) adds or removes parameter-server
replicas mid-job. This module is the transport-agnostic engine underneath:
given the old and new ring (``hashing.uniform_splits`` or a sparsity-aware
:class:`~persia_tpu.embedding.tiering.shard_planner.ShardPlanner` plan), it
moves exactly the sign ranges whose ownership changes, under the same
exactly-once journal discipline PR 5 built for gradient batches:

- Every handoff op (range import, range delete) carries a
  :func:`~persia_tpu.jobstate.handoff_journal_id` — the 0x80 low-byte
  namespace of the PS apply-journal, so a resumed reshard replaying its op
  list dedupes against what the crashed run already applied, and can never
  collide with a gradient batch's per-replica id.
- ``export_range`` is read-only and byte-deterministic (sign-sorted), so a
  re-export after a source restore produces the identical blob and crc; an
  import probe of ``-1`` (id known, crc differs) means the source range was
  already released by phase 2 — the original import stands and the replay
  skips it.

Crash matrix (the flagship chaos test kills at every point):

==================  =========================================================
victim / phase      recovery
==================  =========================================================
source, handoff     restore from the fence snapshot in the ``handoff``
                    manifest (:func:`source_snapshot`), re-run the plan —
                    re-exports are bit-identical, imports dedupe.
dest, handoff       restart FRESH (its journal died with it); re-imports
                    re-apply the identical blobs.
dest, imported      restore from the post-import snapshot in the
                    ``imported`` manifest (:func:`dest_snapshot`); remaining
                    deletes re-apply (idempotent) or dedupe.
coordinator, any    the phase-fenced manifests are durable; a new process
                    calls :func:`resume_reshard` and re-executes from the
                    recorded phase — journal ids are recomputed from the
                    recorded ``base_id`` + deterministic move order, so
                    every already-applied op dedupes.
coordinator,        the ``aborting`` manifest is durable before the first
aborting            rollback release; :func:`resume_reshard` re-enters the
                    abort arm, every already-released arc dedupes via its
                    :func:`~persia_tpu.jobstate.abort_journal_id`, and the
                    terminal ``aborted`` manifest commits — bit-identical
                    to an uninterrupted abort.
==================  =========================================================

Phase order is what makes the matrix closed: the ``handoff`` manifest
(fence snapshot of every source) commits BEFORE the first import; the
``imported`` manifest (post-import snapshot of every dest) commits before
the first delete; the ``done`` manifest commits last. Until ``done``, the
reshard is visibly incomplete and :func:`find_reshard_manifest` will hand
it to the resume path.

ABORT arm (PR 20): a higher-priority control-plane intent (a HEAL under
the :mod:`persia_tpu.autopilot.arbiter` lease) may preempt an in-flight
reshard at a phase boundary. ``execute_reshard(abort_check=...)`` polls
the check after the ``handoff`` commit and again after the imports; the
``imported`` commit is the point of no return — past it the router swap
is the cheaper path and the protocol rolls FORWARD. An abort commits an
``aborting`` manifest, releases every partially imported arc on its
destination through journaled range deletes in the dedicated abort
journal-id namespace (exactly-once under SIGKILL+resume), then commits
the terminal ``aborted`` manifest and raises :class:`ReshardAborted`.
Only ring→ring plans are abortable: under a modulo bootstrap the moved
arcs overlap entries the destinations legitimately own, so a rollback
range-delete would destroy live data — ``plan.abortable`` is False and
the preemption request is ignored (the protocol runs to ``done``).

The caller guarantees the FENCE invariant: the training stream is drained
(no in-flight lookups/updates against the moving ranges) for the duration.
The router swap (``ShardedLookup.swap_topology``) happens at the
``imported`` boundary — entries exist on BOTH the old and new owner until
the deletes run, so lookups racing the tail of the reshard still hit live
data whichever ring they routed by.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from persia_tpu import jobstate, tracing
from persia_tpu.analysis.crashcheck import reach
from persia_tpu.logger import get_default_logger
from persia_tpu.metrics import get_metrics

logger = get_default_logger("persia_tpu.elastic")

_RING = 1 << 64
# handoff_journal_id's op_index is 7 bits; one plan's imports + deletes
# must fit the namespace
MAX_HANDOFF_OPS = 128

_m = get_metrics()
_m_reshards = _m.counter(
    "persia_tpu_reshard_total", "resharding plans driven to the done phase"
)
_m_moved_bytes = _m.counter(
    "persia_tpu_reshard_moved_bytes", "bytes imported across PS replicas by handoffs"
)
_m_deduped = _m.counter(
    "persia_tpu_reshard_ops_deduped",
    "handoff ops skipped because the apply-journal already held them (resume replay)",
)
_m_aborts = _m.counter(
    "persia_tpu_reshard_aborts_total",
    "resharding plans rolled back to the aborted phase by a preemption",
)


class ReshardAborted(RuntimeError):
    """An in-flight reshard was preempted at a phase boundary and rolled
    back. The rollback ran to the terminal ``aborted`` manifest before this
    was raised — the fleet is back on the OLD ring with every partially
    imported arc released. ``stats`` carries the run counters (including
    the rollback's ``aborts_applied`` / ``aborts_deduped``)."""

    def __init__(self, stats: Dict):
        super().__init__(
            f"reshard preempted and rolled back: {stats.get('aborts_applied', 0)}"
            " arc release(s) applied"
        )
        self.stats = stats


# ------------------------------------------------------------------- planning


@dataclass(frozen=True)
class Move:
    """One range handoff: entries of ``src`` whose ring position falls in
    ``[lo, hi)`` (``hi == 0`` meaning 2^64, the ``hash_range_mask``
    convention) move to ``dst``."""

    src: int
    dst: int
    lo: int
    hi: int


@dataclass
class ReshardPlan:
    old_n: int
    new_n: int
    old_splits: Optional[List[int]]  # None = legacy modulo routing
    new_splits: List[int]
    base_id: int  # journal-id base; op k applies as handoff_journal_id(base, k)
    moves: List[Move]

    @property
    def abortable(self) -> bool:
        """Only ring→ring plans can roll back: a modulo bootstrap's moved
        arcs overlap entries the destinations legitimately hold, so the
        abort arm's range releases would destroy live data."""
        return self.old_splits is not None

    @property
    def deletes(self) -> List[Move]:
        """Phase-2 release ops: every moved-away range still held by a
        SURVIVING source (removed replicas are shut down whole, nothing to
        delete). Same deterministic order as ``moves`` — op indices (and so
        journal ids) are reproducible from the plan alone."""
        return [m for m in self.moves if m.src < self.new_n]

    def to_meta(self) -> Dict:
        return {
            "old_n": self.old_n,
            "new_n": self.new_n,
            "old_splits": None if self.old_splits is None
            else [int(x) for x in self.old_splits],
            "new_splits": [int(x) for x in self.new_splits],
            "base_id": int(self.base_id),
        }

    @classmethod
    def from_meta(cls, meta: Dict) -> "ReshardPlan":
        r = meta["reshard"]
        return plan_reshard(
            int(r["old_n"]), int(r["new_n"]), r["old_splits"],
            r["new_splits"], int(r["base_id"]),
        )


def _ranges(splits: Optional[Sequence[int]], n: int) -> List[Tuple[int, int]]:
    """Contiguous ring arcs per shard, in PYTHON ints with an exclusive
    ``hi`` (2^64 for the last arc — converted to the wire's 0 only at Move
    construction)."""
    if n == 1:
        return [(0, _RING)]
    s = [int(x) for x in splits]  # type: ignore[union-attr]
    if len(s) != n - 1 or any(b <= a for a, b in zip(s, s[1:])) or s[0] <= 0:
        raise ValueError(f"need {n - 1} strictly-ascending positive splits, got {s}")
    edges = [0] + s + [_RING]
    return [(edges[i], edges[i + 1]) for i in range(n)]


def _isect(a: Tuple[int, int], b: Tuple[int, int]) -> Optional[Tuple[int, int]]:
    lo, hi = max(a[0], b[0]), min(a[1], b[1])
    return (lo, hi) if lo < hi else None


def plan_reshard(
    old_n: int,
    new_n: int,
    old_splits: Optional[Sequence[int]],
    new_splits: Sequence[int],
    base_id: int,
) -> ReshardPlan:
    """Derive the deterministic move list. ``old_splits=None`` means the
    incumbent topology routes by modulo (the pre-elastic default): every
    source may hold signs anywhere on the ring, so each moves the WHOLE of
    every other dest's new arc (its own arc's entries stay put — the delete
    phase strips everything else). Ring→ring reshards move only the arc
    intersections whose owner changed."""
    if old_n < 1 or new_n < 1:
        raise ValueError(f"replica counts must be >= 1 ({old_n} -> {new_n})")
    new_r = _ranges(new_splits, new_n)
    old_r = [(0, _RING)] * old_n if old_splits is None else _ranges(old_splits, old_n)
    moves: List[Move] = []
    for s in range(old_n):
        for d in range(new_n):
            if s == d:
                continue  # the overlap (if any) is already in place
            r = _isect(old_r[s], new_r[d])
            if r is not None:
                moves.append(Move(s, d, r[0], r[1] % _RING))
    plan = ReshardPlan(old_n, new_n,
                       None if old_splits is None else [int(x) for x in old_splits],
                       [int(x) for x in new_splits], int(base_id), moves)
    n_ops = len(moves) + len(plan.deletes)
    if n_ops >= MAX_HANDOFF_OPS:
        raise ValueError(
            f"reshard {old_n}->{new_n} needs {n_ops} handoff ops but the "
            f"journal-id namespace holds {MAX_HANDOFF_OPS - 1}; reshard in "
            f"smaller steps"
        )
    return plan


def reshard_base_id(mgr: "jobstate.JobStateManager", step: int = 0) -> int:
    """Journal-id base for a new plan: the epoch the fence manifest will
    (most likely) land on + the caller's step. Uniqueness vs gradient ids
    is structural (the 0x80 namespace); vs other reshards it only needs to
    differ, and the recorded manifest is the source of truth on resume."""
    latest = mgr.latest()
    epoch = (latest.job_epoch + 1) if latest is not None else 1
    return jobstate.make_journal_id(epoch, step)


# ------------------------------------------------------------------ manifests


def _blob_counts(replicas: Sequence) -> List[int]:
    return [int(r.num_internal_shards) for r in replicas]


def _capture(writer: "jobstate.EpochWriter", prefix: str, replicas: Sequence) -> List[int]:
    counts = _blob_counts(replicas)
    for ri, rep in enumerate(replicas):
        for si in range(counts[ri]):
            writer.add_blob(f"reshard/{prefix}_{ri}_shard_{si}.emb", rep.dump_shard(si))
    return counts


def _snapshot(man: "jobstate.Manifest", prefix: str, counts_key: str, idx: int) -> List[bytes]:
    counts = man.meta.get(counts_key) or []
    if idx >= len(counts):
        raise jobstate.ManifestError(
            f"reshard manifest {man.dir} has no {prefix} {idx} snapshot"
        )
    return [
        man.read_blob(f"reshard/{prefix}_{idx}_shard_{si}.emb")
        for si in range(int(counts[idx]))
    ]


def source_snapshot(man: "jobstate.Manifest", src: int) -> List[bytes]:
    """Fence-time shard blobs of source ``src`` (``handoff`` manifest) —
    what a SIGKILLed source restores from before the plan re-runs."""
    return _snapshot(man, "source", "source_shards", src)


def dest_snapshot(man: "jobstate.Manifest", dst: int) -> List[bytes]:
    """Post-import shard blobs of dest ``dst`` (``imported`` manifest) —
    what a dest killed during the delete phase restores from."""
    return _snapshot(man, "dest", "dest_shards", dst)


def find_reshard_manifest(
    mgr: "jobstate.JobStateManager",
) -> Optional["jobstate.Manifest"]:
    """Newest committed manifest of ``kind == "reshard"`` regardless of
    phase (callers check ``meta["phase"]``); None if no reshard ever ran."""
    for _e, d in reversed(mgr._epoch_dirs()):
        m = mgr._load_manifest(d)
        if m is not None and m.meta.get("kind") == "reshard":
            return m
    return None


def find_phase_manifest(
    mgr: "jobstate.JobStateManager", phase: str, base_id: int,
) -> Optional["jobstate.Manifest"]:
    """Newest reshard manifest recording ``phase`` for the plan identified
    by ``base_id``. The abort resume path needs this: the fence snapshots
    live on the ``handoff`` manifest, but by the time a mid-abort SIGKILL
    resumes, the NEWEST reshard manifest is the snapshot-less ``aborting``
    one."""
    for _e, d in reversed(mgr._epoch_dirs()):
        m = mgr._load_manifest(d)
        if (m is not None and m.meta.get("kind") == "reshard"
                and m.meta.get("phase") == phase
                and int(m.meta.get("reshard", {}).get("base_id", -1))
                == int(base_id)):
            return m
    return None


def prime_joiner(client, optimizer, batch_advances: Optional[Dict]) -> None:
    """Bring a FRESH store onto the fleet's optimizer time-base before it
    serves its first train lookup: register the optimizer (a store without
    it re-initializes imported entries on entry-width mismatch), then
    re-advance the per-group batch counters (Adam beta-power schedule) to
    the fence. Single-sourced for every path that births a replica
    mid-job — reshard joiners, resume-restored joiners, and standby
    promotion: a parked standby that skips this applies Adam updates from
    t=0 and silently diverges bitwise from the survivors."""
    if optimizer is not None:
        client.register_optimizer(optimizer)
    for group, count in (batch_advances or {}).items():
        for _ in range(int(count)):
            client.advance_batch_state(int(group))


# ------------------------------------------------------------------ execution

FaultHook = Callable[[str, int, Move], None]


def _run_imports(
    plan: ReshardPlan, sources: Sequence, dests: Sequence,
    stats: Dict, fault_hook: Optional[FaultHook],
) -> None:
    with tracing.span("reshard.handoff", moves=len(plan.moves)):
        for idx, mv in enumerate(plan.moves):
            if fault_hook is not None:
                fault_hook("import", idx, mv)
            reach("elastic.op.import")
            blob = sources[mv.src].export_range(mv.lo, mv.hi)
            jid = jobstate.handoff_journal_id(plan.base_id, idx)
            crc = zlib.crc32(blob) & 0xFFFFFFFF
            applied = dests[mv.dst].import_range_journaled(jid, crc, blob)
            if applied:
                stats["imports_applied"] += 1
                stats["moved_bytes"] += len(blob)
                _m_moved_bytes.inc(len(blob))
            else:
                stats["imports_deduped"] += 1
                _m_deduped.inc()
            tracing.record_event(
                "reshard.import", op=idx, src=mv.src, dst=mv.dst,
                bytes=len(blob), applied=bool(applied),
            )


def _run_deletes(
    plan: ReshardPlan, sources: Sequence,
    stats: Dict, fault_hook: Optional[FaultHook],
) -> None:
    deletes = plan.deletes
    with tracing.span("reshard.release", deletes=len(deletes)):
        for i, mv in enumerate(deletes):
            if fault_hook is not None:
                fault_hook("delete", i, mv)
            reach("elastic.op.delete")
            jid = jobstate.handoff_journal_id(plan.base_id, len(plan.moves) + i)
            crc = jobstate.payload_crc(np.array([mv.lo, mv.hi], dtype=np.uint64))
            applied, removed = sources[mv.src].delete_range_journaled(
                jid, crc, mv.lo, mv.hi
            )
            if applied:
                stats["deletes_applied"] += 1
                stats["entries_removed"] += int(removed)
            else:
                stats["deletes_deduped"] += 1
                _m_deduped.inc()
            tracing.record_event(
                "reshard.release", op=i, src=mv.src, removed=int(removed),
                applied=bool(applied),
            )


def _run_abort(
    plan: ReshardPlan, dests: Sequence, mgr: "jobstate.JobStateManager",
    stats: Dict, start_phase: str, fault_hook: Optional[FaultHook],
    extra_meta: Optional[Dict],
) -> Dict:
    """Roll an interrupted plan BACK: commit the ``aborting`` manifest,
    release every (possibly) imported arc on its destination through a
    journaled range delete in the abort journal-id namespace, then commit
    the terminal ``aborted`` manifest. Pure replay like ``_finish`` — a
    SIGKILL anywhere in here resumes through the ``aborting`` arm of
    :func:`resume_reshard` and every already-released arc dedupes, so the
    resumed end state is bit-identical to an uninterrupted abort."""
    if start_phase != "aborting":
        reach("elastic.phase.aborting")
        _commit_phase(mgr, plan, "aborting", extra_meta)
    epoch = plan.base_id >> 40
    step = (plan.base_id >> 8) & 0xFFFFFFFF
    with tracing.span("reshard.abort", moves=len(plan.moves)):
        for idx, mv in enumerate(plan.moves):
            if fault_hook is not None:
                fault_hook("abort", idx, mv)
            reach("elastic.op.abort_release")
            jid = jobstate.abort_journal_id(epoch, step, idx)
            crc = jobstate.payload_crc(np.array([mv.lo, mv.hi], dtype=np.uint64))
            applied, removed = dests[mv.dst].delete_range_journaled(
                jid, crc, mv.lo, mv.hi
            )
            if applied:
                stats["aborts_applied"] += 1
                stats["entries_removed"] += int(removed)
            else:
                stats["aborts_deduped"] += 1
                _m_deduped.inc()
            tracing.record_event(
                "reshard.abort_release", op=idx, dst=mv.dst,
                removed=int(removed), applied=bool(applied),
            )
    reach("elastic.phase.aborted")
    _commit_phase(mgr, plan, "aborted", extra_meta)
    _m_aborts.inc()
    stats["aborted"] = True
    logger.info(
        "reshard %d->%d ABORTED: %d/%d arc releases applied/deduped, "
        "%d entries released",
        plan.old_n, plan.new_n, stats["aborts_applied"],
        stats["aborts_deduped"], stats["entries_removed"],
    )
    return stats


def _commit_phase(
    mgr: "jobstate.JobStateManager", plan: ReshardPlan, phase: str,
    extra: Optional[Dict] = None, capture: Optional[Tuple[str, str, Sequence]] = None,
) -> "jobstate.Manifest":
    writer = mgr.begin_epoch()
    meta: Dict = {"kind": "reshard", "phase": phase, "reshard": plan.to_meta()}
    meta.update(extra or {})
    if capture is not None:
        prefix, counts_key, replicas = capture
        meta[counts_key] = _capture(writer, prefix, replicas)
    man = writer.commit(meta)
    tracing.record_event(
        "reshard.phase", phase=phase, job_epoch=writer.job_epoch,
        old_n=plan.old_n, new_n=plan.new_n,
    )
    return man


def _finish(
    plan: ReshardPlan, sources: Sequence, dests: Sequence,
    mgr: "jobstate.JobStateManager", stats: Dict, start_phase: str,
    fault_hook: Optional[FaultHook], on_imported: Optional[Callable[[], None]],
    extra_meta: Optional[Dict],
    abort_check: Optional[Callable[[], bool]] = None,
) -> Dict:
    """Drive the plan from ``start_phase`` to ``done``. Everything in here
    is a pure replay: journal ids come from the plan, so re-entering after
    any crash dedupes instead of double-applying. ``abort_check`` is polled
    at the phase boundaries BEFORE the ``imported`` commit (the point of no
    return); True rolls the plan back and raises :class:`ReshardAborted`."""
    def _preempted() -> bool:
        return (abort_check is not None and plan.abortable
                and bool(abort_check()))

    if start_phase == "handoff":
        if _preempted():
            raise ReshardAborted(_run_abort(
                plan, dests, mgr, stats, start_phase, fault_hook, extra_meta))
        _run_imports(plan, sources, dests, stats, fault_hook)
        if _preempted():
            raise ReshardAborted(_run_abort(
                plan, dests, mgr, stats, start_phase, fault_hook, extra_meta))
        reach("elastic.phase.imported")
        _commit_phase(mgr, plan, "imported", extra_meta,
                      capture=("dest", "dest_shards", dests))
    if on_imported is not None:
        reach("elastic.swap")
        on_imported()
    _run_deletes(plan, sources, stats, fault_hook)
    reach("elastic.phase.done")
    _commit_phase(mgr, plan, "done", extra_meta)
    _m_reshards.inc()
    logger.info(
        "reshard %d->%d done: %d/%d imports applied/deduped, %d/%d deletes, "
        "%d bytes moved, %d entries released",
        plan.old_n, plan.new_n, stats["imports_applied"],
        stats["imports_deduped"], stats["deletes_applied"],
        stats["deletes_deduped"], stats["moved_bytes"], stats["entries_removed"],
    )
    return stats


def _new_stats(start_phase: str, resumed: bool) -> Dict:
    return {
        "imports_applied": 0, "imports_deduped": 0,
        "deletes_applied": 0, "deletes_deduped": 0,
        "aborts_applied": 0, "aborts_deduped": 0,
        "moved_bytes": 0, "entries_removed": 0,
        "start_phase": start_phase, "resumed": resumed, "aborted": False,
    }


def execute_reshard(
    plan: ReshardPlan,
    sources: Sequence,
    dests: Sequence,
    job_state,
    *,
    fault_hook: Optional[FaultHook] = None,
    on_imported: Optional[Callable[[], None]] = None,
    extra_meta: Optional[Dict] = None,
    abort_check: Optional[Callable[[], bool]] = None,
) -> Dict:
    """Run a fresh plan end to end. ``sources``/``dests`` are store handles
    (StoreClient or in-process stores) indexed by OLD/NEW replica index —
    surviving replicas appear in both lists as the same endpoint. The
    caller holds the stream fence. ``fault_hook(kind, op_index, move)``
    fires before every handoff op (chaos injection); ``on_imported`` fires
    once at the imported boundary (where the router swaps rings);
    ``extra_meta`` (e.g. the optimizer config) rides on every phase
    manifest so the resume path can rebuild dead replicas. ``abort_check``
    (the arbiter's preemption flag) is polled at the phase boundaries
    before the ``imported`` commit — True rolls the plan back through the
    journaled abort arm and raises :class:`ReshardAborted`."""
    if len(sources) != plan.old_n or len(dests) != plan.new_n:
        raise ValueError(
            f"plan is {plan.old_n}->{plan.new_n} but got "
            f"{len(sources)} sources / {len(dests)} dests"
        )
    mgr = jobstate.coerce_manager(job_state)
    with tracing.span("reshard.fence", old_n=plan.old_n, new_n=plan.new_n):
        reach("elastic.phase.handoff")
        _commit_phase(mgr, plan, "handoff", extra_meta,
                      capture=("source", "source_shards", sources))
    stats = _new_stats("handoff", resumed=False)
    return _finish(plan, sources, dests, mgr, stats, "handoff",
                   fault_hook, on_imported, extra_meta, abort_check)


def resume_reshard(
    job_state,
    sources: Sequence,
    dests: Sequence,
    *,
    fault_hook: Optional[FaultHook] = None,
    on_imported: Optional[Callable[[], None]] = None,
    abort_check: Optional[Callable[[], bool]] = None,
) -> Optional[Dict]:
    """Re-enter an interrupted reshard from its recorded phase. Returns the
    run stats, or None when the newest reshard already reached ``done`` or
    ``aborted`` (or none ever ran). ``abort_check`` carries a preemption
    request that is STILL pending at resume time (the request itself is
    arbiter memory, not manifest state — absent a live request, an
    interrupted forward plan rolls forward). The caller restores any DEAD replicas
    first — from :func:`source_snapshot` / :func:`dest_snapshot` per the
    crash matrix — and passes live handles here; this function only replays
    ops, and the journal turns every already-applied one into a dedupe. A
    plan recorded in the ``aborting`` phase re-enters the ABORT arm and
    runs it to the terminal ``aborted`` manifest (stats carry
    ``aborted=True`` so the caller knows not to finalize the new ring)."""
    mgr = jobstate.coerce_manager(job_state)
    man = find_reshard_manifest(mgr)
    if man is None or man.meta.get("phase") in ("done", "aborted"):
        return None
    plan = ReshardPlan.from_meta(man.meta)
    if len(sources) != plan.old_n or len(dests) != plan.new_n:
        raise ValueError(
            f"recorded plan is {plan.old_n}->{plan.new_n} but got "
            f"{len(sources)} sources / {len(dests)} dests"
        )
    phase = man.meta["phase"]
    if phase not in ("handoff", "imported", "aborting"):
        # an unknown phase must be loud: falling through to _finish would
        # run deletes-only and release source ranges that never imported
        raise jobstate.ManifestError(
            f"reshard manifest records unknown phase {phase!r} "
            "(expected 'handoff', 'imported' or 'aborting')"
        )
    extra = {"optimizer": man.meta["optimizer"]} if "optimizer" in man.meta else None
    tracing.record_event("reshard.resume", phase=phase,
                         old_n=plan.old_n, new_n=plan.new_n)
    stats = _new_stats(phase, resumed=True)
    if phase == "aborting":
        return _run_abort(plan, dests, mgr, stats, phase, fault_hook, extra)
    return _finish(plan, sources, dests, mgr, stats, phase,
                   fault_hook, on_imported, extra, abort_check)

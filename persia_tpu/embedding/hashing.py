"""Sign hashing, shard routing, hash-stack, and index-prefix math.

Parity target: the reference's id-preprocessing hot loops
(`embedding_worker_service/mod.rs:341-484`): ``sign_to_shard_modulo``
(farmhash64 % replica_size), ``indices_to_hashstack_indices`` (multi-round
vocabulary compression) and ``indices_add_prefix`` (per-slot key-space
partitioning).

Design difference: we use the splitmix64 finalizer instead of farmhash — it is
4 instructions, has excellent avalanche behavior, and is trivially identical
in vectorized numpy (here) and C++ (`native/ps.cpp`). All math is wrapping
u64.
"""

from __future__ import annotations

import numpy as np

_C1 = np.uint64(0x9E3779B97F4A7C15)
_C2 = np.uint64(0xBF58476D1CE4E5B9)
_C3 = np.uint64(0x94D049BB133111EB)

# Per-round xor seeds for the hash stack (arbitrary odd constants).
_ROUND_SEEDS = np.array(
    [(0x243F6A8885A308D3 + 0x9E3779B97F4A7C15 * r) & 0xFFFFFFFFFFFFFFFF for r in range(16)],
    dtype=np.uint64,
)


def splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer over a u64 array (wrapping arithmetic)."""
    x = x.astype(np.uint64, copy=True)
    x += _C1
    x ^= x >> np.uint64(30)
    x *= _C2
    x ^= x >> np.uint64(27)
    x *= _C3
    x ^= x >> np.uint64(31)
    return x


def sign_to_shard(signs: np.ndarray, num_shards: int) -> np.ndarray:
    """Route each sign to a PS replica (ref: mod.rs:342-345)."""
    return (splitmix64(signs) % np.uint64(num_shards)).astype(np.int64)


def uniform_splits(num_shards: int) -> np.ndarray:
    """Hash-uniform ring split points for ``num_shards`` PS replicas: the
    ``num_shards - 1`` ascending u64 boundaries at ``k * 2^64 / n``. Replica
    ``k`` owns hash positions ``[splits[k-1], splits[k])`` (half-open, with
    the implicit ends 0 and 2^64). The elastic tier's planner replaces these
    with load-weighted boundaries; routing stays :func:`sign_to_range_shard`
    either way."""
    n = int(num_shards)
    if n < 1:
        raise ValueError(f"num_shards must be >= 1, got {n}")
    return np.array(
        [(k * (1 << 64)) // n for k in range(1, n)], dtype=np.uint64
    )


def sign_to_range_shard(signs: np.ndarray, splits: np.ndarray) -> np.ndarray:
    """Route each sign to a PS replica by its position on the splitmix64
    ring: replica index = number of split points <= hash. ``splits`` is an
    ascending u64 array of length ``n - 1`` (see :func:`uniform_splits`);
    with load-weighted splits the same function implements the elastic
    tier's skew-balanced routing. NOT numerically interchangeable with the
    modulo router :func:`sign_to_shard` — a ring swap at a fence must move
    the affected ranges first."""
    h = splitmix64(np.asarray(signs, dtype=np.uint64))
    return np.searchsorted(
        np.asarray(splits, dtype=np.uint64), h, side="right"
    ).astype(np.int64)


def hash_range_mask(signs: np.ndarray, lo: int, hi: int) -> np.ndarray:
    """Bool mask of signs whose splitmix64 hash lies in ``[lo, hi)`` —
    ``hi == 0`` means "to the end of the ring" (2^64, which a u64 cannot
    carry). The Python mirror of the native ``ps_export_range`` /
    ``ps_delete_range`` ownership predicate."""
    h = splitmix64(np.asarray(signs, dtype=np.uint64))
    m = h >= np.uint64(lo)
    if hi:
        m &= h < np.uint64(hi)
    return m


def hash_stack(signs: np.ndarray, rounds: int, embedding_size: int) -> np.ndarray:
    """Expand each sign into ``rounds`` compressed table keys.

    Round ``r`` maps a sign into ``[r * embedding_size, (r+1) * embedding_size)``;
    the caller sums the rows of all rounds (ref: mod.rs:348-400). Returns shape
    ``(len(signs), rounds)``.
    """
    out = np.empty((len(signs), rounds), dtype=np.uint64)
    for r in range(rounds):
        h = splitmix64(signs ^ _ROUND_SEEDS[r])
        out[:, r] = h % np.uint64(embedding_size) + np.uint64(r * embedding_size)
    return out


def add_index_prefix(signs: np.ndarray, prefix: int, prefix_bit: int) -> np.ndarray:
    """Partition one global key space across slots by OR-ing a per-slot prefix
    into the top ``prefix_bit`` bits (ref: mod.rs:403-429)."""
    if prefix == 0 or prefix_bit == 0:
        return signs.astype(np.uint64, copy=False)
    mask = np.uint64((1 << (64 - prefix_bit)) - 1)
    return (signs.astype(np.uint64) & mask) | np.uint64(prefix)


def uniform_init_for_sign(
    sign: int, seed: int, n: int, lo: float, hi: float
) -> np.ndarray:
    """Deterministic per-sign embedding init, identical bit-for-bit between
    this numpy golden model and the C++ core (`native/ps.cpp`).

    Counter-mode splitmix64: ``u_i = splitmix64(splitmix64(sign ^ seed) + i)``
    mapped to [lo, hi) via the top 53 bits (ref concept: seeded-by-sign entry
    init, emb_entry.rs:28-60)."""
    base = np.uint64(seed_for_sign(sign, seed))
    states = splitmix64(base + np.arange(n, dtype=np.uint64))
    u = (states >> np.uint64(11)).astype(np.float64) * (1.0 / (1 << 53))
    return (lo + u * (hi - lo)).astype(np.float32)


def uniform_init_for_signs(
    signs: np.ndarray, seed: int, n: int, lo: float, hi: float
) -> np.ndarray:
    """Vectorized ``uniform_init_for_sign`` over many signs at once —
    bit-identical rows, one (M, n) batch instead of M Python calls (the
    cached tier inits every cold miss per step)."""
    bases = splitmix64(signs.astype(np.uint64) ^ np.uint64(seed))  # seed_for_sign
    states = splitmix64(bases[:, None] + np.arange(n, dtype=np.uint64)[None, :])
    u = (states >> np.uint64(11)).astype(np.float64) * (1.0 / (1 << 53))
    return (lo + u * (hi - lo)).astype(np.float32)


def seed_for_sign(sign: int, base_seed: int = 0) -> int:
    """Deterministic per-sign RNG seed for reproducible embedding init
    (ref: emb_entry.rs:28-60 seeds the entry RNG by sign)."""
    arr = np.array([np.uint64(sign) ^ np.uint64(base_seed)], dtype=np.uint64)
    return int(splitmix64(arr)[0])


# ---------------------------------------------------------- init methods
#
# Seeded-by-sign init distributions beyond uniform (ref: InitializationMethod,
# persia-embedding-config/src/lib.rs:79-98; seeded entry init,
# emb_entry.rs:28-60). Each element i of a row gets its own splitmix64
# substream, so rejection sampling (gamma) and variable-draw-count algorithms
# (poisson) stay deterministic per element regardless of how many uniforms a
# neighbour consumed. All transcendentals go through scalar libm (math.*),
# which is the same glibc code C++ `std::` calls — that is what makes the
# numpy golden bit-identical to `native/ps.cpp` (pinned by
# tests/test_init_methods.py).

_M64 = (1 << 64) - 1
_TO_UNIT = 1.0 / 9007199254740992.0  # 2^-53
_TWO_PI = 6.283185307179586


def _sm64(x: int) -> int:
    """Scalar splitmix64 (wrapping u64), identical to the vectorized one."""
    x = (x + 0x9E3779B97F4A7C15) & _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


class _SubStream:
    """The j-th uniform of element ``i``: to_unit(sm64(sm64(base + i) + 1 + j))."""

    def __init__(self, base: int, i: int):
        self._b = _sm64((base + i) & _M64)
        self._j = 0

    def next(self) -> float:
        u = (_sm64((self._b + 1 + self._j) & _M64) >> 11) * _TO_UNIT
        self._j += 1
        return u


def _normal_from(st: "_SubStream", mean: float, std: float) -> float:
    import math

    u1 = max(st.next(), _TO_UNIT)
    u2 = st.next()
    return mean + std * (math.sqrt(-2.0 * math.log(u1)) * math.cos(_TWO_PI * u2))


def _poisson_from(st: "_SubStream", lam: float) -> float:
    import math

    if lam <= 0.0:
        return 0.0
    big_l = math.exp(-lam)
    k, p = 0, 1.0
    while k < 4096:  # hard cap mirrored in native/ps.cpp
        k += 1
        p *= st.next()
        if not p > big_l:
            break
    return float(k - 1)


def _gamma_from(st: "_SubStream", shape: float, scale: float) -> float:
    """Marsaglia-Tsang; for shape<1 boost via u^(1/shape) drawn FIRST."""
    import math

    if shape <= 0.0:
        return 0.0
    boost, k = 1.0, shape
    if k < 1.0:
        boost = math.pow(max(st.next(), _TO_UNIT), 1.0 / k)
        k += 1.0
    d = k - 1.0 / 3.0
    c = 1.0 / (3.0 * math.sqrt(d))
    for _ in range(1024):  # cap mirrored in native/ps.cpp
        x = _normal_from(st, 0.0, 1.0)
        v = 1.0 + c * x
        if v <= 0.0:
            continue
        v = v * v * v
        u = st.next()
        if u < 1.0 - 0.0331 * x * x * x * x:
            return boost * d * v * scale
        if math.log(max(u, _TO_UNIT)) < 0.5 * x * x + d * (1.0 - v + math.log(v)):
            return boost * d * v * scale
    return boost * d * scale  # pathological-params fallback (same in C++)


def init_for_sign(sign: int, seed: int, n: int, method) -> np.ndarray:
    """Dispatch on ``config.InitializationMethod``; f32 row of length n."""
    import math

    kind = method.kind
    if kind == "uniform":
        return uniform_init_for_sign(sign, seed, n, method.p0, method.p1)
    if kind == "inverse_sqrt":
        b = 1.0 / math.sqrt(n)
        return uniform_init_for_sign(sign, seed, n, -b, b)
    base = seed_for_sign(sign, seed)
    out = np.empty(n, dtype=np.float32)
    for i in range(n):
        st = _SubStream(base, i)
        if kind == "normal":
            out[i] = _normal_from(st, method.p0, method.p1)
        elif kind == "poisson":
            out[i] = _poisson_from(st, method.p0)
        elif kind == "gamma":
            out[i] = _gamma_from(st, method.p0, method.p1)
        else:
            raise ValueError(f"unknown init kind: {kind!r}")
    return out


def init_for_signs(signs: np.ndarray, seed: int, n: int, method) -> np.ndarray:
    """Rows of ``init_for_sign`` stacked to (M, n); uniform kinds take the
    vectorized path (the only init on a hot path — cached-tier cold misses)."""
    if method.kind == "uniform":
        return uniform_init_for_signs(signs, seed, n, method.p0, method.p1)
    if method.kind == "inverse_sqrt":
        b = 1.0 / float(np.sqrt(n))
        return uniform_init_for_signs(signs, seed, n, -b, b)
    rows = [init_for_sign(int(s), seed, n, method) for s in np.asarray(signs).ravel()]
    if not rows:
        return np.empty((0, n), dtype=np.float32)
    return np.stack(rows)

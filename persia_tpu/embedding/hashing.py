"""Sign hashing, shard routing, hash-stack, and index-prefix math.

Parity target: the reference's id-preprocessing hot loops
(`embedding_worker_service/mod.rs:341-484`): ``sign_to_shard_modulo``
(farmhash64 % replica_size), ``indices_to_hashstack_indices`` (multi-round
vocabulary compression) and ``indices_add_prefix`` (per-slot key-space
partitioning).

Design difference: we use the splitmix64 finalizer instead of farmhash — it is
4 instructions, has excellent avalanche behavior, and is trivially identical
in vectorized numpy (here) and C++ (`native/ps.cpp`). All math is wrapping
u64.
"""

from __future__ import annotations

import numpy as np

_C1 = np.uint64(0x9E3779B97F4A7C15)
_C2 = np.uint64(0xBF58476D1CE4E5B9)
_C3 = np.uint64(0x94D049BB133111EB)

# Per-round xor seeds for the hash stack (arbitrary odd constants).
_ROUND_SEEDS = np.array(
    [(0x243F6A8885A308D3 + 0x9E3779B97F4A7C15 * r) & 0xFFFFFFFFFFFFFFFF for r in range(16)],
    dtype=np.uint64,
)


def splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer over a u64 array (wrapping arithmetic)."""
    x = x.astype(np.uint64, copy=True)
    x += _C1
    x ^= x >> np.uint64(30)
    x *= _C2
    x ^= x >> np.uint64(27)
    x *= _C3
    x ^= x >> np.uint64(31)
    return x


def sign_to_shard(signs: np.ndarray, num_shards: int) -> np.ndarray:
    """Route each sign to a PS replica (ref: mod.rs:342-345)."""
    return (splitmix64(signs) % np.uint64(num_shards)).astype(np.int64)


def hash_stack(signs: np.ndarray, rounds: int, embedding_size: int) -> np.ndarray:
    """Expand each sign into ``rounds`` compressed table keys.

    Round ``r`` maps a sign into ``[r * embedding_size, (r+1) * embedding_size)``;
    the caller sums the rows of all rounds (ref: mod.rs:348-400). Returns shape
    ``(len(signs), rounds)``.
    """
    out = np.empty((len(signs), rounds), dtype=np.uint64)
    for r in range(rounds):
        h = splitmix64(signs ^ _ROUND_SEEDS[r])
        out[:, r] = h % np.uint64(embedding_size) + np.uint64(r * embedding_size)
    return out


def add_index_prefix(signs: np.ndarray, prefix: int, prefix_bit: int) -> np.ndarray:
    """Partition one global key space across slots by OR-ing a per-slot prefix
    into the top ``prefix_bit`` bits (ref: mod.rs:403-429)."""
    if prefix == 0 or prefix_bit == 0:
        return signs.astype(np.uint64, copy=False)
    mask = np.uint64((1 << (64 - prefix_bit)) - 1)
    return (signs.astype(np.uint64) & mask) | np.uint64(prefix)


def uniform_init_for_sign(
    sign: int, seed: int, n: int, lo: float, hi: float
) -> np.ndarray:
    """Deterministic per-sign embedding init, identical bit-for-bit between
    this numpy golden model and the C++ core (`native/ps.cpp`).

    Counter-mode splitmix64: ``u_i = splitmix64(splitmix64(sign ^ seed) + i)``
    mapped to [lo, hi) via the top 53 bits (ref concept: seeded-by-sign entry
    init, emb_entry.rs:28-60)."""
    base = np.uint64(seed_for_sign(sign, seed))
    states = splitmix64(base + np.arange(n, dtype=np.uint64))
    u = (states >> np.uint64(11)).astype(np.float64) * (1.0 / (1 << 53))
    return (lo + u * (hi - lo)).astype(np.float32)


def uniform_init_for_signs(
    signs: np.ndarray, seed: int, n: int, lo: float, hi: float
) -> np.ndarray:
    """Vectorized ``uniform_init_for_sign`` over many signs at once —
    bit-identical rows, one (M, n) batch instead of M Python calls (the
    cached tier inits every cold miss per step)."""
    bases = splitmix64(signs.astype(np.uint64) ^ np.uint64(seed))  # seed_for_sign
    states = splitmix64(bases[:, None] + np.arange(n, dtype=np.uint64)[None, :])
    u = (states >> np.uint64(11)).astype(np.float64) * (1.0 / (1 << 53))
    return (lo + u * (hi - lo)).astype(np.float32)


def seed_for_sign(sign: int, base_seed: int = 0) -> int:
    """Deterministic per-sign RNG seed for reproducible embedding init
    (ref: emb_entry.rs:28-60 seeds the entry RNG by sign)."""
    arr = np.array([np.uint64(sign) ^ np.uint64(base_seed)], dtype=np.uint64)
    return int(splitmix64(arr)[0])

"""Embedding-worker tier: id preprocessing, sharded lookup, pooling
postprocess, and the gradient-return path.

Parity target: ``rust/persia-embedding-server/src/embedding_worker_service/``:

- preprocess: hashstack + index prefix + dedup + shard-by-sign
  (`mod.rs:341-484`, `persia-common/src/lib.rs:30-83`)
- postprocess: sum-pooling with optional sqrt scaling, or "raw" distinct-row
  layout for sequence slots (`mod.rs:486-629`)
- gradient path: NaN skip, AMP scale-factor division, sqrt scaling, per-sign
  accumulation, shard-by-sign update fan-out (`mod.rs:703-872`)
- train buffers + bounded staleness (`mod.rs:632-701,991-1129`)

TPU-first differences: everything is vectorized numpy on the worker host (the
C++ service wraps the same routines); "raw" slots ship distinct rows plus an
index matrix so the TPU gathers/scatters with static shapes, and the gradient
for raw slots arrives already reduced per distinct row (the device's autodiff
does the scatter-add via XLA, replacing torch ``index_add_``).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from persia_tpu.config import EmbeddingConfig, HyperParameters, SlotConfig
from persia_tpu.data import IDTypeFeature, PersiaBatch
from persia_tpu.embedding import native_worker
from persia_tpu.embedding.hashing import (
    add_index_prefix,
    hash_stack,
    sign_to_range_shard,
    sign_to_shard,
    splitmix64,
)
from persia_tpu.embedding.store import EmbeddingStore
from persia_tpu.metrics import get_metrics
from persia_tpu.monitor import EmbeddingMonitor
from persia_tpu.utils import round_up_pow2


class ForwardIdNotFound(RuntimeError):
    """A forward ref that expired (``buffered_data_expired_sec``), was already
    consumed, or never existed (typed reply, ref: "forward id not found",
    embedding_worker_service/mod.rs:1031-1074). RPC clients can match on the
    class name in the error string and drop/rebuild the batch instead of
    killing the pipeline."""


@dataclass
class ProcessedSlot:
    """One slot after preprocessing: table keys + dedup layout."""

    config: SlotConfig
    batch_size: int
    counts: np.ndarray  # (B,) ids per sample (pre-truncation for pooled; truncated for raw)
    distinct: np.ndarray  # (D,) distinct original signs (prefix applied, pre-hashstack)
    inverse: np.ndarray  # (n_ids,) position of each id in ``distinct``
    keys: np.ndarray  # (D * rounds,) actual table keys (post-hashstack), row-major per distinct id
    rounds: int  # hash-stack rounds (1 = disabled)
    _sample_of_id: Optional[np.ndarray] = None

    @property
    def name(self) -> str:
        return self.config.name

    @property
    def num_distinct(self) -> int:
        return len(self.distinct)

    @property
    def sample_of_id(self) -> np.ndarray:
        """(n_ids,) sample index of each id — derived from ``counts`` on
        first use (the cached tier never touches it; materializing 26 of
        these per batch was measurable on the single-core feeder)."""
        if self._sample_of_id is None:
            self._sample_of_id = np.repeat(
                np.arange(len(self.counts), dtype=np.int64), self.counts
            )
        return self._sample_of_id


@dataclass
class ProcessedBatch:
    slots: List[ProcessedSlot]
    batch_size: int
    batch_id: Optional[int] = None
    created_at: float = field(default_factory=time.time)


@dataclass
class SumEmbeddingBatch:
    """Pooled slot output: one (B, dim) array (ref: FeatureEmbeddingBatch::Sum,
    persia-common/src/lib.rs:85-113)."""

    name: str
    pooled: np.ndarray  # (B, dim) f32


@dataclass
class RawEmbeddingBatch:
    """Sequence slot output (ref: FeatureEmbeddingBatch::Raw).

    ``index`` holds positions into ``distinct`` padded with ``len(distinct)``;
    the device side appends one zero row to ``distinct`` so gathers of padding
    produce zeros and autodiff sends padding gradients to the throwaway row.
    """

    name: str
    distinct: np.ndarray  # (D, dim) f32
    index: np.ndarray  # (B, sample_fixed_size) int32, pad value == D
    sample_id_num: np.ndarray  # (B,) int32


@dataclass
class DevicePooledBatch:
    """Sum slot shipped UNPOOLED: distinct rows + gather layout, with the
    sum-pool (and sqrt scaling) differentiated ON DEVICE.

    TPU-first replacement for the reference's worker-side sum pooling
    (embedding_worker_service/mod.rs:486-629): the host↔device link carries
    only per-DISTINCT rows each way — at production zipf skew ~3x fewer
    bytes than (B, dim) pooled tensors, and the returning gradient is
    already reduced per distinct sign (the host-side scatter-accumulate
    disappears). ``index`` pads with ``len(distinct)``; the staged table
    zero-pads past D, so padded gathers contribute zero and their gradients
    land on sliced-off rows. ``sqrt_scaling`` is applied on device from
    ``counts`` (rsqrt), so gradients arrive fully scaled."""

    name: str
    distinct: np.ndarray  # (D, dim) f32 — hash-stack rounds summed, UNSCALED
    index: np.ndarray  # (B, L) int32, L = padded max ids/sample, pad == D
    counts: np.ndarray  # (B,) int32 true ids per sample
    sqrt_scaling: bool = False


FeatureEmbeddingBatch = Union[SumEmbeddingBatch, RawEmbeddingBatch, DevicePooledBatch]


def preprocess_slot(
    feature: IDTypeFeature, config: SlotConfig, prefix_bit: int
) -> ProcessedSlot:
    """Dedup + prefix + hashstack for one slot (ref: mod.rs:341-484,
    lib.rs:30-83). Dedup runs on original (prefixed) signs; hashstack expands
    each *distinct* sign into ``rounds`` table keys whose rows are summed."""
    flat, counts = feature.flat_counts()
    flat = add_index_prefix(flat.astype(np.uint64, copy=False), config.index_prefix, prefix_bit)
    native = native_worker.dedup(flat)
    if native is not None:
        distinct, inverse = native
    else:
        distinct, inverse = np.unique(flat, return_inverse=True)
    hs = config.hash_stack_config
    if hs.enabled:
        rounds = hs.hash_stack_rounds
        keys = hash_stack(distinct, rounds, hs.embedding_size).reshape(-1)
        keys = add_index_prefix(keys, config.index_prefix, prefix_bit)
    else:
        rounds = 1
        keys = distinct
    return ProcessedSlot(
        config=config,
        batch_size=len(counts),
        counts=counts,
        distinct=distinct,
        inverse=inverse.astype(np.int64),
        keys=keys,
        rounds=rounds,
    )


def preprocess_batch(
    id_type_features: Sequence[IDTypeFeature],
    embedding_config: EmbeddingConfig,
    batch_id: Optional[int] = None,
) -> ProcessedBatch:
    slots = []
    for f in id_type_features:
        cfg = embedding_config.slot(f.name)
        slots.append(preprocess_slot(f, cfg, embedding_config.feature_index_prefix_bit))
    bs = slots[0].batch_size if slots else 0
    return ProcessedBatch(slots=slots, batch_size=bs, batch_id=batch_id)


class ShardedLookup:
    """Routes table keys across PS replicas and reassembles responses
    (ref: AllEmbeddingServerClient + lookup_batched_all_slots, mod.rs:139-339,
    448-629). ``replicas`` are store-like objects (in-process stores or RPC
    clients exposing the same methods)."""

    def __init__(
        self,
        replicas: Sequence,
        recover=None,
        policy=None,
        degraded_init=None,
        ring=None,
    ):
        if not replicas:
            raise ValueError("need at least one PS replica")
        # --- versioned topology (elastic PS tier) ---------------------------
        # The replica list and its optional routing ring live in ONE tuple
        # swapped atomically at a reshard fence (``swap_topology``): a reader
        # that captured the tuple sees a consistent (replicas, ring) pair even
        # while a swap publishes the next version. ``ring`` is the ascending
        # u64 split-point array of hashing.sign_to_range_shard (len == n - 1);
        # None keeps the legacy hash-modulo routing (and its native one-pass
        # partition fast path).
        self._ring_lock = threading.Lock()  # serializes swaps, not reads
        self._topo = (list(replicas), self._check_ring(ring, len(replicas)), 0)
        # --- hot-sign read replication (persia_tpu/autopilot) ---------------
        # ``(sorted hot signs u64, fanout, salt)`` or None. READ fan-out
        # only: a hot sign's lookups round-robin over ``fanout`` consecutive
        # ring neighbours (per-sign hash phase + per-call sequence), while
        # every WRITE surface (gradient updates, checkout, set_embedding,
        # scrub) keeps owner routing — the single-writer invariant that
        # preserves the apply-journal's exactly-once story. Replicas serve
        # bounded-stale copies refreshed at stream fences (the same
        # staleness contract PS-tier training already runs under).
        self._hot = None
        self._hot_seq = 0  # read-call sequence for the round-robin spread
        # callable(replica) -> None: re-push optimizer + hyperparams to a
        # replica that lost its runtime config (restarted PS; ref: the
        # worker rebuilds its PS client pool on RpcError,
        # embedding_worker_service/mod.rs:1320-1333)
        self.recover = recover
        # --- resilience / graceful degradation (service/resilience.py) ---
        # ``policy.degrade_after_s`` set => a replica that stays down past
        # that budget stops stalling the caller: its signs are served
        # DETERMINISTIC init-vector embeddings (``degraded_init(signs,
        # dim)``; zeros fallback), every such sign is recorded so its
        # gradient return is DROPPED (never misapplied to the real row),
        # and the record is reconciled away when the sign is next served
        # from a live shard. ``policy is None`` keeps the legacy behavior:
        # transport failures propagate to the caller.
        self.policy = policy
        self.degraded_init = degraded_init
        self._deg_lock = threading.Lock()
        self._degraded_signs: set = set()  # served degraded, not yet reconciled
        self._win_degraded = 0  # windowed counters: take_degraded_window()
        self._win_total = 0
        m = get_metrics()
        self._m_degraded = m.counter(
            "persia_tpu_degraded_lookup_count",
            "signs served deterministic init vectors because their PS shard was down",
        )
        self._m_deg_grad_dropped = m.counter(
            "persia_tpu_degraded_grad_rows_dropped",
            "gradient rows dropped because their sign was served degraded",
        )
        self._m_deg_frac = m.gauge(
            "persia_tpu_degraded_lookup_frac",
            "degraded fraction of the most recent lookup window",
        )
        self._m_down_grad_dropped = m.counter(
            "persia_tpu_grad_rows_dropped_shard_down",
            "gradient rows dropped because their PS shard stayed down past the degrade budget",
        )
        self._m_down_wb_dropped = m.counter(
            "persia_tpu_writeback_rows_dropped_shard_down",
            "eviction write-back rows dropped because their PS shard stayed down",
        )
        # exactly-once resume accounting (persia_tpu.jobstate): gradient
        # batches skipped because the PS apply-journal already held their
        # (id, crc) record, and per-group Adam batch-state advance counts
        # (captured into the snapshot manifest; a PS rewind re-advances
        # from them so beta powers match the fence)
        self.journal_skips = 0
        self.batch_advances: Dict[int, int] = {}
        self._m_journal_skips = m.counter(
            "persia_tpu_journal_dup_skips",
            "gradient batches skipped by the PS apply-journal on resume replay",
        )
        self._m_replicas = m.gauge(
            "persia_tpu_ps_replicas",
            "PS replica count in the router's current topology",
        )
        self._m_replicas.set(len(replicas))
        self._m_hot_signs = m.gauge(
            "persia_tpu_hot_replicated_signs",
            "heavy-hitter signs currently read-replicated across PS shards",
        )
        self._m_hot_reads = m.counter(
            "persia_tpu_hot_replica_reads",
            "lookup rows served by a hot-sign read replica (not the owner)",
        )
        # eager pool (lazy init would race: EmbeddingWorker's slot threads
        # call the router concurrently): sized for replicas x concurrent
        # slot callers — the transport below is the pooled RpcClient
        # (8 in-flight per replica), so the executor must not be the funnel
        if len(self.replicas) > 1:
            from concurrent.futures import ThreadPoolExecutor

            self._fan_pool = ThreadPoolExecutor(
                max_workers=min(32, 8 * len(self.replicas)),
                thread_name_prefix="ps-fanout",
            )
        else:
            self._fan_pool = None
        # leaf pool for per-GROUP fallback calls against replicas without a
        # batched surface (remote clients predating the batched RPC): one
        # serialized RPC per slot per batch would stack 26+ round-trips —
        # created lazily, never used for nested tasks (no deadlock)
        self._group_pool = None

    def _with_recovery(self, replica, fn):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — match the typed remote error
            if self.recover is not None and "no optimizer registered" in repr(e):
                self.recover(replica)
                return fn()
            raise

    # ------------------------------------------------- versioned topology

    @staticmethod
    def _check_ring(ring, n: int):
        """Validate a split-point ring against the replica count: ``None``
        (modulo routing) or an ascending u64 array of length ``n - 1``."""
        if ring is None:
            return None
        ring = np.asarray(ring, dtype=np.uint64)
        if ring.shape != (n - 1,):
            raise ValueError(
                f"ring has {ring.shape[0] if ring.ndim == 1 else ring.shape} "
                f"split points, need {n - 1} for {n} replicas"
            )
        if ring.size > 1 and not (ring[:-1] < ring[1:]).all():
            raise ValueError("ring split points must be strictly ascending")
        return ring

    @property
    def replicas(self) -> List:
        """Current replica list (one consistent topology snapshot)."""
        return self._topo[0]

    @property
    def ring(self):
        """Current split-point ring (None => hash-modulo routing)."""
        return self._topo[1]

    @property
    def topology_version(self) -> int:
        """Monotonic version, bumped by every swap — reshard telemetry and
        tests pin ring swaps to it."""
        return self._topo[2]

    def swap_topology(self, replicas: Sequence, ring=None) -> int:
        """Atomically publish a new (replicas, ring) pair — the router half
        of a reshard fence. The caller guarantees the stream is drained (no
        in-flight lookups straddle the swap) and the sign ranges have been
        handed off; this method only swaps routing. Degraded-sign records
        and per-endpoint circuit breakers deliberately SURVIVE: degraded
        records are keyed by sign (still valid under any routing) and
        breakers by endpoint (a surviving replica keeps its health history
        across the swap). Returns the new topology version."""
        replicas = list(replicas)
        if not replicas:
            raise ValueError("need at least one PS replica")
        ring = self._check_ring(ring, len(replicas))
        with self._ring_lock:
            version = self._topo[2] + 1
            if len(replicas) > 1 and self._fan_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._fan_pool = ThreadPoolExecutor(
                    max_workers=min(32, 8 * len(replicas)),
                    thread_name_prefix="ps-fanout",
                )
            self._topo = (replicas, ring, version)
            # a topology change invalidates the hot-read map wholesale:
            # replica copies were placed relative to the OLD owner layout,
            # so keeping the map would fan reads out to shards that never
            # received the rows. The controller re-replicates at the next
            # fence from the same sketch signal.
            self._hot = None
        self._m_replicas.set(len(replicas))
        self._m_hot_signs.set(0)
        from persia_tpu import tracing

        tracing.record_event(
            "reshard.ring_swap",
            version=version,
            replicas=len(replicas),
            ring="range" if ring is not None else "modulo",
        )
        return version

    # ------------------------------------------- hot-sign read replication

    def set_hot_read_replicas(self, signs, fanout: int, salt: int = 0) -> int:
        """Install (or clear) the hot-sign read fan-out map. ``signs`` are
        the heavy hitters whose full entries the caller has ALREADY copied
        onto the ``fanout - 1`` ring neighbours after each owner
        (:func:`persia_tpu.autopilot.replicate.replicate_hot_signs` — the
        journaled copy and this routing swap are one actuation). Reads for
        a hot sign round-robin over its ``fanout`` copies: ``(owner +
        (mix(sign ^ salt) + seq + occurrence) % fanout) % n``, where
        ``occurrence`` is the read's rank among same-sign rows in the
        batch and ``seq`` advances once per call — a single scorching sign
        (the atomic point mass no ring split can spread) really does
        divide by ``fanout`` inside every batch, each sign phase-shifted
        by its hash so the hot set never marches in lockstep. ``seq``
        resets on install, so a replayed run reroutes identically. Empty
        signs or ``fanout <= 1`` clears the map. Returns the number of
        hot signs installed."""
        signs = np.asarray(signs if signs is not None else [], dtype=np.uint64)
        with self._ring_lock:
            self._hot_seq = 0
            if len(signs) == 0 or fanout <= 1 or len(self._topo[0]) <= 1:
                self._hot = None
                n_hot = 0
            else:
                self._hot = (
                    np.sort(signs),
                    int(min(fanout, len(self._topo[0]))),
                    np.uint64(salt),
                )
                n_hot = len(signs)
        self._m_hot_signs.set(n_hot)
        from persia_tpu import tracing

        tracing.record_event(
            "autopilot.hot_read_map", signs=n_hot,
            fanout=int(fanout) if n_hot else 0,
        )
        return n_hot

    def hot_read_state(self):
        """(signs, fanout, salt) of the installed hot-read map, or None."""
        hot = self._hot
        return None if hot is None else (hot[0].copy(), hot[1], int(hot[2]))

    def _hot_reroute(self, signs: np.ndarray, shard: np.ndarray, n: int):
        """Apply the hot-read map to an owner-shard array (READ paths
        only): members of the hot set move to their per-sign replica."""
        hot = self._hot
        if hot is None or n <= 1:
            return shard
        hsigns, fanout, salt = hot
        idx = np.searchsorted(hsigns, signs)
        np.minimum(idx, len(hsigns) - 1, out=idx)
        member = hsigns[idx] == signs
        if not member.any():
            return shard
        seq = self._hot_seq  # benign race: any value spreads the load
        self._hot_seq = seq + 1
        m_signs = signs[member]
        # per-occurrence round-robin: a batch carrying k reads of one hot
        # sign sends ~k/fanout to EACH of its copies (the occurrence rank
        # within the batch advances the offset), so a single scorching
        # sign divides by ``fanout`` inside every batch, not just across
        # batches; ``seq`` rotates the phase call-to-call on top
        order = np.argsort(m_signs, kind="stable")
        s_sorted = m_signs[order]
        starts = np.flatnonzero(
            np.r_[True, s_sorted[1:] != s_sorted[:-1]]
        )
        runs = np.diff(np.r_[starts, len(s_sorted)])
        occ = np.empty(len(s_sorted), dtype=np.uint64)
        occ[order] = (np.arange(len(s_sorted), dtype=np.uint64)
                      - np.repeat(starts, runs).astype(np.uint64))
        offs = (splitmix64(m_signs ^ salt) + np.uint64(seq) + occ) \
            % np.uint64(fanout)
        shard = shard.copy()
        moved = (shard[member].astype(np.uint64) + offs) % np.uint64(n)
        self._m_hot_reads.inc(int((moved != shard[member]).sum()))
        shard[member] = moved.astype(shard.dtype)
        return shard

    # ----------------------------------------------- degraded-mode machinery

    def replace_replica(self, idx: int, replica) -> None:
        """Swap replica ``idx`` for a promoted standby or a restarted
        process (same sign-partition slot, new transport). In-flight calls
        on the old handle finish or fail through their own retry path; new
        calls route to the fresh replica.

        Unlike ``swap_topology`` (surviving replicas keep their history),
        the slot's health state is RESET here: the fresh process inherits
        no breaker penalty from its predecessor (a stale OPEN breaker on
        the reused endpoint would quarantine a healthy standby for a full
        reset window), and degraded-sign records routed to this slot are
        purged — the new replica serves the real rows, so their next
        gradients must NOT be dropped as degraded."""
        with self._ring_lock:
            reps, ring, version = self._topo
            if not (0 <= idx < len(reps)):
                raise IndexError(f"replica index {idx} out of range 0..{len(reps) - 1}")
            reps = list(reps)
            reps[idx] = replica
            self._topo = (reps, ring, version + 1)
        endpoint = getattr(replica, "endpoint", None)
        if self.policy is not None and endpoint is not None:
            self.policy.reset_breaker(endpoint)
        self._purge_degraded_for_slot(idx)
        from persia_tpu import tracing

        tracing.record_event(
            "reshard.replace_replica", slot=idx, endpoint=str(endpoint)
        )

    def _purge_degraded_for_slot(self, idx: int) -> None:
        """Drop degraded-sign records that route to replica slot ``idx``
        under the CURRENT topology (their stand-in rows came from this
        slot's dead predecessor; the fresh process serves real rows)."""
        with self._deg_lock:
            if not self._degraded_signs:
                return
            signs = np.fromiter(
                self._degraded_signs, dtype=np.uint64,
                count=len(self._degraded_signs),
            )
        reps, ring, _ = self._topo
        if ring is not None:
            routed = sign_to_range_shard(signs, ring)
        else:
            routed = sign_to_shard(signs, len(reps))
        mine = signs[routed == idx]
        if len(mine):
            with self._deg_lock:
                self._degraded_signs.difference_update(int(s) for s in mine)

    def _slot_of(self, rep) -> Optional[int]:
        """Identity-resolve ``rep``'s slot in the CURRENT topology (None
        for a handle that is no longer — or never was — a member)."""
        for i, r in enumerate(self._topo[0]):
            if r is rep:
                return i
        return None

    def _resolve_slot(self, slot: Optional[int], cur):
        """The fresh handle now occupying ``slot``, or None if ``cur`` is
        still it (or the slot is unknown)."""
        if slot is None:
            return None
        reps = self._topo[0]
        if slot < len(reps) and reps[slot] is not cur:
            return reps[slot]
        return None

    def _guarded(self, rep, fn, signs_for_fallback, fallback):
        """One replica call under the resilience policy: transport failures
        block-retry (riding breaker half-open probes via ``wait_ready``)
        while the ``degrade_after_s`` budget lasts, then either serve the
        degraded ``fallback`` (recording the signs) or raise. ``fn`` takes
        the replica handle to call, because the call is NOT pinned to the
        handle it started on: each retry re-resolves the slot against the
        current topology, so a call in flight when ``replace_replica``
        promoted a standby migrates to the fresh process instead of
        burning the whole degrade budget against the corpse (the
        self-heal path's "no dropped in-flight requests" contract).
        Returns ``(result, degraded)``."""
        pol = self.policy
        cur = rep
        if pol is None or pol.degrade_after_s is None:
            return self._with_recovery(cur, lambda: fn(cur)), False
        from persia_tpu.service.rpc import _is_transportish

        slot = self._slot_of(rep)
        t0 = time.monotonic()
        attempt = 0
        while True:
            try:
                return self._with_recovery(cur, lambda: fn(cur)), False
            except Exception as e:  # noqa: BLE001 — classify then decide
                if not _is_transportish(e):
                    raise
                budget_left = pol.degrade_after_s - (time.monotonic() - t0)
                if budget_left <= 0:
                    if fallback is None:
                        raise
                    break
                # a concurrent heal may have swapped this slot's handle —
                # migrate and retry immediately (the fresh process answers)
                swapped = self._resolve_slot(slot, cur)
                if swapped is not None:
                    cur = swapped
                    continue
                # wait for the shard to answer probes again (ping is
                # breaker-exempt: its success re-closes the breaker), then
                # retry the real call; if even the probe times out, back off
                ready = False
                try:
                    if hasattr(cur, "wait_ready"):
                        cur.wait_ready(
                            timeout_s=min(max(budget_left, 0.05), 1.0)
                        )
                        ready = True
                except Exception:  # noqa: BLE001 — still down
                    pass
                if not ready:
                    time.sleep(
                        min(pol.backoff(attempt), max(budget_left, 0.0))
                    )
                attempt += 1
        self._record_degraded(signs_for_fallback)
        return fallback(), True

    def _record_total(self, n: int) -> None:
        if self.policy is None:
            return
        with self._deg_lock:
            self._win_total += int(n)

    def _record_degraded(self, signs) -> None:
        n = len(signs)
        self._m_degraded.inc(n)
        with self._deg_lock:
            self._win_degraded += n
            self._degraded_signs.update(int(s) for s in signs)

    def _record_served(self, signs) -> None:
        """Reconcile: a sign served from a LIVE shard again drops out of the
        degraded record — its next gradient was computed against the real
        row and may be applied."""
        with self._deg_lock:
            if self._degraded_signs:
                self._degraded_signs.difference_update(
                    int(s) for s in signs
                )

    def take_degraded_window(self):
        """(degraded, total) sign counts since the last take — the stream's
        per-step ``degraded_lookup_frac`` source. Resets the window."""
        with self._deg_lock:
            d, t = self._win_degraded, self._win_total
            self._win_degraded = self._win_total = 0
        self._m_deg_frac.set(d / t if t else 0.0)
        return d, t

    def degraded_intersection(self, signs: np.ndarray) -> np.ndarray:
        """Boolean mask of ``signs`` currently in the degraded record."""
        with self._deg_lock:
            if not self._degraded_signs:
                return np.zeros(len(signs), dtype=bool)
            reg = np.fromiter(
                self._degraded_signs, dtype=np.uint64,
                count=len(self._degraded_signs),
            )
        return np.isin(np.asarray(signs, dtype=np.uint64), reg)

    def _check_abort(self, degraded_n: int, total_n: int) -> None:
        pol = self.policy
        if pol is None or not degraded_n or not total_n:
            return
        frac = degraded_n / total_n
        if frac > pol.max_degraded_frac:
            raise RuntimeError(
                f"degraded_lookup_frac {frac:.3f} exceeds the abort "
                f"threshold {pol.max_degraded_frac:.3f} — refusing to train "
                "on mostly-synthetic embeddings (raise max_degraded_frac or "
                "restore the PS tier)"
            )

    def _guarded_update(self, rep, fn, n_rows: int, counter=None) -> None:
        """Apply-side guard: block-retry within the degrade budget, then
        DROP the rows (counted in a metric) instead of stalling or killing
        the pipeline — a shard that stayed down past the budget loses
        those updates either way, and dropping is bounded + measured."""
        _res, deg = self._guarded(rep, fn, (), lambda: None)
        if deg:
            (counter if counter is not None
             else self._m_down_grad_dropped).inc(n_rows)

    def _degraded_rows(self, signs: np.ndarray, dim: int) -> np.ndarray:
        """Deterministic stand-in rows for a dead shard's signs: the
        configured seeded init (what a cold sign would be born with), so
        the forward stays well-conditioned and reproducible."""
        if self.degraded_init is not None:
            return self.degraded_init(signs, dim)
        return np.zeros((len(signs), dim), dtype=np.float32)

    def _filter_degraded_updates(self, keys: np.ndarray, *arrays):
        """Drop gradient rows whose sign is in the degraded record — their
        forward used a synthetic embedding, so applying the gradient to the
        real row would be a misapplication, not training."""
        if self.policy is None:
            return (keys, *arrays)
        mask = self.degraded_intersection(keys)
        if not mask.any():
            return (keys, *arrays)
        self._m_deg_grad_dropped.inc(int(mask.sum()))
        keep = ~mask
        return (keys[keep], *(a[keep] for a in arrays))

    def _concurrent(self, thunks):
        """Run per-replica thunks CONCURRENTLY and return their results in
        order. Against N remote replicas a serial fan-out costs N RTTs per
        call — the reference issues all PS futures at once
        (embedding_worker_service/mod.rs:886-907); this is that fan-out.
        Single-thunk calls stay inline (no pool, no overhead)."""
        if len(thunks) <= 1 or self._fan_pool is None:
            return [t() for t in thunks]
        return [f.result() for f in [self._fan_pool.submit(t) for t in thunks]]

    def _concurrent_groups(self, thunks):
        """Concurrent per-GROUP fallback calls (replica lacks the batched
        surface). These are leaf RPCs — a bounded dedicated pool is safe."""
        if len(thunks) <= 1:
            return [t() for t in thunks]
        if self._group_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._group_pool = ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="ps-group-fanout"
            )
        return [f.result() for f in [self._group_pool.submit(t) for t in thunks]]

    def _partition(self, signs: np.ndarray, read: bool = False):
        """[(replica_index, positions-or-mask), ...] for the touched
        replicas — the one sign-routing split every fan-out method shares
        (native one-pass partition when available, boolean masks otherwise;
        both index forms select rows identically downstream). With a
        split-point ring installed the native modulo partition is invalid —
        range routing via :func:`sign_to_range_shard` replaces it.

        ``read=True`` (lookup paths only) additionally applies the
        hot-sign read fan-out map: heavy hitters spread over their owner's
        ring neighbours. Write paths keep ``read=False`` owner routing."""
        reps, ring, _ = self._topo
        n = len(reps)
        hot_active = read and self._hot is not None and n > 1
        sel = []
        if ring is not None or hot_active:
            shard = (sign_to_range_shard(signs, ring) if ring is not None
                     else sign_to_shard(signs, n))
            if hot_active:
                shard = self._hot_reroute(signs, shard, n)
            for r in range(n):
                mask = shard == r
                if mask.any():
                    sel.append((r, mask))
            return sel
        part = native_worker.shard_partition(signs, n)
        if part is not None:
            pos, counts = part
            start = 0
            for r in range(n):
                c = int(counts[r])
                if c:
                    sel.append((r, pos[start:start + c]))
                start += c
        else:
            shard = sign_to_shard(signs, n)
            for r in range(n):
                mask = shard == r
                if mask.any():
                    sel.append((r, mask))
        return sel

    def _partition_positions(self, signs: np.ndarray, read: bool = False):
        """Like ``_partition`` but always ascending position arrays (the
        grouped fan-outs need ``searchsorted`` over them)."""
        return [
            (r, idx if idx.dtype != np.bool_ else np.flatnonzero(idx))
            for r, idx in self._partition(signs, read=read)
        ]

    def lookup_groups(
        self, groups: Sequence, train: bool
    ) -> List[np.ndarray]:
        """Multi-slot lookup: ONE call per replica per batch instead of one
        per slot (ref: lookup_batched_all_slots issues a single batched
        future per PS, embedding_worker_service/mod.rs:874-942). ``groups``
        is ``[(keys, dim), ...]``; returns per-group ``(len(keys), dim)``
        arrays. Falls back to per-group calls on replicas without a
        ``lookup_batched`` surface."""
        if not groups:
            return []
        dims = np.fromiter((d for _, d in groups), dtype=np.uint32, count=len(groups))
        key_ofs = np.zeros(len(groups) + 1, dtype=np.int64)
        np.cumsum([len(k) for k, _ in groups], out=key_ofs[1:])
        n = len(self.replicas)
        self._record_total(int(key_ofs[-1]))
        if n == 1:
            r0 = self.replicas[0]
            if hasattr(r0, "lookup_batched"):
                all_keys = np.concatenate([k for k, _ in groups]) if len(groups) > 1 \
                    else np.asarray(groups[0][0])

                def fb():
                    parts = [
                        self._degraded_rows(
                            all_keys[key_ofs[g]:key_ofs[g + 1]], int(dims[g])
                        ).reshape(-1)
                        for g in range(len(dims))
                    ]
                    return (
                        np.concatenate(parts) if parts
                        else np.empty(0, np.float32)
                    )

                flat, deg = self._guarded(
                    r0,
                    lambda rep: rep.lookup_batched(all_keys, key_ofs, dims, train),
                    all_keys, fb,
                )
                if deg:
                    self._check_abort(len(all_keys), len(all_keys))
                else:
                    self._record_served(all_keys)
                return _split_flat_rows(flat, key_ofs, dims)
            return self._concurrent_groups([
                (lambda k=k, d=d: self._guarded(
                    r0, lambda rep: rep.lookup(k, d, train), k,
                    lambda k=k, d=d: self._degraded_rows(k, d))[0])
                for k, d in groups
            ])
        all_keys = np.concatenate([k for k, _ in groups])
        outs = [
            np.zeros((len(k), int(d)), dtype=np.float32) for k, d in groups
        ]
        sel = self._partition_positions(all_keys, read=True)

        def one_replica(rep, pos):
            sub_keys = all_keys[pos]
            sub_ofs = np.searchsorted(pos, key_ofs).astype(np.int64)

            def live(rep):
                if hasattr(rep, "lookup_batched"):
                    flat = rep.lookup_batched(sub_keys, sub_ofs, dims, train)
                    return _split_flat_rows(flat, sub_ofs, dims)

                def one_group(g):
                    if sub_ofs[g] == sub_ofs[g + 1]:  # no rows here
                        return np.empty((0, int(dims[g])), np.float32)
                    return rep.lookup(
                        sub_keys[sub_ofs[g]:sub_ofs[g + 1]], int(dims[g]),
                        train,
                    )

                return self._concurrent_groups(
                    [(lambda g=g: one_group(g)) for g in range(len(groups))]
                )

            def fb():
                return [
                    self._degraded_rows(
                        sub_keys[sub_ofs[g]:sub_ofs[g + 1]], int(dims[g])
                    )
                    for g in range(len(groups))
                ]

            rows_list, deg = self._guarded(rep, live, sub_keys, fb)
            if not deg:
                self._record_served(sub_keys)
            return sub_ofs, rows_list, (len(sub_keys) if deg else 0)

        thunks = [
            (lambda rep=self.replicas[r], pos=pos: one_replica(rep, pos))
            for r, pos in sel
        ]
        deg_n = 0
        for (r, pos), (sub_ofs, rows_list, deg_count) in zip(
            sel, self._concurrent(thunks)
        ):
            deg_n += deg_count
            for g, rows in enumerate(rows_list):
                b, e = sub_ofs[g], sub_ofs[g + 1]
                if b < e:
                    outs[g][pos[b:e] - key_ofs[g]] = rows
        self._check_abort(deg_n, len(all_keys))
        return outs

    def _journaled_update_batched(
        self, rep, replica_index: int, journal_id: int,
        keys, key_ofs, dims, flat, opt_groups,
    ) -> None:
        """Apply one replica's share of a gradient batch through the PS
        apply-journal (exactly-once across a trainer crash + resume replay,
        persia_tpu.jobstate): the id carries (manifest epoch, step, this
        replica), the crc fingerprints the payload."""
        from persia_tpu.jobstate import journal_shard_id, payload_crc

        jid = journal_shard_id(journal_id, replica_index)
        crc = payload_crc(keys, flat)
        applied = rep.update_batched_journaled(
            jid, crc, keys, key_ofs, dims, flat, opt_groups
        )
        if not applied:
            self.journal_skips += 1
            self._m_journal_skips.inc()

    def update_groups(self, groups: Sequence, journal_id=None) -> None:
        """Multi-slot gradient fan-out: ONE call per replica per gradient
        batch. ``groups`` is ``[(keys, grads (n, dim) f32, opt_group), ...]``.
        The caller advances Adam batch state once per batch per opt group
        first (batch-level beta powers, optim.rs:99-221).

        ``journal_id`` (a :func:`persia_tpu.jobstate.make_journal_id` base)
        routes the apply through the PS apply-journal — exactly-once under
        trainer-crash resume. Only the batched path journals (both shipped
        store backends and the RPC client have it); the per-group legacy
        fallback stays at-least-once."""
        if not groups:
            return
        # gradients for signs that were served DEGRADED are dropped here —
        # their forward used a synthetic embedding, so applying them to the
        # real (restored) rows would be a misapplication
        if self.policy is not None:
            groups = [
                (k2, g2, og)
                for (k, g, og) in groups
                for k2, g2 in (self._filter_degraded_updates(k, g),)
            ]
        dims = np.fromiter(
            (g.shape[1] for _, g, _ in groups), dtype=np.uint32, count=len(groups)
        )
        opt_groups = np.fromiter(
            (og for _, _, og in groups), dtype=np.int32, count=len(groups)
        )
        key_ofs = np.zeros(len(groups) + 1, dtype=np.int64)
        np.cumsum([len(k) for k, _, _ in groups], out=key_ofs[1:])
        n = len(self.replicas)
        if n == 1:
            r0 = self.replicas[0]
            if hasattr(r0, "update_batched"):
                all_keys = np.concatenate([k for k, _, _ in groups]) \
                    if len(groups) > 1 else np.asarray(groups[0][0])
                flat = np.concatenate([g.reshape(-1) for _, g, _ in groups]) \
                    if len(groups) > 1 else np.asarray(groups[0][1]).reshape(-1)
                if journal_id is not None and hasattr(r0, "update_batched_journaled"):
                    self._guarded_update(
                        r0,
                        lambda rep: self._journaled_update_batched(
                            rep, 0, journal_id, all_keys, key_ofs, dims, flat,
                            opt_groups,
                        ),
                        len(all_keys),
                    )
                    return
                self._guarded_update(
                    r0,
                    lambda rep: rep.update_batched(all_keys, key_ofs, dims, flat, opt_groups),
                    len(all_keys),
                )
                return
            self._concurrent_groups([
                (lambda k=k, g=g, og=og: self._guarded_update(
                    r0, lambda rep, k=k, g=g, og=og: rep.update_gradients(k, g, og), len(k)))
                for k, g, og in groups
            ])
            return
        all_keys = np.concatenate([k for k, _, _ in groups])
        sel = self._partition_positions(all_keys)

        def one_replica(rep, ridx, pos):
            sub_ofs = np.searchsorted(pos, key_ofs).astype(np.int64)
            sub_keys = all_keys[pos]
            subs = [
                np.ascontiguousarray(
                    groups[g][1][pos[sub_ofs[g]:sub_ofs[g + 1]] - key_ofs[g]]
                )
                for g in range(len(groups))
            ]
            if hasattr(rep, "update_batched"):
                flat = (
                    np.concatenate([s.reshape(-1) for s in subs])
                    if subs else np.empty(0, np.float32)
                )
                if journal_id is not None and hasattr(rep, "update_batched_journaled"):
                    self._guarded_update(
                        rep,
                        lambda rep: self._journaled_update_batched(
                            rep, ridx, journal_id, sub_keys, sub_ofs, dims,
                            flat, opt_groups,
                        ),
                        len(sub_keys),
                    )
                    return
                self._guarded_update(
                    rep,
                    lambda rep: rep.update_batched(sub_keys, sub_ofs, dims, flat, opt_groups),
                    len(sub_keys),
                )
                return
            self._concurrent_groups([
                (lambda g=g: self._guarded_update(
                    rep,
                    lambda rep, g=g: rep.update_gradients(
                        sub_keys[sub_ofs[g]:sub_ofs[g + 1]], subs[g],
                        int(opt_groups[g]),
                    ),
                    int(sub_ofs[g + 1] - sub_ofs[g]),
                ))
                for g in range(len(groups))
                if sub_ofs[g] < sub_ofs[g + 1]
            ])

        self._concurrent([
            (lambda rep=self.replicas[r], r=r, pos=pos: one_replica(rep, r, pos))
            for r, pos in sel
        ])

    def lookup(self, keys: np.ndarray, dim: int, train: bool) -> np.ndarray:
        n = len(self.replicas)
        self._record_total(len(keys))
        if n == 1:
            r0 = self.replicas[0]
            vals, deg = self._guarded(
                r0, lambda rep: rep.lookup(keys, dim, train), keys,
                lambda: self._degraded_rows(keys, dim),
            )
            if deg:
                self._check_abort(len(keys), len(keys))
            else:
                self._record_served(keys)
            return vals
        out = np.zeros((len(keys), dim), dtype=np.float32)
        sel = self._partition(keys, read=True)

        def one(rep, idx):
            sub = keys[idx]
            return self._guarded(
                rep, lambda rep: rep.lookup(sub, dim, train), sub,
                lambda: self._degraded_rows(sub, dim),
            )

        thunks = [
            (lambda rep=self.replicas[r], idx=idx: one(rep, idx))
            for r, idx in sel
        ]
        deg_n = 0
        for (r, idx), (vals, deg) in zip(sel, self._concurrent(thunks)):
            out[idx] = vals
            if deg:
                deg_n += len(vals)
            else:
                self._record_served(keys[idx])
        self._check_abort(deg_n, len(keys))
        return out

    def checkout_entries(self, signs: np.ndarray, dim: int) -> np.ndarray:
        """Sign-routed full-entry checkout for the HBM cache tier: each sign
        reaches its owning PS replica (same partition as lookup/update);
        returns (n, dim + state_dim) ``[emb | state]`` rows."""
        n = len(self.replicas)
        # checkout has no degraded form (it needs the optimizer-state half
        # of the entry): _guarded without a fallback still rides out a
        # restart within the degrade budget, then raises
        if n == 1:
            r0 = self.replicas[0]
            return self._guarded(
                r0, lambda rep: rep.checkout_entries(signs, dim), signs, None
            )[0]
        out: Optional[np.ndarray] = None
        sel = self._partition(signs)
        thunks = [
            (lambda rep=self.replicas[r], idx=idx: self._guarded(
                rep, lambda rep, idx=idx: rep.checkout_entries(signs[idx], dim),
                signs[idx], None)[0])
            for r, idx in sel
        ]
        for (r, idx), vals in zip(sel, self._concurrent(thunks)):
            if out is None:
                out = np.empty((len(signs), vals.shape[1]), np.float32)
            out[idx] = vals
        if out is None:  # empty request
            out = np.empty((0, dim), np.float32)
        return out

    def probe_entries(self, signs: np.ndarray, dim: int,
                      vals_out: Optional[np.ndarray] = None,
                      warm_out: Optional[np.ndarray] = None):
        """Sign-routed warm/cold split (no admission) for the HBM cache
        tier. Returns (warm (n,) bool, vals (n, dim + state_dim)).

        ``vals_out``/``warm_out``: optional caller-owned result buffers (the
        cache tier's per-step probes would otherwise mmap-allocate ~1 MB
        per call); replicas that support direct writes fill them natively,
        others fall back to an extra copy."""
        n = len(self.replicas)
        self._record_total(len(signs))
        if n == 1:
            r = self.replicas[0]

            def fallback():
                # degraded probe = "everything cold": the caller's cold
                # path births deterministic host-seeded rows, so no PS
                # data is needed — exactly the init-vector degradation
                nv = len(signs)
                w = np.zeros(nv, dtype=bool)
                if warm_out is not None:
                    warm_out[:nv] = 0
                if vals_out is not None:
                    vals_out[:nv] = 0.0
                    return w, vals_out
                return w, np.zeros((nv, dim), np.float32)

            if getattr(r, "supports_probe_out", False):
                res, deg = self._guarded(
                    r,
                    lambda rep: rep.probe_entries(
                        signs, dim, vals_out=vals_out, warm_out=warm_out
                    ),
                    signs, fallback,
                )
                if deg:
                    self._check_abort(len(signs), len(signs))
                else:
                    self._record_served(signs)
                return res
            (warm, vals), deg = self._guarded(
                r, lambda rep: rep.probe_entries(signs, dim), signs, fallback
            )
            if deg:
                self._check_abort(len(signs), len(signs))
                return warm, vals
            self._record_served(signs)
            if vals_out is not None:
                vals_out[:len(signs)] = vals
                vals = vals_out
            if warm_out is not None:
                warm_out[:len(signs)] = warm
            return warm, vals
        # multi-replica assembly honors the out-buffers too: the cache
        # tier's chunked _probe DISCARDS the return value and reads the
        # buffers it passed in, so ignoring them here would hand it
        # uninitialized memory
        warm = np.zeros(len(signs), dtype=bool)
        vals: Optional[np.ndarray] = None
        if vals_out is not None:
            vals = vals_out
            vals[:len(signs)] = 0.0
        sel = self._partition(signs)

        def one(rep, idx):
            sub = signs[idx]
            # degraded marker: (None, None) — the assembly leaves warm
            # False and vals zeroed for that replica's span (= cold)
            return self._guarded(
                rep, lambda rep: rep.probe_entries(sub, dim), sub,
                lambda: (None, None),
            )

        thunks = [
            (lambda rep=self.replicas[r], idx=idx: one(rep, idx))
            for r, idx in sel
        ]
        deg_n = 0
        for (r, idx), ((w, v), deg) in zip(sel, self._concurrent(thunks)):
            if deg:
                deg_n += len(signs[idx])
                continue
            self._record_served(signs[idx])
            if vals is None:
                vals = np.zeros((len(signs), v.shape[1]), np.float32)
            warm[idx] = w
            vals[idx] = v
        if vals is None:
            vals = (
                vals_out if vals_out is not None
                else np.zeros((len(signs), dim), np.float32)
            )
        if warm_out is not None:
            warm_out[:len(signs)] = warm
        self._check_abort(deg_n, len(signs))
        return warm, vals

    def set_embedding(
        self, signs: np.ndarray, values: np.ndarray, dim: Optional[int] = None,
        commit_incremental: bool = False,
    ) -> None:
        """Sign-routed raw-entry insert (cache write-back + checkpoint
        re-shard path, ref: set_embedding chunking, core/rpc.rs:77-106).
        ``commit_incremental``: write-backs are training updates and must
        feed the incremental-update manager; loads must not."""
        n = len(self.replicas)
        if n == 1:
            r0 = self.replicas[0]
            self._guarded_update(
                r0,
                lambda rep: rep.set_embedding(
                    signs, values, dim, commit_incremental=commit_incremental
                ),
                len(signs), counter=self._m_down_wb_dropped,
            )
            return
        self._concurrent([
            (lambda rep=self.replicas[r], idx=idx: self._guarded_update(
                rep,
                lambda rep, idx=idx: rep.set_embedding(
                    signs[idx], values[idx], dim,
                    commit_incremental=commit_incremental,
                ),
                len(signs[idx]), counter=self._m_down_wb_dropped,
            ))
            for r, idx in self._partition(signs)
        ])

    def scan_nonfinite(self, cap: int = 65536):
        """Health scrub fan-out (persia_tpu/health): repair non-finite
        rows on every replica to the deterministic seeded init. Returns
        the aggregate ``(repaired_count, signs)``. For journaled
        exactly-once scrubs use ``health.scrub.scrub_router`` — it probes
        each replica's apply-journal before scanning."""
        total = 0
        signs: list = []
        for rep in self.replicas:
            n, s = self._with_recovery(rep, lambda rep=rep: rep.scan_nonfinite(cap=cap))
            total += int(n)
            signs.extend(int(x) for x in s)
        return total, signs[:cap]

    def advance_batch_state(self, group: int) -> None:
        # counted for the snapshot manifest: a PS rewind replays exactly
        # this many advances so Adam's beta powers match the fence
        self.batch_advances[group] = self.batch_advances.get(group, 0) + 1
        self._concurrent([
            (lambda rep=r: self._guarded_update(
                rep, lambda rep: rep.advance_batch_state(group), 0))
            for r in self.replicas
        ])

    def update(self, keys: np.ndarray, grads: np.ndarray, group: int) -> None:
        """Fan one slot's keyed gradients out to the owning replicas. The
        caller advances Adam batch state once per gradient batch (not per
        slot — matches the reference's batch-level beta powers)."""
        keys, grads = self._filter_degraded_updates(keys, grads)
        if not len(keys):
            return
        n = len(self.replicas)
        if n == 1:
            r0 = self.replicas[0]
            self._guarded_update(
                r0, lambda rep: rep.update_gradients(keys, grads, group), len(keys)
            )
            return
        self._concurrent([
            (lambda rep=self.replicas[r], idx=idx: self._guarded_update(
                rep,
                lambda rep, idx=idx: rep.update_gradients(keys[idx], grads[idx], group),
                len(keys[idx]),
            ))
            for r, idx in self._partition(keys)
        ])


def _split_flat_rows(
    flat: np.ndarray, key_ofs: np.ndarray, dims: np.ndarray
) -> List[np.ndarray]:
    """Slice a batched-lookup reply (flat f32, groups back to back) into
    per-group (count, dim) views."""
    out = []
    off = 0
    for g in range(len(dims)):
        c = int(key_ofs[g + 1] - key_ofs[g])
        d = int(dims[g])
        out.append(flat[off:off + c * d].reshape(c, d))
        off += c * d
    return out


def _distinct_rows(
    slot: ProcessedSlot, lookup: ShardedLookup, train: bool
) -> np.ndarray:
    """Fetch (D, dim) rows for a slot's distinct signs, summing hash-stack
    rounds (ref: mod.rs:348-400)."""
    dim = slot.config.dim
    rows = lookup.lookup(slot.keys, dim, train)
    return _sum_hashstack_rounds(slot, rows)


def _sum_hashstack_rounds(slot: ProcessedSlot, rows: np.ndarray) -> np.ndarray:
    if slot.rounds > 1:
        rows = rows.reshape(slot.num_distinct, slot.rounds, slot.config.dim).sum(axis=1)
    return rows


def postprocess_slot(
    slot: ProcessedSlot, rows: np.ndarray, device_pooling: bool = False
) -> FeatureEmbeddingBatch:
    """Pooling/layout postprocess of one slot's looked-up key rows
    (ref: mod.rs:486-629). ``rows`` is (len(keys), dim) — hash-stack rounds
    are summed here. ``device_pooling`` ships sum slots unpooled
    (``DevicePooledBatch``) so the pool runs on device."""
    dim = slot.config.dim
    rows = _sum_hashstack_rounds(slot, rows)
    if slot.config.embedding_summation and device_pooling:
        D = slot.num_distinct
        counts = slot.counts.astype(np.int32, copy=False)
        # L is a compiled SHAPE: bucket to pow2 so the step program count
        # stays bounded (single-id streams pin it at 1)
        L = round_up_pow2(int(counts.max()) if len(counts) else 1, floor=1)
        index = native_worker.raw_index(slot.counts, slot.inverse, L, D)
        if index is None:
            index = np.full((slot.batch_size, L), D, dtype=np.int32)
            pos = 0
            for b, c in enumerate(slot.counts.tolist()):
                take = min(c, L)
                index[b, :take] = slot.inverse[pos:pos + take]
                pos += c
        return DevicePooledBatch(
            slot.name, rows, index, counts, slot.config.sqrt_scaling
        )
    if slot.config.embedding_summation:
        if len(slot.sample_of_id):
            pooled = native_worker.sum_pool(
                rows, slot.inverse, slot.sample_of_id, slot.batch_size
            )
            if pooled is None:
                pooled = np.zeros((slot.batch_size, dim), dtype=np.float32)
                np.add.at(pooled, slot.sample_of_id, rows[slot.inverse])
        else:
            pooled = np.zeros((slot.batch_size, dim), dtype=np.float32)
        if slot.config.sqrt_scaling:
            scale = 1.0 / np.sqrt(np.maximum(slot.counts, 1)).astype(np.float32)
            pooled *= scale[:, None]
        return SumEmbeddingBatch(slot.name, pooled)

    L = slot.config.sample_fixed_size
    D = slot.num_distinct
    sample_id_num = np.minimum(slot.counts, L).astype(np.int32)
    index = native_worker.raw_index(slot.counts, slot.inverse, L, D)
    if index is None:
        index = np.full((slot.batch_size, L), D, dtype=np.int32)
        pos = 0
        for b, c in enumerate(slot.counts.tolist()):
            take = min(c, L)
            index[b, :take] = slot.inverse[pos : pos + take]
            pos += c
    if slot.config.sqrt_scaling:
        rows = rows / np.sqrt(np.maximum(D, 1)).astype(np.float32)
    return RawEmbeddingBatch(slot.name, rows, index, sample_id_num)


def lookup_slot(
    slot: ProcessedSlot, lookup: ShardedLookup, train: bool
) -> FeatureEmbeddingBatch:
    """Lookup + postprocess one slot (ref: mod.rs:486-629). The batched
    multi-slot path (``EmbeddingWorker.forward_batch_id``) fetches all
    slots' rows in one router call and postprocesses each; this per-slot
    form remains for single-slot callers."""
    return postprocess_slot(slot, lookup.lookup(slot.keys, slot.config.dim, train))


def slot_gradient_to_keys(
    slot: ProcessedSlot, grad: np.ndarray, scale_factor: float = 1.0,
    device_pooled: bool = False,
) -> Optional[np.ndarray]:
    """Convert a slot's device gradient into per-table-key gradients
    (ref: update_all_batched_gradients, mod.rs:703-872).

    Pooled slots: ``grad`` is (B, dim) — every id in sample b receives
    ``grad[b]`` (sum-pool distributes), accumulated per distinct sign.
    Device-pooled sum slots (``device_pooled``): ``grad`` is (D, dim),
    already reduced per distinct sign WITH sqrt scaling folded in by the
    device's autodiff — no host-side redistribution at all.
    Raw slots: ``grad`` is (D, dim), already reduced per distinct row by the
    device's autodiff scatter. Hash-stack keys each receive the distinct id's
    gradient (sum of rows distributes). Non-finite gradients skip the whole
    slot (NaN-skip, mod.rs:716-744). Returns (len(keys), dim) or None if
    skipped.
    """
    if not np.isfinite(grad).all():
        return None
    grad = grad.astype(np.float32)
    if scale_factor != 1.0:
        grad = grad / np.float32(scale_factor)
    dim = slot.config.dim
    if slot.config.embedding_summation and device_pooled:
        if grad.shape[0] != slot.num_distinct:
            raise ValueError(
                f"device-pooled slot {slot.name!r}: grad rows {grad.shape[0]} "
                f"!= distinct {slot.num_distinct}"
            )
        per_distinct = grad
    elif slot.config.embedding_summation:
        if slot.config.sqrt_scaling:
            scale = 1.0 / np.sqrt(np.maximum(slot.counts, 1)).astype(np.float32)
            grad = grad * scale[:, None]
        if len(slot.sample_of_id):
            per_distinct = native_worker.grad_accum(
                grad, slot.inverse, slot.sample_of_id, slot.num_distinct
            )
            if per_distinct is None:
                per_distinct = np.zeros((slot.num_distinct, dim), dtype=np.float32)
                np.add.at(per_distinct, slot.inverse, grad[slot.sample_of_id])
        else:
            per_distinct = np.zeros((slot.num_distinct, dim), dtype=np.float32)
    else:
        if grad.shape[0] != slot.num_distinct:
            raise ValueError(
                f"raw slot {slot.name!r}: grad rows {grad.shape[0]} != distinct {slot.num_distinct}"
            )
        per_distinct = grad
        if slot.config.sqrt_scaling:
            per_distinct = per_distinct / np.sqrt(
                np.maximum(slot.num_distinct, 1)
            ).astype(np.float32)
    if slot.rounds > 1:
        per_key = np.repeat(per_distinct, slot.rounds, axis=0)
    else:
        per_key = per_distinct
    return per_key


class EmbeddingWorker:
    """Stateful worker tier: train-path buffers + bounded staleness accounting
    (ref: EmbeddingWorkerInner, mod.rs:632-701,991-1129).

    The *staleness semaphore itself* lives in the NN-worker feeder
    (``persia_tpu/data_loader.py``); this counter mirrors the reference's
    server-side gauge.
    """

    def __init__(
        self,
        embedding_config: EmbeddingConfig,
        replicas: Sequence,
        hyperparams: HyperParameters = HyperParameters(),
        forward_buffer_size: int = 1000,
        buffered_data_expired_sec: int = 3600,
        num_threads: int = 8,
        device_pooling: bool = False,
        policy=None,
    ):
        # device_pooling: sum slots ship unpooled (DevicePooledBatch) and
        # their gradients return per-distinct — the worker-wide mode covers
        # both directions, so forward outputs and update_gradient_batched
        # inputs stay consistent
        self.device_pooling = device_pooling
        self.embedding_config = embedding_config
        # ``policy`` (service/resilience.py): hands the router failover +
        # degraded-lookup behavior; the degraded stand-in rows use the SAME
        # seeded init a cold sign would be born with (deterministic, and
        # consistent with a later real admission of the sign)
        self.lookup_router = ShardedLookup(
            replicas, recover=self._recover_replica, policy=policy,
            degraded_init=self._degraded_init_rows,
        )
        self.hyperparams = hyperparams
        self._optimizer = None  # cached for replica recovery
        self.forward_buffer_size = forward_buffer_size
        self.buffered_data_expired_sec = buffered_data_expired_sec
        self.forward_id_buffer: Dict[int, ProcessedBatch] = {}
        self.post_forward_buffer: Dict[int, ProcessedBatch] = {}
        self.staleness = 0
        self._ref_id = 0
        # guards buffers + staleness gauge + ref counter against the
        # DataLoader's concurrent lookup/backward threads
        self._buf_lock = threading.Lock()
        # serializes gradient batches so Adam batch-state advance + apply is
        # atomic per batch
        self._grad_lock = threading.Lock()
        # ``num_threads`` is accepted for config compatibility; the slot
        # fan-out is batched into ONE router call per batch (the reference's
        # lookup_batched_all_slots, mod.rs:874-942) — a per-slot thread pool
        # measured as pure overhead on a single-core feeder host, and the
        # multi-replica fan-out keeps its own pool in ShardedLookup
        self.num_threads = num_threads
        # worker-tier observability (ref: emb_worker metrics, mod.rs:49-105,
        # + distinct-id monitor, monitor.rs:29-114)
        m = get_metrics()
        self.monitor = EmbeddingMonitor()
        self._m_staleness = m.gauge(
            "persia_tpu_staleness", "batches looked up but not yet gradient-updated"
        )
        self._m_pending = m.gauge(
            "persia_tpu_num_pending_batches", "batches buffered awaiting forward"
        )
        self._m_unique_rate = m.gauge(
            "persia_tpu_batch_unique_indices_rate", "distinct ids / total ids per batch"
        )
        self._m_nan_skipped = m.counter(
            "persia_tpu_nan_grad_skipped", "slot gradients skipped for non-finite values"
        )
        self._m_lookup_time = m.histogram(
            "persia_tpu_lookup_total_time_cost_sec", "worker-side lookup latency"
        )
        self._m_update_time = m.histogram(
            "persia_tpu_update_gradient_time_cost_sec", "worker-side gradient-update latency"
        )

    def dump(self, path: str, blocking: bool = True) -> None:
        """Checkpoint fan-out to all PS replicas (ref: emb_worker dump,
        mod.rs:1131-1148). Works with both RPC StoreClients (the server dumps
        its own shards) and in-process stores. One shared session id ties the
        replicas' markers together so stale markers from an earlier dump into
        the same directory cannot complete this one."""
        import time as _time

        from persia_tpu.checkpoint import dump_store

        session = f"s{_time.time_ns()}"
        n = len(self.lookup_router.replicas)
        for i, r in enumerate(self.lookup_router.replicas):
            if hasattr(r, "dump_to_dir"):
                r.dump_to_dir(path, blocking=blocking, session=session)
            else:
                dump_store(r, path, replica_index=i, replica_size=n, session=session)

    def load(self, path: str) -> int:
        """Checkpoint load fan-out; entries re-route by sign so replica/shard
        count changes re-shard transparently (ref: emb_worker:1150-1259)."""
        from persia_tpu.checkpoint import load_store

        n = len(self.lookup_router.replicas)
        total = 0
        for i, r in enumerate(self.lookup_router.replicas):
            if hasattr(r, "load_from_dir"):
                total += r.load_from_dir(path)
            else:
                total += load_store(r, path, replica_index=i, replica_size=n)
        return total

    def register_optimizer(self, optimizer) -> None:
        """Fan the sparse-optimizer registration to every PS replica
        (ref: register_optimizer fan-out, emb_worker:1286-1307)."""
        self._optimizer = optimizer
        for r in self.lookup_router.replicas:
            r.register_optimizer(optimizer)

    def _degraded_init_rows(self, signs: np.ndarray, dim: int) -> np.ndarray:
        """Deterministic init-vector rows for degraded lookups: the seeded
        per-sign init the PS tier itself uses (hashing.init_for_signs), so
        a degraded forward is reproducible and matches what the sign would
        look like freshly admitted."""
        from persia_tpu.embedding.hashing import init_for_signs

        seed = getattr(self.lookup_router.replicas[0], "seed", 0) or 0
        method = self.hyperparams.resolved_init_method()
        return init_for_signs(
            np.asarray(signs, dtype=np.uint64), int(seed), dim, method
        )

    def _recover_replica(self, replica) -> None:
        """Re-push runtime config to a replica that lost it (restarted PS):
        the typed 'no optimizer registered' reply triggers this, after which
        the failed call is retried (ref: rebuild-on-error,
        embedding_worker_service/mod.rs:1320-1333). A worker that never
        registered the optimizer itself (multi-worker topologies register
        through one worker) sources the config from a healthy sibling
        replica."""
        import persia_tpu.logger as _log

        _log.get_default_logger("persia_tpu.worker").warning(
            "re-pushing optimizer/hyperparams to a restarted PS replica"
        )
        opt = self._optimizer
        if opt is None:
            for sib in self.lookup_router.replicas:
                if sib is replica:
                    continue
                try:
                    if hasattr(sib, "get_optimizer"):
                        opt = sib.get_optimizer()
                    else:
                        opt = getattr(sib, "optimizer", None)
                except Exception:  # noqa: BLE001 — sibling may be down too
                    continue
                if opt is not None:
                    break
        if opt is not None:
            self._optimizer = opt
            replica.register_optimizer(opt)
        replica.configure(self.hyperparams)

    def configure(self, hyperparams: HyperParameters) -> None:
        """Push runtime hyperparameters to every PS replica
        (ref: configure_embedding_parameter_servers)."""
        self.hyperparams = hyperparams
        for r in self.lookup_router.replicas:
            r.configure(hyperparams)

    # -------------------------------------------------- data-loader side API

    def can_forward_batched(self) -> bool:
        """Backpressure + expiry of stale buffered batches (ref: mod.rs:991-1029)."""
        now = time.time()
        with self._buf_lock:
            expired = [
                k
                for k, v in self.forward_id_buffer.items()
                if now - v.created_at > self.buffered_data_expired_sec
            ]
            for k in expired:
                del self.forward_id_buffer[k]
            self._m_pending.set(len(self.forward_id_buffer))
            return len(self.forward_id_buffer) < self.forward_buffer_size

    def put_forward_ids(self, batch: PersiaBatch) -> int:
        """Buffer a batch's preprocessed ids, return the remote ref id
        (ref: forward_batched NATS entry, mod.rs:1512-1530)."""
        processed = preprocess_batch(
            batch.id_type_features, self.embedding_config, batch_id=batch.batch_id
        )
        total = distinct = 0
        for slot in processed.slots:
            total += len(slot.inverse)
            distinct += slot.num_distinct
        self.monitor.observe_many(
            [(slot.name, slot.distinct) for slot in processed.slots]
        )
        if total:
            self._m_unique_rate.set(distinct / total)
        with self._buf_lock:
            self._ref_id += 1
            ref = self._ref_id
            self.forward_id_buffer[ref] = processed
            self._m_pending.set(len(self.forward_id_buffer))
        return ref

    # ----------------------------------------------------- nn-worker side API

    def forward_batch_id(self, ref: int, train: bool = True) -> List[FeatureEmbeddingBatch]:
        """Train path: take buffered ids, lookup, stash for the gradient
        round-trip (ref: mod.rs:1031-1074)."""
        with self._buf_lock:
            processed = self.forward_id_buffer.pop(ref, None)
            self._m_pending.set(len(self.forward_id_buffer))
        if processed is None:
            raise ForwardIdNotFound(
                f"forward id {ref} not found (expired or already consumed)"
            )
        with self._m_lookup_time.time():
            out = self._lookup_slots(processed.slots, train)
        if train:
            with self._buf_lock:
                self.post_forward_buffer[ref] = processed
                self.staleness += 1
                self._m_staleness.set(self.staleness)
        return out

    def _lookup_slots(
        self, slots: Sequence[ProcessedSlot], train: bool
    ) -> List[FeatureEmbeddingBatch]:
        """All slots' lookups in ONE batched router call, then per-slot
        postprocess (pooling is vectorized numpy/native — parallelism across
        slots bought nothing once the store call count collapsed to one)."""
        rows_list = self.lookup_router.lookup_groups(
            [(s.keys, s.config.dim) for s in slots], train
        )
        return [
            postprocess_slot(s, rows, device_pooling=self.device_pooling)
            for s, rows in zip(slots, rows_list)
        ]

    def forward_directly(
        self, batch: PersiaBatch, train: bool = False
    ) -> List[FeatureEmbeddingBatch]:
        """Lookup-direct path for eval/infer (ref: mod.rs:1076-1107)."""
        processed = preprocess_batch(batch.id_type_features, self.embedding_config)
        return self._lookup_slots(processed.slots, train)

    def abort_gradient(self, ref: int) -> None:
        """Drop a stashed post-forward batch without applying gradients (the
        NN worker's step failed); releases the staleness slot so the pipeline
        and buffers cannot leak."""
        with self._buf_lock:
            if self.post_forward_buffer.pop(ref, None) is not None:
                self.staleness = max(0, self.staleness - 1)
                self._m_staleness.set(self.staleness)

    def update_gradient_batched(
        self, ref: int, slot_grads: Dict[str, np.ndarray],
        scale_factor: float = 1.0, journal_id=None,
    ) -> Dict[str, int]:
        """Gradient return: pop the stashed layout, convert device grads to
        per-key grads, fan out to PS replicas (ref: mod.rs:1109-1129,703-872).
        Returns per-slot skip info for metrics. ``journal_id`` (see
        jobstate.make_journal_id) routes the apply through the PS
        apply-journal for exactly-once trainer resume."""
        with self._buf_lock:
            processed = self.post_forward_buffer.pop(ref, None)
            if processed is not None:
                self.staleness = max(0, self.staleness - 1)
                self._m_staleness.set(self.staleness)
        if processed is None:
            raise ForwardIdNotFound(
                f"forward id {ref} not found in post-forward buffer "
                "(already updated, aborted, or never forwarded)"
            )
        skipped = {}
        with self._m_update_time.time():
            # per-slot grad→key conversion (vectorized numpy + the native
            # accum kernel) runs on a batch this call exclusively owns — it
            # was popped from the buffer above — so it stays OUTSIDE
            # _grad_lock: holding the lock across lib.wk_grad_accum stalled
            # every sibling gradient thread behind pure compute (CONC005)
            trip = []
            for slot in processed.slots:
                grad = slot_grads.get(slot.name)
                if grad is None:
                    continue
                per_key = slot_gradient_to_keys(
                    slot, grad, scale_factor, device_pooled=self.device_pooling
                )
                if per_key is None:
                    skipped[slot.name] = 1
                    continue
                trip.append(
                    (slot.keys, per_key, self.embedding_config.group_of(slot.name))
                )
            # gradient batches are serialized so the Adam batch-state advance
            # is atomic with its batch's updates (ref: batch-level beta
            # powers, optim.rs:99-221); that atomicity is exactly why the
            # replica fan-out must stay under the lock even though its
            # transport-retry path can sleep (bounded by degrade_after_s)
            with self._grad_lock:
                groups = {
                    self.embedding_config.group_of(s.name)
                    for s in processed.slots
                    if s.name in slot_grads
                }
                for g in sorted(groups):
                    self.lookup_router.advance_batch_state(g)
                self.lookup_router.update_groups(trip, journal_id=journal_id)  # persia-lint: disable=CONC005
        if skipped:
            self._m_nan_skipped.inc(len(skipped))
        return skipped

"""Build every native C++ core up front: ``python -m persia_tpu.embedding.build_native``.

Each library also builds lazily on first use (content-hash stamped, so
rebuilds only happen when the source changes); this entry point exists for
images/CI that want the compile cost paid at build time, and as a quick
toolchain check. Cores:

- ``native/libpersia_ps.so`` — parameter-server store (sharded LRU +
  sparse optimizers; ref: persia-embedding-holder + persia-simd)
- ``native/libpersia_worker.so`` — embedding-worker hot loops (dedup,
  shard partition, pooling; ref: embedding_worker_service preprocessing)
- ``native/libpersia_cache.so`` — HBM write-back cache directory +
  positions-level admit + the fused feeder entry point
  (``cache_feed_batch``: admit + eviction selection + row LUT + hazard
  ledger in one call) + the mutex-protected pending-sign map + seeded init

``scripts/round_preflight.sh`` step 0 force-rebuilds all three and runs
the ABI parity tests (tests/test_native_feed.py) so a broken ctypes
signature cannot land silently.
"""

from __future__ import annotations


def main() -> int:
    from persia_tpu.embedding import hbm_cache, native_store, native_worker

    for name, builder in (
        ("ps", native_store.build_native),
        ("worker", native_worker.build_native),
        ("cache", hbm_cache.build_native),
    ):
        path = builder()
        print(f"{name}: {path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

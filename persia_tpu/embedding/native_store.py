"""ctypes bindings for the native C++ parameter-server core (`native/ps.cpp`).

``NativeEmbeddingStore`` exposes the exact same API as the numpy golden model
``persia_tpu.embedding.store.EmbeddingStore`` and is numerically parity-tested
against it (tests/test_native_store.py). ``create_store(backend="auto")``
prefers the native core and falls back to numpy if the toolchain is missing.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from persia_tpu.config import HyperParameters
from persia_tpu.embedding.optim import OptimizerConfig
from persia_tpu.embedding.store import EmbeddingStore
from persia_tpu.logger import get_default_logger

logger = get_default_logger("persia_tpu.native")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "ps.cpp")
_SO = os.path.join(_REPO_ROOT, "native", "libpersia_ps.so")
_LIB: Optional[ctypes.CDLL] = None


def build_native(force: bool = False) -> str:
    """Compile the native core if missing or stale (source-hash gated,
    atomic + cross-process race-safe — see ``_native_build.build_so``)."""
    from persia_tpu.embedding._native_build import build_so

    return build_so(
        _SRC, _SO,
        ["-O3", "-mavx2", "-mfma", "-std=c++17", "-fPIC", "-shared", "-Wall"],
        logger, force=force,
    )


def _load_lib() -> ctypes.CDLL:
    global _LIB
    if _LIB is not None:
        return _LIB
    # CDLL the path build_native RETURNS: under PERSIA_NATIVE_SANITIZE it
    # is the sanitizer-variant artifact, not _SO
    so_path = build_native()
    lib = ctypes.CDLL(so_path)
    u64, u32, i64, i32, f32 = (
        ctypes.c_uint64, ctypes.c_uint32, ctypes.c_int64, ctypes.c_int32, ctypes.c_float,
    )
    p = ctypes.c_void_p
    u64p = ctypes.POINTER(u64)
    f32p = ctypes.POINTER(f32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    # every binding declares BOTH restype and argtypes (restype = None for
    # void) — persia-lint ABI003/ABI007 enforce it mechanically
    lib.ps_create.restype = p
    lib.ps_create.argtypes = [u64, u32, u64]
    lib.ps_destroy.restype = None
    lib.ps_destroy.argtypes = [p]
    lib.ps_configure.restype = None
    lib.ps_configure.argtypes = [p, ctypes.c_double, ctypes.c_double, ctypes.c_double, f32]
    lib.ps_set_init_method.restype = None
    lib.ps_set_init_method.argtypes = [p, i32, ctypes.c_double, ctypes.c_double]
    lib.ps_register_optimizer.restype = None
    lib.ps_register_optimizer.argtypes = [p, i32, f32, f32, f32, f32, f32, i32, f32, f32]
    lib.ps_num_shards.restype = u32
    lib.ps_num_shards.argtypes = [p]
    lib.ps_lookup.restype = None
    lib.ps_lookup.argtypes = [p, u64p, i64, u32, i32, f32p]
    lib.ps_checkout.restype = i64
    lib.ps_checkout.argtypes = [p, u64p, i64, u32, f32p]
    lib.ps_probe_entries.restype = i64
    lib.ps_probe_entries.argtypes = [p, u64p, i64, u32, f32p, u8p]
    lib.ps_advance_batch_state.restype = None
    lib.ps_advance_batch_state.argtypes = [p, i32]
    lib.ps_update_gradients.restype = i32
    lib.ps_update_gradients.argtypes = [p, u64p, i64, u32, f32p, i32]
    lib.ps_set_embedding.restype = None
    lib.ps_set_embedding.argtypes = [p, u64p, i64, u32, u32, f32p]
    lib.ps_get_entry.restype = i32
    lib.ps_get_entry.argtypes = [p, u64, f32p, i32]
    lib.ps_get_entry_dim.restype = i32
    lib.ps_get_entry_dim.argtypes = [p, u64]
    lib.ps_size.restype = i64
    lib.ps_size.argtypes = [p]
    lib.ps_clear.restype = None
    lib.ps_clear.argtypes = [p]
    lib.ps_dump_shard_size.restype = i64
    lib.ps_dump_shard_size.argtypes = [p, u32]
    lib.ps_dump_shard.restype = i64
    lib.ps_dump_shard.argtypes = [p, u32, u8p, i64]
    lib.ps_load_shard.restype = i64
    lib.ps_load_shard.argtypes = [p, u8p, i64]
    i64p = ctypes.POINTER(i64)
    u32p = ctypes.POINTER(u32)
    i32p = ctypes.POINTER(i32)
    lib.ps_lookup_batched.restype = None
    lib.ps_lookup_batched.argtypes = [p, u64p, i64p, u32p, i64p, i32, i32, f32p]
    lib.ps_update_batched.restype = i32
    lib.ps_update_batched.argtypes = [p, u64p, i64p, u32p, f32p, i64p, i32p, i32]
    # bounded apply-journal (exactly-once trainer resume, jobstate.py)
    lib.ps_journal_record.restype = None
    lib.ps_journal_record.argtypes = [p, u64, u32]
    lib.ps_journal_probe.restype = i32
    lib.ps_journal_probe.argtypes = [p, u64, u32]
    lib.ps_journal_len.restype = i64
    lib.ps_journal_len.argtypes = [p]
    lib.ps_journal_clear.restype = None
    lib.ps_journal_clear.argtypes = [p]
    lib.ps_scan_nonfinite.restype = i64
    lib.ps_scan_nonfinite.argtypes = [p, u64p, i64]
    # elastic handoff (live resharding): hash-range export/delete
    lib.ps_export_range_size.restype = i64
    lib.ps_export_range_size.argtypes = [p, u64, u64]
    lib.ps_export_range.restype = i64
    lib.ps_export_range.argtypes = [p, u64, u64, u8p, i64]
    lib.ps_delete_range.restype = i64
    lib.ps_delete_range.argtypes = [p, u64, u64]
    _LIB = lib
    return lib


def _check_group_layout(signs: np.ndarray, key_ofs: np.ndarray,
                        dims: np.ndarray) -> None:
    """The native batched calls trust this layout with raw pointers: a bad
    ``key_ofs`` from Python would walk rows outside the group table (stale
    thread-local group ids → out-of-bounds writes), so reject it here."""
    if len(key_ofs) != len(dims) + 1:
        raise ValueError("key_ofs must have len(dims) + 1 entries")
    if len(key_ofs) == 0 or key_ofs[0] != 0 or key_ofs[-1] != len(signs):
        raise ValueError("key_ofs must start at 0 and end at len(signs)")
    if np.any(np.diff(key_ofs) < 0):
        raise ValueError("key_ofs must be non-decreasing")


def _u64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class NativeEmbeddingStore:
    """Drop-in replacement for the numpy ``EmbeddingStore`` backed by the C++
    core. See `native/ps.cpp` for semantics/citations."""

    def __init__(
        self,
        capacity: int = 1 << 20,
        num_internal_shards: int = 8,
        hyperparams: HyperParameters = HyperParameters(),
        optimizer: Optional[OptimizerConfig] = None,
        seed: int = 0,
    ):
        if num_internal_shards <= 0 or capacity <= 0:
            raise ValueError("capacity and num_internal_shards must be positive")
        self._lib = _load_lib()
        self._h = self._lib.ps_create(capacity, num_internal_shards, seed)
        if not self._h:
            raise MemoryError("ps_create failed")
        self.seed = seed
        self._num_shards = num_internal_shards
        self.optimizer: Optional[OptimizerConfig] = None
        self.inc_manager = None  # set by persia_tpu.incremental.attach_incremental
        self.configure(hyperparams)
        if optimizer is not None:
            self.register_optimizer(optimizer)

    # lifecycle ------------------------------------------------------------

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.ps_destroy(h)
            self._h = None

    # config ---------------------------------------------------------------

    def configure(self, hyperparams: HyperParameters) -> None:
        self.hyperparams = hyperparams
        lo, hi = hyperparams.emb_initialization
        self._lib.ps_configure(
            self._h, lo, hi, hyperparams.admit_probability, hyperparams.weight_bound
        )
        m = hyperparams.resolved_init_method()
        self._lib.ps_set_init_method(self._h, m.code, m.p0, m.p1)

    def register_optimizer(self, optimizer: OptimizerConfig) -> None:
        self.optimizer = optimizer
        o = optimizer
        self._lib.ps_register_optimizer(
            self._h, o.kind, o.lr, o.weight_decay, o.initialization,
            o.g_square_momentum, o.eps, int(o.vectorwise_shared), o.beta1, o.beta2,
        )

    # data plane -----------------------------------------------------------

    def lookup(self, signs: np.ndarray, dim: int, train: bool) -> np.ndarray:
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        out = np.empty((len(signs), dim), dtype=np.float32)
        self._lib.ps_lookup(self._h, _u64p(signs), len(signs), dim, int(train), _f32p(out))
        return out

    def checkout_entries(self, signs: np.ndarray, dim: int) -> np.ndarray:
        """Batched [emb | optimizer state] fetch for the HBM cache tier —
        same semantics as the numpy golden model's ``checkout_entries``."""
        if self.optimizer is None:
            # see EmbeddingStore.checkout_entries: a config-less store must
            # not serve state-less rows to the cache tier
            raise RuntimeError("no optimizer registered")
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        entry_len = dim + self.optimizer.state_dim(dim)
        out = np.empty((len(signs), entry_len), dtype=np.float32)
        got = self._lib.ps_checkout(self._h, _u64p(signs), len(signs), dim, _f32p(out))
        if got != entry_len:
            raise RuntimeError(f"ps_checkout entry_len {got} != expected {entry_len}")
        return out

    supports_probe_out = True

    def probe_entries(self, signs: np.ndarray, dim: int,
                      vals_out=None, warm_out=None):
        """Warm/cold split (no admission) — see the golden model's
        ``probe_entries``. Returns (warm (n,) bool, vals (n, entry_len)).
        Cold rows of ``vals`` are UNSPECIFIED (callers read warm rows only);
        caller-owned ``vals_out``/``warm_out`` avoid the per-call mmap
        allocation on the cache tier's hot path. ``warm_out`` may be any
        1-byte dtype; the native call writes every element."""
        if self.optimizer is None:
            raise RuntimeError("no optimizer registered")  # see checkout_entries
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        entry_len = dim + self.optimizer.state_dim(dim)
        n = len(signs)
        vals = vals_out if vals_out is not None else np.empty(
            (n, entry_len), dtype=np.float32
        )
        warm = warm_out if warm_out is not None else np.empty(n, dtype=np.uint8)
        got = self._lib.ps_probe_entries(
            self._h, _u64p(signs), n, dim, _f32p(vals),
            warm.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        if got != entry_len:
            raise RuntimeError(f"ps_probe_entries entry_len {got} != {entry_len}")
        return warm.view(np.bool_)[:n] if warm_out is not None else warm.astype(bool), vals

    def lookup_batched(self, signs: np.ndarray, key_ofs: np.ndarray,
                       dims: np.ndarray, train: bool) -> np.ndarray:
        """Multi-slot lookup in ONE native call (ref batching:
        lookup_batched_all_slots, embedding_worker_service/mod.rs:874-942).
        Group g covers ``signs[key_ofs[g]:key_ofs[g+1]]`` with dim
        ``dims[g]``; returns one flat f32 buffer with group g's rows at
        float offset ``sum(counts[:g] * dims[:g])`` (the layout
        ``EmbeddingStore.lookup_batched`` documents). State effects are
        identical to per-group ``lookup`` calls."""
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        key_ofs = np.ascontiguousarray(key_ofs, dtype=np.int64)
        dims = np.ascontiguousarray(dims, dtype=np.uint32)
        _check_group_layout(signs, key_ofs, dims)
        counts = np.diff(key_ofs)
        sizes = counts * dims.astype(np.int64)
        out_ofs = np.zeros(len(dims), dtype=np.int64)
        np.cumsum(sizes[:-1], out=out_ofs[1:])
        out = np.empty(int(sizes.sum()), dtype=np.float32)
        self._lib.ps_lookup_batched(
            self._h, _u64p(signs),
            key_ofs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            dims.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            out_ofs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(dims), int(train), _f32p(out),
        )
        return out

    def update_batched(self, signs: np.ndarray, key_ofs: np.ndarray,
                       dims: np.ndarray, grads: np.ndarray,
                       opt_groups: np.ndarray) -> None:
        """Multi-slot gradient update in ONE native call; ``grads`` is the
        flat f32 buffer in ``lookup_batched``'s layout, ``opt_groups[g]`` the
        optimizer group of slot g. The caller advances Adam batch state once
        per gradient batch beforehand (batch-level beta powers,
        optim.rs:99-221)."""
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        key_ofs = np.ascontiguousarray(key_ofs, dtype=np.int64)
        dims = np.ascontiguousarray(dims, dtype=np.uint32)
        _check_group_layout(signs, key_ofs, dims)
        grads = np.ascontiguousarray(grads, dtype=np.float32).reshape(-1)
        opt_groups = np.ascontiguousarray(opt_groups, dtype=np.int32)
        counts = np.diff(key_ofs)
        sizes = counts * dims.astype(np.int64)
        grad_ofs = np.zeros(len(dims), dtype=np.int64)
        np.cumsum(sizes[:-1], out=grad_ofs[1:])
        if grads.size != int(sizes.sum()):
            raise ValueError("grads size does not match key_ofs/dims layout")
        rc = self._lib.ps_update_batched(
            self._h, _u64p(signs),
            key_ofs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            dims.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            _f32p(grads),
            grad_ofs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            opt_groups.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(dims),
        )
        if rc != 0:
            raise RuntimeError("no optimizer registered")
        if self.inc_manager is not None:
            self.inc_manager.commit(signs)

    def advance_batch_state(self, group: int) -> None:
        self._lib.ps_advance_batch_state(self._h, group)

    def update_gradients(self, signs: np.ndarray, grads: np.ndarray, group: int = 0) -> None:
        if grads.shape[0] != len(signs):
            raise ValueError("signs/grads length mismatch")
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        grads = np.ascontiguousarray(grads, dtype=np.float32)
        rc = self._lib.ps_update_gradients(
            self._h, _u64p(signs), len(signs), grads.shape[1], _f32p(grads), group
        )
        if rc != 0:
            raise RuntimeError("no optimizer registered")
        if self.inc_manager is not None:
            self.inc_manager.commit(signs)

    # apply-journal ----------------------------------------------------------

    def journal_record(self, journal_id: int, crc: int) -> None:
        self._lib.ps_journal_record(self._h, journal_id, crc & 0xFFFFFFFF)

    def journal_probe(self, journal_id: int, crc: int) -> int:
        """1 = already applied (crc matches), 0 = unknown, -1 = same id
        recorded with a DIFFERENT payload crc (replay divergence)."""
        return int(self._lib.ps_journal_probe(self._h, journal_id, crc & 0xFFFFFFFF))

    def journal_len(self) -> int:
        return int(self._lib.ps_journal_len(self._h))

    def scan_nonfinite(self, cap: int = 65536):
        """Health scrub (persia_tpu/health): repair every NaN/Inf row to
        the deterministic seeded init. Returns ``(repaired_count, signs)``
        — ``signs`` holds at most ``cap`` repaired signs."""
        out = np.zeros(max(int(cap), 1), dtype=np.uint64)
        n = int(self._lib.ps_scan_nonfinite(self._h, _u64p(out), len(out)))
        return n, out[: min(n, len(out))].copy()

    def journal_clear(self) -> None:
        self._lib.ps_journal_clear(self._h)

    def update_batched_journaled(
        self, journal_id: int, crc: int, signs, key_ofs, dims, grads, opt_groups,
    ) -> bool:
        """Exactly-once gradient apply: skip if the journal already holds
        (id, crc); apply + record otherwise. Returns True when applied,
        False on a duplicate. See ``EmbeddingStore.update_batched_journaled``
        for the window semantics."""
        st = self.journal_probe(journal_id, crc)
        if st != 0:
            if st == -1:
                # journal-only resume: the replay recomputed different
                # gradients (its forwards saw post-fence PS state); the
                # original application stands — skip = exactly-once
                logger.warning(
                    "apply-journal id %#x replayed with a different payload "
                    "crc — keeping the original application (exactly-once)",
                    journal_id,
                )
            return False
        self.update_batched(signs, key_ofs, dims, grads, opt_groups)
        self.journal_record(journal_id, crc)
        return True

    # management -----------------------------------------------------------

    def set_embedding(
        self, signs: np.ndarray, values: np.ndarray, dim: Optional[int] = None,
        commit_incremental: bool = False,
    ) -> None:
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        values = np.ascontiguousarray(values, dtype=np.float32)
        if dim is None:
            dim = values.shape[1]
        self._lib.ps_set_embedding(
            self._h, _u64p(signs), len(signs), dim, values.shape[1], _f32p(values)
        )
        if commit_incremental and self.inc_manager is not None:
            # write-backs are the cached tier's gradient path (see
            # EmbeddingStore.set_embedding)
            self.inc_manager.commit(signs)

    def get_embedding_entry(self, sign: int) -> Optional[np.ndarray]:
        # two locked calls (size, then copy): retry if a concurrent eviction
        # or re-init changes the entry in between
        for _ in range(8):
            ln = self._lib.ps_get_entry(self._h, sign, None, 0)
            if ln < 0:
                return None
            out = np.empty(ln, dtype=np.float32)
            ln2 = self._lib.ps_get_entry(self._h, sign, _f32p(out), ln)
            if ln2 == ln:
                return out
            if ln2 < 0:
                return None
        raise RuntimeError(f"entry for sign {sign} kept changing concurrently")

    def get_entry_dim(self, sign: int) -> Optional[int]:
        d = self._lib.ps_get_entry_dim(self._h, sign)
        return None if d < 0 else int(d)

    def get_entry_record(self, sign: int):
        """(dim, full entry) snapshot; dim is re-read after the copy and the
        pair is retried if a concurrent re-init changed it in between."""
        for _ in range(8):
            d = self._lib.ps_get_entry_dim(self._h, sign)
            if d < 0:
                return None
            vec = self.get_embedding_entry(sign)
            if vec is None:
                return None
            if self._lib.ps_get_entry_dim(self._h, sign) == d and d <= len(vec):
                return int(d), vec
        raise RuntimeError(f"entry for sign {sign} kept changing concurrently")

    def clear(self) -> None:
        self._lib.ps_clear(self._h)

    def size(self) -> int:
        return int(self._lib.ps_size(self._h))

    @property
    def num_internal_shards(self) -> int:
        return self._num_shards

    # checkpoint -----------------------------------------------------------

    def dump_shard(self, shard_idx: int) -> bytes:
        n = self._lib.ps_dump_shard_size(self._h, shard_idx)
        if n < 0:
            raise IndexError(f"shard {shard_idx} out of range")
        # the size and dump calls take the shard mutex separately, so a
        # non-blocking checkpoint racing with training can see the shard grow
        # in between (ps_dump_shard returns -1 on overflow) — re-measure with
        # headroom and retry; growth is bounded by the shard's LRU capacity
        for _ in range(8):
            buf = np.empty(max(n, 4), dtype=np.uint8)
            written = self._lib.ps_dump_shard(
                self._h, shard_idx,
                buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(buf),
            )
            if written >= 0:
                return buf[:written].tobytes()
            n = max(self._lib.ps_dump_shard_size(self._h, shard_idx), n * 2)
        raise RuntimeError("dump_shard failed: shard kept growing concurrently")

    def load_shard_bytes(self, raw: bytes) -> int:
        buf = np.frombuffer(raw, dtype=np.uint8)
        n = self._lib.ps_load_shard(
            self._h, buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(buf)
        )
        if n < 0:
            raise ValueError("corrupt shard payload")
        return int(n)

    # elastic handoff --------------------------------------------------------

    def export_range(self, lo: int, hi: int) -> bytes:
        """Serialize every entry whose routing hash lies in ``[lo, hi)``
        (``hi == 0`` = 2^64), sorted by sign — deterministic bytes so the
        handoff journal's crc dedups re-exports. Same size/retry idiom as
        ``dump_shard`` (the size and export calls lock separately)."""
        n = self._lib.ps_export_range_size(self._h, lo, hi)
        for _ in range(8):
            buf = np.empty(max(n, 4), dtype=np.uint8)
            written = self._lib.ps_export_range(
                self._h, lo, hi,
                buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(buf),
            )
            if written >= 0:
                return buf[:written].tobytes()
            n = max(self._lib.ps_export_range_size(self._h, lo, hi), n * 2)
        raise RuntimeError("export_range failed: range kept growing concurrently")

    def delete_range(self, lo: int, hi: int) -> int:
        """Drop every entry whose routing hash lies in ``[lo, hi)``; returns
        the removed count (0 on an idempotent replay)."""
        return int(self._lib.ps_delete_range(self._h, lo, hi))

    def import_range_journaled(self, journal_id: int, crc: int, blob: bytes) -> bool:
        """Exactly-once range import — see the golden model's docstring for
        the -1 (source-already-released) resume semantics."""
        st = self.journal_probe(journal_id, crc)
        if st != 0:
            if st == -1:
                logger.info(
                    "handoff import id %#x re-offered with a different crc — "
                    "source already released the range; original import "
                    "stands (exactly-once)", journal_id,
                )
            return False
        self.load_shard_bytes(blob)
        self.journal_record(journal_id, crc)
        return True

    def delete_range_journaled(self, journal_id: int, crc: int, lo: int, hi: int):
        """Exactly-once source-side range release; (lo, hi)-constant crc.
        Returns (applied, removed)."""
        if self.journal_probe(journal_id, crc) != 0:
            return False, 0
        removed = self.delete_range(lo, hi)
        self.journal_record(journal_id, crc)
        return True, removed


def native_available() -> bool:
    try:
        _load_lib()
        return True
    except Exception as e:  # toolchain missing / compile error
        logger.warning("native PS core unavailable, falling back to numpy: %s", e)
        return False


def store_backend_name(store) -> str:
    """Human-readable backend of a lookup replica: ``native`` (C++ core,
    carries a ctypes handle), ``numpy`` (the golden model), or ``remote``
    (an RPC client proxying a server whose backend is its own business).
    The serving/PS health surfaces report this so a mixed-backend fleet is
    diagnosable from the outside."""
    if getattr(store, "_h", None):
        return "native"
    if isinstance(store, EmbeddingStore):
        return "numpy"
    return "remote"


def create_store(backend: str = "auto", **kwargs):
    """Factory: ``auto`` prefers the C++ core, ``native`` requires it,
    ``numpy`` forces the golden model."""
    if backend == "numpy":
        return EmbeddingStore(**kwargs)
    if backend == "native":
        _load_lib()
        return NativeEmbeddingStore(**kwargs)
    if backend == "auto":
        if native_available():
            return NativeEmbeddingStore(**kwargs)
        return EmbeddingStore(**kwargs)
    raise ValueError(f"unknown store backend {backend!r}")

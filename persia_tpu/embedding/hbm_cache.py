"""Write-back HBM embedding cache over the host parameter-server tier.

The TPU answer to the reference's beyond-GPU-memory regime
(`README.md:29` — 100T parameters on CPU parameter servers): keep the
authoritative, unbounded-vocab store on the host PS tier
(`persia_tpu.embedding.store` / `native_store`), but keep the *working set*
resident in HBM as a fixed-size row pool, so

- **hits** never cross the host↔device boundary at all: the step receives
  int32 cache-row indices (4 B/id instead of ``4·dim`` B/id), gathers from
  HBM, and applies the sparse optimizer **on device** to the cached rows —
  gradients never leave the chip;
- **misses** check full ``[emb | optimizer state]`` rows out of the PS
  (`checkout_entries`) and scatter them into the cache inside the same
  jitted step;
- **evictions** (LRU, decided by the native C++ directory `native/cache.cpp`)
  read the victim rows back out of the step (they ride the step's output)
  and write them to the PS — the write-back.

With a skewed (production-like) id distribution the steady-state miss rate
is small, so per-step host↔device traffic approaches the fused HBM path's
(ids only) while vocabulary stays unbounded like the reference's PS. This
replaces the reference's *bounded-staleness* asynchrony with *bounded
residency*: cached rows train fully synchronously (stronger than the
reference's staleness>0 mode); only tier migration is asynchronous-ish.

Limitations (v1): hash-stack slots are not cacheable (their table keys are
many-to-one per distinct id); Adam's beta powers advance on-device per step
— mixing cached and uncached gradient updates for the same table under Adam
can diverge slightly from a pure-PS run (Adagrad/SGD are exact).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from persia_tpu.config import EmbeddingConfig
from persia_tpu.data import PersiaBatch
from persia_tpu.embedding.optim import OptimizerConfig
from persia_tpu.embedding.worker import (
    ProcessedBatch,
    ProcessedSlot,
    ShardedLookup,
    preprocess_batch,
)
from persia_tpu.logger import get_default_logger
from persia_tpu.ops.sparse_update import sparse_update

logger = get_default_logger("persia_tpu.hbm_cache")

# ------------------------------------------------------------------ ctypes

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "cache.cpp")
_SO = os.path.join(_REPO_ROOT, "native", "libpersia_cache.so")
_BUILD_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None

_i64p = ctypes.POINTER(ctypes.c_int64)
_u64p = ctypes.POINTER(ctypes.c_uint64)


def build_native(force: bool = False) -> str:
    stamp = _SO + ".srchash"
    with _BUILD_LOCK:
        with open(_SRC, "rb") as f:
            h = hashlib.sha256(f.read()).hexdigest()
        if not force and os.path.exists(_SO) and os.path.exists(stamp):
            with open(stamp) as f:
                if f.read().strip() == h:
                    return _SO
        cmd = ["g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-Wall", "-o", _SO, _SRC]
        logger.info("building native cache directory: %s", " ".join(cmd))
        subprocess.check_call(cmd)
        with open(stamp, "w") as f:
            f.write(h)
        return _SO


def _load_lib() -> ctypes.CDLL:
    global _LIB
    if _LIB is None:
        build_native()
        lib = ctypes.CDLL(_SO)
        i64, p = ctypes.c_int64, ctypes.c_void_p
        lib.cache_create.restype = p
        lib.cache_create.argtypes = [i64]
        lib.cache_destroy.argtypes = [p]
        lib.cache_len.restype = i64
        lib.cache_len.argtypes = [p]
        lib.cache_capacity.restype = i64
        lib.cache_capacity.argtypes = [p]
        lib.cache_admit.restype = i64
        lib.cache_admit.argtypes = [p, _u64p, i64, _i64p, _i64p, _u64p, _i64p, _i64p]
        lib.cache_probe.argtypes = [p, _u64p, i64, _i64p]
        lib.cache_drain.restype = i64
        lib.cache_drain.argtypes = [p, _u64p, _i64p]
        _LIB = lib
    return _LIB


class CacheDirectory:
    """LRU map sign → device cache row (native C++, O(1) per op)."""

    def __init__(self, capacity: int):
        self._lib = _load_lib()
        self._h = self._lib.cache_create(capacity)
        self.capacity = capacity

    def __del__(self):
        if getattr(self, "_h", None) is not None:
            self._lib.cache_destroy(self._h)
            self._h = None

    def __len__(self) -> int:
        return self._lib.cache_len(self._h)

    def admit(self, signs: np.ndarray):
        """signs must be deduplicated. Returns (rows (n,), miss_idx (M,),
        evict_signs (K,), evict_rows (K,))."""
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        n = len(signs)
        rows = np.empty(n, dtype=np.int64)
        miss_idx = np.empty(n, dtype=np.int64)
        ev_signs = np.empty(n, dtype=np.uint64)
        ev_rows = np.empty(n, dtype=np.int64)
        n_evict = ctypes.c_int64(0)
        n_miss = self._lib.cache_admit(
            self._h, signs.ctypes.data_as(_u64p), n,
            rows.ctypes.data_as(_i64p), miss_idx.ctypes.data_as(_i64p),
            ev_signs.ctypes.data_as(_u64p), ev_rows.ctypes.data_as(_i64p),
            ctypes.byref(n_evict),
        )
        k = n_evict.value
        return rows, miss_idx[:n_miss].copy(), ev_signs[:k].copy(), ev_rows[:k].copy()

    def probe(self, signs: np.ndarray) -> np.ndarray:
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        rows = np.empty(len(signs), dtype=np.int64)
        self._lib.cache_probe(self._h, signs.ctypes.data_as(_u64p), len(signs),
                              rows.ctypes.data_as(_i64p))
        return rows

    def drain(self) -> Tuple[np.ndarray, np.ndarray]:
        """Empty the directory; returns (signs, rows) of everything resident."""
        cap = self.capacity
        signs = np.empty(cap, dtype=np.uint64)
        rows = np.empty(cap, dtype=np.int64)
        k = self._lib.cache_drain(self._h, signs.ctypes.data_as(_u64p),
                                  rows.ctypes.data_as(_i64p))
        return signs[:k].copy(), rows[:k].copy()


# ------------------------------------------------------------ device state


@flax.struct.dataclass
class CachedTrainState:
    params: object
    batch_stats: object
    opt_state: object
    tables: Dict[str, jnp.ndarray]  # group → (C+1, dim); row C is the zero pad row
    emb_state: Dict[str, Dict[str, jnp.ndarray]]  # group → optimizer state (C+1, ·)
    emb_batch_state: jnp.ndarray
    step: jnp.ndarray


@dataclass(frozen=True)
class CacheGroup:
    """One HBM row pool shared by all slots of one embedding dim."""

    name: str
    dim: int
    rows: int  # cache capacity C (the table itself has C+1 rows)
    state_dim: int
    slots: Tuple[str, ...]


def _round_up_pow2(n: int, floor: int = 8) -> int:
    p = floor
    while p < n:
        p <<= 1
    return p


def make_cache_groups(
    cfg: EmbeddingConfig, rows_per_group: Dict[int, int], sparse_cfg: OptimizerConfig
) -> List[CacheGroup]:
    """Group slots by dim (all same-dim slots share one row pool — signs are
    already disjoint across slots via index prefixes, the reference's global
    key space partition, `embedding_worker_service/mod.rs:403-429`)."""
    by_dim: Dict[int, List[str]] = {}
    for name, slot in cfg.slots_config.items():
        if slot.hash_stack_config.enabled:
            raise ValueError(
                f"slot {name!r}: hash-stack slots are not cacheable (many table "
                "keys per id) — keep them on the pure PS path"
            )
        by_dim.setdefault(slot.dim, []).append(name)
    groups = []
    for dim in sorted(by_dim):
        groups.append(
            CacheGroup(
                name=f"cache_d{dim}",
                dim=dim,
                rows=rows_per_group[dim],
                state_dim=sparse_cfg.state_dim(dim),
                slots=tuple(sorted(by_dim[dim])),
            )
        )
    return groups


def init_cached_tables(
    groups: Sequence[CacheGroup], sparse_cfg: OptimizerConfig, dtype=jnp.float32
):
    """Zeroed row pools (+1 pad row at index C whose zeros absorb padding
    gathers). Content arrives via checkout scatters; initial values are
    irrelevant except the pad row, which the masked sparse update never
    touches."""
    from persia_tpu.ops.sparse_update import init_sparse_state

    tables, emb_state = {}, {}
    for g in groups:
        tables[g.name] = jnp.zeros((g.rows + 1, g.dim), dtype=dtype)
        emb_state[g.name] = init_sparse_state(sparse_cfg, g.rows + 1, g.dim)
    return tables, emb_state


def _entry_to_state_cols(state: Dict[str, jnp.ndarray], entry_tail):
    """Split the PS entry's state tail (M, state_dim) into sparse_update's
    per-key columns — PS entry layout is [emb | acc] (adagrad) or
    [emb | m | v] (adam), `persia_tpu/embedding/optim.py` init_state /
    update_dense."""
    out = {}
    off = 0
    for key in ("acc", "m", "v"):
        if key in state:
            w = state[key].shape[1]
            out[key] = entry_tail[:, off:off + w]
            off += w
    return out


# ----------------------------------------------------------- device step


def build_cached_train_step(
    model,
    dense_optimizer,
    sparse_cfg: OptimizerConfig,
    groups: Sequence[CacheGroup],
    loss_fn=None,
    donate: bool = True,
):
    """Jitted ``step(state, batch) -> (state, (header, evict_payload))``.

    batch = {
      "dense": [(B,F) f32], "labels": [(B,1) f32],
      "rows": {slot: (B, L) int32 cache rows, pad = C (the zero row)},
      "scale": {slot: (B,) f32 pooling scale (1 or 1/sqrt(count)) or None},
      "pooled": {slot: bool},
      "miss_rows": {group: (Mp,) int32, pad = C+1 (dropped by scatter)},
      "miss_entries": {group: (Mp, dim+state_dim) f32},
      "evict_rows": {group: (Kp,) int32, pad = C (host slices true K)},
    }
    ``evict_payload`` = {group: (Kp, dim+state_dim) f32} read BEFORE the
    miss scatter overwrites the reused rows.
    """
    from persia_tpu.parallel.train_step import default_loss_fn

    loss_fn = loss_fn or default_loss_fn
    by_name = {g.name: g for g in groups}
    slot_group = {}
    for g in groups:
        for s in g.slots:
            slot_group[s] = g.name

    def step(state: CachedTrainState, batch: Dict):
        tables, emb_state = dict(state.tables), dict(state.emb_state)

        # 1) read evicted rows out (pre-scatter values = the write-back data)
        evict_payload = {}
        for gname, ev_rows in batch["evict_rows"].items():
            g = by_name[gname]
            parts = [tables[gname][ev_rows]]
            st = emb_state[gname]
            for key in ("acc", "m", "v"):
                if key in st:
                    parts.append(st[key][ev_rows])
            evict_payload[gname] = jnp.concatenate(parts, axis=1)

        # 2) scatter checked-out PS entries into the cache (pad rows drop)
        for gname, m_rows in batch["miss_rows"].items():
            g = by_name[gname]
            ent = batch["miss_entries"][gname]
            emb = ent[:, : g.dim].astype(tables[gname].dtype)
            tables[gname] = tables[gname].at[m_rows].set(emb, mode="drop")
            st = dict(emb_state[gname])
            cols = _entry_to_state_cols(st, ent[:, g.dim:])
            for key, vals in cols.items():
                st[key] = st[key].at[m_rows].set(vals, mode="drop")
            emb_state[gname] = st

        # 3) gather the batch's rows once per slot; differentiate w.r.t. the
        # GATHERED arrays (like the fused path) so cotangents stay (B, L, dim)
        # instead of dense table-shaped scatters
        slot_names = sorted(batch["rows"])
        gathered = {
            name: tables[slot_group[name]][batch["rows"][name]]
            for name in slot_names
        }
        masks = {
            name: batch["rows"][name] < by_name[slot_group[name]].rows
            for name in slot_names
        }

        def loss_wrapper(params, gathered_in):
            model_emb = []
            for name in slot_names:
                g = gathered_in[name]  # (B, L, dim)
                mask = masks[name]
                if batch["pooled"][name]:
                    m = mask[..., None].astype(g.dtype)
                    pooled = (g * m).sum(axis=1)
                    scale = batch["scale"][name]
                    if scale is not None:
                        pooled = pooled * scale[:, None].astype(pooled.dtype)
                    model_emb.append(pooled)
                else:
                    model_emb.append((g, mask))
            variables = {"params": params}
            if state.batch_stats:
                variables["batch_stats"] = state.batch_stats
                logits, updates = model.apply(
                    variables, batch["dense"], model_emb, train=True,
                    mutable=["batch_stats"],
                )
                new_stats = updates["batch_stats"]
            else:
                logits = model.apply(variables, batch["dense"], model_emb, train=True)
                new_stats = state.batch_stats
            loss = loss_fn(logits, batch["labels"][0])
            return loss, (logits, new_stats)

        (loss, (logits, new_stats)), (param_grads, emb_grads) = jax.value_and_grad(
            loss_wrapper, argnums=(0, 1), has_aux=True
        )(state.params, gathered)

        # 4) dense update
        import optax as _optax

        updates, new_opt_state = dense_optimizer.update(
            param_grads, state.opt_state, state.params
        )
        new_params = _optax.apply_updates(state.params, updates)

        # 5) on-device sparse update of the cached rows (dedup inside
        # sparse_update handles the same row appearing in several slots)
        batch_state = state.emb_batch_state * jnp.array(
            [sparse_cfg.beta1, sparse_cfg.beta2], dtype=jnp.float32
        )
        for g in groups:
            idp, gp, mp = [], [], []
            for name in g.slots:
                if name not in batch["rows"]:
                    continue
                rows = batch["rows"][name]
                flat_rows = rows.reshape(-1)
                flat_g = emb_grads[name].astype(jnp.float32).reshape(-1, g.dim)
                idp.append(flat_rows)
                gp.append(flat_g)
                mp.append(masks[name].reshape(-1))
            if not idp:
                continue
            tables[g.name], emb_state[g.name] = sparse_update(
                sparse_cfg,
                tables[g.name],
                emb_state[g.name],
                jnp.concatenate(idp) if len(idp) > 1 else idp[0],
                jnp.concatenate(gp) if len(gp) > 1 else gp[0],
                batch_state,
                mask=jnp.concatenate(mp) if len(mp) > 1 else mp[0],
            )

        new_state = CachedTrainState(
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt_state,
            tables=tables,
            emb_state=emb_state,
            emb_batch_state=batch_state,
            step=state.step + 1,
        )
        header = jnp.concatenate(
            [jnp.reshape(loss, (1,)).astype(jnp.float32),
             jnp.reshape(jax.nn.sigmoid(logits), (-1,)).astype(jnp.float32)]
        )
        return new_state, (header, evict_payload)

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def build_cached_eval_step(model, groups: Sequence[CacheGroup]):
    """Jitted ``eval_step(state, batch) -> preds`` over the same batch layout
    (the miss scatter still runs so checked-out rows are visible)."""
    by_name = {g.name: g for g in groups}
    slot_group = {}
    for g in groups:
        for s in g.slots:
            slot_group[s] = g.name

    def eval_step(state: CachedTrainState, batch: Dict):
        tables = dict(state.tables)
        for gname, m_rows in batch["miss_rows"].items():
            g = by_name[gname]
            emb = batch["miss_entries"][gname][:, : g.dim].astype(tables[gname].dtype)
            tables[gname] = tables[gname].at[m_rows].set(emb, mode="drop")
        model_emb = []
        for name in sorted(batch["rows"]):
            gname = slot_group[name]
            rows = batch["rows"][name]
            g = tables[gname][rows]
            mask = rows < by_name[gname].rows
            if batch["pooled"][name]:
                m = mask[..., None].astype(g.dtype)
                pooled = (g * m).sum(axis=1)
                scale = batch["scale"][name]
                if scale is not None:
                    pooled = pooled * scale[:, None].astype(pooled.dtype)
                model_emb.append(pooled)
            else:
                model_emb.append((g, mask))
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        logits = model.apply(variables, batch["dense"], model_emb, train=False)
        return jax.nn.sigmoid(logits)

    return jax.jit(eval_step)


# -------------------------------------------------------------- host tier


class CachedEmbeddingTier:
    """Host orchestration: directory admits, PS checkouts, write-backs.

    ``worker`` is an ``EmbeddingWorker`` (its ``lookup_router`` fans checkout
    and write-back out to the sharded PS replicas; its dump/load provide the
    checkpoint path for the authoritative store)."""

    def __init__(
        self,
        worker,
        sparse_cfg: OptimizerConfig,
        rows: int | Dict[int, int],
        embedding_config: Optional[EmbeddingConfig] = None,
    ):
        self.worker = worker
        self.cfg = embedding_config or worker.embedding_config
        self.sparse_cfg = sparse_cfg
        dims = {slot.dim for slot in self.cfg.slots_config.values()}
        rows_per_group = rows if isinstance(rows, dict) else {d: rows for d in dims}
        self.groups = make_cache_groups(self.cfg, rows_per_group, sparse_cfg)
        self.dirs = {g.name: CacheDirectory(g.rows) for g in self.groups}
        self._slot_group = {s: g for g in self.groups for s in g.slots}

    @property
    def router(self) -> ShardedLookup:
        return self.worker.lookup_router

    def prepare_batch(self, batch: PersiaBatch):
        """Admit the batch's distinct signs, check misses out of the PS, and
        build the device step inputs. Returns (device_inputs, evict_meta)
        where evict_meta = {group: (evict_signs, true_K)} for the write-back
        after the step."""
        pb = preprocess_batch(
            batch.id_type_features, self.cfg,
        )
        slots_by_group: Dict[str, List[ProcessedSlot]] = {}
        for slot in pb.slots:
            slots_by_group.setdefault(self._slot_group[slot.name].name, []).append(slot)

        rows_in: Dict[str, np.ndarray] = {}
        scale_in: Dict[str, Optional[np.ndarray]] = {}
        pooled_in: Dict[str, bool] = {}
        miss_rows_in: Dict[str, np.ndarray] = {}
        miss_entries_in: Dict[str, np.ndarray] = {}
        evict_rows_in: Dict[str, np.ndarray] = {}
        evict_meta: Dict[str, Tuple[np.ndarray, int]] = {}

        for g in self.groups:
            slots = slots_by_group.get(g.name, [])
            if not slots:
                continue
            C = g.rows
            all_signs = np.concatenate([s.distinct for s in slots]) if slots else np.empty(0, np.uint64)
            rows, miss_idx, ev_signs, ev_rows = self.dirs[g.name].admit(all_signs)
            if (rows < 0).any():
                raise RuntimeError(
                    f"cache group {g.name}: batch distinct count {len(all_signs)} "
                    f"exceeds cache rows {C}"
                )
            # checkout PS entries for the misses
            miss_signs = all_signs[miss_idx]
            entry_len = g.dim + g.state_dim
            m = len(miss_signs)
            mp = _round_up_pow2(max(m, 1))
            m_rows = np.full(mp, C + 1, dtype=np.int32)  # pad → scatter-drop
            m_entries = np.zeros((mp, entry_len), dtype=np.float32)
            if m:
                m_rows[:m] = rows[miss_idx]
                m_entries[:m] = self.router.checkout_entries(miss_signs, g.dim)
            miss_rows_in[g.name] = m_rows
            miss_entries_in[g.name] = m_entries
            # evictions: rows to read back (pad → zero row, host slices K)
            k = len(ev_rows)
            kp = _round_up_pow2(max(k, 1))
            e_rows = np.full(kp, C, dtype=np.int32)
            if k:
                e_rows[:k] = ev_rows
            evict_rows_in[g.name] = e_rows
            evict_meta[g.name] = (ev_signs, k)

            # per-slot (B, L) cache-row matrices
            off = 0
            for slot in slots:
                d = slot.num_distinct
                slot_rows = rows[off:off + d].astype(np.int64)
                off += d
                is_pooled = slot.config.embedding_summation
                if is_pooled:
                    L = _round_up_pow2(max(int(slot.counts.max()) if len(slot.counts) else 1, 1), floor=1)
                else:
                    L = slot.config.sample_fixed_size
                idx = _position_index(slot, L)
                # map distinct positions → cache rows; pad position (== d) → C
                lut = np.append(slot_rows, np.int64(C))
                rows_in[slot.name] = lut[idx].astype(np.int32)
                pooled_in[slot.name] = is_pooled
                if is_pooled and slot.config.sqrt_scaling:
                    scale_in[slot.name] = (
                        1.0 / np.sqrt(np.maximum(slot.counts, 1))
                    ).astype(np.float32)
                else:
                    scale_in[slot.name] = None

        device_inputs = {
            "dense": [f.data.astype(np.float32) for f in batch.non_id_type_features],
            "labels": [l.data.astype(np.float32) for l in batch.labels],
            "rows": rows_in,
            "scale": scale_in,
            "pooled": pooled_in,
            "miss_rows": miss_rows_in,
            "miss_entries": miss_entries_in,
            "evict_rows": evict_rows_in,
        }
        return device_inputs, evict_meta

    def write_back(self, evict_meta, evict_payload) -> None:
        """Persist evicted rows to the PS (full [emb | state] entries)."""
        for gname, (ev_signs, k) in evict_meta.items():
            if not k:
                continue
            g = next(gr for gr in self.groups if gr.name == gname)
            payload = np.asarray(evict_payload[gname], dtype=np.float32)[:k]
            self.router.set_embedding(ev_signs[:k], payload, dim=g.dim)

    def flush(self, tables, emb_state) -> None:
        """Drain every cached row back to the PS (checkpoint/eval boundary).
        ``tables``/``emb_state`` are the CURRENT device arrays."""
        for g in self.groups:
            signs, rows = self.dirs[g.name].drain()
            if not len(signs):
                continue
            tbl = np.asarray(tables[g.name], dtype=np.float32)
            parts = [tbl[rows]]
            st = emb_state[g.name]
            for key in ("acc", "m", "v"):
                if key in st:
                    parts.append(np.asarray(st[key], dtype=np.float32)[rows])
            self.router.set_embedding(
                signs, np.concatenate(parts, axis=1), dim=g.dim
            )


def _position_index(slot: ProcessedSlot, L: int) -> np.ndarray:
    """(B, L) matrix of positions into the slot's distinct array (pad == D),
    reusing the native raw-index builder."""
    from persia_tpu.embedding import native_worker

    idx = native_worker.raw_index(slot.counts, slot.inverse, L, slot.num_distinct)
    if idx is None:
        idx = np.full((slot.batch_size, L), slot.num_distinct, dtype=np.int32)
        pos = 0
        for b, c in enumerate(slot.counts.tolist()):
            take = min(c, L)
            idx[b, :take] = slot.inverse[pos:pos + take]
            pos += c
    return idx


# ------------------------------------------------------------------- ctx


class CachedTrainCtx:
    """Training context for the HBM-cached hybrid tier — the TrainCtx-shaped
    API (train_step / eval_batch / dump_checkpoint / load_checkpoint) with
    on-device sparse updates and write-back tier migration."""

    def __init__(
        self,
        model,
        dense_optimizer,
        embedding_optimizer,
        worker,
        embedding_config: EmbeddingConfig,
        cache_rows: int | Dict[int, int] = 1 << 20,
        loss_fn=None,
        table_dtype=jnp.float32,
    ):
        self.model = model
        self.dense_optimizer = dense_optimizer
        self.sparse_cfg = embedding_optimizer.config
        self.worker = worker
        self.embedding_config = embedding_config
        self.tier = CachedEmbeddingTier(
            worker, self.sparse_cfg, cache_rows, embedding_config
        )
        self._step = build_cached_train_step(
            model, dense_optimizer, self.sparse_cfg, self.tier.groups,
            loss_fn=loss_fn,
        )
        self._eval = build_cached_eval_step(model, self.tier.groups)
        self.table_dtype = table_dtype
        self.state: Optional[CachedTrainState] = None

    def __enter__(self):
        self.worker.register_optimizer(self.sparse_cfg)
        return self

    def __exit__(self, *exc):
        return False

    def init_state(self, rng, sample_inputs: Dict) -> CachedTrainState:
        import optax

        tables, emb_state = init_cached_tables(
            self.tier.groups, self.sparse_cfg, dtype=self.table_dtype
        )
        # build model inputs shaped like the step's to init params
        model_emb = []
        for name in sorted(sample_inputs["rows"]):
            g = self.tier._slot_group[name]
            rows = jnp.asarray(sample_inputs["rows"][name])
            gathered = tables[g.name][rows]
            mask = rows < g.rows
            if sample_inputs["pooled"][name]:
                model_emb.append((gathered * mask[..., None].astype(gathered.dtype)).sum(axis=1))
            else:
                model_emb.append((gathered, mask))
        variables = self.model.init(
            rng, sample_inputs["dense"], model_emb, train=False
        )
        params = variables["params"]
        self.state = CachedTrainState(
            params=params,
            batch_stats=variables.get("batch_stats", {}),
            opt_state=self.dense_optimizer.init(params),
            tables=tables,
            emb_state=emb_state,
            emb_batch_state=jnp.ones((2,), dtype=jnp.float32),
            step=jnp.zeros((), dtype=jnp.int32),
        )
        return self.state

    def train_step(self, batch: PersiaBatch) -> Dict:
        device_inputs, evict_meta = self.tier.prepare_batch(batch)
        if self.state is None:
            self.init_state(jax.random.PRNGKey(0), device_inputs)
        self.state, (header, evict_payload) = self._step(self.state, device_inputs)
        # PS-side Adam beta powers advance once per gradient batch, mirroring
        # the device's emb_batch_state, so write-backs land in a store whose
        # future updates use consistent powers
        self.router_advance()
        self.tier.write_back(evict_meta, evict_payload)
        header = np.asarray(header)
        labels = device_inputs["labels"][0]
        return {
            "loss": float(header[0]),
            "preds": header[1:].reshape(labels.shape),
        }

    def router_advance(self) -> None:
        self.tier.router.advance_batch_state(0)

    def eval_batch(self, batch: PersiaBatch) -> np.ndarray:
        device_inputs, evict_meta = self.tier.prepare_batch(batch)
        preds = self._eval(self.state, device_inputs)
        # eval admits (simplest single code path): scattered rows are only in
        # the eval-local table copy, so undo the directory state for misses
        # by writing their PS values back on eviction as usual
        self.tier.write_back(
            evict_meta,
            {g: np.zeros((len(device_inputs["evict_rows"][g]),
                          self._group(g).dim + self._group(g).state_dim),
                         np.float32)
             for g in device_inputs["evict_rows"]},
        )
        return np.asarray(preds)

    def _group(self, name: str) -> CacheGroup:
        return next(g for g in self.tier.groups if g.name == name)

    def flush(self) -> None:
        """Write every cached row back to the PS (checkpoint/eval boundary);
        the cache restarts cold."""
        if self.state is None:
            return
        self.tier.flush(self.state.tables, self.state.emb_state)
        # the directory is drained; zero the pools so stale rows can never be
        # mistaken for fresh checkouts
        tables, emb_state = init_cached_tables(
            self.tier.groups, self.sparse_cfg, dtype=self.table_dtype
        )
        self.state = self.state.replace(tables=tables, emb_state=emb_state)

    def dump_checkpoint(self, dst: str, blocking: bool = True) -> None:
        self.flush()
        self.worker.dump(dst, blocking=blocking)

    def load_checkpoint(self, src: str) -> None:
        self.flush()
        self.worker.load(src)

"""Write-back HBM embedding cache over the host parameter-server tier.

The TPU answer to the reference's beyond-GPU-memory regime
(`README.md:29` — 100T parameters on CPU parameter servers): keep the
authoritative, unbounded-vocab store on the host PS tier
(`persia_tpu.embedding.store` / `native_store`), but keep the *working set*
resident in HBM as a fixed-size row pool, so

- **hits** never cross the host↔device boundary at all: the step receives
  int32 cache-row indices (4 B/id instead of ``4·dim`` B/id), gathers from
  HBM, and applies the sparse optimizer **on device** to the cached rows —
  gradients never leave the chip;
- **misses** check full ``[emb | optimizer state]`` rows out of the PS
  (`checkout_entries`) and scatter them into the cache inside the same
  jitted step;
- **evictions** (LRU, decided by the native C++ directory `native/cache.cpp`)
  read the victim rows back out of the step (they ride the step's output)
  and write them to the PS — the write-back.

With a skewed (production-like) id distribution the steady-state miss rate
is small, so per-step host↔device traffic approaches the fused HBM path's
(ids only) while vocabulary stays unbounded like the reference's PS. This
replaces the reference's *bounded-staleness* asynchrony with *bounded
residency*: cached rows train fully synchronously (stronger than the
reference's staleness>0 mode); only tier migration is asynchronous-ish.

Pipelining: ``CachedTrainCtx.train_step`` defers the previous step's
eviction write-back (and metric fetch) until after the current step is
dispatched, so host-side preprocessing and PS traffic overlap the device
step — the TPU analogue of the reference's latency-hiding lookup workers
(`rust/persia-core/src/forward.rs:640-779`). A same-sign
evict-then-re-miss across adjacent steps is detected on the host (the
directory reports evictions synchronously) and forces the pending
write-back to land before the fresh checkout reads the PS.

Limitations (v1): hash-stack slots are not cacheable (their table keys are
many-to-one per distinct id); Adam's beta powers advance on-device per step
— mixing cached and uncached gradient updates for the same table under Adam
can diverge slightly from a pure-PS run (Adagrad/SGD are exact).
"""

from __future__ import annotations

import ctypes
import os
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from persia_tpu.config import EmbeddingConfig
from persia_tpu.data import PersiaBatch
from persia_tpu.embedding.optim import OPTIMIZER_ADAM, OptimizerConfig
from persia_tpu.embedding.worker import (
    ProcessedBatch,
    ProcessedSlot,
    ShardedLookup,
    preprocess_batch,
)
from persia_tpu.logger import get_default_logger
from persia_tpu.utils import round_up_pow2 as _round_up_pow2
from persia_tpu.metrics import get_metrics
from persia_tpu.ops.sparse_update import sparse_update
from persia_tpu.tracing import span

logger = get_default_logger("persia_tpu.hbm_cache")

# ------------------------------------------------------------------ ctypes

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "cache.cpp")
_SO = os.path.join(_REPO_ROOT, "native", "libpersia_cache.so")
_LIB: Optional[ctypes.CDLL] = None

_i64p = ctypes.POINTER(ctypes.c_int64)
_u64p = ctypes.POINTER(ctypes.c_uint64)


def build_native(force: bool = False) -> str:
    from persia_tpu.embedding._native_build import build_so

    return build_so(
        _SRC, _SO, ["-O3", "-std=c++17", "-fPIC", "-shared", "-Wall"],
        logger, force=force,
    )


def _load_lib() -> ctypes.CDLL:
    global _LIB
    if _LIB is None:
        build_native()
        lib = ctypes.CDLL(_SO)
        i64, p = ctypes.c_int64, ctypes.c_void_p
        lib.cache_create.restype = p
        lib.cache_create.argtypes = [i64]
        lib.cache_destroy.argtypes = [p]
        lib.cache_len.restype = i64
        lib.cache_len.argtypes = [p]
        lib.cache_capacity.restype = i64
        lib.cache_capacity.argtypes = [p]
        lib.cache_admit.restype = i64
        lib.cache_admit.argtypes = [p, _u64p, i64, _i64p, _i64p, _u64p, _i64p, _i64p]
        lib.cache_probe.argtypes = [p, _u64p, i64, _i64p]
        lib.cache_drain.restype = i64
        lib.cache_drain.argtypes = [p, _u64p, _i64p]
        lib.cache_snapshot.restype = i64
        lib.cache_snapshot.argtypes = [p, _u64p, _i64p]
        lib.cache_set_admit_touches.argtypes = [p, i64]
        _i32p = ctypes.POINTER(ctypes.c_int32)
        lib.cache_admit_positions.restype = i64
        lib.cache_admit_positions.argtypes = [
            p, _u64p, i64, _i32p, _u64p, _i64p, _u64p, _i64p,
            ctypes.POINTER(i64), ctypes.POINTER(i64),
        ]
        lib.cache_uniform_init.argtypes = [
            _u64p, i64, i64, ctypes.c_uint64, ctypes.c_double,
            ctypes.c_double, ctypes.POINTER(ctypes.c_float),
        ]
        _LIB = lib
    return _LIB


def native_uniform_init(
    signs: np.ndarray, seed: int, dim: int, lo: float, hi: float,
    out: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Seeded cold-miss embedding init in C++ — bit-identical to
    ``hashing.uniform_init_for_signs`` (tested). ``out`` (M, dim) f32
    C-contiguous is filled in place when given."""
    lib = _load_lib()
    signs = np.ascontiguousarray(signs, dtype=np.uint64)
    m = len(signs)
    if out is None:
        out = np.empty((m, dim), dtype=np.float32)
    assert out.flags["C_CONTIGUOUS"] and out.dtype == np.float32
    lib.cache_uniform_init(
        signs.ctypes.data_as(_u64p), m, dim, ctypes.c_uint64(seed),
        lo, hi, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
    )
    return out


class _BufRing:
    """Reusable host staging buffers for the per-step hot path.

    Fresh ``np.zeros``/``np.empty`` of ~0.5-1 MB per step cross the
    allocator's mmap threshold, so every step pays mmap + first-touch page
    faults + munmap TLB churn — profiled at ~20 ms/step of pure allocator
    cost on a single-core host, dwarfing the actual compute. A ring of
    ``depth`` buffers per call-site key amortizes that to zero while keeping
    a buffer alive long enough for any in-flight async ``device_put`` to
    finish serializing before the slot comes around again (depth must
    exceed the stream's prefetch depth; 8 > 3)."""

    def __init__(self, depth: int = 8):
        self.depth = depth
        self._slots: Dict = {}

    def ensure_depth(self, depth: int) -> None:
        """Grow the ring so ``depth`` buffers rotate before any reuse.

        Safe at any time: ``get`` keeps appending fresh buffers per key
        until the ring holds ``self.depth`` of them, so raising the depth
        simply extends the rotation; existing hand-outs are unaffected."""
        if depth > self.depth:
            self.depth = depth

    def get(self, key, shape, dtype) -> np.ndarray:
        arrs, idx = self._slots.get(key, ([], 0))
        if len(arrs) < self.depth:
            arr = np.empty(shape, dtype)
            arrs.append(arr)
            self._slots[key] = (arrs, 0)
            return arr
        arr = arrs[idx]
        if arr.shape != shape or arr.dtype != np.dtype(dtype):
            arr = np.empty(shape, dtype)
            arrs[idx] = arr
        self._slots[key] = (arrs, (idx + 1) % self.depth)
        return arr

    def full(self, key, shape, dtype, fill) -> np.ndarray:
        arr = self.get(key, shape, dtype)
        arr.fill(fill)
        return arr


class CacheDirectory:
    """LRU map sign → device cache row (native C++, O(1) per op).

    ``admit_touches`` — touch-gated admission (the reference's
    ``admit_probability`` analogue, reference
    `persia-embedding-config/src/lib.rs` HyperParameters): a non-resident
    sign is admitted only on its Nth distinct-batch touch; earlier touches
    map to the pad row ``capacity`` (zero forward contribution, gradient
    dropped — the reference's non-admitted-sign semantics). Default 1 =
    admit on first touch (exact parity with the ungated tier)."""

    def __init__(self, capacity: int, admit_touches: int = 1):
        self._lib = _load_lib()
        self._h = self._lib.cache_create(capacity)
        self.capacity = capacity
        self.admit_touches = int(admit_touches)
        if self.admit_touches > 1:
            self._lib.cache_set_admit_touches(self._h, self.admit_touches)
        # reusable admit_positions outputs: 5 scratch arrays (miss/evict
        # results are .copy()'d out, so a single reused buffer each is safe)
        # plus a ring for the per-position rows (which ESCAPE to the async
        # device staging path as views)
        self._scratch_n = 0
        self._rows_ring = _BufRing()

    def _ensure_scratch(self, n: int) -> None:
        if n <= self._scratch_n:
            return
        self._scratch_n = n
        self._s_miss_signs = np.empty(n, dtype=np.uint64)
        self._s_miss_rows = np.empty(n, dtype=np.int64)
        self._s_ev_signs = np.empty(n, dtype=np.uint64)
        self._s_ev_rows = np.empty(n, dtype=np.int64)
        self._s_miss_idx = np.empty(n, dtype=np.int64)

    def __del__(self):
        if getattr(self, "_h", None) is not None:
            self._lib.cache_destroy(self._h)
            self._h = None

    def __len__(self) -> int:
        return self._lib.cache_len(self._h)

    def admit(self, signs: np.ndarray):
        """signs must be deduplicated. Returns (rows (n,), miss_idx (M,),
        evict_signs (K,), evict_rows (K,)). Raises if the batch's distinct
        count exceeds capacity (the C call returns -1 *before* writing
        rows_out, so the outputs are uninitialized in that case)."""
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        n = len(signs)
        self._ensure_scratch(n)
        # bucketed ring shape (n varies per batch; exact shapes would
        # reallocate every call), result is the [:n] slice
        rows = self._rows_ring.get("rows64", (_bucket(max(n, 1)),), np.int64)[:n]
        miss_idx = self._s_miss_idx
        ev_signs = self._s_ev_signs
        ev_rows = self._s_ev_rows
        n_evict = ctypes.c_int64(0)
        n_miss = self._lib.cache_admit(
            self._h, signs.ctypes.data_as(_u64p), n,
            rows.ctypes.data_as(_i64p), miss_idx.ctypes.data_as(_i64p),
            ev_signs.ctypes.data_as(_u64p), ev_rows.ctypes.data_as(_i64p),
            ctypes.byref(n_evict),
        )
        if n_miss < 0:
            raise RuntimeError(
                f"batch distinct-sign count {n} exceeds cache capacity "
                f"{self.capacity} — raise cache rows or shrink the batch"
            )
        k = n_evict.value
        return rows, miss_idx[:n_miss].copy(), ev_signs[:k].copy(), ev_rows[:k].copy()

    def admit_positions(self, signs: np.ndarray):
        """Admit a RAW (duplicated) position-level sign stream — the dedup
        happens natively. Returns (rows (n,) int32 per position,
        miss_signs (M,), miss_rows (M,), evict_signs (K,), evict_rows (K,),
        n_unique). One call replaces per-slot dedup + cross-slot dedup +
        admit + row LUT for the single-id fast path."""
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        n = signs.size
        self._ensure_scratch(n)
        rows = self._rows_ring.get("rows", (_bucket(max(n, 1)),), np.int32)[:n]
        miss_signs = self._s_miss_signs
        miss_rows = self._s_miss_rows
        ev_signs = self._s_ev_signs
        ev_rows = self._s_ev_rows
        n_unique = ctypes.c_int64(0)
        n_evict = ctypes.c_int64(0)
        i32p = ctypes.POINTER(ctypes.c_int32)
        n_miss = self._lib.cache_admit_positions(
            self._h, signs.ctypes.data_as(_u64p), n,
            rows.ctypes.data_as(i32p),
            miss_signs.ctypes.data_as(_u64p), miss_rows.ctypes.data_as(_i64p),
            ev_signs.ctypes.data_as(_u64p), ev_rows.ctypes.data_as(_i64p),
            ctypes.byref(n_unique), ctypes.byref(n_evict),
        )
        if n_miss < 0:
            raise RuntimeError(
                f"batch distinct-sign count exceeds cache capacity "
                f"{self.capacity} — raise cache rows or shrink the batch"
            )
        k = n_evict.value
        return (
            rows, miss_signs[:n_miss].copy(), miss_rows[:n_miss].copy(),
            ev_signs[:k].copy(), ev_rows[:k].copy(), n_unique.value,
        )

    def probe(self, signs: np.ndarray) -> np.ndarray:
        """Read-only residency check: row per sign, -1 on miss. No admit, no
        LRU touch — safe for eval/infer batches."""
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        rows = np.empty(len(signs), dtype=np.int64)
        self._lib.cache_probe(self._h, signs.ctypes.data_as(_u64p), len(signs),
                              rows.ctypes.data_as(_i64p))
        return rows

    def drain(self) -> Tuple[np.ndarray, np.ndarray]:
        """Empty the directory; returns (signs, rows) of everything resident."""
        cap = self.capacity
        signs = np.empty(cap, dtype=np.uint64)
        rows = np.empty(cap, dtype=np.int64)
        k = self._lib.cache_drain(self._h, signs.ctypes.data_as(_u64p),
                                  rows.ctypes.data_as(_i64p))
        return signs[:k].copy(), rows[:k].copy()

    def snapshot(self) -> Tuple[np.ndarray, np.ndarray]:
        """Non-destructive (signs, rows) of everything resident — no LRU
        churn, no eviction, directory unchanged."""
        cap = self.capacity
        signs = np.empty(cap, dtype=np.uint64)
        rows = np.empty(cap, dtype=np.int64)
        k = self._lib.cache_snapshot(self._h, signs.ctypes.data_as(_u64p),
                                     rows.ctypes.data_as(_i64p))
        return signs[:k].copy(), rows[:k].copy()


# ------------------------------------------------------------ device state


@flax.struct.dataclass
class CachedTrainState:
    params: object
    batch_stats: object
    opt_state: object
    tables: Dict[str, jnp.ndarray]  # group → (C+1, dim); row C is the zero pad row
    emb_state: Dict[str, Dict[str, jnp.ndarray]]  # group → optimizer state (C+1, ·)
    emb_batch_state: jnp.ndarray
    step: jnp.ndarray
    # dynamic mixed-precision loss scaling (None = static); same state the
    # hybrid TrainCtx carries (parallel/train_step.py LossScaleState)
    loss_scale: Optional[object] = None


@dataclass(frozen=True)
class CacheGroup:
    """One HBM row pool shared by all slots of one embedding dim."""

    name: str
    dim: int
    rows: int  # cache capacity C (the table itself has C+1 rows)
    state_dim: int
    pooled_slots: Tuple[str, ...]  # stacked: one gather/update for all of them
    raw_slots: Tuple[str, ...]  # sequence slots, per-slot (B, L) rows

    @property
    def slots(self) -> Tuple[str, ...]:
        return self.pooled_slots + self.raw_slots


def _lazy_pool(existing, prefix: str, workers: int = 8):
    """Idempotent daemon ThreadPoolExecutor creation (shared by the tier's
    chunking pool and the stream's fetch pool)."""
    if existing is None:
        from concurrent.futures import ThreadPoolExecutor

        existing = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix=prefix
        )
    return existing


def make_cache_groups(
    cfg: EmbeddingConfig, rows_per_group: Dict[int, int],
    sparse_cfg: OptimizerConfig, exclude: Sequence[str] = (),
) -> Tuple[List[CacheGroup], Tuple[str, ...]]:
    """Group slots by dim (all same-dim slots share one row pool; cross-slot
    sign collisions are handled by the group-level dedup in
    ``CachedEmbeddingTier.prepare_batch``, so a prefix-bit-0 config cannot
    violate the directory's distinct-signs contract).

    Returns ``(groups, ps_slots)``: hash-stack slots (many table keys per
    id — uncacheable by construction) and any ``exclude``d names ride the
    pure worker/PS path inside the same ctx (the mixed-tier arrangement)."""
    unknown = set(exclude) - set(cfg.slots_config)
    if unknown:
        raise KeyError(
            f"exclude names not in embedding config: {sorted(unknown)}"
        )
    by_dim: Dict[int, Tuple[List[str], List[str]]] = {}
    ps_slots: List[str] = []
    for name, slot in cfg.slots_config.items():
        if slot.hash_stack_config.enabled or name in exclude:
            ps_slots.append(name)
            continue
        pooled, raw = by_dim.setdefault(slot.dim, ([], []))
        (pooled if slot.embedding_summation else raw).append(name)
    groups = []
    for dim in sorted(by_dim):
        pooled, raw = by_dim[dim]
        groups.append(
            CacheGroup(
                name=f"cache_d{dim}",
                dim=dim,
                rows=rows_per_group[dim],
                state_dim=sparse_cfg.state_dim(dim),
                pooled_slots=tuple(sorted(pooled)),
                raw_slots=tuple(sorted(raw)),
            )
        )
    return groups, tuple(sorted(ps_slots))


def init_cached_tables(
    groups: Sequence[CacheGroup], sparse_cfg: OptimizerConfig, dtype=jnp.float32
):
    """Zeroed row pools (+1 pad row at index C whose zeros absorb padding
    gathers). Content arrives via checkout scatters; initial values are
    irrelevant except the pad row, which the masked sparse update never
    touches."""
    from persia_tpu.ops.sparse_update import init_sparse_state

    tables, emb_state = {}, {}
    for g in groups:
        tables[g.name] = jnp.zeros((g.rows + 1, g.dim), dtype=dtype)
        emb_state[g.name] = init_sparse_state(sparse_cfg, g.rows + 1, g.dim)
    return tables, emb_state


def _entry_to_state_cols(state: Dict[str, jnp.ndarray], entry_tail):
    """Split the PS entry's state tail (M, state_dim) into sparse_update's
    per-key columns — PS entry layout is [emb | acc] (adagrad) or
    [emb | m | v] (adam), `persia_tpu/embedding/optim.py` init_state /
    update_dense."""
    out = {}
    off = 0
    for key in ("acc", "m", "v"):
        if key in state:
            w = state[key].shape[1]
            out[key] = entry_tail[:, off:off + w]
            off += w
    return out


# ----------------------------------------------------------- device step


def _model_emb_from_gathered(
    groups: Sequence[CacheGroup],
    batch: Dict,
    layout: "CacheLayout",
    stacked_gathered: Dict[str, jnp.ndarray],
    raw_gathered: Dict[str, jnp.ndarray],
    pad_row: Callable[[str], int],
    ps_model_inputs: Optional[List] = None,
):
    """Build the per-slot model input list (global sorted slot order) from
    the per-group stacked gather and per-slot raw gathers. ``pad_row(gname)``
    returns the row index whose gather must be masked out (the zero pad)."""
    slot_emb: Dict[str, object] = {}
    stacked_names = dict(layout.stacked)
    for gname, got in stacked_gathered.items():
        rows = batch["stacked_rows"][gname]  # (S, B, L)
        mask = rows != pad_row(gname)
        m = mask[..., None].astype(got.dtype)
        pooled = (got * m).sum(axis=2)  # (S, B, dim)
        scale = batch.get("stacked_scale", {}).get(gname)
        if scale is not None:
            pooled = pooled * scale[..., None].astype(pooled.dtype)
        for i, name in enumerate(stacked_names[gname]):
            slot_emb[name] = pooled[i]
    for name, got in raw_gathered.items():
        gname = _slot_group_of(groups, name)
        rows = batch["raw_rows"][name]
        slot_emb[name] = (got, rows != pad_row(gname))
    if ps_model_inputs is not None:
        # mixed-tier: worker/PS-served slots join the cached ones in the
        # same globally-sorted slot order the model expects
        for name, emb in zip(layout.ps, ps_model_inputs):
            slot_emb[name] = emb
    return [slot_emb[n] for n in sorted(slot_emb)]


def _slot_group_of(groups: Sequence[CacheGroup], slot: str) -> str:
    for g in groups:
        if slot in g.slots:
            return g.name
    raise KeyError(slot)


@dataclass(frozen=True)
class CacheLayout:
    """Static (hashable) description of which slots a batch carries —
    ``stacked``: ((group, (slot, ...)), ...) in stack order. Passed as a
    static jit argument so slot membership never rides in the traced pytree
    (it changes at most a handful of times per run)."""

    stacked: Tuple[Tuple[str, Tuple[str, ...]], ...]
    # mixed-tier: slot names served by the worker/PS path (hash-stack or
    # explicitly excluded), in the order their entries ride batch["ps_emb"]
    ps: Tuple[str, ...] = ()


# Tiny per-group device ops kept OUT of the main train step so that the
# variable miss/evict counts (pow2-bucketed) only ever recompile these
# trivial programs, never the model fwd/bwd. The main step's shapes are
# fixed per (B, L, slot-layout) and compile exactly once.


from functools import partial as _partial


def _scatter_entry_block(table, state: Dict[str, jnp.ndarray], rows, entries):
    """Shared body: scatter ``[emb | state]`` rows into the cache pools
    (out-of-range pad rows drop)."""
    dim = table.shape[1]
    table = table.at[rows].set(entries[:, :dim].astype(table.dtype), mode="drop")
    out_state = dict(state)
    cols = _entry_to_state_cols(out_state, entries[:, dim:])
    for key, vals in cols.items():
        out_state[key] = out_state[key].at[rows].set(
            vals.astype(out_state[key].dtype), mode="drop"
        )
    return table, out_state


@jax.jit
def _gather_entry_rows(table, state: Dict[str, jnp.ndarray], rows):
    """(K, dim + state_dim) ``[emb | state]`` of the given rows — the
    flush/publish read path (device gather, then ONE bounded d2h)."""
    parts = [table[rows]]
    for key in ("acc", "m", "v"):
        if key in state:
            parts.append(state[key][rows])
    return jnp.concatenate(parts, axis=1)


@_partial(jax.jit, donate_argnums=(0, 1))
def _restore_rows(table, state: Dict[str, jnp.ndarray], payload, src_idx, dst_rows):
    """Re-admit rows whose write-back is still in flight straight from the
    DEVICE-resident eviction payload (device→host transfers on a
    remote-attached chip cost ~60 ms latency each — the hazard path must
    never wait on one)."""
    return _scatter_entry_block(table, state, dst_rows, payload[src_idx])


@_partial(jax.jit, donate_argnums=(0, 1), static_argnums=(7, 8))
def _apply_aux(table, state: Dict[str, jnp.ndarray], ev_rows, m_rows,
               m_entries, c_rows, c_emb, state_consts, wb_bf16=False):
    """Fused per-group per-step aux program: read the eviction payload (from
    the PRE-scatter table — a missed row may reuse an evicted one), then
    scatter warm entries and cold seeds. One dispatch instead of three:
    after the first write-back d2h the runtime's per-dispatch latency
    degrades ~200× (see ``train_stream``), so the steady-state eviction
    regime pays per CALL, not per byte. Absent pieces ride as 0-row arrays.

    Compile-cache tradeoff: fusing keys the jit on the COMBINATION of the
    three piece-size buckets (worst case the cross-product, vs the per-piece
    sum for split jits). In practice the regimes are disjoint — fill phase
    is cold-only, steady state is (warm, evict) in one or two stable buckets
    each with cold decaying — so observed combinations stay within a few
    dozen tiny programs; the per-call dispatch saving dominates once the
    runtime is in the degraded-dispatch mode."""
    parts = [table[ev_rows]]
    for key in ("acc", "m", "v"):
        if key in state:
            parts.append(state[key][ev_rows])
    payload = jnp.concatenate(parts, axis=1)
    if wb_bf16:
        # bf16 write-back wire (the reference ships f16 lookup/grad wires,
        # lib.rs:157-180): halves the d2h bytes that bound the eviction
        # steady state; opt-in because the default tier is bit-exact
        payload = payload.astype(jnp.bfloat16)
    table, out_state = _scatter_entry_block(table, state, m_rows, m_entries)
    table = table.at[c_rows].set(c_emb.astype(table.dtype), mode="drop")
    for key, val in state_consts:
        st = out_state[key]
        fill = jnp.full((c_rows.shape[0], st.shape[1]), val, dtype=st.dtype)
        out_state[key] = st.at[c_rows].set(fill, mode="drop")
    return table, out_state, payload


def _state_init_consts(cfg: OptimizerConfig):
    """(key, scalar) pairs for a fresh entry's optimizer-state tail —
    mirrors ``init_sparse_state`` / the PS's ``init_state``."""
    from persia_tpu.embedding.optim import OPTIMIZER_ADAGRAD

    if cfg.kind == OPTIMIZER_ADAGRAD:
        return (("acc", float(cfg.initialization)),)
    if cfg.kind == OPTIMIZER_ADAM:
        return (("m", 0.0), ("v", 0.0))
    return ()


def _bucket(m: int) -> int:
    """Padded size: pow2 below 4096, then 4096-multiples (the miss arrays are
    the dominant per-step transfer — pow2 padding would waste up to 2×)."""
    return _round_up_pow2(m) if m < 4096 else -(-m // 4096) * 4096


def build_cached_train_step(
    model,
    dense_optimizer,
    sparse_cfg: OptimizerConfig,
    groups: Sequence[CacheGroup],
    loss_fn=None,
    donate: bool = True,
    ps_grad_dtype=jnp.float32,
    dynamic_loss_scale: bool = False,
    growth_interval: int = 2000,
    growth_factor: float = 2.0,
    backoff_factor: float = 0.5,
    max_scale: float = float(2 ** 24),
):
    """Jitted ``step(state, batch, layout) -> (state, header)``.

    batch = {
      "dense": [(B,F) f32], "labels": [(B,1) f32],
      "stacked_rows": {group: (S, B, L) int32 cache rows for the group's
                       pooled slots (stack order = layout.stacked), pad = C
                       (the zero row)},
      "stacked_scale": {group: (S, B) f32} — omitted when no slot scales,
      "raw_rows": {slot: (B, L) int32} for sequence slots,
      "ps_emb": [ {"pooled": (B,D)} | {"distinct","index","mask"} ... ] —
                mixed-tier slots served by the worker/PS path
                (layout.ps names them, in order),
    }
    Miss scatters and the evict-payload read run as a separate fused tiny
    jit (``_apply_aux``) dispatched by the ctx around this step, so this —
    the expensive compile — sees only fixed-shape inputs. Returns
    ``(state, header, ps_gpacked)``: header = [loss, preds...]; ps_gpacked
    = flat f32 gradients of the ps_emb entries (empty when none) for the
    worker's gradient return.

    ``dynamic_loss_scale`` (same management as the hybrid path's
    build_train_step; ref GradScaler, persia/ctx.py:926-1005): the loss is
    scaled before backward, an on-device finite check over EVERY gradient
    (dense + cached + ps) gates the update — overflow skips the dense
    update AND the cached-row sparse update (scale *= backoff), a finite
    streak grows the scale. Header becomes [loss | scale | finite | preds],
    and ps_gpacked carries [grads... | scale | finite] so the write-back
    thread can unscale/skip without any extra device fetch. One documented
    divergence from the reference: the Adam beta powers (device AND PS)
    advance on overflow-skipped steps too — keeping the two tiers' powers
    in lockstep without a per-step device sync; the skipped step itself
    applies no gradient anywhere.
    """
    from functools import partial

    from persia_tpu.parallel.train_step import default_loss_fn

    loss_fn = loss_fn or default_loss_fn
    by_name = {g.name: g for g in groups}

    @partial(jax.jit, static_argnums=(2,), donate_argnums=(0,) if donate else ())
    def step(state: CachedTrainState, batch: Dict, layout: CacheLayout):
        tables, emb_state = dict(state.tables), dict(state.emb_state)

        # ONE gather per group for all its stacked pooled slots, plus one
        # per raw slot; differentiate w.r.t. the GATHERED arrays (like the
        # fused path) so cotangents stay gather-shaped instead of dense
        # table-shaped scatters
        stacked_gathered = {
            gname: tables[gname][rows]  # (S, B, L, dim)
            for gname, rows in batch["stacked_rows"].items()
        }
        raw_gathered = {
            name: tables[_slot_group_of(groups, name)][rows]
            for name, rows in batch["raw_rows"].items()
        }
        from persia_tpu.parallel.train_step import (
            _embedding_model_inputs, _split_emb,
        )

        ps_diff, ps_static = _split_emb(batch.get("ps_emb", []))

        scale = (
            state.loss_scale.scale
            if dynamic_loss_scale
            else jnp.asarray(1.0, jnp.float32)
        )

        def loss_wrapper(params, stacked_in, raw_in, ps_in):
            model_emb = _model_emb_from_gathered(
                groups, batch, layout, stacked_in, raw_in,
                pad_row=lambda gname: by_name[gname].rows,
                ps_model_inputs=_embedding_model_inputs(ps_in, ps_static),
            )
            variables = {"params": params}
            if state.batch_stats:
                variables["batch_stats"] = state.batch_stats
                logits, updates = model.apply(
                    variables, batch["dense"], model_emb, train=True,
                    mutable=["batch_stats"],
                )
                new_stats = updates["batch_stats"]
            else:
                logits = model.apply(variables, batch["dense"], model_emb, train=True)
                new_stats = state.batch_stats
            loss = loss_fn(logits, batch["labels"][0])
            return loss * scale.astype(loss.dtype), (loss, logits, new_stats)

        (_, (loss, logits, new_stats)), (param_grads, stacked_g, raw_g, ps_g) = (
            jax.value_and_grad(
                loss_wrapper, argnums=(0, 1, 2, 3), has_aux=True
            )(state.params, stacked_gathered, raw_gathered, ps_diff)
        )

        if dynamic_loss_scale:
            leaves = (
                jax.tree.leaves(param_grads)
                + jax.tree.leaves(stacked_g) + jax.tree.leaves(raw_g)
                + jax.tree.leaves(ps_g)
            )
            finite = jnp.all(
                jnp.stack([jnp.all(jnp.isfinite(g)) for g in leaves])
            )
            inv = jnp.where(finite, 1.0 / scale, 0.0).astype(jnp.float32)
            param_grads = jax.tree.map(
                lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype),
                param_grads,
            )
        else:
            finite = jnp.asarray(True)
            inv = jnp.asarray(1.0, jnp.float32)

        import optax as _optax

        updates, new_opt_state = dense_optimizer.update(
            param_grads, state.opt_state, state.params
        )
        new_params = _optax.apply_updates(state.params, updates)
        if dynamic_loss_scale:
            # overflow: dense update skipped entirely
            new_params = jax.tree.map(
                lambda new, old: jnp.where(finite, new, old),
                new_params, state.params,
            )
            new_opt_state = jax.tree.map(
                lambda new, old: jnp.where(finite, new, old),
                new_opt_state, state.opt_state,
            )

        # on-device sparse update of the cached rows — ONE duplicate-safe
        # scatter per group (dedup inside sparse_update merges the same row
        # appearing in several slots)
        batch_state = state.emb_batch_state * jnp.array(
            [sparse_cfg.beta1, sparse_cfg.beta2], dtype=jnp.float32
        )
        for g in groups:
            idp, gp, mp = [], [], []
            if g.name in batch["stacked_rows"]:
                rows = batch["stacked_rows"][g.name]
                idp.append(rows.reshape(-1))
                # unscale under dynamic loss scaling; on overflow every row
                # is MASKED OUT below (sparse_update touches no row at all —
                # exact skip for every optimizer incl. weight decay and
                # Adam's state decay, at O(touched rows)); the grads are
                # also selected to zero so inf*0 NaNs never enter the math
                sg = stacked_g[g.name].astype(jnp.float32).reshape(-1, g.dim)
                gp.append(jnp.where(finite, sg * inv, 0.0))
                mp.append(((rows < g.rows) & finite).reshape(-1))
            for name in g.raw_slots:
                if name not in batch["raw_rows"]:
                    continue
                rows = batch["raw_rows"][name]
                idp.append(rows.reshape(-1))
                rg = raw_g[name].astype(jnp.float32).reshape(-1, g.dim)
                gp.append(jnp.where(finite, rg * inv, 0.0))
                mp.append(((rows < g.rows) & finite).reshape(-1))
            if not idp:
                continue
            tables[g.name], emb_state[g.name] = sparse_update(
                sparse_cfg,
                tables[g.name],
                emb_state[g.name],
                jnp.concatenate(idp) if len(idp) > 1 else idp[0],
                jnp.concatenate(gp) if len(gp) > 1 else gp[0],
                batch_state,
                mask=jnp.concatenate(mp) if len(mp) > 1 else mp[0],
            )

        new_ls = state.loss_scale
        if dynamic_loss_scale:
            from persia_tpu.parallel.train_step import LossScaleState

            good = jnp.where(finite, state.loss_scale.good_steps + 1, 0)
            grown = good >= growth_interval
            new_scale = jnp.where(
                finite,
                jnp.where(grown, scale * growth_factor, scale),
                scale * backoff_factor,
            )
            new_scale = jnp.clip(new_scale, 1.0, max_scale)
            new_ls = LossScaleState(
                scale=new_scale, good_steps=jnp.where(grown, 0, good)
            )
        new_state = CachedTrainState(
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt_state,
            tables=tables,
            emb_state=emb_state,
            emb_batch_state=batch_state,
            step=state.step + 1,
            loss_scale=new_ls,
        )
        head = [jnp.reshape(loss, (1,)).astype(jnp.float32)]
        if dynamic_loss_scale:
            head.append(jnp.reshape(scale, (1,)).astype(jnp.float32))
            head.append(jnp.reshape(finite, (1,)).astype(jnp.float32))
        head.append(jnp.reshape(jax.nn.sigmoid(logits), (-1,)).astype(jnp.float32))
        header = jnp.concatenate(head)
        # ps-tier gradients are an inherent d2h; a bf16 wire halves the
        # bytes on the return path (the reference ships scaled-f16 grad
        # wires, lib.rs:157-180) — the host casts back to f32 before the
        # worker's unscale/update. Under dynamic scaling the buffer's last
        # two entries are [scale | finite] (both exact in bf16: scale is a
        # power of two), so the write-back thread needs no extra fetch.
        ps_flat = [jnp.reshape(g, (-1,)).astype(ps_grad_dtype) for g in ps_g]
        if dynamic_loss_scale and ps_flat:
            ps_flat.append(
                jnp.stack([scale, finite.astype(jnp.float32)]).astype(ps_grad_dtype)
            )
        ps_gpacked = (
            jnp.concatenate(ps_flat) if ps_flat
            else jnp.zeros((0,), ps_grad_dtype)
        )
        return new_state, header, ps_gpacked

    return step


def build_cached_eval_step(model, groups: Sequence[CacheGroup]):
    """Jitted ``eval_step(state, batch, layout) -> preds``.

    Eval must not mutate the cache (no admits, no evictions, no directory
    churn — the ADVICE round-1 corruption bug): resident signs gather from
    the live cache tables; misses arrive as a host-side PS lookup
    (``miss_tables``: {group: (Mp, dim)}) with rows pre-assigned to C+1+j.
    Values come from a two-gather select (no table concat — concatenating
    would copy the multi-GB pool per eval batch). Mask rule here is
    ``rows != C`` (pad) since miss rows legitimately exceed C."""
    from functools import partial

    by_name = {g.name: g for g in groups}

    def _gather_ext(table, miss_table, rows, C):
        from_cache = table[jnp.minimum(rows, C)]
        miss_idx = jnp.maximum(rows - (C + 1), 0)
        from_miss = miss_table[miss_idx].astype(table.dtype)
        return jnp.where((rows > C)[..., None], from_miss, from_cache)

    @partial(jax.jit, static_argnums=(2,))
    def eval_step(state: CachedTrainState, batch: Dict, layout: CacheLayout):
        stacked_gathered = {}
        for gname, rows in batch["stacked_rows"].items():
            C = by_name[gname].rows
            stacked_gathered[gname] = _gather_ext(
                state.tables[gname], batch["miss_tables"][gname], rows, C
            )
        raw_gathered = {}
        for name, rows in batch["raw_rows"].items():
            gname = _slot_group_of(groups, name)
            C = by_name[gname].rows
            raw_gathered[name] = _gather_ext(
                state.tables[gname], batch["miss_tables"][gname], rows, C
            )
        from persia_tpu.parallel.train_step import (
            _embedding_model_inputs, _split_emb,
        )

        ps_diff, ps_static = _split_emb(batch.get("ps_emb", []))
        model_emb = _model_emb_from_gathered(
            groups, batch, layout, stacked_gathered, raw_gathered,
            pad_row=lambda gname: by_name[gname].rows,
            ps_model_inputs=_embedding_model_inputs(ps_diff, ps_static),
        )
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        logits = model.apply(variables, batch["dense"], model_emb, train=False)
        return jax.nn.sigmoid(logits)

    return eval_step


# -------------------------------------------------------------- host tier


class CachedEmbeddingTier:
    """Host orchestration: directory admits, PS checkouts, write-backs.

    ``worker`` is an ``EmbeddingWorker`` (its ``lookup_router`` fans checkout
    and write-back out to the sharded PS replicas; its dump/load provide the
    checkpoint path for the authoritative store)."""

    def __init__(
        self,
        worker,
        sparse_cfg: OptimizerConfig,
        rows: "int | Dict[int, int]",
        embedding_config: Optional[EmbeddingConfig] = None,
        init_seed: Optional[int] = None,
        ps_slots: Sequence[str] = (),
        admit_touches: int = 1,
        aux_wire_dtype: str = "float32",
    ):
        self.worker = worker
        self.cfg = embedding_config or worker.embedding_config
        self.sparse_cfg = sparse_cfg
        if aux_wire_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"aux_wire_dtype must be float32/bfloat16, got {aux_wire_dtype!r}"
            )
        # host→device wire dtype for the per-step miss/cold aux matrices
        # (the largest per-step transfers). bf16 halves the bytes on a
        # bandwidth-starved link; the device scatter casts to the table
        # dtype, so only the checked-out entries/seeds are quantized (the
        # reference ships f16 lookup wires the same way, lib.rs:157-180).
        import ml_dtypes

        self.aux_np_dtype = (
            np.dtype(ml_dtypes.bfloat16)
            if aux_wire_dtype == "bfloat16" else np.dtype(np.float32)
        )
        # cold misses are seeded-init ON THE HOST (bit-identical to the PS's
        # init) and never touch the PS until eviction — the tier must know
        # the PS seed + init bounds (all replicas share them by convention)
        if init_seed is None:
            init_seed = getattr(worker.lookup_router.replicas[0], "seed", None)
            if init_seed is None:
                raise ValueError(
                    "init_seed not given and PS replicas expose no .seed "
                    "(pass init_seed= to CachedEmbeddingTier/CachedTrainCtx)"
                )
        self.init_seed = int(init_seed)
        self.init_bounds = tuple(worker.hyperparams.emb_initialization)
        dims = {
            slot.dim
            for name, slot in self.cfg.slots_config.items()
            if not slot.hash_stack_config.enabled and name not in ps_slots
        }
        rows_per_group = rows if isinstance(rows, dict) else {d: rows for d in dims}
        self.groups, self.ps_slots = make_cache_groups(
            self.cfg, rows_per_group, sparse_cfg, exclude=ps_slots
        )
        # a feature group is ONE shared key space (members share an index
        # prefix): a cached slot and a ps-tier slot in the same group would
        # be two incoherent writers to the same PS entries (cache copies go
        # stale against direct PS updates) — reject the arrangement
        cached_names = {s for g in self.groups for s in g.slots}
        for fg_name, members in self.cfg.feature_groups.items():
            ms = set(members)
            if ms & cached_names and ms & set(self.ps_slots):
                raise ValueError(
                    f"feature group {fg_name!r} mixes cached slots "
                    f"{sorted(ms & cached_names)} with PS-tier slots "
                    f"{sorted(ms & set(self.ps_slots))}: one key space "
                    "cannot span both tiers"
                )
        # The tier-disjointness above only partitions the PS key space when
        # groups carry distinct sign prefixes. With feature_index_prefix_bit
        # == 0 every slot hashes into one raw u64 space, so a PS-tier sign
        # can collide with a cached-tier sign across groups and eviction
        # flushes vs ps-grad applies would become unordered writers to the
        # same PS entry.
        if self.groups and self.ps_slots and self.cfg.feature_index_prefix_bit == 0:
            raise ValueError(
                "mixed-tier config (cached groups + PS-tier slots "
                f"{sorted(self.ps_slots)}) requires feature_index_prefix_bit "
                "> 0 so per-group sign prefixes partition the PS key space; "
                "with prefix bit 0 a cached-tier sign can collide with a "
                "PS-tier sign and the two tiers would race on one PS entry"
            )
        self.dirs = {
            g.name: CacheDirectory(g.rows, admit_touches=admit_touches)
            for g in self.groups
        }
        # host staging-buffer reuse (see _BufRing): all per-step aux pieces
        # and probe results come from here instead of fresh mmap allocations
        self._ring = _BufRing()
        self._slot_group = {s: g for g in self.groups for s in g.slots}
        # static fast-path eligibility per slot (config is immutable): the
        # per-batch check reduces to "every feature single-id" (the only
        # data-dependent part)
        self._fast_prefix: Dict[str, np.uint64] = {}
        self._fast_eligible: Dict[str, bool] = {}
        for name, slot in self.cfg.slots_config.items():
            self._fast_eligible[name] = (
                slot.embedding_summation
                and not slot.sqrt_scaling
                and not slot.hash_stack_config.enabled
            )
            self._fast_prefix[name] = slot.index_prefix
        m = get_metrics()
        self._m_hit = m.counter(
            "persia_tpu_cache_hit_count", "batch distinct signs resident in HBM"
        )
        self._m_miss = m.counter(
            "persia_tpu_cache_miss_count", "batch distinct signs checked out of the PS"
        )
        self._m_evict = m.counter(
            "persia_tpu_cache_evict_count", "rows written back to the PS on eviction"
        )

    @property
    def router(self) -> ShardedLookup:
        return self.worker.lookup_router

    # PS traffic helpers: big checkout/write-back calls chunk across the
    # worker's thread pool (the native store releases the GIL; its internal
    # shard mutexes make disjoint chunks near-contention-free)
    _PAR_CHUNK = 8192
    _chunk_pool_obj = None

    def _chunk_pool(self):
        """Pool for chunking big host store calls (probe/write-back): ctypes
        store calls release the GIL, so chunks get real parallelism on
        multi-core feeder hosts. Daemon threads; lives with the tier."""
        self._chunk_pool_obj = _lazy_pool(self._chunk_pool_obj, "cache-chunk")
        return self._chunk_pool_obj

    def _probe(self, signs: np.ndarray, dim: int):
        """Chunk-parallel warm/cold probe across the worker's thread pool.
        Results land in ring-reused caller-owned buffers (chunks write
        disjoint slices, so concurrent fills are safe)."""
        n = len(signs)
        entry_len = dim + self.sparse_cfg.state_dim(dim)
        # ring shapes are bucketed (n varies every step; an exact-shape ring
        # would reallocate every call), results are the [:n] slices
        nb = _bucket(max(n, 1))
        vals = self._ring.get(
            ("probe_vals", entry_len), (nb, entry_len), np.float32
        )[:n]
        warm8 = self._ring.get("probe_warm", (nb,), np.uint8)[:n]
        if n <= self._PAR_CHUNK:
            return self.router.probe_entries(
                signs, dim, vals_out=vals, warm_out=warm8
            )
        pool = self._chunk_pool()
        bounds = list(range(0, n, self._PAR_CHUNK)) + [n]

        def chunk(se):
            s, e = se
            self.router.probe_entries(
                signs[s:e], dim, vals_out=vals[s:e], warm_out=warm8[s:e]
            )

        list(pool.map(chunk, zip(bounds[:-1], bounds[1:])))
        return warm8.view(np.bool_), vals

    def _set_embedding(self, signs: np.ndarray, values: np.ndarray, dim: int) -> None:
        n = len(signs)
        if n <= self._PAR_CHUNK:
            self.router.set_embedding(
                signs, values, dim=dim, commit_incremental=True
            )
            return
        pool = self._chunk_pool()
        bounds = list(range(0, n, self._PAR_CHUNK)) + [n]
        list(
            pool.map(
                lambda se: self.router.set_embedding(
                    signs[se[0]:se[1]], values[se[0]:se[1]], dim=dim,
                    commit_incremental=True,
                ),
                zip(bounds[:-1], bounds[1:]),
            )
        )

    # ------------------------------------------------------------- helpers

    def _group_slots(self, pb: ProcessedBatch) -> Dict[str, List[ProcessedSlot]]:
        out: Dict[str, List[ProcessedSlot]] = {}
        for slot in pb.slots:
            out.setdefault(self._slot_group[slot.name].name, []).append(slot)
        for slots in out.values():
            slots.sort(key=lambda s: s.name)
        return out

    @staticmethod
    def _dedup_group_signs(slots: List[ProcessedSlot]):
        """Concatenate the group's per-slot distinct signs and dedup ACROSS
        slots (the directory's contract requires globally distinct signs —
        with feature_index_prefix_bit=0 two slots can carry the same sign)."""
        from persia_tpu.embedding import native_worker

        all_signs = (
            np.concatenate([s.distinct for s in slots])
            if slots else np.empty(0, np.uint64)
        )
        native = native_worker.dedup(all_signs)
        if native is not None:
            uniq, inv = native
        else:
            uniq, inv = np.unique(all_signs, return_inverse=True)
        return all_signs, uniq, inv.astype(np.int64)

    def _stack_layout(self, g: CacheGroup, slots: List[ProcessedSlot]):
        """Common (B, L) layout for the group's pooled slots: L = max count
        across those slots (pow2-bucketed to bound recompiles)."""
        pooled = [s for s in slots if s.config.embedding_summation]
        if not pooled:
            return pooled, 0
        max_c = max((int(s.counts.max()) if len(s.counts) else 1) for s in pooled)
        return pooled, _round_up_pow2(max(max_c, 1), floor=1)

    def _slot_rows(
        self, slot: ProcessedSlot, slot_rows: np.ndarray, L: int, pad_row: int
    ) -> np.ndarray:
        idx = _position_index(slot, L)
        lut = np.append(slot_rows, np.int64(pad_row))
        return lut[idx].astype(np.int32)

    # ------------------------------------------------------------ train path

    def _admit_aux(
        self, g: CacheGroup, miss_signs, rows_miss, ev_signs, ev_rows,
        n_unique, hazard_gate, miss_aux, cold_aux, restore_aux, evict_aux,
        evict_meta,
    ) -> None:
        """Post-admit bookkeeping shared by the general and single-id fast
        paths: metrics, the cross-step write-back hazard gate, the
        warm/cold miss split (WARM = PS holds trained state, full entry
        ships; COLD = brand-new sign, host-seeded emb only, no PS touch
        until eviction), and the eviction read-back bucket."""
        C = g.rows
        self._m_hit.inc(n_unique - len(miss_signs))
        self._m_miss.inc(len(miss_signs))
        self._m_evict.inc(len(ev_signs))

        resolved = None
        if hazard_gate is not None and len(miss_signs):
            resolved = hazard_gate(g.name, miss_signs)

        m = len(miss_signs)
        if m:
            handled = np.zeros(m, dtype=bool)
            if resolved:
                for payload, src_idx, pos in resolved:
                    handled[pos] = True
                    # pow2-bucketed; src pad reads row 0 harmlessly, dst
                    # pad C+1 is dropped by the scatter
                    S = len(pos)
                    sp = _round_up_pow2(S)
                    src = np.zeros(sp, dtype=np.int64)
                    dst = np.full(sp, C + 1, dtype=np.int32)
                    src[:S] = src_idx
                    dst[:S] = rows_miss[pos]
                    restore_aux.setdefault(g.name, []).append(
                        (payload, src, dst)
                    )
            with span("cache.ps_probe", n=m):
                warm, vals = self._probe(miss_signs, g.dim)
            widx = np.nonzero(warm[:m] & ~handled)[0]
            cidx = np.nonzero(~warm[:m] & ~handled)[0]
            # aux buffers come from the reuse ring and escape to the async
            # staging path; pad regions carry garbage values on purpose —
            # pad rows are C+1, which the scatters drop
            if len(widx):
                entry_len = g.dim + g.state_dim
                wp = _bucket(len(widx))
                w_rows = self._ring.full(("w_rows", g.name), (wp,), np.int32, C + 1)
                w_entries = self._ring.get(
                    ("w_entries", g.name), (wp, entry_len), self.aux_np_dtype
                )
                w_rows[:len(widx)] = rows_miss[widx]
                w_entries[:len(widx)] = vals[widx]  # casts on a bf16 wire
                miss_aux[g.name] = (w_rows, w_entries)
            if len(cidx):
                lo, hi = self.init_bounds
                cp = _bucket(len(cidx))
                c_rows = self._ring.full(("c_rows", g.name), (cp,), np.int32, C + 1)
                c_f32 = self._ring.get(("c_emb_f32", g.name), (cp, g.dim), np.float32)
                c_rows[:len(cidx)] = rows_miss[cidx]
                native_uniform_init(
                    miss_signs[cidx], self.init_seed, g.dim, lo, hi,
                    out=c_f32[:len(cidx)],
                )
                if self.aux_np_dtype == np.float32:
                    c_emb = c_f32
                else:
                    c_emb = self._ring.get(
                        ("c_emb", g.name), (cp, g.dim), self.aux_np_dtype
                    )
                    c_emb[:len(cidx)] = c_f32[:len(cidx)]
                cold_aux[g.name] = (c_rows, c_emb)
        # evictions: rows to read back (pad → zero row, host slices K)
        k = len(ev_rows)
        if k:
            kp = _bucket(k)
            e_rows = self._ring.full(("e_rows", g.name), (kp,), np.int32, C)
            e_rows[:k] = ev_rows
            evict_aux[g.name] = e_rows
            evict_meta[g.name] = (ev_signs, k)

    def _single_id_groups(self, batch: PersiaBatch):
        """The fast-path precondition: EVERY group is pooled-only, no
        hash-stack, no sqrt scaling, and every feature carries exactly one
        id per sample. Returns [(group, slot_names, (S, B) prefixed sign
        matrix), ...] or None (→ general path)."""
        from persia_tpu.embedding import native_worker
        from persia_tpu.embedding.hashing import add_index_prefix

        feats = {
            f.name: f for f in batch.id_type_features
            if f.name not in self.ps_slots  # mixed-tier: worker/PS path
        }
        for name in feats:
            if name not in self._slot_group:
                # same loud failure the general path's preprocess raises
                raise KeyError(f"unknown slot {name!r} (not in embedding config)")
            if not self._fast_eligible[name]:  # static per-slot precompute
                return None

        out = []
        prefix_bit = self.cfg.feature_index_prefix_bit
        for g in self.groups:
            names = [n for n in g.pooled_slots if n in feats]
            if not names:
                continue
            flats = []
            for name in names:
                flat, counts = feats[name].flat_counts()
                # exactly one id per sample — a total that merely EQUALS the
                # batch size (counts like [2, 0, 1, ...]) would misalign ids
                # to samples
                if len(flat) != len(counts) or not (counts == 1).all():
                    return None
                flats.append(np.ascontiguousarray(flat, dtype=np.uint64))
            mat = self._ring.get(
                ("sid_mat", g.name), (len(names), len(flats[0])), np.uint64
            )
            # ONE native call builds every prefixed row (the per-slot numpy
            # prefix-OR + copy loop was a measurable share of the feeder)
            prefixes = np.array(
                [self._fast_prefix[n] for n in names], dtype=np.uint64
            )
            if not native_worker.build_sid_matrix(
                flats, prefixes, prefix_bit, mat
            ):
                for i, (name, flat) in enumerate(zip(names, flats)):
                    mat[i] = add_index_prefix(
                        flat, self._fast_prefix[name], prefix_bit
                    )
            out.append((g, tuple(names), mat))
        return out

    def prepare_batch(
        self,
        batch: PersiaBatch,
        hazard_gate: Optional[Callable[[np.ndarray], None]] = None,
    ):
        """Admit the batch's distinct signs, check misses out of the PS, and
        build the device step inputs. Returns (device_inputs, layout,
        miss_aux, cold_aux, restore_aux, evict_aux, evict_meta) where
        miss_aux/cold_aux hold warm/cold miss scatters, restore_aux holds
        device-side re-admissions resolved by the hazard gate, and
        evict_meta = {group: (evict_signs, true_K)} describes the write-back
        due after the step.

        ``hazard_gate(group_name, miss_signs)``: called before each group's
        PS probe. When a pipelined caller has eviction write-backs still in
        flight, a fresh miss on one of those signs would read stale data
        from the PS. The gate returns a list of ``(payload, src_idx,
        positions)`` restore descriptors — ``payload`` a DEVICE-resident
        eviction payload array, ``src_idx`` rows within it, ``positions``
        the resolved indices into ``miss_signs`` — and those signs are
        re-admitted by an on-device row restore instead of a host checkout.
        ``None`` means no overlap."""
        fast = self._single_id_groups(batch)
        if fast is not None:
            return self._prepare_batch_single_id(batch, fast, hazard_gate)
        cached_feats = [
            f for f in batch.id_type_features if f.name not in self.ps_slots
        ]
        pb = preprocess_batch(cached_feats, self.cfg)
        slots_by_group = self._group_slots(pb)

        stacked_rows: Dict[str, np.ndarray] = {}
        stacked_scale: Dict[str, np.ndarray] = {}
        layout_stacked: List[Tuple[str, Tuple[str, ...]]] = []
        raw_rows: Dict[str, np.ndarray] = {}
        miss_aux: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        cold_aux: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        restore_aux: Dict[str, List] = {}
        evict_aux: Dict[str, np.ndarray] = {}
        evict_meta: Dict[str, Tuple[np.ndarray, int]] = {}
        any_scale = False

        for g in self.groups:
            slots = slots_by_group.get(g.name, [])
            if not slots:
                continue
            C = g.rows
            all_signs, uniq, inv = self._dedup_group_signs(slots)
            rows_u, miss_idx, ev_signs, ev_rows = self.dirs[g.name].admit(uniq)
            rows = rows_u[inv]  # per original (slot-concatenated) position
            miss_signs = uniq[miss_idx]
            self._admit_aux(
                g, miss_signs, rows_u[miss_idx], ev_signs, ev_rows,
                len(uniq), hazard_gate,
                miss_aux, cold_aux, restore_aux, evict_aux, evict_meta,
            )

            # per-slot row matrices: pooled slots stack into (S, B, L)
            pooled, L = self._stack_layout(g, slots)
            off = 0
            stack_mats, scale_mats, stack_names = [], [], []
            for slot in slots:
                d = slot.num_distinct
                srows = rows[off:off + d]
                off += d
                if slot.config.embedding_summation:
                    stack_names.append(slot.name)
                    stack_mats.append(self._slot_rows(slot, srows, L, C))
                    if slot.config.sqrt_scaling:
                        any_scale = True
                        scale_mats.append(
                            (1.0 / np.sqrt(np.maximum(slot.counts, 1))).astype(np.float32)
                        )
                    else:
                        scale_mats.append(
                            np.ones(slot.batch_size, dtype=np.float32)
                        )
                else:
                    raw_rows[slot.name] = self._slot_rows(
                        slot, srows, slot.config.sample_fixed_size, C
                    )
            if stack_mats:
                stacked_rows[g.name] = np.stack(stack_mats)
                stacked_scale[g.name] = np.stack(scale_mats)
                layout_stacked.append((g.name, tuple(stack_names)))

        device_inputs = {
            "dense": [np.asarray(f.data, dtype=np.float32) for f in batch.non_id_type_features],
            "labels": [np.asarray(l.data, dtype=np.float32) for l in batch.labels],
            "stacked_rows": stacked_rows,
            "raw_rows": raw_rows,
        }
        if any_scale:
            device_inputs["stacked_scale"] = stacked_scale
        layout = CacheLayout(stacked=tuple(layout_stacked))
        return (
            device_inputs, layout, miss_aux, cold_aux, restore_aux,
            evict_aux, evict_meta,
        )

    def _prepare_batch_single_id(self, batch: PersiaBatch, fast, hazard_gate):
        """Single-id fast path: ONE native call per group
        (``cache_admit_positions``: dedup + admit + per-position rows) and
        the row matrix is its output reshaped — no per-slot dedup, no row
        LUT, no stack copy. Dominates the 1-core feeder's budget on the
        Criteo-style all-single-id shape."""
        stacked_rows: Dict[str, np.ndarray] = {}
        layout_stacked: List[Tuple[str, Tuple[str, ...]]] = []
        miss_aux: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        cold_aux: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        restore_aux: Dict[str, List] = {}
        evict_aux: Dict[str, np.ndarray] = {}
        evict_meta: Dict[str, Tuple[np.ndarray, int]] = {}

        for g, names, mat in fast:
            S, B = mat.shape
            with span("cache.admit", group=g.name, n=mat.size):
                (rows, miss_signs, miss_rows, ev_signs, ev_rows,
                 n_unique) = self.dirs[g.name].admit_positions(mat.reshape(-1))
            with span("cache.admit_aux", group=g.name, misses=len(miss_signs)):
                self._admit_aux(
                    g, miss_signs, miss_rows, ev_signs, ev_rows, n_unique,
                    hazard_gate, miss_aux, cold_aux, restore_aux, evict_aux,
                    evict_meta,
                )
            stacked_rows[g.name] = rows.reshape(S, B, 1)
            layout_stacked.append((g.name, names))

        device_inputs = {
            "dense": [np.asarray(f.data, dtype=np.float32) for f in batch.non_id_type_features],
            "labels": [np.asarray(l.data, dtype=np.float32) for l in batch.labels],
            "stacked_rows": stacked_rows,
            "raw_rows": {},
        }
        layout = CacheLayout(stacked=tuple(layout_stacked))
        return (
            device_inputs, layout, miss_aux, cold_aux, restore_aux,
            evict_aux, evict_meta,
        )

    # ------------------------------------------------------------- eval path

    def prepare_eval_batch(self, batch: PersiaBatch):
        """Build eval-step inputs with ZERO cache mutation: resident signs
        map to their cache rows via a read-only probe; misses get a plain
        infer PS lookup (zeros for never-trained signs, no admission) and
        ride as an appended miss table with rows C+1+j."""
        cached_feats = [
            f for f in batch.id_type_features if f.name not in self.ps_slots
        ]
        pb = preprocess_batch(cached_feats, self.cfg)
        slots_by_group = self._group_slots(pb)

        stacked_rows: Dict[str, np.ndarray] = {}
        stacked_scale: Dict[str, np.ndarray] = {}
        layout_stacked: List[Tuple[str, Tuple[str, ...]]] = []
        raw_rows: Dict[str, np.ndarray] = {}
        miss_tables: Dict[str, np.ndarray] = {}
        any_scale = False

        for g in self.groups:
            slots = slots_by_group.get(g.name, [])
            if not slots:
                continue
            C = g.rows
            all_signs, uniq, inv = self._dedup_group_signs(slots)
            rows_u = self.dirs[g.name].probe(uniq)
            miss_mask = rows_u < 0
            miss_signs = uniq[miss_mask]
            m = len(miss_signs)
            mp = _round_up_pow2(max(m, 1))
            mt = np.zeros((mp, g.dim), dtype=np.float32)
            if m:
                mt[:m] = self.router.lookup(miss_signs, g.dim, train=False)
                rows_u = rows_u.copy()
                rows_u[miss_mask] = C + 1 + np.arange(m)
            miss_tables[g.name] = mt
            rows = rows_u[inv]

            pooled, L = self._stack_layout(g, slots)
            off = 0
            stack_mats, scale_mats, stack_names = [], [], []
            for slot in slots:
                d = slot.num_distinct
                srows = rows[off:off + d]
                off += d
                if slot.config.embedding_summation:
                    stack_names.append(slot.name)
                    stack_mats.append(self._slot_rows(slot, srows, L, C))
                    if slot.config.sqrt_scaling:
                        any_scale = True
                        scale_mats.append(
                            (1.0 / np.sqrt(np.maximum(slot.counts, 1))).astype(np.float32)
                        )
                    else:
                        scale_mats.append(np.ones(slot.batch_size, dtype=np.float32))
                else:
                    raw_rows[slot.name] = self._slot_rows(
                        slot, srows, slot.config.sample_fixed_size, C
                    )
            if stack_mats:
                stacked_rows[g.name] = np.stack(stack_mats)
                stacked_scale[g.name] = np.stack(scale_mats)
                layout_stacked.append((g.name, tuple(stack_names)))

        inputs = {
            "dense": [np.asarray(f.data, dtype=np.float32) for f in batch.non_id_type_features],
            "labels": [np.asarray(l.data, dtype=np.float32) for l in batch.labels],
            "stacked_rows": stacked_rows,
            "raw_rows": raw_rows,
            "miss_tables": miss_tables,
        }
        if any_scale:
            inputs["stacked_scale"] = stacked_scale
        return inputs, CacheLayout(stacked=tuple(layout_stacked))

    # ------------------------------------------------------------ write-back

    def write_back(self, evict_meta, evict_payload) -> None:
        """Persist evicted rows to the PS (full [emb | state] entries)."""
        for gname, (ev_signs, k) in evict_meta.items():
            if not k:
                continue
            g = next(gr for gr in self.groups if gr.name == gname)
            payload = np.asarray(evict_payload[gname])[:k].astype(np.float32)
            self._set_embedding(ev_signs[:k], payload, dim=g.dim)

    def _write_rows(self, g: CacheGroup, signs, rows, tables, emb_state) -> None:
        """Shared flush/publish body: gather ``[emb | state]`` for the given
        rows ON DEVICE (one d2h transfer of only those entries — fetching
        the full pool arrays would cost the whole table per call on a
        bandwidth-starved link) and persist to the PS as training updates."""
        kp = _round_up_pow2(len(rows))
        rpad = np.zeros(kp, dtype=np.int64)  # pad rows re-read row 0, sliced off
        rpad[:len(rows)] = rows
        payload = _gather_entry_rows(
            tables[g.name], emb_state[g.name], jax.device_put(rpad)
        )
        host = np.asarray(payload)[:len(rows)].astype(np.float32)
        self._set_embedding(signs, host, dim=g.dim)

    def flush(self, tables, emb_state) -> None:
        """Drain every cached row back to the PS (checkpoint/eval boundary).
        ``tables``/``emb_state`` are the CURRENT device arrays."""
        for g in self.groups:
            signs, rows = self.dirs[g.name].drain()
            if len(signs):
                self._write_rows(g, signs, rows, tables, emb_state)

    def publish(self, tables, emb_state) -> int:
        """Write every RESIDENT row to the PS without evicting anything —
        the serving-freshness valve. Eviction write-backs only cover rows
        that LEAVE the cache, so a hot sign trained every step would ship no
        incremental update while it stays resident; publishing on the
        serving cadence closes that gap (the reference needs no equivalent —
        its PS sees every gradient). Returns the number of rows published."""
        total = 0
        for g in self.groups:
            signs, rows = self.dirs[g.name].snapshot()  # no directory churn
            if len(signs):
                self._write_rows(g, signs, rows, tables, emb_state)
                total += len(signs)
        return total


def _position_index(slot: ProcessedSlot, L: int) -> np.ndarray:
    """(B, L) matrix of positions into the slot's distinct array (pad == D),
    reusing the native raw-index builder."""
    from persia_tpu.embedding import native_worker

    idx = native_worker.raw_index(slot.counts, slot.inverse, L, slot.num_distinct)
    if idx is None:
        idx = np.full((slot.batch_size, L), slot.num_distinct, dtype=np.int32)
        pos = 0
        for b, c in enumerate(slot.counts.tolist()):
            take = min(c, L)
            idx[b, :take] = slot.inverse[pos:pos + take]
            pos += c
    return idx


# ------------------------------------------------------------------- ctx


class CachedTrainCtx:
    """Training context for the HBM-cached hybrid tier — the TrainCtx-shaped
    API (train_step / eval_batch / dump_checkpoint / load_checkpoint) with
    on-device sparse updates and write-back tier migration.

    Pipelined by default: ``train_step`` dispatches the jitted step and
    defers the previous step's eviction write-back + metric fetch, so host
    preprocessing for step N+1 overlaps device compute of step N (the
    reference hides PS latency the same way with concurrent lookup workers,
    forward.rs:640-779). Call with ``fetch_metrics=False`` to keep the
    loop free of device syncs; ``drain()``/``last_metrics()`` at the end.
    """

    def __init__(
        self,
        model,
        dense_optimizer,
        embedding_optimizer,
        worker,
        embedding_config: EmbeddingConfig,
        cache_rows: "int | Dict[int, int]" = 1 << 20,
        loss_fn=None,
        table_dtype=jnp.float32,
        init_seed: Optional[int] = None,
        mesh=None,
        wb_wire_dtype: str = "float32",
        ps_slots: Sequence[str] = (),
        admit_touches: int = 1,
        aux_wire_dtype: str = "float32",
        ps_wire_dtype: str = "float32",
        dynamic_loss_scale: bool = False,
        loss_scale_init: float = float(2 ** 15),
        loss_scale_growth_interval: int = 2000,
        loss_scale_max: float = float(2 ** 24),
    ):
        self.model = model
        self.dense_optimizer = dense_optimizer
        self.sparse_cfg = embedding_optimizer.config
        self.worker = worker
        self.embedding_config = embedding_config
        # DP mesh: batch-dim inputs shard over "data", cache pools + aux
        # scatters replicate; XLA reduces the sparse scatter deltas across
        # replicas exactly like replicated dense params (the capacity tier's
        # multi-chip story — the PS side is already sharded host-side)
        self.mesh = mesh
        if wb_wire_dtype not in ("float32", "bfloat16"):
            raise ValueError(f"wb_wire_dtype must be float32/bfloat16, got {wb_wire_dtype!r}")
        # bf16 eviction wire halves the d2h bytes that bound the eviction
        # steady state (the reference ships f16 wires); default stays f32
        # because the cached tier is otherwise bit-exact vs the pure-PS path
        self._wb_bf16 = wb_wire_dtype == "bfloat16"
        self.tier = CachedEmbeddingTier(
            worker, self.sparse_cfg, cache_rows, embedding_config,
            init_seed=init_seed, ps_slots=ps_slots,
            admit_touches=admit_touches, aux_wire_dtype=aux_wire_dtype,
        )
        # feature groups containing cached slots: the PS-side Adam beta
        # powers of EVERY one of them mirror the device's per-step advance
        self._cached_groups = tuple(sorted({
            embedding_config.group_of(s)
            for g in self.tier.groups for s in g.slots
        }))
        self._state_consts = _state_init_consts(self.sparse_cfg)
        if ps_wire_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"ps_wire_dtype must be float32/bfloat16, got {ps_wire_dtype!r}"
            )
        self.dynamic_loss_scale = dynamic_loss_scale
        self._loss_scale_init = loss_scale_init
        self._step = build_cached_train_step(
            model, dense_optimizer, self.sparse_cfg, self.tier.groups,
            loss_fn=loss_fn,
            ps_grad_dtype=(
                jnp.bfloat16 if ps_wire_dtype == "bfloat16" else jnp.float32
            ),
            dynamic_loss_scale=dynamic_loss_scale,
            growth_interval=loss_scale_growth_interval,
            max_scale=loss_scale_max,
        )
        self._eval = build_cached_eval_step(model, self.tier.groups)
        # forward-side ps wire: stage PS-tier entries in the same reduced
        # dtype the gradients return in (host->device rows are the other
        # half of the PS tier's link bill)
        self._ps_stage_dtype = (
            np.dtype("bfloat16") if ps_wire_dtype == "bfloat16" else None
        )
        self.table_dtype = table_dtype
        self.state: Optional[CachedTrainState] = None
        # concurrent device->host gradient/eviction fetch pool for the
        # stream's write-back thread: each fetch pays the full link
        # round-trip, so batched fetches MUST overlap (a serial loop is
        # latency x count)
        self._fetch_pool_obj = None
        # deferred write-back: (evict_meta, device payload, device header,
        # label shape) of the most recent dispatched step
        self._pending = None
        self._pending_signs: Set[int] = set()
        self._last_metrics: Optional[Dict] = None
        # (device header, label shape) of a fetch_final=False stream's last
        # step — materialized lazily by last_metrics()
        self._last_header_dev = None
        # per-group 0-row stand-ins for absent aux pieces (_group_empties)
        self._empties: Dict[str, Dict[str, jnp.ndarray]] = {}

    def __enter__(self):
        self.worker.register_optimizer(self.sparse_cfg)
        return self

    def __exit__(self, *exc):
        self.drain()
        return False

    # ------------------------------------------------------------- lifecycle

    def init_state(self, rng, sample_inputs: Dict, layout: CacheLayout) -> CachedTrainState:
        import optax

        tables, emb_state = init_cached_tables(
            self.tier.groups, self.sparse_cfg, dtype=self.table_dtype
        )
        by_name = {g.name: g for g in self.tier.groups}
        stacked_gathered = {
            gname: tables[gname][jnp.asarray(rows)]
            for gname, rows in sample_inputs["stacked_rows"].items()
        }
        raw_gathered = {
            name: tables[self.tier._slot_group[name].name][jnp.asarray(rows)]
            for name, rows in sample_inputs["raw_rows"].items()
        }
        ps_model_inputs = None
        if sample_inputs.get("ps_emb"):
            from persia_tpu.parallel.train_step import (
                _embedding_model_inputs, _split_emb,
            )

            ps_diff, ps_static = _split_emb(sample_inputs["ps_emb"])
            ps_model_inputs = _embedding_model_inputs(
                [jnp.asarray(d) for d in ps_diff], ps_static
            )
        model_emb = _model_emb_from_gathered(
            self.tier.groups,
            {
                k: (
                    {kk: jnp.asarray(vv) for kk, vv in v.items()}
                    if isinstance(v, dict) else v
                )
                for k, v in sample_inputs.items()
            },
            layout,
            stacked_gathered,
            raw_gathered,
            pad_row=lambda gname: by_name[gname].rows,
            ps_model_inputs=ps_model_inputs,
        )
        variables = self.model.init(
            rng, sample_inputs["dense"], model_emb, train=False
        )
        params = variables["params"]
        ls = None
        if self.dynamic_loss_scale:
            from persia_tpu.parallel.train_step import LossScaleState

            ls = LossScaleState(
                scale=jnp.asarray(self._loss_scale_init, jnp.float32),
                good_steps=jnp.zeros((), jnp.int32),
            )
        self.state = CachedTrainState(
            params=params,
            batch_stats=variables.get("batch_stats", {}),
            opt_state=self.dense_optimizer.init(params),
            tables=tables,
            emb_state=emb_state,
            emb_batch_state=jnp.ones((2,), dtype=jnp.float32),
            step=jnp.zeros((), dtype=jnp.int32),
            loss_scale=ls,
        )
        rep = self._replicated()
        if rep is not None:
            self.state = jax.tree.map(
                lambda x: jax.device_put(x, rep), self.state
            )
        return self.state

    # ------------------------------------------------------------ train/eval

    def _sync_hazard_gate(self, gname: str, miss_signs: np.ndarray):
        if self._pending_signs and not self._pending_signs.isdisjoint(
            miss_signs.tolist()
        ):
            self._land_pending()  # after landing, the PS probe sees them warm
        return None

    def _fetch_pool(self):
        """Pool for CONCURRENT device→host fetches in the stream's
        write-back thread (each fetch pays a full link round-trip)."""
        self._fetch_pool_obj = _lazy_pool(self._fetch_pool_obj, "cache-fetch")
        return self._fetch_pool_obj

    def _replicated(self):
        if self.mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P())

    def _stage(self, device_inputs, miss_aux, cold_aux, evict_aux):
        """Host→device staging with mesh shardings when a DP mesh is set:
        batch-dim leaves shard over ``data`` (dense/labels (B,·); stacked
        row/scale matrices on their middle axis), aux scatters replicate
        (they address the replicated cache pools)."""
        if self.mesh is None:
            return (
                jax.device_put(device_inputs), jax.device_put(miss_aux),
                jax.device_put(cold_aux), jax.device_put(evict_aux),
            )
        from jax.sharding import NamedSharding, PartitionSpec as P

        bsh = NamedSharding(self.mesh, P("data"))
        mid = NamedSharding(self.mesh, P(None, "data"))
        rep = self._replicated()
        di = {
            "dense": [jax.device_put(x, bsh) for x in device_inputs["dense"]],
            "labels": [jax.device_put(x, bsh) for x in device_inputs["labels"]],
            "stacked_rows": {
                k: jax.device_put(v, mid)
                for k, v in device_inputs["stacked_rows"].items()
            },
            "raw_rows": {
                k: jax.device_put(v, bsh)
                for k, v in device_inputs["raw_rows"].items()
            },
        }
        if "stacked_scale" in device_inputs:
            di["stacked_scale"] = {
                k: jax.device_put(v, mid)
                for k, v in device_inputs["stacked_scale"].items()
            }
        if "ps_emb" in device_inputs:
            ps = []
            for e in device_inputs["ps_emb"]:
                if "pooled" in e:
                    ps.append({"pooled": jax.device_put(e["pooled"], bsh)})
                elif "pool_index" in e:  # device-pooled sum slot
                    entry = {
                        "distinct": jax.device_put(e["distinct"], rep),
                        "pool_index": jax.device_put(e["pool_index"], bsh),
                    }
                    if "pool_counts" in e:
                        entry["pool_counts"] = jax.device_put(e["pool_counts"], bsh)
                    ps.append(entry)
                else:
                    ps.append({
                        "distinct": jax.device_put(e["distinct"], rep),
                        "index": jax.device_put(e["index"], bsh),
                        "mask": jax.device_put(e["mask"], bsh),
                    })
            di["ps_emb"] = ps
        return (
            di,
            jax.device_put(miss_aux, rep),
            jax.device_put(cold_aux, rep),
            jax.device_put(evict_aux, rep),
        )

    def _group_empties(self, gname: str):
        """Cached 0-row device arrays standing in for absent aux pieces, so
        the fused ``_apply_aux`` keeps ONE dispatch per touched group."""
        em = self._empties.get(gname)
        if em is None:
            g = next(gr for gr in self.tier.groups if gr.name == gname)
            rep = self._replicated()
            put = (
                jax.device_put if rep is None
                else (lambda a: jax.device_put(a, rep))
            )
            aux_dt = self.tier.aux_np_dtype
            em = self._empties[gname] = {
                "rows": put(np.empty(0, dtype=np.int32)),
                "entries": put(
                    np.empty((0, g.dim + g.state_dim), dtype=aux_dt)
                ),
                "emb": put(np.empty((0, g.dim), dtype=aux_dt)),
            }
        return em

    def _dispatch(
        self, device_inputs, layout, miss_aux, cold_aux, restore_aux, evict_aux
    ):
        """Dispatch the per-step device programs: ONE fused aux program per
        touched group (evict-payload read → warm scatter → cold scatter; see
        ``_apply_aux``) + in-flight restores + the main step. Inputs must
        already be device arrays."""
        evict_payload = {}
        touched = set(miss_aux) | set(cold_aux) | set(evict_aux)
        if touched or restore_aux:
            tables = dict(self.state.tables)
            emb_state = dict(self.state.emb_state)
            for gname in sorted(touched):
                em = self._group_empties(gname)
                ev_rows = evict_aux.get(gname, em["rows"])
                m_rows, m_entries = miss_aux.get(gname, (em["rows"], em["entries"]))
                c_rows, c_emb = cold_aux.get(gname, (em["rows"], em["emb"]))
                tables[gname], emb_state[gname], payload = _apply_aux(
                    tables[gname], emb_state[gname], ev_rows,
                    m_rows, m_entries, c_rows, c_emb, self._state_consts,
                    self._wb_bf16,
                )
                if gname in evict_aux:
                    evict_payload[gname] = payload
            for gname, restores in restore_aux.items():
                for payload, src_idx, dst_rows in restores:
                    tables[gname], emb_state[gname] = _restore_rows(
                        tables[gname], emb_state[gname], payload,
                        src_idx, dst_rows,
                    )
            self.state = self.state.replace(tables=tables, emb_state=emb_state)
        self.state, header, ps_gpacked = self._step(
            self.state, device_inputs, layout
        )
        return header, evict_payload, ps_gpacked

    def _ps_forward(self, batch: PersiaBatch):
        """Forward the PS-tier slot subset through the worker's forward-ref
        machinery. Returns (ref, emb_batches, counts, entries) or None when
        the batch carries no ps slots. The ref's staleness slot is ALWAYS
        released on failure after the forward — any exception past
        put_forward_ids aborts before propagating."""
        if not self.tier.ps_slots:
            return None
        ps_feats = [
            f for f in batch.id_type_features if f.name in self.tier.ps_slots
        ]
        if not ps_feats:
            return None
        from persia_tpu.ctx import stage_embeddings

        ref = self.worker.put_forward_ids(PersiaBatch(ps_feats, requires_grad=False))
        try:
            embs = self.worker.forward_batch_id(ref, train=True)
            entries, counts = stage_embeddings(embs, dtype=self._ps_stage_dtype)
        except BaseException:
            self.worker.abort_gradient(ref)
            raise
        return ref, embs, counts, entries

    def _apply_ps_grads(self, ps_item, ps_gpacked) -> None:
        """Unpack the step's packed ps-slot gradients (one layout
        convention: unpack_step_grads) and return them to the worker; the
        ref is released either by the update or by an abort on failure."""
        from persia_tpu.parallel.train_step import unpack_step_grads

        ref, embs, counts, entries = ps_item
        try:
            gp = np.asarray(ps_gpacked)
            if gp.dtype != np.float32:  # bf16 ps-grad wire
                gp = gp.astype(np.float32)
            scale_factor = 1.0
            if self.dynamic_loss_scale:
                # buffer tail = [scale | finite] (see build_cached_train_step)
                scale_factor = float(gp[-2])
                if not gp[-1] > 0.5:  # overflow: skip-step — drop the grads
                    self.worker.abort_gradient(ref)
                    return
                gp = gp[:-2]
            grads = unpack_step_grads(gp, {"emb": entries})
            slot_grads = {
                eb.name: (g if d is None else g[:d])
                for eb, g, d in zip(embs, grads, counts)
            }
            self.worker.update_gradient_batched(
                ref, slot_grads, scale_factor=scale_factor
            )
        except BaseException:
            self.worker.abort_gradient(ref)
            raise

    def train_step(self, batch: PersiaBatch, fetch_metrics: bool = True):
        (device_inputs, layout, miss_aux, cold_aux, restore_aux, evict_aux,
         evict_meta) = self.tier.prepare_batch(
            batch, hazard_gate=self._sync_hazard_gate
        )
        # mixed-tier: worker/PS-served slots (hash-stack or excluded) flow
        # through the same forward-ref machinery the hybrid ctx uses; their
        # gradients come back as a step output
        ps_item = self._ps_forward(batch)
        try:
            if ps_item is not None:
                _ref, embs, _counts, entries = ps_item
                device_inputs["ps_emb"] = entries
                layout = CacheLayout(
                    stacked=layout.stacked,
                    ps=tuple(eb.name for eb in embs),
                )
            if self.state is None:
                self.init_state(jax.random.PRNGKey(0), device_inputs, layout)
            # explicit async host→device staging: passing numpy leaves
            # straight into jit makes the arg conversion a synchronous
            # per-leaf round-trip on remote-attached chips (measured 84 ms
            # vs 1 ms for the same data)
            device_inputs, miss_aux, cold_aux, evict_aux = self._stage(
                device_inputs, miss_aux, cold_aux, evict_aux
            )
            header, evict_payload, ps_gpacked = self._dispatch(
                device_inputs, layout, miss_aux, cold_aux, restore_aux,
                evict_aux,
            )
        except Exception:
            # any failure after the forward must release the staleness slot
            # + stashed layout, or the worker buffers leak (same contract as
            # TrainCtx.train_step)
            if ps_item is not None:
                self.worker.abort_gradient(ps_item[0])
            raise
        if ps_item is not None:
            # the PS-tier gradient return is an inherent d2h (same as the
            # hybrid path); the helper aborts the ref itself on failure.
            # Ordering vs the deferred eviction write-back below is a
            # non-issue: the constructor rejects feature groups spanning
            # both tiers, so these gradients can never touch a sign an
            # eviction wrote back (same invariant the stream path's
            # _flush_ps documents).
            self._apply_ps_grads(ps_item, ps_gpacked)
        prev = self._pending
        self._pending = (
            evict_meta, evict_payload, header, device_inputs["labels"][0].shape
        )
        self._pending_signs = {
            int(s) for ev_signs, k in evict_meta.values() for s in ev_signs[:k]
        }
        if prev is not None:
            self._write_back_only(prev)
        if self.sparse_cfg.kind == OPTIMIZER_ADAM:
            # PS-side Adam beta powers advance once per gradient batch,
            # mirroring the device's shared emb_batch_state for EVERY
            # feature group holding cached slots, so write-backs land in a
            # store whose future updates use consistent powers. PS-tier
            # slots' groups advance inside the worker's gradient batch
            # instead — the constructor guarantees the two tier's feature
            # groups are disjoint, so no group can be advanced twice.
            for grp in self._cached_groups:
                self.tier.router.advance_batch_state(grp)
        if fetch_metrics:
            return self._fetch_metrics()
        return None

    def _write_back_only(self, pending) -> None:
        evict_meta, evict_payload, _header, _shape = pending
        self.tier.write_back(evict_meta, evict_payload)

    def _land_pending(self) -> None:
        """Force the deferred write-back to the PS (hazard or boundary)."""
        if self._pending is not None:
            self._fetch_metrics()  # also materializes header once
            self._write_back_only(self._pending)
            self._pending = None
            self._pending_signs = set()

    def _parse_header(self, h: np.ndarray, label_shape) -> Dict:
        """Host view of the step header — the layout is owned by ONE pair
        of decoders (parallel/train_step.py unpack_step_header[_dynamic]);
        this adapter only supplies the label shape."""
        from types import SimpleNamespace

        from persia_tpu.parallel.train_step import (
            unpack_step_header,
            unpack_step_header_dynamic,
        )

        shaped = {"labels": [SimpleNamespace(shape=label_shape)]}
        if self.dynamic_loss_scale:
            loss, preds, scale, finite = unpack_step_header_dynamic(h, shaped)
            return {
                "loss": loss, "preds": preds,
                "loss_scale": scale, "grads_finite": finite,
            }
        loss, preds = unpack_step_header(h, shaped)
        return {"loss": loss, "preds": preds}

    def _fetch_metrics(self) -> Dict:
        if self._pending is None:
            return self._last_metrics or {}
        _meta, _payload, header, label_shape = self._pending
        self._last_metrics = self._parse_header(np.asarray(header), label_shape)
        self._last_header_dev = None  # fresher than any stashed stream header
        return self._last_metrics

    def drain(self) -> Optional[Dict]:
        """Land any deferred write-back and return the last step's metrics
        (materializing a ``fetch_final=False`` stream's stashed header if
        that is the freshest result)."""
        if self._pending is not None:
            self._fetch_metrics()
            self._land_pending()
        return self.last_metrics()

    # -------------------------------------------------------------- pipeline

    def train_stream(
        self,
        batches,
        prefetch: int = 3,
        on_metrics: Optional[Callable[[Dict], None]] = None,
        wb_flush_steps: int = 8,
        fetch_final: bool = True,
        psgrad_batch: int = 8,
    ) -> Optional[Dict]:
        """Fully-pipelined training over an iterable of ``PersiaBatch``.

        Three concurrent stages (the TPU analogue of the reference's
        latency-hiding forward/backward engines, forward.rs:640-779 /
        backward.rs:304-354):

        - a **feeder thread** runs host preprocessing, the directory admit,
          the PS checkout, and kicks off the async host→device staging for
          batch N+k while the device executes batch N;
        - the **caller's thread** only dispatches the (tiny) device programs
          in order;
        - a **write-back thread** materializes each step's eviction payload
          (the device→host transfer) and persists it to the PS.

        Correctness across threads: the directory is only touched by the
        feeder (serial admits), and the feeder's hazard gate blocks a PS
        checkout while an overlapping eviction write-back is in flight.
        Returns the final step's metrics; ``on_metrics`` (if given) receives
        every step's metrics at the cost of a per-step device sync.

        Mixed-tier configs stream too: PS-tier slots forward in the feeder
        thread and their gradients return through the write-back thread, so
        they train under BOUNDED staleness (a forward may read entries
        whose previous-step gradients are in flight, the window set by the
        prefetch depth) — the reference's async mode; cached slots stay
        fully synchronous.

        ``psgrad_batch``: PS-tier gradient returns are device→host fetches;
        on a high-latency link a serial per-step fetch caps the whole
        pipeline at 1/latency. The write-back thread therefore accumulates
        up to ``psgrad_batch`` consecutive steps' gradient outputs and
        fetches them CONCURRENTLY (parallel transfers share the latency),
        then applies them to the worker in step order — the staleness
        window grows to ``prefetch + psgrad_batch`` steps, the same
        throughput/staleness trade the reference's lookup-worker count
        sets (forward.rs:640-779).

        ``fetch_final=False`` keeps the loop COMPLETELY free of
        device→host transfers: the final header is only
        ``block_until_ready``-synced (completion without a fetch) and
        stashed device-side; ``last_metrics()`` materializes it on demand.
        On a remote-attached chip a d2h fetch costs tens of ms and can
        permanently degrade the runtime's dispatch latency (measured ~200×
        on the axon tunnel), so throughput-critical loops should defer every
        fetch past the region they care about.
        """
        import queue as _queue

        if prefetch < 1:
            raise ValueError(f"prefetch must be >= 1, got {prefetch}")
        # The feeder→stager path holds up to prefetch (prep_q) + 2 in-hand
        # batches of host staging buffers, each still referenced by an async
        # device_put until its h2d lands. Size every staging ring so a slot
        # cannot come around for reuse while that many items (plus h2d
        # slack) are in flight — otherwise a deep-prefetch stream would
        # silently corrupt device-side data.
        need_depth = prefetch + 4
        self.tier._ring.ensure_depth(need_depth)
        for d in self.tier.dirs.values():
            d._rows_ring.ensure_depth(need_depth)

        self._land_pending()  # do not mix with a sync-path deferred step
        # pending eviction write-backs, seq → per-group record:
        #   {"sorted": {g: sorted u64 signs}, "order": {g: payload row of
        #    each sorted sign}, "payload": None | {g: DEVICE (Kp, entry_len)}}
        # "payload" is filled by the main thread at dispatch; the record is
        # deleted once the batched write-back lands it in the PS.
        pending: Dict[int, Dict] = {}
        cv = threading.Condition()
        stop = threading.Event()
        staged_q: "_queue.Queue" = _queue.Queue(maxsize=prefetch)
        # bounds device-memory retention: at most ~(queue + one flush batch)
        # steps of eviction payloads (+ one psgrad batch) stay pinned in HBM
        # while the PS lags
        wb_q: "_queue.Queue" = _queue.Queue(
            maxsize=max(1, wb_flush_steps) + prefetch + max(1, psgrad_batch)
        )
        SENTINEL = object()
        errors: List[BaseException] = []

        def gate(gname: str, miss_signs: np.ndarray):
            """Resolve re-missed pending-evicted signs against the in-flight
            DEVICE payloads: returns restore descriptors, never waits for a
            device→host transfer (only, rarely, for the main thread to
            dispatch the step that produces a just-evicted payload)."""
            out = []
            with cv:
                while not (stop.is_set() or errors):
                    out.clear()
                    waiting = False
                    picks: Dict[int, Tuple[int, int]] = {}  # pos → (seq, src)
                    for seq in sorted(pending):  # later steps override earlier
                        rec = pending[seq]
                        sg = rec["sorted"].get(gname)
                        if sg is None:
                            continue
                        loc = np.searchsorted(sg, miss_signs)
                        loc_c = np.minimum(loc, len(sg) - 1)
                        mask = sg[loc_c] == miss_signs
                        if not mask.any():
                            continue
                        if rec["payload"] is None:
                            waiting = True  # step not yet dispatched
                            continue
                        order = rec["order"][gname]
                        for i in np.nonzero(mask)[0].tolist():
                            picks[i] = (seq, int(order[loc_c[i]]))
                    if not waiting:
                        by_seq: Dict[int, List] = {}
                        for i, (seq, j) in picks.items():
                            by_seq.setdefault(seq, []).append((i, j))
                        for seq, ij in by_seq.items():
                            pos = np.array([i for i, _ in ij], dtype=np.int64)
                            src = np.array([j for _, j in ij], dtype=np.int64)
                            out.append(
                                (pending[seq]["payload"][gname], src, pos)
                            )
                        break
                    cv.wait(timeout=1.0)
            return out or None

        prep_q: "_queue.Queue" = _queue.Queue(maxsize=prefetch)

        def _put(q, item) -> bool:
            while not (stop.is_set() or errors):
                try:
                    q.put(item, timeout=0.5)
                    return True
                except _queue.Full:
                    continue
            return False

        def feeder_prep():
            """Stage 1: host preprocessing + directory admit + PS probe."""
            seq = 0
            try:
                for batch in batches:
                    if stop.is_set() or errors:
                        break
                    with span("stream.prep"):
                        item = self.tier.prepare_batch(batch, hazard_gate=gate)
                    with span("stream.ps_forward"):
                        ps_item = self._ps_forward(batch)
                    if ps_item is not None:
                        _ref, embs, _counts, entries = ps_item
                        di0 = item[0]
                        di0["ps_emb"] = entries
                        layout0 = CacheLayout(
                            stacked=item[1].stacked,
                            ps=tuple(eb.name for eb in embs),
                        )
                        item = (di0, layout0) + item[2:]
                    evict_meta = item[6]
                    # evicted signs become hazard-gated HERE (admit time): a
                    # later batch's probe must not trust the PS for them
                    # until the write-back lands their payload
                    if evict_meta:
                        rec = {"sorted": {}, "order": {}, "payload": None}
                        for gn, (ev, k) in evict_meta.items():
                            order = np.argsort(ev[:k])
                            rec["sorted"][gn] = ev[:k][order]
                            rec["order"][gn] = order
                        with cv:
                            pending[seq] = rec
                    if not _put(prep_q, (seq, item, ps_item)):
                        if ps_item is not None:
                            self.worker.abort_gradient(ps_item[0])
                        return
                    seq += 1
            except BaseException as e:  # noqa: BLE001 — propagate to caller
                errors.append(e)
                with cv:
                    cv.notify_all()
            finally:
                prep_q.put(SENTINEL)

        def feeder_dp():
            """Stage 2: async host→device staging, overlapped with stage 1's
            preprocessing of the following batch."""
            try:
                while True:
                    got = prep_q.get()
                    if got is SENTINEL:
                        break
                    seq, item, ps_item = got
                    (di, layout, miss_aux, cold_aux, restore_aux, evict_aux,
                     evict_meta) = item
                    with span("stream.stage"):
                        di, miss_aux, cold_aux, evict_aux = self._stage(
                            di, miss_aux, cold_aux, evict_aux
                        )
                    # restore index arrays must commit like every other aux
                    # input: on a mesh an uncommitted put lands on one
                    # device and _restore_rows would see incompatible
                    # devices against the replicated tables
                    rep = self._replicated()
                    restore_aux = (
                        jax.device_put(restore_aux) if rep is None
                        else jax.device_put(restore_aux, rep)
                    )
                    if not _put(
                        staged_q,
                        (seq, di, layout, miss_aux, cold_aux, restore_aux,
                         evict_aux, evict_meta, ps_item),
                    ):
                        if ps_item is not None:
                            self.worker.abort_gradient(ps_item[0])
                        return
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
                with cv:
                    cv.notify_all()
            finally:
                staged_q.put(SENTINEL)  # main's shutdown drain guarantees room

        # device→host transfers cost ~60 ms latency each regardless of size,
        # so the write-back batches many steps' payloads and fetches them
        # CONCURRENTLY (parallel transfers share the latency), then persists
        # to the PS. The gate never needs host data (device-side restore).
        FLUSH_STEPS = max(1, wb_flush_steps)

        def _flush_acc(acc) -> None:
            if not acc:
                return
            with span("stream.wb_flush", steps=len(acc)):
                _flush_acc_inner(acc)

        def _flush_acc_inner(acc) -> None:
            pool = self._fetch_pool()
            fetches = []  # (seq, gname, k, device payload)
            for seq, evict_meta, evict_payload in acc:
                for gn, (ev, k) in evict_meta.items():
                    fetches.append((seq, gn, ev, k, evict_payload[gn]))

            def fetch(f):
                return np.asarray(f[4])[:f[3]].astype(np.float32)

            hosts = list(pool.map(fetch, fetches)) if pool else [fetch(f) for f in fetches]
            for (seq, gn, ev, k, _p), host in zip(fetches, hosts):
                g = next(gr for gr in self.tier.groups if gr.name == gn)
                self.tier._set_embedding(ev[:k], host[:k], dim=g.dim)
            with cv:
                for seq, _m, _p in acc:
                    pending.pop(seq, None)
                cv.notify_all()
            acc.clear()

        PS_BATCH = max(1, psgrad_batch)

        def _abort_ps_refs(items) -> None:
            """Best-effort staleness-slot release for queued psgrad items
            (shutdown paths): one place owns which tuple element holds the
            ref and the swallow-exceptions policy."""
            for it in items:
                try:
                    self.worker.abort_gradient(it[1][0])
                except Exception:  # noqa: BLE001 — shutdown best-effort
                    pass
            if isinstance(items, list):
                items.clear()

        def _flush_ps(ps_acc) -> None:
            """Fetch the accumulated steps' packed ps-grad outputs
            CONCURRENTLY (d2h latency is shared), then apply to the worker
            in step order. On an apply failure, not-yet-applied refs are
            aborted (the failing apply aborts its own ref itself).

            Ordering vs eviction write-backs: NONE needed — the constructor
            rejects configs where a feature group spans both tiers, so a PS
            gradient can never touch a sign an eviction wrote back; psgrad
            batches and eviction flushes proceed independently, each keeping
            its own concurrent-fetch batching."""
            if not ps_acc:
                return
            pool = self._fetch_pool()

            def fetch(it):
                return np.asarray(it[2])

            hosts = (
                list(pool.map(fetch, ps_acc)) if pool
                else [fetch(it) for it in ps_acc]
            )
            k = 0
            try:
                for k, ((_tag, ps_item, _g), host) in enumerate(
                    zip(ps_acc, hosts)
                ):
                    self._apply_ps_grads(ps_item, host)
            except BaseException:
                _abort_ps_refs(ps_acc[k + 1:])
                ps_acc.clear()
                raise
            ps_acc.clear()

        def writeback():
            acc: List = []
            ps_acc: List = []
            while True:
                item = wb_q.get()
                try:
                    if item is SENTINEL:
                        _flush_acc(acc)
                        _flush_ps(ps_acc)
                        return
                    if isinstance(item, tuple) and item[0] == "psgrad":
                        ps_acc.append(item)
                        if len(ps_acc) >= PS_BATCH:
                            _flush_ps(ps_acc)
                        continue
                    acc.append(item)
                    if len(acc) >= FLUSH_STEPS:
                        _flush_acc(acc)
                except BaseException as e:  # noqa: BLE001
                    errors.append(e)
                    _abort_ps_refs(ps_acc)
                    with cv:
                        for seq, _m, _p in acc:
                            pending.pop(seq, None)
                        acc.clear()
                        cv.notify_all()
                    if item is SENTINEL:
                        return

        feeder_t = threading.Thread(target=feeder_prep, daemon=True, name="cache-feeder")
        dp_t = threading.Thread(target=feeder_dp, daemon=True, name="cache-stager")
        wb_t = threading.Thread(target=writeback, daemon=True, name="cache-writeback")
        feeder_t.start()
        dp_t.start()
        wb_t.start()
        header = None
        label_shape = None

        def _abort_drained(got) -> None:
            # a drained-but-never-applied item may carry a PS-tier forward
            # ref: release its staleness slot + stashed layout
            if (
                isinstance(got, tuple) and len(got) >= 3
                and got[-1] is not None
                and isinstance(got[-1], tuple) and len(got[-1]) == 4
            ):
                try:
                    self.worker.abort_gradient(got[-1][0])
                except Exception:  # noqa: BLE001 — shutdown best-effort
                    pass

        try:
            while True:
                item = staged_q.get()
                if item is SENTINEL:
                    break
                if errors:
                    _abort_drained(item)
                    break
                (seq, di, layout, miss_aux, cold_aux, restore_aux, evict_aux,
                 evict_meta, ps_item) = item
                try:
                    if self.state is None:
                        self.init_state(jax.random.PRNGKey(0), di, layout)
                    with span("stream.dispatch"):
                        header, evict_payload, ps_gpacked = self._dispatch(
                            di, layout, miss_aux, cold_aux, restore_aux,
                            evict_aux
                        )
                except BaseException:
                    # the in-hand item is already off the queue: the
                    # shutdown drain in finally can't see it, so its
                    # staleness ref must be released HERE or it leaks
                    if ps_item is not None:
                        try:
                            self.worker.abort_gradient(ps_item[0])
                        except Exception:  # noqa: BLE001 — shutdown best-effort
                            pass
                    raise
                if ps_item is not None:
                    # gradient return for PS-tier slots rides the write-back
                    # thread (its d2h is off the dispatch path); FIFO order
                    # keeps the worker's per-batch Adam advance in step order
                    wb_q.put(("psgrad", ps_item, ps_gpacked))
                label_shape = di["labels"][0].shape
                if evict_meta:
                    # publish the DEVICE payload so the feeder's gate can
                    # build restores for re-missed signs without any d2h
                    with cv:
                        if seq in pending:
                            pending[seq]["payload"] = evict_payload
                        cv.notify_all()
                    wb_q.put((seq, evict_meta, evict_payload))
                if self.sparse_cfg.kind == OPTIMIZER_ADAM:
                    # mirror the device's beta-power advance on the PS every
                    # gradient batch (same contract as the sync train_step)
                    for grp in self._cached_groups:
                        self.tier.router.advance_batch_state(grp)
                if on_metrics is not None:
                    self._last_metrics = self._parse_header(
                        np.asarray(header), label_shape
                    )
                    on_metrics(self._last_metrics)
        finally:
            stop.set()
            with cv:
                cv.notify_all()

            # unblock stages stuck on full queues, then reap all threads
            while feeder_t.is_alive() or dp_t.is_alive():
                try:
                    _abort_drained(prep_q.get_nowait())
                except _queue.Empty:
                    pass
                try:
                    _abort_drained(staged_q.get(timeout=0.1))
                except _queue.Empty:
                    pass
            # final sweep AFTER the feeders died: on an error shutdown they
            # exit on their own, leaving queued items whose PS forward refs
            # would otherwise leak staleness slots
            for q in (prep_q, staged_q):
                while True:
                    try:
                        _abort_drained(q.get_nowait())
                    except _queue.Empty:
                        break
            wb_q.put(SENTINEL)
            feeder_t.join(timeout=300)
            dp_t.join(timeout=300)
            wb_t.join(timeout=300)
        if errors:
            raise RuntimeError("cached train pipeline failed") from errors[0]
        if header is not None:
            if on_metrics is not None or fetch_final:
                if on_metrics is None:
                    self._last_metrics = self._parse_header(
                        np.asarray(header), label_shape
                    )
                self._last_header_dev = None  # this stream is the freshest
            else:
                jax.block_until_ready(header)  # completion, no transfer
                self._last_header_dev = (header, label_shape)
                return None
        return self._last_metrics

    def last_metrics(self) -> Optional[Dict]:
        if self._pending:
            return self._fetch_metrics()
        if self._last_header_dev is not None:
            header, label_shape = self._last_header_dev
            self._last_metrics = self._parse_header(
                np.asarray(header), label_shape
            )
            self._last_header_dev = None
        return self._last_metrics

    def eval_batch(self, batch: PersiaBatch) -> np.ndarray:
        # eval misses consult the PS, so a deferred eviction must land first
        self._land_pending()
        inputs, layout = self.tier.prepare_eval_batch(batch)
        if self.tier.ps_slots:
            from persia_tpu.ctx import stage_embeddings

            ps_feats = [
                f for f in batch.id_type_features
                if f.name in self.tier.ps_slots
            ]
            if ps_feats:
                ps_sub = PersiaBatch(ps_feats, requires_grad=False)
                emb_batches = self.worker.forward_directly(ps_sub, train=False)
                entries, _ = stage_embeddings(emb_batches)
                inputs["ps_emb"] = entries
                layout = CacheLayout(
                    stacked=layout.stacked,
                    ps=tuple(eb.name for eb in emb_batches),
                )
        if self.state is None:
            raise RuntimeError("eval before any train_step/init_state")
        # eval stays simple under a mesh: everything replicated is correct
        # (no gradient reduction to get right) and eval is off the hot path
        rep = self._replicated()
        inputs = jax.device_put(inputs) if rep is None else jax.device_put(inputs, rep)
        return np.asarray(self._eval(self.state, inputs, layout))

    # ------------------------------------------------------------ checkpoint

    def publish(self) -> int:
        """Serving-freshness valve: write every resident row to the PS (and
        its incremental-update manager) WITHOUT evicting — hot signs that
        never leave the cache would otherwise ship no online-serving deltas
        between checkpoints. Call on the serving cadence; costs one
        device→host read of the resident rows. Returns rows published."""
        self._land_pending()
        if self.state is None:
            return 0
        return self.tier.publish(self.state.tables, self.state.emb_state)

    def flush(self) -> None:
        """Write every cached row back to the PS (checkpoint boundary); the
        cache restarts cold."""
        self._land_pending()
        if self.state is None:
            return
        self.tier.flush(self.state.tables, self.state.emb_state)
        # the directory is drained; zero the pools so stale rows can never be
        # mistaken for fresh checkouts
        tables, emb_state = init_cached_tables(
            self.tier.groups, self.sparse_cfg, dtype=self.table_dtype
        )
        self.state = self.state.replace(tables=tables, emb_state=emb_state)

    def dump_checkpoint(self, dst: str, blocking: bool = True) -> None:
        self.flush()
        self.worker.dump(dst, blocking=blocking)

    def load_checkpoint(self, src: str) -> None:
        self.flush()
        self.worker.load(src)

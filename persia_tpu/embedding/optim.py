"""Sparse (embedding) optimizers.

Two halves:

- User-facing config classes ``SGD`` / ``Adagrad`` / ``Adam`` mirroring
  ``persia/embedding/optim.py`` — these are declarative descriptions shipped to
  the parameter servers at context entry.
- The ``Optimizable`` implementations used by the numpy reference store
  (`persia_tpu/embedding/store.py`), mirroring the reference trait
  ``Optimizable {update, require_space, state_initialization,
  get_batch_level_state}`` (`rust/persia-common/src/optim.rs:66-92`) and its
  SIMD kernels (`rust/persia-simd/src/lib.rs`). The C++ core implements the
  same math; tests assert parity against these.

All state lives *inside the embedding entry* as a trailing f32 block
(``[emb | state]``), exactly like the reference's ``HashMapEmbeddingEntry``
(`persia-embedding-holder/src/emb_entry.rs:16-76`), so LRU eviction and
checkpointing move optimizer state together with the weights for free.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Dict, Tuple

import numpy as np

OPTIMIZER_SGD = 0
OPTIMIZER_ADAGRAD = 1
OPTIMIZER_ADAM = 2


@dataclass(frozen=True)
class OptimizerConfig:
    """Wire-level optimizer description registered to every PS
    (ref: rust/persia-core/src/optim.rs:61-66)."""

    kind: int
    lr: float = 0.01
    weight_decay: float = 0.0
    # adagrad
    initialization: float = 0.01
    g_square_momentum: float = 1.0
    eps: float = 1e-10
    vectorwise_shared: bool = False
    # adam
    beta1: float = 0.9
    beta2: float = 0.999

    def state_dim(self, dim: int) -> int:
        if self.kind == OPTIMIZER_SGD:
            return 0
        if self.kind == OPTIMIZER_ADAGRAD:
            return 1 if self.vectorwise_shared else dim
        if self.kind == OPTIMIZER_ADAM:
            return 2 * dim
        raise ValueError(f"unknown optimizer kind {self.kind}")

    def init_state(self, dim: int) -> np.ndarray:
        n = self.state_dim(dim)
        if self.kind == OPTIMIZER_ADAGRAD:
            return np.full(n, self.initialization, dtype=np.float32)
        return np.zeros(n, dtype=np.float32)

    def update_dense(
        self,
        emb: np.ndarray,
        state: np.ndarray,
        grad: np.ndarray,
        batch_state: Tuple[float, float],
    ) -> None:
        """In-place update of one entry. ``batch_state`` = accumulated
        (beta1^t, beta2^t) for Adam (ref: optim.rs:99-221 keeps these per
        feature group, advanced once per batch)."""
        if self.kind == OPTIMIZER_SGD:
            # ref: NaiveSGD (optim.rs:223-244) / decayed_sgd_avx2 (simd:124)
            if self.weight_decay:
                grad = grad + self.weight_decay * emb
            emb -= self.lr * grad
        elif self.kind == OPTIMIZER_ADAGRAD:
            # ref: Adagrad incl. vectorwise shared (optim.rs:246-307),
            # decayed_adagrad_avx2 (simd:21-122)
            if self.weight_decay:
                grad = grad + self.weight_decay * emb
            if self.vectorwise_shared:
                g2 = float(np.mean(grad * grad))
                state[0] = state[0] * self.g_square_momentum + g2
                emb -= self.lr * grad / np.sqrt(state[0] + self.eps)
            else:
                state *= self.g_square_momentum
                state += (grad * grad).astype(np.float32)
                emb -= self.lr * grad / np.sqrt(state + self.eps)
        elif self.kind == OPTIMIZER_ADAM:
            # ref: Adam with per-group accumulated beta powers (optim.rs:99-221),
            # adam_avx2 (simd:147)
            dim = emb.shape[0]
            m = state[:dim]
            v = state[dim:]
            m *= self.beta1
            m += (1.0 - self.beta1) * grad
            v *= self.beta2
            v += (1.0 - self.beta2) * grad * grad
            beta1_pow, beta2_pow = batch_state
            m_hat = m / (1.0 - beta1_pow)
            v_hat = v / (1.0 - beta2_pow)
            emb -= self.lr * m_hat / (np.sqrt(v_hat) + self.eps)
        else:
            raise ValueError(f"unknown optimizer kind {self.kind}")

    def advance_batch_state(self, prev: Tuple[float, float]) -> Tuple[float, float]:
        if self.kind != OPTIMIZER_ADAM:
            return prev
        return (prev[0] * self.beta1, prev[1] * self.beta2)

    def initial_batch_state(self) -> Tuple[float, float]:
        return (1.0, 1.0)

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict) -> "OptimizerConfig":
        return cls(**d)


class SGD:
    """User-facing sparse SGD (ref: persia/embedding/optim.py:19-41)."""

    def __init__(self, lr: float = 0.01, weight_decay: float = 0.0):
        self.config = OptimizerConfig(OPTIMIZER_SGD, lr=lr, weight_decay=weight_decay)


class Adagrad:
    """User-facing sparse Adagrad (ref: persia/embedding/optim.py:60-96;
    ``vectorwise_shared`` shares one accumulator per embedding vector)."""

    def __init__(
        self,
        lr: float = 0.01,
        weight_decay: float = 0.0,
        initialization: float = 0.01,
        g_square_momentum: float = 1.0,
        eps: float = 1e-10,
        vectorwise_shared: bool = False,
    ):
        self.config = OptimizerConfig(
            OPTIMIZER_ADAGRAD,
            lr=lr,
            weight_decay=weight_decay,
            initialization=initialization,
            g_square_momentum=g_square_momentum,
            eps=eps,
            vectorwise_shared=vectorwise_shared,
        )


class Adam:
    """User-facing sparse Adam (ref: persia/embedding/optim.py:43-58)."""

    def __init__(
        self,
        lr: float = 0.001,
        betas: Tuple[float, float] = (0.9, 0.999),
        weight_decay: float = 0.0,
        eps: float = 1e-8,
    ):
        self.config = OptimizerConfig(
            OPTIMIZER_ADAM,
            lr=lr,
            beta1=betas[0],
            beta2=betas[1],
            weight_decay=weight_decay,
            eps=eps,
        )

"""Embedding subsystem: hashing/routing, parameter store, worker tier,
sparse optimizers (ref: persia/embedding/ + rust/persia-embedding-server)."""

from persia_tpu.config import HyperParameters as EmbeddingHyperParameters  # noqa: F401
from persia_tpu.embedding.optim import SGD, Adagrad, Adam  # noqa: F401
from persia_tpu.embedding.store import EmbeddingStore  # noqa: F401
from persia_tpu.embedding.tpu_table import (  # noqa: F401
    EmbeddingSpec,
    create_table,
    create_tables,
    embedding_bag,
    embedding_lookup,
    lookup_all,
)
from persia_tpu.embedding.worker import EmbeddingWorker  # noqa: F401

"""Embedding subsystem: hashing/routing, parameter store, worker tier,
sparse optimizers (ref: persia/embedding/ + rust/persia-embedding-server)."""

from persia_tpu.config import HyperParameters as EmbeddingHyperParameters  # noqa: F401
from persia_tpu.embedding.optim import SGD, Adagrad, Adam  # noqa: F401
from persia_tpu.embedding.store import EmbeddingStore  # noqa: F401
from persia_tpu.embedding.worker import EmbeddingWorker  # noqa: F401

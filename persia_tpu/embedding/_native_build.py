"""Race-safe native-library builds shared by the ps/worker/cache cores.

The .so is gated on a source hash (git checkouts do not preserve mtimes).
Builds must be safe against CONCURRENT builders in other processes (pytest
xdist workers, a bench subprocess, an editor-triggered rebuild): two g++
invocations writing the same output path interleave their writes and produce
a loadable-but-corrupt library — observed as silently wrong results, not a
load error. So: compile to a per-pid temp file, ``os.replace`` it into place
(atomic on POSIX — a concurrent ``dlopen`` sees the old or the new inode,
never a mix), all under an ``flock``'d lockfile with a re-check so losers of
the race reuse the winner's build instead of rebuilding.
"""

from __future__ import annotations

import fcntl
import hashlib
import os
import subprocess
import threading

_PROC_LOCK = threading.Lock()


def _hash_file(path: str) -> str:
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _is_fresh(so: str, stamp: str, h: str) -> bool:
    if not (os.path.exists(so) and os.path.exists(stamp)):
        return False
    with open(stamp) as f:
        return f.read().strip() == h


def build_so(src, so: str, flags, logger, force: bool = False) -> str:
    """Build ``src`` (one path or a list of paths) into ``so`` with g++ if
    stale; returns ``so``."""
    srcs = [src] if isinstance(src, str) else list(src)
    stamp = so + ".srchash"
    with _PROC_LOCK:
        h = "".join(_hash_file(p) for p in srcs)
        if not force and _is_fresh(so, stamp, h):
            return so
        with open(so + ".lock", "w") as lf:
            fcntl.flock(lf, fcntl.LOCK_EX)
            try:
                if not force and _is_fresh(so, stamp, h):
                    return so  # another process just built it
                tmp = f"{so}.tmp.{os.getpid()}"
                cmd = ["g++", *flags, "-o", tmp, *srcs]
                logger.info("building %s: %s", os.path.basename(so), " ".join(cmd))
                try:
                    subprocess.check_call(cmd)
                    os.replace(tmp, so)
                finally:
                    if os.path.exists(tmp):
                        os.unlink(tmp)
                stamp_tmp = f"{stamp}.tmp.{os.getpid()}"
                with open(stamp_tmp, "w") as f:
                    f.write(h)
                os.replace(stamp_tmp, stamp)
                return so
            finally:
                fcntl.flock(lf, fcntl.LOCK_UN)

"""Race-safe native-library builds shared by the ps/worker/cache cores.

The .so is gated on a build hash (git checkouts do not preserve mtimes).
The hash covers the SOURCE BYTES **and** the full compiler flag vector +
sanitizer variant: a flag change (new -D, -O level, added -fsanitize=...)
must never reuse a stale cached library — that was exactly the stale-.so
class of silent corruption the source-only hash still allowed.

Builds must be safe against CONCURRENT builders in other processes (pytest
xdist workers, a bench subprocess, an editor-triggered rebuild): two g++
invocations writing the same output path interleave their writes and produce
a loadable-but-corrupt library — observed as silently wrong results, not a
load error. So: compile to a per-pid temp file, ``os.replace`` it into place
(atomic on POSIX — a concurrent ``dlopen`` sees the old or the new inode,
never a mix), all under an ``flock``'d lockfile with a re-check so losers of
the race reuse the winner's build instead of rebuilding.

Sanitizer variants (``PERSIA_NATIVE_SANITIZE=asan|ubsan|tsan``) build to a
DISTINCT path (``libpersia_ps.asan.so``) with the sanitizer flags appended
to the normal flag vector (same -O3/-mavx2 base, so fp codegen — and the
bit-parity suites — match the production build). Callers must load the
path ``build_so`` RETURNS, not a precomputed constant, or the variant
never takes effect; ``scripts/sanitize_native.sh`` drives the parity
suites through these variants. ASan libraries need the ASan runtime
preloaded into the host python (the script handles LD_PRELOAD).
"""

from __future__ import annotations

import fcntl
import hashlib
import os
import subprocess
import threading
from typing import List

_PROC_LOCK = threading.Lock()

SANITIZER_FLAGS = {
    # -g for symbolized reports; no -fno-omit-frame-pointer tradeoff debates
    # here — these are test-only variants, never the serving build
    "asan": ["-fsanitize=address", "-fno-omit-frame-pointer", "-g"],
    # halt on the first report: a UBSan finding must fail the parity suite,
    # not scroll past it
    "ubsan": ["-fsanitize=undefined", "-fno-sanitize-recover=undefined", "-g"],
    # ThreadSanitizer: every instrumented load/store is checked against the
    # happens-before graph, so the seeded multi-thread stress harness
    # (tests/test_race_stress.py via scripts/race_native.sh) turns "the
    # PendingMap/AccessSketch/journal mutexes actually cover every shared
    # access" into a machine-checked claim. Needs libtsan preloaded into
    # the host python (the script handles LD_PRELOAD) and abort-on-report
    # TSAN_OPTIONS so a race fails the suite instead of scrolling past.
    "tsan": ["-fsanitize=thread", "-fno-omit-frame-pointer", "-g"],
}


def sanitize_variant() -> str:
    """Current sanitizer variant from ``PERSIA_NATIVE_SANITIZE`` ("" when
    unset). Unknown values raise rather than silently building vanilla."""
    v = os.environ.get("PERSIA_NATIVE_SANITIZE", "").strip().lower()
    if v in ("", "0", "none", "off"):
        return ""
    if v not in SANITIZER_FLAGS:
        raise ValueError(
            f"PERSIA_NATIVE_SANITIZE={v!r}: expected one of "
            f"{sorted(SANITIZER_FLAGS)} (or unset)"
        )
    return v


def variant_so_path(so: str, variant: str) -> str:
    """libpersia_ps.so -> libpersia_ps.asan.so (distinct artifact per
    variant: a sanitized .so must never shadow the production one)."""
    if not variant:
        return so
    base, ext = os.path.splitext(so)
    return f"{base}.{variant}{ext}"


def _hash_file(path: str) -> str:
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _build_hash(srcs: List[str], flags: List[str], variant: str) -> str:
    h = hashlib.sha256()
    for p in srcs:
        h.update(_hash_file(p).encode())
        h.update(b"\x00")
    h.update(("flags:" + "\x1f".join(flags)).encode())
    h.update(("variant:" + variant).encode())
    return h.hexdigest()


def _is_fresh(so: str, stamp: str, h: str) -> bool:
    if not (os.path.exists(so) and os.path.exists(stamp)):
        return False
    with open(stamp) as f:
        return f.read().strip() == h


def build_so(src, so: str, flags, logger, force: bool = False) -> str:
    """Build ``src`` (one path or a list of paths) into ``so`` with g++ if
    stale; returns the path actually built — the sanitizer-variant path
    when ``PERSIA_NATIVE_SANITIZE`` is set. Always ``CDLL`` the returned
    path."""
    srcs = [src] if isinstance(src, str) else list(src)
    variant = sanitize_variant()
    so = variant_so_path(so, variant)
    flags = list(flags) + (SANITIZER_FLAGS[variant] if variant else [])
    stamp = so + ".srchash"
    with _PROC_LOCK:
        h = _build_hash(srcs, flags, variant)
        if not force and _is_fresh(so, stamp, h):
            return so
        with open(so + ".lock", "w") as lf:
            fcntl.flock(lf, fcntl.LOCK_EX)
            try:
                if not force and _is_fresh(so, stamp, h):
                    return so  # another process just built it
                tmp = f"{so}.tmp.{os.getpid()}"
                cmd = ["g++", *flags, "-o", tmp, *srcs]
                logger.info("building %s: %s", os.path.basename(so), " ".join(cmd))
                try:
                    # blocking-under-lock is the POINT here: the lock exists
                    # to serialize concurrent builders onto one compile
                    subprocess.check_call(cmd)  # persia-lint: disable=CONC003
                    os.replace(tmp, so)
                finally:
                    if os.path.exists(tmp):
                        os.unlink(tmp)
                stamp_tmp = f"{stamp}.tmp.{os.getpid()}"
                with open(stamp_tmp, "w") as f:
                    f.write(h)
                os.replace(stamp_tmp, stamp)
                return so
            finally:
                fcntl.flock(lf, fcntl.LOCK_UN)

"""ctypes bindings for the native C++ embedding-worker hot loops
(`native/worker.cpp`).

Drop-in accelerators for the numpy golden routines in
`persia_tpu.embedding.worker`: id dedup (np.unique), sum-pooling /
per-sign gradient accumulation (np.add.at), raw-slot index construction,
and shard partitioning. Bit-exact parity with the numpy path is asserted in
tests/test_native_worker.py; `PERSIA_TPU_NATIVE_WORKER=0` disables the
native path (the pure-numpy fallback stays the golden model).
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional, Tuple

import numpy as np

from persia_tpu.logger import get_default_logger

logger = get_default_logger("persia_tpu.native_worker")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "worker.cpp")
_SO = os.path.join(_REPO_ROOT, "native", "libpersia_worker.so")
_LIB: Optional[ctypes.CDLL] = None
_LOAD_FAILED = False

_i64p = ctypes.POINTER(ctypes.c_int64)
_u64p = ctypes.POINTER(ctypes.c_uint64)
_f32p = ctypes.POINTER(ctypes.c_float)
_i32p = ctypes.POINTER(ctypes.c_int32)


def build_native(force: bool = False) -> str:
    """Compile the worker core if missing or stale (source-hash stamped,
    atomic + cross-process race-safe — see ``_native_build.build_so``)."""
    from persia_tpu.embedding._native_build import build_so

    return build_so(
        _SRC, _SO,
        ["-O3", "-mavx2", "-mfma", "-std=c++17", "-fPIC", "-shared", "-Wall"],
        logger, force=force,
    )


def _load_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _LOAD_FAILED
    if _LIB is not None or _LOAD_FAILED:
        return _LIB
    if os.environ.get("PERSIA_TPU_NATIVE_WORKER", "1") != "1":
        _LOAD_FAILED = True
        return None
    try:
        # CDLL the path build_native RETURNS (sanitizer-variant aware)
        so_path = build_native()
        lib = ctypes.CDLL(so_path)
    except Exception as e:  # toolchain missing → numpy fallback
        logger.warning("native worker core unavailable (%s); using numpy", e)
        _LOAD_FAILED = True
        return None
    i64, u32, i32 = ctypes.c_int64, ctypes.c_uint32, ctypes.c_int32
    # restype = None on the void hot loops — persia-lint ABI003 enforces it
    lib.wk_dedup.restype = i64
    lib.wk_dedup.argtypes = [_u64p, i64, _u64p, _i64p]
    lib.wk_sum_pool.restype = None
    lib.wk_sum_pool.argtypes = [_f32p, _i64p, _i64p, i64, i64, _f32p]
    lib.wk_grad_accum.restype = None
    lib.wk_grad_accum.argtypes = [_f32p, _i64p, _i64p, i64, i64, _f32p]
    lib.wk_raw_index.restype = None
    lib.wk_raw_index.argtypes = [_i64p, _i64p, i64, i64, i32, _i32p]
    lib.wk_shard_partition.restype = None
    lib.wk_shard_partition.argtypes = [_u64p, i64, u32, _i64p, _i64p]
    lib.wk_build_sid_matrix.restype = None
    lib.wk_build_sid_matrix.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), _u64p, i64, i64, i32, _u64p,
    ]
    _LIB = lib
    return _LIB


def available() -> bool:
    return _load_lib() is not None


def _ptr(a: np.ndarray, typ):
    return a.ctypes.data_as(typ)


def dedup(ids: np.ndarray) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(distinct, inverse) with distinct in first-seen order (np.unique
    returns sorted order instead — interchangeable since every consumer
    pairs distinct with inverse). None if the native core is unavailable."""
    lib = _load_lib()
    if lib is None:
        return None
    ids = np.ascontiguousarray(ids, dtype=np.uint64)
    n = len(ids)
    distinct = np.empty(n, dtype=np.uint64)
    inverse = np.empty(n, dtype=np.int64)
    m = lib.wk_dedup(_ptr(ids, _u64p), n, _ptr(distinct, _u64p), _ptr(inverse, _i64p))
    return distinct[:m].copy(), inverse


def sum_pool(
    rows: np.ndarray, inverse: np.ndarray, sample_of_id: np.ndarray, batch_size: int
) -> Optional[np.ndarray]:
    """pooled[sample_of_id[i]] += rows[inverse[i]] (np.add.at order)."""
    lib = _load_lib()
    if lib is None:
        return None
    rows = np.ascontiguousarray(rows, dtype=np.float32)
    inverse = np.ascontiguousarray(inverse, dtype=np.int64)
    sample_of_id = np.ascontiguousarray(sample_of_id, dtype=np.int64)
    dim = rows.shape[1] if rows.ndim == 2 else 0
    pooled = np.zeros((batch_size, dim), dtype=np.float32)
    lib.wk_sum_pool(
        _ptr(rows, _f32p), _ptr(inverse, _i64p), _ptr(sample_of_id, _i64p),
        len(inverse), dim, _ptr(pooled, _f32p),
    )
    return pooled


def grad_accum(
    grad: np.ndarray, inverse: np.ndarray, sample_of_id: np.ndarray, num_distinct: int
) -> Optional[np.ndarray]:
    """per_distinct[inverse[i]] += grad[sample_of_id[i]] (np.add.at order)."""
    lib = _load_lib()
    if lib is None:
        return None
    grad = np.ascontiguousarray(grad, dtype=np.float32)
    inverse = np.ascontiguousarray(inverse, dtype=np.int64)
    sample_of_id = np.ascontiguousarray(sample_of_id, dtype=np.int64)
    dim = grad.shape[1]
    out = np.zeros((num_distinct, dim), dtype=np.float32)
    lib.wk_grad_accum(
        _ptr(grad, _f32p), _ptr(inverse, _i64p), _ptr(sample_of_id, _i64p),
        len(inverse), dim, _ptr(out, _f32p),
    )
    return out


def raw_index(
    counts: np.ndarray, inverse: np.ndarray, sample_fixed_size: int, pad: int
) -> Optional[np.ndarray]:
    """(B, L) int32 index matrix for raw slots (pad value = num_distinct)."""
    lib = _load_lib()
    if lib is None:
        return None
    counts = np.ascontiguousarray(counts, dtype=np.int64)
    inverse = np.ascontiguousarray(inverse, dtype=np.int64)
    B = len(counts)
    out = np.empty((B, sample_fixed_size), dtype=np.int32)
    lib.wk_raw_index(
        _ptr(counts, _i64p), _ptr(inverse, _i64p), B, sample_fixed_size,
        pad, _ptr(out, _i32p),
    )
    return out


def build_sid_matrix(
    id_arrays, prefixes: np.ndarray, prefix_bit: int, out: np.ndarray
) -> bool:
    """Fill ``out`` (S, B) with per-slot prefixed sign rows in ONE native
    call (the cached tier's single-id fast path). ``id_arrays``: S
    contiguous (B,) uint64 arrays; ``prefixes``: (S,) uint64. Returns False
    when the native core is unavailable (caller falls back to numpy)."""
    lib = _load_lib()
    if lib is None:
        return False
    S, B = out.shape
    # fail as loudly as the numpy fallback would: the native call trusts
    # raw pointers and would read OOB / NULL on a malformed input
    if len(id_arrays) != S:
        raise ValueError(f"expected {S} id arrays, got {len(id_arrays)}")
    for a in id_arrays:
        if a.dtype != np.uint64 or a.size < B or not a.flags.c_contiguous:
            raise ValueError("id arrays must be contiguous uint64 of >= B ids")
    ptrs = (ctypes.c_void_p * S)(*[a.ctypes.data for a in id_arrays])
    prefixes = np.ascontiguousarray(prefixes, dtype=np.uint64)
    lib.wk_build_sid_matrix(
        ptrs, _ptr(prefixes, _u64p), S, B, prefix_bit, _ptr(out, _u64p)
    )
    return True


def shard_partition(
    signs: np.ndarray, num_shards: int
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Returns (positions grouped by shard in stable order, per-shard counts).

    ``positions[start[s]:start[s]+counts[s]]`` are the indices of shard s,
    where start = cumsum-exclusive of counts — one pass instead of the numpy
    router's per-shard boolean masks."""
    lib = _load_lib()
    if lib is None:
        return None
    signs = np.ascontiguousarray(signs, dtype=np.uint64)
    n = len(signs)
    pos = np.empty(n, dtype=np.int64)
    counts = np.empty(num_shards, dtype=np.int64)
    lib.wk_shard_partition(_ptr(signs, _u64p), n, num_shards, _ptr(pos, _i64p), _ptr(counts, _i64p))
    return pos, counts

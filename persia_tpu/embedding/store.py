"""Embedding parameter store — numpy reference implementation.

Parity target: the reference's embedding-parameter-server core:

- sharded LRU store ``PersiaEmbeddingHolder = Sharded<EvictionMap>``
  (`persia-embedding-holder/src/{sharded.rs,eviction_map.rs,array_linked_list.rs}`)
- entry layout ``[emb | optimizer state]`` in one flat f32 vector with
  seeded-by-sign init (`emb_entry.rs:16-76`)
- lookup semantics: train → LRU touch, miss → admit-probability gate + init;
  dim mismatch → re-init; infer → zeros on miss
  (`embedding_parameter_service/mod.rs:162-262`)
- gradient path: optimizer update + weight-bound clamp
  (`embedding_parameter_service/mod.rs:359-427`)

This Python implementation is the *golden model*: slow but obviously correct.
The C++ core (`native/ps.cpp`, wrapped by
``persia_tpu.embedding.native_store``) implements identical math and is
asserted equal in ``tests/test_native_store.py``.
"""

from __future__ import annotations

import io
import struct
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from persia_tpu.config import HyperParameters
from persia_tpu.embedding.hashing import (
    init_for_sign,
    splitmix64,
    uniform_init_for_sign,  # noqa: F401  (re-export; golden-test anchor)
)
from persia_tpu.embedding.optim import OptimizerConfig
from persia_tpu.logger import get_default_logger
from persia_tpu.metrics import get_metrics

logger = get_default_logger("persia_tpu.store")


class _Shard:
    """One internal shard: an insertion-ordered dict used as an O(1) LRU
    (Python-dict equivalent of the reference's hashmap + array-linked-list
    ``EvictionMap``, eviction_map.rs:11-107). Entries are ``(emb_dim, vec)``
    — each entry records its own embedding dim, like the reference's
    ``HashMapEmbeddingEntry`` (emb_entry.rs:16-76), so inference can never
    misread optimizer state as embedding values."""

    __slots__ = ("entries", "capacity")

    def __init__(self, capacity: int):
        self.entries: Dict[int, Tuple[int, np.ndarray]] = {}
        self.capacity = capacity

    def get_refresh(self, sign: int) -> Optional[Tuple[int, np.ndarray]]:
        e = self.entries.pop(sign, None)
        if e is not None:
            self.entries[sign] = e  # reinsert → most-recently-used
        return e

    def get(self, sign: int) -> Optional[Tuple[int, np.ndarray]]:
        return self.entries.get(sign)

    def insert(self, sign: int, dim: int, vec: np.ndarray) -> None:
        if sign in self.entries:
            self.entries.pop(sign)
        elif len(self.entries) >= self.capacity:
            self.entries.pop(next(iter(self.entries)))  # evict LRU
        self.entries[sign] = (dim, vec)

    def __len__(self) -> int:
        return len(self.entries)


class EmbeddingStore:
    """One parameter-server replica's store (numpy golden model).

    ``lookup``/``update_gradients`` operate on one slot's worth of signs at a
    time (single dim); the worker tier groups requests per slot and per
    replica before calling.
    """

    def __init__(
        self,
        capacity: int = 1 << 20,
        num_internal_shards: int = 8,
        hyperparams: HyperParameters = HyperParameters(),
        optimizer: Optional[OptimizerConfig] = None,
        seed: int = 0,
    ):
        if num_internal_shards <= 0 or capacity <= 0:
            raise ValueError("capacity and num_internal_shards must be positive")
        per_shard = max(1, capacity // num_internal_shards)
        self._shards = [_Shard(per_shard) for _ in range(num_internal_shards)]
        self._num_shards = num_internal_shards
        # one coarse lock: this numpy store is the correctness golden model,
        # called concurrently by the DataLoader's lookup/backward threads
        # (the C++ core has fine-grained per-shard mutexes instead)
        self._lock = threading.RLock()
        self.hyperparams = hyperparams
        self.optimizer = optimizer
        self.seed = seed
        self.inc_manager = None  # set by persia_tpu.incremental.attach_incremental
        # Adam per-feature-group accumulated beta powers (ref: optim.rs:99-221).
        self._batch_state: Dict[int, Tuple[float, float]] = {}
        # bounded apply-journal: id -> payload crc32 of gradient batches
        # already applied between snapshot fences (exactly-once trainer
        # resume, persia_tpu.jobstate). FIFO-bounded, mirroring the native
        # core's ring — safe because a resume only replays post-fence ids.
        self._journal: Dict[int, int] = {}
        self._journal_order: List[int] = []
        self._journal_cap = 1 << 16
        # PS-tier observability (ref: emb_param metrics, mod.rs:27-79)
        m = get_metrics()
        self._m_miss = m.counter(
            "persia_tpu_index_miss_count", "train lookups that missed the store"
        )
        self._m_lookups = m.counter(
            "persia_tpu_index_count", "total train lookups against the store"
        )
        self._m_miss_ratio = m.gauge(
            "persia_tpu_index_miss_ratio", "miss ratio of the last train lookup"
        )
        self._m_grad_miss = m.counter(
            "persia_tpu_gradient_id_miss_count",
            "gradient updates whose sign was evicted or never admitted",
        )

    # ------------------------------------------------------------------ util

    def configure(self, hyperparams: HyperParameters) -> None:
        self.hyperparams = hyperparams

    def register_optimizer(self, optimizer: OptimizerConfig) -> None:
        self.optimizer = optimizer
        self._batch_state.clear()

    def _shard_of(self, sign: int) -> _Shard:
        h = int(splitmix64(np.array([sign ^ 0xA5A5A5A5], dtype=np.uint64))[0])
        return self._shards[h % self._num_shards]

    def _init_entry(self, sign: int, dim: int) -> np.ndarray:
        entry = np.empty(dim + self._state_dim(dim), dtype=np.float32)
        entry[:dim] = init_for_sign(
            sign, self.seed, dim, self.hyperparams.resolved_init_method()
        )
        if self.optimizer is not None:
            entry[dim:] = self.optimizer.init_state(dim)
        return entry

    def _state_dim(self, dim: int) -> int:
        return self.optimizer.state_dim(dim) if self.optimizer is not None else 0

    def _admit(self, sign: int) -> bool:
        p = self.hyperparams.admit_probability
        if p >= 1.0:
            return True
        if p <= 0.0:
            return False
        h = int(splitmix64(np.array([sign ^ 0xC0FFEE], dtype=np.uint64))[0])
        return (h % (1 << 24)) / float(1 << 24) < p

    # ---------------------------------------------------------------- lookup

    def lookup(self, signs: np.ndarray, dim: int, train: bool) -> np.ndarray:
        """Fetch ``(len(signs), dim)`` embedding rows.

        Train: LRU-touch hits; misses pass the admit gate then get a seeded
        init (or zeros if rejected). Infer: zeros on miss, no touch, no admit
        (ref: embedding_parameter_service/mod.rs:162-262).
        """
        with self._lock:
            return self._lookup_locked(signs, dim, train)

    def _lookup_locked(self, signs: np.ndarray, dim: int, train: bool) -> np.ndarray:
        out = np.zeros((len(signs), dim), dtype=np.float32)
        entry_len = dim + self._state_dim(dim)
        misses = 0
        for i, s in enumerate(signs.tolist()):
            shard = self._shard_of(s)
            if train:
                entry = shard.get_refresh(s)
                # pre-registration tolerance: a boot-restored entry carries
                # its optimizer state (wider than dim) while this store has
                # no optimizer registered yet — re-initializing it here
                # would DESTROY restored rows during the restart window
                ok = entry is not None and entry[0] == dim and (
                    len(entry[1]) == entry_len
                    or (self.optimizer is None and len(entry[1]) >= dim)
                )
                if not ok:
                    misses += 1
                    if entry is None and not self._admit(s):
                        continue
                    vec = self._init_entry(s, dim)
                    shard.insert(s, dim, vec)
                    out[i] = vec[:dim]
                else:
                    out[i] = entry[1][:dim]
            else:
                entry = shard.get(s)
                if entry is not None and entry[0] == dim:
                    out[i] = entry[1][:dim]
        if train and len(signs):
            self._m_miss.inc(misses)
            self._m_lookups.inc(len(signs))
            self._m_miss_ratio.set(misses / len(signs))
        return out

    def lookup_batched(self, signs: np.ndarray, key_ofs: np.ndarray,
                       dims: np.ndarray, train: bool) -> np.ndarray:
        """Multi-slot lookup in one call (the golden model of
        ``NativeEmbeddingStore.lookup_batched``): group g covers
        ``signs[key_ofs[g]:key_ofs[g+1]]`` with dim ``dims[g]``. Returns one
        flat f32 buffer — group g's ``(count_g, dims[g])`` rows start at
        float offset ``sum(counts[:g] * dims[:g])``. State effects are
        exactly sequential per-group ``lookup`` calls."""
        key_ofs = np.asarray(key_ofs, dtype=np.int64)
        parts = [
            self.lookup(signs[key_ofs[g]:key_ofs[g + 1]], int(dims[g]), train).reshape(-1)
            for g in range(len(dims))
        ]
        return np.concatenate(parts) if parts else np.empty(0, np.float32)

    def update_batched(self, signs: np.ndarray, key_ofs: np.ndarray,
                       dims: np.ndarray, grads: np.ndarray,
                       opt_groups: np.ndarray) -> None:
        """Multi-slot gradient update in one call (golden model of
        ``NativeEmbeddingStore.update_batched``); ``grads`` is flat in
        ``lookup_batched``'s layout. Exactly sequential per-group
        ``update_gradients`` calls."""
        key_ofs = np.asarray(key_ofs, dtype=np.int64)
        grads = np.asarray(grads, dtype=np.float32).reshape(-1)
        off = 0
        for g in range(len(dims)):
            d = int(dims[g])
            ks = signs[key_ofs[g]:key_ofs[g + 1]]
            size = len(ks) * d
            self.update_gradients(
                ks, grads[off:off + size].reshape(len(ks), d), int(opt_groups[g])
            )
            off += size

    def checkout_entries(self, signs: np.ndarray, dim: int) -> np.ndarray:
        """Batched full-entry fetch for the HBM cache tier: ``(n, dim +
        state_dim)`` rows of ``[emb | optimizer state]`` so the device-side
        sparse optimizer continues from the PS's accumulated state. Misses
        are admitted unconditionally (the cache tier owns admission — its
        write-back re-inserts on eviction regardless) with the same seeded
        init as ``lookup``; dim-mismatched entries re-init, matching
        ``lookup``."""
        if self.optimizer is None:
            # a restarted PS that lost its runtime config must NOT serve
            # state-less entries (wrong width silently corrupts the cache
            # tier); the typed error triggers the caller's re-register+retry
            raise RuntimeError("no optimizer registered")
        entry_len = dim + self._state_dim(dim)
        out = np.empty((len(signs), entry_len), dtype=np.float32)
        with self._lock:
            for i, s in enumerate(signs.tolist()):
                shard = self._shard_of(s)
                entry = shard.get_refresh(s)
                if entry is not None and entry[0] == dim and len(entry[1]) == entry_len:
                    out[i] = entry[1]
                else:
                    vec = self._init_entry(s, dim)
                    shard.insert(s, dim, vec)
                    out[i] = vec
        return out

    def probe_entries(self, signs: np.ndarray, dim: int):
        """Warm/cold split for the HBM cache tier: rows whose sign exists
        (dim-matched) return their full ``[emb | state]`` entry with an LRU
        touch; missing signs are **not** admitted — the cache owns them
        until its eviction write-back re-inserts. Returns (warm (n,) bool,
        vals (n, dim + state_dim) — zeros on cold rows)."""
        if self.optimizer is None:
            raise RuntimeError("no optimizer registered")  # see checkout_entries
        entry_len = dim + self._state_dim(dim)
        warm = np.zeros(len(signs), dtype=bool)
        vals = np.zeros((len(signs), entry_len), dtype=np.float32)
        with self._lock:
            for i, s in enumerate(signs.tolist()):
                entry = self._shard_of(s).get_refresh(s)
                if entry is not None and entry[0] == dim and len(entry[1]) == entry_len:
                    warm[i] = True
                    vals[i] = entry[1]
        return warm, vals

    # -------------------------------------------------------------- gradient

    def advance_batch_state(self, group: int) -> None:
        """Advance Adam's per-group beta powers once per gradient batch."""
        if self.optimizer is None:
            return
        with self._lock:
            prev = self._batch_state.get(group, self.optimizer.initial_batch_state())
            self._batch_state[group] = self.optimizer.advance_batch_state(prev)

    def update_gradients(self, signs: np.ndarray, grads: np.ndarray, group: int = 0) -> None:
        """Apply the registered sparse optimizer to each sign's entry, then
        clamp to ±weight_bound (ref: embedding_parameter_service/mod.rs:359-427).
        Signs never seen (evicted or never admitted) are skipped
        (``gradient_id_miss_count`` in the reference)."""
        if self.optimizer is None:
            raise RuntimeError("no optimizer registered")
        if grads.shape[0] != len(signs):
            raise ValueError("signs/grads length mismatch")
        with self._lock:
            self._update_locked(signs, grads, group)
        if self.inc_manager is not None:
            # commit outside the store lock (the manager's flush reads entries
            # back through the locked accessors)
            self.inc_manager.commit(signs)

    def _update_locked(self, signs: np.ndarray, grads: np.ndarray, group: int) -> None:
        dim = grads.shape[1]
        entry_len = dim + self._state_dim(dim)
        batch_state = self._batch_state.get(group, self.optimizer.advance_batch_state(
            self.optimizer.initial_batch_state()
        ))
        bound = self.hyperparams.weight_bound
        grad_misses = 0
        for i, s in enumerate(signs.tolist()):
            shard = self._shard_of(s)
            entry = shard.get_refresh(s)
            if entry is None or entry[0] != dim or len(entry[1]) != entry_len:
                grad_misses += 1
                continue
            vec = entry[1]
            self.optimizer.update_dense(vec[:dim], vec[dim:], grads[i], batch_state)
            if bound > 0:
                np.clip(vec[:dim], -bound, bound, out=vec[:dim])
        if grad_misses:
            self._m_grad_miss.inc(grad_misses)

    # --------------------------------------------------------- apply-journal

    def journal_record(self, journal_id: int, crc: int) -> None:
        with self._lock:
            if journal_id in self._journal:
                self._journal[journal_id] = crc & 0xFFFFFFFF
                return
            if len(self._journal_order) >= self._journal_cap:
                self._journal.pop(self._journal_order.pop(0), None)
            self._journal_order.append(journal_id)
            self._journal[journal_id] = crc & 0xFFFFFFFF

    def journal_probe(self, journal_id: int, crc: int) -> int:
        """1 = already applied (crc matches), 0 = unknown, -1 = same id
        recorded with a DIFFERENT payload crc (replay divergence)."""
        with self._lock:
            rec = self._journal.get(journal_id)
        if rec is None:
            return 0
        return 1 if rec == (crc & 0xFFFFFFFF) else -1

    def journal_len(self) -> int:
        with self._lock:
            return len(self._journal)

    def journal_clear(self) -> None:
        """Drop every journal record — MUST accompany a PS rewind (clear +
        shard replay): after rewinding to a fence, the post-fence batches
        the journal remembers have been UN-applied and must re-apply."""
        with self._lock:
            self._journal.clear()
            self._journal_order.clear()

    def update_batched_journaled(
        self, journal_id: int, crc: int, signs, key_ofs, dims, grads, opt_groups,
    ) -> bool:
        """Exactly-once gradient apply for crash-consistent resume: a
        (journal_id, crc) already recorded means the crashed run applied
        this batch after the last fence — skip it (returns False); a
        matching id with a different crc means the replay diverged (error).
        Check→apply→record is not atomic against a PS crash between apply
        and record, but the journal protects against TRAINER crashes — a
        PS crash loses the whole store and recovers through shard replay
        (helper.restart_ps) or a fence rewind, both of which reset the
        journal with the data."""
        st = self.journal_probe(journal_id, crc)
        if st != 0:
            # 1 = exact duplicate; -1 = same id, different payload (a
            # journal-only resume recomputes the replay window against a
            # PS that already moved past the fence, so its gradients can
            # legitimately differ). Either way the crashed run's ORIGINAL
            # application stands — skipping preserves exactly-once; the -1
            # case is surfaced for observability via journal_probe.
            if st == -1:
                logger.warning(
                    "apply-journal id %#x replayed with a different payload "
                    "crc — keeping the original application (exactly-once)",
                    journal_id,
                )
            return False
        self.update_batched(signs, key_ofs, dims, grads, opt_groups)
        self.journal_record(journal_id, crc)
        return True

    def scan_nonfinite(self, cap: int = 65536):
        """Health scrub (persia_tpu/health): walk every live entry and
        repair any row with a NaN/Inf anywhere in its ``[emb | state]``
        floats back to the deterministic seeded init — the exact entry a
        fresh admit of the same sign would create (``_init_entry``), which
        is also the degraded-mode lookup contract. Returns
        ``(repaired_count, signs)`` with at most ``cap`` signs reported."""
        repaired = 0
        signs: List[int] = []
        with self._lock:
            for shard in self._shards:
                for sign, (dim, vec) in shard.entries.items():
                    if np.isfinite(vec).all():
                        continue
                    vec[:] = self._init_entry(sign, dim)
                    if repaired < cap:
                        signs.append(sign)
                    repaired += 1
        return repaired, signs

    # ------------------------------------------------------------ management

    def set_embedding(
        self, signs: np.ndarray, values: np.ndarray, dim: Optional[int] = None,
        commit_incremental: bool = False,
    ) -> None:
        """Insert raw entries (checkpoint re-shard path; ref mod.rs set_embedding).
        ``values`` rows are full entries ``[emb | state]``; ``dim`` is the
        embedding dim (defaults to the full row = stateless entries).
        ``commit_incremental=True`` marks the signs as TRAINING updates for
        the incremental-update manager (cached-tier eviction write-backs and
        publishes; a sign ships when its row LEAVES the cache or when the
        caller ``publish()``es — hot resident signs rely on the publish
        cadence for freshness). Checkpoint loads keep the default (a load is
        not an update)."""
        if dim is None:
            dim = values.shape[1]
        with self._lock:
            for i, s in enumerate(signs.tolist()):
                self._shard_of(s).insert(s, dim, values[i].astype(np.float32).copy())
        if commit_incremental and self.inc_manager is not None:
            self.inc_manager.commit(signs)

    def get_embedding_entry(self, sign: int) -> Optional[np.ndarray]:
        with self._lock:
            e = self._shard_of(sign).get(sign)
            return None if e is None else e[1]

    def get_entry_dim(self, sign: int) -> Optional[int]:
        with self._lock:
            e = self._shard_of(sign).get(sign)
            return None if e is None else e[0]

    def get_entry_record(self, sign: int) -> Optional[Tuple[int, np.ndarray]]:
        """Atomic (dim, full entry) snapshot — concurrent eviction/re-init
        cannot tear the pair (the incremental flusher depends on this)."""
        with self._lock:
            e = self._shard_of(sign).get(sign)
            return None if e is None else (e[0], e[1].copy())

    def clear(self) -> None:
        with self._lock:
            for shard in self._shards:
                shard.entries.clear()
            self._batch_state.clear()

    def size(self) -> int:
        with self._lock:
            return sum(len(s) for s in self._shards)

    @property
    def num_internal_shards(self) -> int:
        return self._num_shards

    # ---------------------------------------------------------- serialization

    def dump_shard(self, shard_idx: int) -> bytes:
        """Serialize one internal shard (checkpoint unit, ref:
        model-manager:242-343 dumps per internal shard)."""
        # snapshot under the lock (a non-blocking checkpoint dumps from a
        # thread while training mutates the shard — "dictionary changed size
        # during iteration" otherwise); serialize outside it so lookups and
        # updates aren't stalled for the whole struct/tobytes pass
        with self._lock:
            items = list(self._shards[shard_idx].entries.items())
        buf = io.BytesIO()
        buf.write(struct.pack("<I", len(items)))
        for sign, (dim, vec) in items:
            buf.write(struct.pack("<QII", sign, dim, len(vec)))
            buf.write(vec.tobytes())
        return buf.getvalue()

    def _range_signs(self, lo: int, hi: int) -> List[int]:
        """Signs owned by the hash range ``[lo, hi)`` (``hi == 0`` = 2^64)
        under the ROUTING hash (``splitmix64(sign)`` — what
        ``sign_to_range_shard`` positions on the ring, NOT the store-internal
        ``^ 0xA5A5A5A5`` shard hash). Caller holds ``_lock``."""
        lo_u, hi_u = np.uint64(lo), np.uint64(hi)
        out: List[int] = []
        for shard in self._shards:
            for sign in shard.entries:
                h = splitmix64(np.array([sign], dtype=np.uint64))[0]
                if h >= lo_u and (hi == 0 or h < hi_u):
                    out.append(sign)
        return out

    def export_range(self, lo: int, hi: int) -> bytes:
        """Serialize every entry whose routing hash lies in ``[lo, hi)``
        (``hi == 0`` = to the end of the ring), SORTED BY SIGN — unlike
        ``dump_shard``'s LRU order, a re-export after any crash/restore
        yields byte-identical payload, so the handoff journal's crc dedups
        replays. Read-only (no LRU touch); the wire format is
        ``dump_shard``'s, so ``load_shard_bytes`` imports it anywhere."""
        with self._lock:
            items = sorted(
                (s, self._shard_of(s).get(s)) for s in self._range_signs(lo, hi)
            )
        buf = io.BytesIO()
        buf.write(struct.pack("<I", len(items)))
        for sign, (dim, vec) in items:
            buf.write(struct.pack("<QII", sign, dim, len(vec)))
            buf.write(vec.tobytes())
        return buf.getvalue()

    def delete_range(self, lo: int, hi: int) -> int:
        """Drop every entry whose routing hash lies in ``[lo, hi)`` — the
        handoff's source-side release after the destination durably holds
        the range. Returns the number of entries removed (idempotent: a
        journal-deduped replay removes 0)."""
        with self._lock:
            signs = self._range_signs(lo, hi)
            for s in signs:
                self._shard_of(s).entries.pop(s, None)
        return len(signs)

    def import_range_journaled(self, journal_id: int, crc: int, blob: bytes) -> bool:
        """Exactly-once range import: a journal hit means the crashed run
        already imported this blob (1) or the source has since released the
        range so a resumed re-export differs (-1) — either way the ORIGINAL
        import stands and we skip. True when applied."""
        st = self.journal_probe(journal_id, crc)
        if st != 0:
            if st == -1:
                logger.info(
                    "handoff import id %#x re-offered with a different crc — "
                    "source already released the range; original import "
                    "stands (exactly-once)", journal_id,
                )
            return False
        self.load_shard_bytes(blob)
        self.journal_record(journal_id, crc)
        return True

    def delete_range_journaled(self, journal_id: int, crc: int, lo: int, hi: int):
        """Exactly-once source-side range release; the crc covers the
        (lo, hi) constants (content-independent — a replayed delete must
        dedup even after the entries are gone). Returns (applied, removed)."""
        if self.journal_probe(journal_id, crc) != 0:
            return False, 0
        removed = self.delete_range(lo, hi)
        self.journal_record(journal_id, crc)
        return True, removed

    def load_shard_bytes(self, raw: bytes) -> int:
        """Load entries (routed by sign, so files from any shard layout work —
        the re-shard-on-load path, ref: emb_worker:1150-1259)."""
        buf = io.BytesIO(raw)
        (n,) = struct.unpack("<I", buf.read(4))
        with self._lock:
            for _ in range(n):
                sign, dim, ln = struct.unpack("<QII", buf.read(16))
                vec = np.frombuffer(buf.read(4 * ln), dtype=np.float32).copy()
                self._shard_of(sign).insert(sign, dim, vec)
        return n

    def state_dict(self) -> Dict:
        return {
            "num_internal_shards": self._num_shards,
            "batch_state": dict(self._batch_state),
        }

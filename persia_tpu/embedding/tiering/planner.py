"""Placement planner: sketch stats + capacity budgets -> a tier per slot.

Parallax (PAPERS.md, arxiv 1808.02621) chooses a parallelism architecture
PER VARIABLE from measured sparsity; this is the same move across the
repo's three sparse tiers:

- ``fused``  — the slot's FULL vocabulary lives in HBM (never misses).
  Worth it when the table is small relative to its traffic: score is
  traffic density ``total / vocab`` (accesses each pinned row earns).
- ``cached`` — working set cached in HBM over the PS. Worth it when signs
  repeat: score is ``reuse = total / unique`` (hits each cached row
  earns before eviction).
- ``ps``     — stream through the host PS. The fallback for heavy-tail /
  near-uniform slots whose rows would thrash any cache.

Hysteresis: a slot only MOVES when its score clears the admission
threshold by a ``(1 + hysteresis)`` margin (or falls below by the same
margin on the way down) AND it has dwelled ``min_dwell`` planning rounds
in its current tier. Everything else is a suppressed flap, counted and
exported (``persia_tpu_tiering_flap_suppressed``) — placement decisions
are observable even when nothing moves.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from persia_tpu.embedding.tiering.profiler import SlotStats

TIER_FUSED = "fused"
TIER_CACHED = "cached"
TIER_PS = "ps"
TIERS = (TIER_FUSED, TIER_CACHED, TIER_PS)


@dataclass(frozen=True)
class TierPlan:
    """One planning round's output."""

    placements: Dict[str, str]                 # slot -> tier
    migrations: Dict[str, Tuple[str, str]]     # slot -> (from, to)
    suppressed: int                            # hysteresis-blocked moves
    scores: Dict[str, Dict[str, float]]        # slot -> score breakdown


class PlacementPlanner:
    """Greedy scored assignment under capacity budgets, with hysteresis.

    ``vocabs`` maps slot -> vocabulary size where known; only slots with a
    known vocab are fused candidates (pinning needs a bound).
    ``lockstep_groups``: slots sharing a feature group may not straddle
    the cached/PS boundary (the tier constructor rejects it), so each
    group lands together in the tier carrying its access-mass majority.
    """

    def __init__(
        self,
        cached_row_budget: int,
        fused_row_budget: int = 0,
        vocabs: Optional[Mapping[str, int]] = None,
        cached_min_reuse: float = 2.0,
        fused_min_density: float = 0.05,
        hysteresis: float = 0.25,
        min_dwell: int = 2,
        lockstep_groups: Optional[Sequence[Sequence[str]]] = None,
    ):
        if cached_row_budget < 0 or fused_row_budget < 0:
            raise ValueError("budgets must be >= 0")
        self.cached_row_budget = int(cached_row_budget)
        self.fused_row_budget = int(fused_row_budget)
        self.vocabs = dict(vocabs or {})
        self.cached_min_reuse = float(cached_min_reuse)
        self.fused_min_density = float(fused_min_density)
        self.hysteresis = float(hysteresis)
        self.min_dwell = int(min_dwell)
        self.lockstep_groups = [list(g) for g in (lockstep_groups or [])]
        self._dwell: Dict[str, int] = {}

    # ------------------------------------------------------------ scoring

    def _raw_assign(self, stats: Mapping[str, SlotStats]) -> Dict[str, str]:
        """Budget-constrained greedy assignment ignoring hysteresis."""
        assign: Dict[str, str] = {}
        # fused: best traffic density first, while full vocabs fit
        fused_left = self.fused_row_budget
        density = {
            s: st.total / max(self.vocabs.get(s, 0), 1)
            for s, st in stats.items()
        }
        for s in sorted(stats, key=lambda s: -density[s]):
            vocab = self.vocabs.get(s, 0)
            if (
                vocab > 0 and vocab <= fused_left
                and density[s] >= self.fused_min_density
            ):
                assign[s] = TIER_FUSED
                fused_left -= vocab
        # cached: best reuse first, while working sets fit the cache pool
        cached_left = self.cached_row_budget
        rest = [s for s in stats if s not in assign]
        for s in sorted(rest, key=lambda s: -stats[s].reuse):
            ws = max(int(stats[s].unique), 1)
            if stats[s].reuse >= self.cached_min_reuse and ws <= cached_left:
                assign[s] = TIER_CACHED
                cached_left -= ws
            else:
                assign[s] = TIER_PS
        # lockstep: a feature group may not straddle cached/ps — move the
        # minority (by access mass) to the group's majority side
        for grp in self.lockstep_groups:
            members = [s for s in grp if s in assign]
            sides = {assign[s] for s in members} - {TIER_FUSED}
            if len(sides) <= 1:
                continue
            mass = {t: 0.0 for t in sides}
            for s in members:
                if assign[s] in mass:
                    mass[assign[s]] += stats[s].total
            winner = max(mass, key=lambda t: mass[t])
            for s in members:
                if assign[s] != TIER_FUSED:
                    assign[s] = winner
        return assign

    def _clears_margin(self, slot: str, st: SlotStats, target: str) -> bool:
        """A MOVE must clear its destination's admission threshold by the
        hysteresis margin (or, moving down-tier, have fallen below the
        source threshold by the same margin) — borderline slots stay put."""
        m = 1.0 + self.hysteresis
        if target == TIER_CACHED:
            return st.reuse >= self.cached_min_reuse * m
        if target == TIER_FUSED:
            vocab = max(self.vocabs.get(slot, 0), 1)
            return st.total / vocab >= self.fused_min_density * m
        # down to ps: reuse must be clearly below the cached threshold
        return st.reuse * m <= self.cached_min_reuse
    # ------------------------------------------------------------- plan

    def plan(
        self, stats: Mapping[str, SlotStats], current: Mapping[str, str]
    ) -> TierPlan:
        for t in current.values():
            if t not in TIERS:
                raise ValueError(f"unknown tier {t!r}")
        raw = self._raw_assign(stats)
        placements: Dict[str, str] = {}
        migrations: Dict[str, Tuple[str, str]] = {}
        suppressed = 0
        # hysteresis must act on MOVE UNITS, not slots: a lockstep group
        # moves (or stays) as one — a per-slot veto after _raw_assign
        # harmonized the group would leave the final placement straddling
        # the cached/ps boundary, which the tier constructor rejects
        unit_of: Dict[str, int] = {}
        units: List[List[str]] = []
        for grp in self.lockstep_groups:
            members = [
                s for s in grp
                if s in raw and raw[s] != TIER_FUSED and s not in unit_of
            ]
            if members:
                for s in members:
                    unit_of[s] = len(units)
                units.append(members)
        for s in raw:
            if s not in unit_of:
                units.append([s])
        for unit in units:
            moving = [s for s in unit if current.get(s, raw[s]) != raw[s]]
            if not moving:
                for s in unit:
                    placements[s] = raw[s]
                continue
            # the unit clears hysteresis when every moving member has
            # dwelled AND the unit's aggregate mass clears the margin
            # (the group caches/streams as one working set)
            agg = SlotStats(
                total=sum(stats[s].total for s in moving),
                unique=sum(stats[s].unique for s in moving),
                hot_frac=0.0, top1_frac=0.0,
            )
            ok = all(
                self._dwell.get(s, self.min_dwell) >= self.min_dwell
                for s in moving
            ) and all(
                self._clears_margin(s, agg if len(unit) > 1 else stats[s],
                                    raw[s])
                for s in moving
            )
            if not ok:
                for s in unit:
                    placements[s] = current.get(s, raw[s])
                suppressed += len(moving)
                continue
            for s in unit:
                placements[s] = raw[s]
            for s in moving:
                migrations[s] = (current.get(s, raw[s]), raw[s])
        # dwell accounting: migrated slots restart, everyone else ages
        for s, t in placements.items():
            if s in migrations:
                self._dwell[s] = 0
            else:
                self._dwell[s] = self._dwell.get(s, self.min_dwell) + 1
        scores = {
            s: {
                "reuse": st.reuse,
                "density": st.total / max(self.vocabs.get(s, 0), 1),
                "total": st.total,
                "unique": st.unique,
                "hot_frac": st.hot_frac,
            }
            for s, st in stats.items()
        }
        return TierPlan(placements, migrations, suppressed, scores)

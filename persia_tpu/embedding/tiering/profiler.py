"""Per-slot access-stats profiler over the native sketch.

The feeder's admit walk (``CachedEmbeddingTier.prepare_batch`` /
``_prepare_batch_single_id``) already materializes every sign of every
batch; the profiler taps that stream in place: one ``sketch_observe``
per group per step on the single-id fast path (the flattened (S, B)
matrix attributes positions to slots by stride), one per slot on the
general path. The walk is DRAM-latency-bound like the admit walk it
rides (~75 ns/sign measured on the 1-core build host — the feeder
ceiling stays an order of magnitude above chip dispatch rates; see
PROFILE_FEEDER.md). Everything downstream — the skew/working-set stats
the placement planner scores, the snapshot/resume persistence — reads
the same sketch.

Round 14: the profiler can run **sharded** (``shards=S``): one
sub-sketch per feed-directory shard, partitioned by the same
``shard_route(sign ^ part_salt)`` the directory uses. The fused feed
walk then observes each shard's signs into its own sub-sketch with no
cross-shard locking, while the unfused paths (ServiceCtx, PS slots) go
through ``sketch_observe_routed`` and land in the same sub-sketch the
fused walk would. Each sub-sketch sees ~1/S of the distinct signs, so
its count-min width and working-set bitmap scale down by S — same
per-sketch load factor (same error), same total footprint as the
unsharded profiler. Stats aggregate across the family: totals and
working-set uniques sum (the partition makes per-shard sign sets
disjoint), heavy-hitter fractions mass-weight, top-K lists merge
deterministically (estimate desc, shard asc, rank asc).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from persia_tpu.embedding.tiering.native import (
    NativeSketch,
    observe_routed,
    shard_route,
)


def sketch_sample_k(env: Optional[str] = None) -> int:
    """Parse ``PERSIA_SKETCH_SAMPLE`` into the integer k of the 1/k
    observe sampling rate. Accepts ``1/k`` (the documented form) or a
    bare integer k; unset/invalid/<=1 means no sampling (k=1)."""
    if env is None:
        env = os.environ.get("PERSIA_SKETCH_SAMPLE", "")
    env = env.strip()
    if not env:
        return 1
    try:
        if "/" in env:
            num, den = env.split("/", 1)
            if int(num) != 1:
                return 1
            k = int(den)
        else:
            k = int(env)
    except ValueError:
        return 1
    return max(1, k)


@dataclass(frozen=True)
class SlotStats:
    """Decayed access statistics for one slot.

    ``total``     access mass (position count) under exponential decay;
    ``unique``    working-set estimate (distinct signs, two-window
                  linear counting);
    ``hot_frac``  fraction of the mass carried by the top-K signs;
    ``top1_frac`` fraction carried by the single hottest sign.

    ``reuse`` = total/unique is the planner's primary score: expected
    hits per distinct sign, i.e. how much a cached row earns its HBM.
    A slot whose working-set windows are EMPTY (no traffic for two decay
    rounds) scores 0, not total/1 — residual decayed mass with no recent
    distinct signs is a slot going cold, and inflating its reuse would
    promote exactly the slots that should drain to the PS.
    """

    total: float
    unique: float
    hot_frac: float
    top1_frac: float

    @property
    def reuse(self) -> float:
        if self.unique <= 0.0:
            return 0.0
        return self.total / max(self.unique, 1.0)


class AccessProfiler:
    """Slot-name-addressed wrapper over a :class:`NativeSketch` family.

    ``slot_order`` fixes the name -> sketch-index mapping for the life of
    the profiler (and of every exported blob): keep it stable across
    migrations — a slot keeps its index no matter which tier currently
    serves it, so its history survives the move.

    ``shards``/``part_salt`` (see module docstring) must match the feed
    directory's sharding for the fused observe to ride the admit walk;
    ``shards=None`` is the classic single-sketch profiler, bit-identical
    to every previous round. ``sample`` > 1 turns on 1/k observe sampling
    on every sub-sketch (default: ``PERSIA_SKETCH_SAMPLE``).
    """

    def __init__(
        self,
        slot_order: Sequence[str],
        width_log2: int = 16,
        depth: int = 4,
        bitmap_bits: int = 1 << 15,
        topk: int = 8,
        shards: Optional[int] = None,
        part_salt: int = 0,
        sample: Optional[int] = None,
        slot_salts: Optional[Dict[str, int]] = None,
    ):
        self.slot_order: List[str] = list(slot_order)
        if len(set(self.slot_order)) != len(self.slot_order):
            raise ValueError("duplicate slot names in slot_order")
        self._index: Dict[str, int] = {
            n: i for i, n in enumerate(self.slot_order)
        }
        self._cfg = dict(
            width_log2=width_log2, depth=depth,
            bitmap_bits=bitmap_bits, topk=topk,
        )
        self.shards = None if shards is None else max(1, int(shards))
        self.part_salt = int(part_salt) & (2**64 - 1)
        # per-slot partition salt: each cached group's sharded directory
        # partitions by ITS OWN group salt, so a cached slot's unfused
        # observes must route with that salt to land in the sub-sketch the
        # fused walk uses. Slots without an entry (PS-tier) route by
        # part_salt; any fixed salt is consistent for them because their
        # signs never cross a directory.
        self.slot_salts: Dict[str, int] = {}
        if slot_salts:
            self.set_slot_salts(slot_salts)
        self.sample = sketch_sample_k() if sample is None else max(1, int(sample))
        if self.shards is None:
            self._sks = [NativeSketch(len(self.slot_order), **self._cfg)]
        else:
            # per-sub-sketch geometry: each shard sees ~1/S of the signs,
            # so width and bitmap scale down by S — same load factor per
            # sketch (same count-min / linear-counting error) and the
            # family's total footprint matches the unsharded sketch. This
            # is also the fused walk's cache-footprint contract: a family
            # of full-width sketches measured 0.8x (slower than unfused);
            # the scaled family measures 1.14x (PROFILE_FEEDER round 14).
            lg = (self.shards - 1).bit_length()
            sub = dict(self._cfg)
            sub["width_log2"] = max(4, width_log2 - lg)
            sub["bitmap_bits"] = max(64, bitmap_bits >> lg)
            self._sks = [
                NativeSketch(len(self.slot_order), **sub)
                for _ in range(self.shards)
            ]
        if self.sample > 1:
            for sk in self._sks:
                sk.set_sample(self.sample)
        self._sk = self._sks[0]  # back-compat alias (single-sketch callers)

    @property
    def sketches(self) -> List[NativeSketch]:
        """The sub-sketch family in shard order — what the fused feed walk
        passes to ``CacheDirectory.feed_batch(sketches=...)``."""
        return self._sks

    # ---------------------------------------------------------- observe

    def observe_group(
        self, names: Sequence[str], flat_signs: np.ndarray, batch: int
    ) -> None:
        """Feed one group's flattened (S, B) sign matrix (the single-id
        fast path): position i belongs to ``names[i // batch]``. One
        native call when the group's slots are index-contiguous (they are
        by construction when the profiler is built in group order),
        otherwise one call per slot."""
        if batch <= 0 or flat_signs.size == 0:
            return
        idx = [self._index[n] for n in names]
        if idx == list(range(idx[0], idx[0] + len(idx))):
            self._observe(flat_signs, batch, idx[0])
            return
        for j, i in enumerate(idx):
            self._observe(flat_signs[j * batch:(j + 1) * batch], 0, i)

    def observe_slot(self, name: str, signs: np.ndarray) -> None:
        """Feed one slot's raw (duplicated) sign stream (general path)."""
        if signs.size:
            self._observe(signs, 0, self._index[name])

    def set_slot_salts(self, slot_salts: Dict[str, int]) -> None:
        """Update the routing salts for the named slots (e.g. after a tier
        migration regroups them). Unknown names are rejected; unnamed slots
        keep their current salt."""
        for n in slot_salts:
            if n not in self._index:
                raise KeyError(f"unknown slot {n!r} in slot_salts")
        for n, s in slot_salts.items():
            self.slot_salts[n] = int(s) & (2**64 - 1)

    def _salt_of(self, slot_idx: int) -> int:
        return self.slot_salts.get(self.slot_order[slot_idx], self.part_salt)

    def _observe(
        self, signs: np.ndarray, samples_per_slot: int, slot_base: int
    ) -> None:
        if self.shards is None:
            self._sk.observe(signs, samples_per_slot, slot_base)
        else:
            # a multi-slot (contiguous-group) observe spans ONE group, so
            # the base slot's salt covers every position in the call
            observe_routed(
                self._sks, self._salt_of(slot_base), signs,
                samples_per_slot, slot_base,
            )

    def group_contiguous_base(self, names: Sequence[str]) -> Optional[int]:
        """The base slot index when ``names`` maps to a contiguous index
        run (the precondition for fusing the observe into the sharded feed
        walk), else None."""
        idx = [self._index[n] for n in names]
        if idx == list(range(idx[0], idx[0] + len(idx))):
            return idx[0]
        return None

    # ------------------------------------------------------------ stats

    def decay(self, factor: float = 0.5) -> None:
        """Exponential decay + working-set window slide; call once per
        planning round (fence) so stats track the recent stream."""
        for sk in self._sks:
            sk.decay(factor)

    def stats(self) -> Dict[str, SlotStats]:
        out = {}
        for name, i in self._index.items():
            if self.shards is None:
                total, unique, hot, top1 = self._sk.slot_stats(i)
            else:
                # shard partition makes per-sub sign sets disjoint:
                # totals and working-set uniques SUM exactly; hot_frac
                # mass-weights (union of per-shard top-Ks); top1 is the
                # heaviest single sign across the family.
                total = unique = hot_mass = top1_mass = 0.0
                for sk in self._sks:
                    t, u, h, t1 = sk.slot_stats(i)
                    total += t
                    unique += u
                    hot_mass += t * h
                    top1_mass = max(top1_mass, t * t1)
                hot = hot_mass / total if total > 0 else 0.0
                top1 = top1_mass / total if total > 0 else 0.0
            out[name] = SlotStats(total, unique, hot, top1)
        return out

    def estimate(self, name: str, sign: int) -> float:
        i = self._index[name]
        if self.shards is None:
            return self._sk.estimate(i, sign)
        s = shard_route(sign, self._salt_of(i), self.shards)
        return self._sks[s].estimate(i, sign)

    def slot_tops(self, name: str) -> List[Tuple[int, float]]:
        """Merged heavy-hitter list for one slot: (sign, est) pairs,
        estimate desc; ties broken by shard index then per-shard rank so
        the merge is deterministic at any thread count."""
        i = self._index[name]
        cand = []
        for s, sk in enumerate(self._sks):
            signs, ests = sk.slot_tops(i)
            for k in range(sk.topk):
                if ests[k] > 0.0:
                    cand.append((-float(ests[k]), s, k, int(signs[k])))
        cand.sort()
        topk = self._cfg["topk"]
        return [(sign, -negest) for negest, _, _, sign in cand[:topk]]

    # ------------------------------------------------- snapshot / resume

    def export_bytes(self) -> bytes:
        if self.shards is not None:
            raise RuntimeError(
                "sharded profiler has one blob per sub-sketch — use "
                "export_state()")
        return self._sk.export_bytes()

    def import_bytes(self, blob: bytes) -> None:
        if self.shards is not None:
            raise RuntimeError(
                "sharded profiler has one blob per sub-sketch — use "
                "load_state()")
        self._sk.import_bytes(blob)

    def export_state(self) -> Dict:
        """JSON-safe form for a jobstate component (the blob rides as hex;
        sketches are ~1-2 MB at default geometry, and the manifest epoch
        already carries multi-MB PS shards). Sharded profilers export one
        blob per sub-sketch plus the partition key — a resumed job must
        rebuild the same family shape (pinned by the parity tests)."""
        state = {
            "slot_order": self.slot_order,
            "cfg": dict(self._cfg),
        }
        if self.shards is None:
            state["blob_hex"] = self.export_bytes().hex()
        else:
            state["shards"] = self.shards
            state["part_salt"] = self.part_salt
            state["slot_salts"] = dict(self.slot_salts)
            state["blobs_hex"] = [sk.export_bytes().hex() for sk in self._sks]
        return state

    @classmethod
    def from_state(cls, state: Dict) -> "AccessProfiler":
        prof = cls(
            state["slot_order"], **state["cfg"],
            shards=state.get("shards"),
            part_salt=state.get("part_salt", 0),
            slot_salts=state.get("slot_salts"),
        )
        prof.load_state(state)
        return prof

    def load_state(self, state: Dict) -> None:
        """Import into THIS profiler (geometry and slot order must match)."""
        if list(state["slot_order"]) != self.slot_order:
            raise ValueError(
                "profiler slot_order changed across the snapshot: "
                f"{state['slot_order']} != {self.slot_order}"
            )
        if self.shards is None:
            if "blob_hex" not in state:
                raise ValueError(
                    "sharded profiler snapshot loaded into an unsharded "
                    "profiler — pass shards= to match the snapshot"
                )
            self.import_bytes(bytes.fromhex(state["blob_hex"]))
            return
        blobs = state.get("blobs_hex")
        if blobs is None or len(blobs) != self.shards:
            raise ValueError(
                f"profiler shard count changed across the snapshot: "
                f"{len(blobs) if blobs else None} != {self.shards}"
            )
        if state.get("part_salt", 0) != self.part_salt:
            raise ValueError(
                "profiler part_salt changed across the snapshot — the "
                "sub-sketch partition would no longer match the blobs"
            )
        for sk, blob in zip(self._sks, blobs):
            sk.import_bytes(bytes.fromhex(blob))


# ----------------------------------------------------- /metrics publication


def publish_sketch_metrics(profiler: "AccessProfiler",
                           splits=None) -> Dict[str, float]:
    """Publish the access sketch's view onto the process /metrics endpoint
    (persia_tpu.metrics.serve_http) so the autopilot controller and a human
    operator read the SAME signal — until now the sketch was only readable
    in-process through ``stats()``/``slot_tops()``.

    Exports, all in the ``persia_tpu_`` namespace:

    - ``persia_tpu_ps_shard_load{shard=i}``  modeled load fraction per PS
      shard under ``splits`` (the live ring, or hash-uniform when None) —
      the ShardPlanner's own load model (heavy-hitter point masses +
      uniform residual), i.e. what the reshard decision is made FROM;
    - ``persia_tpu_ps_shard_load_skew``      max/mean of those fractions;
    - ``persia_tpu_sketch_heavy_hitter_mass{slot=...}``  fraction of the
      slot's decayed mass carried by its tracked top-K (hot_frac);
    - ``persia_tpu_sketch_working_set{slot=...}``        distinct-sign
      working-set estimate per slot.

    Returns ``{"skew": ..., "total_mass": ...}`` for the caller's own
    decision path. ``splits`` defaults to hash-uniform for the CURRENT
    modeled shard count only when given explicitly as an int via
    ``uniform_splits`` by the caller; passing None publishes a single
    whole-ring shard (n=1)."""
    from persia_tpu.embedding.tiering.shard_planner import ShardPlanner
    from persia_tpu.metrics import get_metrics

    m = get_metrics()
    g_load = m.gauge(
        "persia_tpu_ps_shard_load",
        "modeled PS shard load fraction from the access sketch",
    )
    g_skew = m.gauge(
        "persia_tpu_ps_shard_load_skew",
        "modeled PS load skew (max/mean) under the current ring",
    )
    g_hh = m.gauge(
        "persia_tpu_sketch_heavy_hitter_mass",
        "fraction of a slot's decayed access mass in its top-K heavy hitters",
    )
    g_ws = m.gauge(
        "persia_tpu_sketch_working_set",
        "distinct-sign working-set estimate per slot",
    )
    pos, w, residual = ShardPlanner.mass_from_profiler(profiler)
    ring = (np.empty(0, np.uint64) if splits is None
            else np.asarray(splits, np.uint64))
    loads = ShardPlanner.shard_loads(ring, pos, w, residual)
    for i, frac in enumerate(loads):
        g_load.set(float(frac), shard=str(i))
    skew = ShardPlanner.skew_of(loads)
    g_skew.set(skew)
    total = 0.0
    for name, st in profiler.stats().items():
        g_hh.set(float(st.hot_frac), slot=name)
        g_ws.set(float(st.unique), slot=name)
        total += float(st.total)
    return {"skew": skew, "total_mass": total}

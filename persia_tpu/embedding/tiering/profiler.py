"""Per-slot access-stats profiler over the native sketch.

The feeder's admit walk (``CachedEmbeddingTier.prepare_batch`` /
``_prepare_batch_single_id``) already materializes every sign of every
batch; the profiler taps that stream in place: one ``sketch_observe``
per group per step on the single-id fast path (the flattened (S, B)
matrix attributes positions to slots by stride), one per slot on the
general path. The walk is DRAM-latency-bound like the admit walk it
rides (~75 ns/sign measured on the 1-core build host — the feeder
ceiling stays an order of magnitude above chip dispatch rates; see
PROFILE_FEEDER.md). Everything downstream — the skew/working-set stats
the placement planner scores, the snapshot/resume persistence — reads
the same sketch.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np

from persia_tpu.embedding.tiering.native import NativeSketch


@dataclass(frozen=True)
class SlotStats:
    """Decayed access statistics for one slot.

    ``total``     access mass (position count) under exponential decay;
    ``unique``    working-set estimate (distinct signs, two-window
                  linear counting);
    ``hot_frac``  fraction of the mass carried by the top-K signs;
    ``top1_frac`` fraction carried by the single hottest sign.

    ``reuse`` = total/unique is the planner's primary score: expected
    hits per distinct sign, i.e. how much a cached row earns its HBM.
    A slot whose working-set windows are EMPTY (no traffic for two decay
    rounds) scores 0, not total/1 — residual decayed mass with no recent
    distinct signs is a slot going cold, and inflating its reuse would
    promote exactly the slots that should drain to the PS.
    """

    total: float
    unique: float
    hot_frac: float
    top1_frac: float

    @property
    def reuse(self) -> float:
        if self.unique <= 0.0:
            return 0.0
        return self.total / max(self.unique, 1.0)


class AccessProfiler:
    """Slot-name-addressed wrapper over one :class:`NativeSketch`.

    ``slot_order`` fixes the name -> sketch-index mapping for the life of
    the profiler (and of every exported blob): keep it stable across
    migrations — a slot keeps its index no matter which tier currently
    serves it, so its history survives the move.
    """

    def __init__(
        self,
        slot_order: Sequence[str],
        width_log2: int = 16,
        depth: int = 4,
        bitmap_bits: int = 1 << 15,
        topk: int = 8,
    ):
        self.slot_order: List[str] = list(slot_order)
        if len(set(self.slot_order)) != len(self.slot_order):
            raise ValueError("duplicate slot names in slot_order")
        self._index: Dict[str, int] = {
            n: i for i, n in enumerate(self.slot_order)
        }
        self._cfg = dict(
            width_log2=width_log2, depth=depth,
            bitmap_bits=bitmap_bits, topk=topk,
        )
        self._sk = NativeSketch(len(self.slot_order), **self._cfg)

    # ---------------------------------------------------------- observe

    def observe_group(
        self, names: Sequence[str], flat_signs: np.ndarray, batch: int
    ) -> None:
        """Feed one group's flattened (S, B) sign matrix (the single-id
        fast path): position i belongs to ``names[i // batch]``. One
        native call when the group's slots are index-contiguous (they are
        by construction when the profiler is built in group order),
        otherwise one call per slot."""
        if batch <= 0 or flat_signs.size == 0:
            return
        idx = [self._index[n] for n in names]
        if idx == list(range(idx[0], idx[0] + len(idx))):
            self._sk.observe(flat_signs, batch, idx[0])
            return
        for j, i in enumerate(idx):
            self._sk.observe(
                flat_signs[j * batch:(j + 1) * batch], 0, i
            )

    def observe_slot(self, name: str, signs: np.ndarray) -> None:
        """Feed one slot's raw (duplicated) sign stream (general path)."""
        if signs.size:
            self._sk.observe(signs, 0, self._index[name])

    # ------------------------------------------------------------ stats

    def decay(self, factor: float = 0.5) -> None:
        """Exponential decay + working-set window slide; call once per
        planning round (fence) so stats track the recent stream."""
        self._sk.decay(factor)

    def stats(self) -> Dict[str, SlotStats]:
        out = {}
        for name, i in self._index.items():
            total, unique, hot, top1 = self._sk.slot_stats(i)
            out[name] = SlotStats(total, unique, hot, top1)
        return out

    def estimate(self, name: str, sign: int) -> float:
        return self._sk.estimate(self._index[name], sign)

    # ------------------------------------------------- snapshot / resume

    def export_bytes(self) -> bytes:
        return self._sk.export_bytes()

    def import_bytes(self, blob: bytes) -> None:
        self._sk.import_bytes(blob)

    def export_state(self) -> Dict:
        """JSON-safe form for a jobstate component (the blob rides as hex;
        sketches are ~1-2 MB at default geometry, and the manifest epoch
        already carries multi-MB PS shards)."""
        return {
            "slot_order": self.slot_order,
            "cfg": dict(self._cfg),
            "blob_hex": self.export_bytes().hex(),
        }

    @classmethod
    def from_state(cls, state: Dict) -> "AccessProfiler":
        prof = cls(state["slot_order"], **state["cfg"])
        prof.import_bytes(bytes.fromhex(state["blob_hex"]))
        return prof

    def load_state(self, state: Dict) -> None:
        """Import into THIS profiler (geometry and slot order must match)."""
        if list(state["slot_order"]) != self.slot_order:
            raise ValueError(
                "profiler slot_order changed across the snapshot: "
                f"{state['slot_order']} != {self.slot_order}"
            )
        self.import_bytes(bytes.fromhex(state["blob_hex"]))

"""AutoTierController: profiler + planner + the fence-point migration.

The stream's snapshot fence (``stream.py _run_fence``) is the ONLY point
where a slot can change tiers: the feeder is parked, the write-back thread
is drained, the hazard ledger is empty (heads == tails), and
``_fence_capture`` has just flushed every cached row to the PS and
committed a manifest — so the PS holds the single authoritative copy of
every migrating slot and the re-registration moves only METADATA. The
controller runs right after that commit: decay the sketch, score a plan,
and (hysteresis permitting) apply the migrations through
``CachedTrainCtx.apply_migration``.

Enable with :func:`enable_auto_tier` (or the launcher's ``--auto-tier``
knob, which exports ``PERSIA_AUTO_TIER=1`` for the training script to
consult).
"""

from __future__ import annotations

import os
from typing import Dict, Mapping, Optional, Tuple

from persia_tpu.logger import get_default_logger
from persia_tpu.metrics import get_metrics
from persia_tpu.tracing import record_event, span

from persia_tpu.embedding.tiering.planner import (
    TIER_CACHED,
    TIER_FUSED,
    TIER_PS,
    PlacementPlanner,
    TierPlan,
)
from persia_tpu.embedding.tiering.profiler import AccessProfiler

logger = get_default_logger("persia_tpu.tiering")

AUTO_TIER_ENV = "PERSIA_AUTO_TIER"


def auto_tier_enabled() -> bool:
    """The launcher's ``--auto-tier`` exports PERSIA_AUTO_TIER=1."""
    return os.environ.get(AUTO_TIER_ENV, "0") == "1"


class AutoTierController:
    """One planning round per stream fence.

    ``placements`` tracks where each slot CURRENTLY lives. Inside a
    ``CachedTrainCtx`` the ``fused`` tier is realized as a cached slot
    whose full vocabulary fits its group pool (it never misses after
    warm-up), so at the re-registration level only the cached/ps boundary
    moves; the three-way label is kept for planning and reporting.
    """

    def __init__(
        self,
        profiler: AccessProfiler,
        planner: PlacementPlanner,
        placements: Mapping[str, str],
        decay: float = 0.5,
        arbiter=None,
    ):
        self.profiler = profiler
        self.planner = planner
        self.placements: Dict[str, str] = dict(placements)
        self.decay = float(decay)
        # when attached, migrations route through the control-plane
        # arbiter's topology lease as TIER intents (imported lazily at
        # actuation time — a top-level autopilot import would cycle
        # through the package __init__ back into this module)
        self.arbiter = arbiter
        self.last_plan: Optional[TierPlan] = None
        m = get_metrics()
        self._m_migrations = m.counter(
            "persia_tpu_tiering_migrations",
            "slots live-migrated between sparse tiers at a fence",
        )
        self._m_suppressed = m.counter(
            "persia_tpu_tiering_flap_suppressed",
            "tier moves suppressed by hysteresis/dwell",
        )

    # ----------------------------------------------------------- fence hook

    def on_fence(self, ctx, gstep: int) -> Dict[str, Tuple[str, str]]:
        """Run one planning round at a drained fence; returns the applied
        migrations ({slot: (from, to)}, empty when nothing moved). Every
        placement DECISION is observable: a flight-recorder event fires
        whether or not a migration happens, and suppressed flaps count."""
        self.profiler.decay(self.decay)
        stats = self.profiler.stats()
        plan = self.planner.plan(stats, self.placements)
        self.last_plan = plan
        self._m_suppressed.inc(plan.suppressed)
        record_event(
            "tiering.plan", step=gstep,
            migrations=len(plan.migrations), suppressed=plan.suppressed,
        )
        if not plan.migrations:
            return {}
        # cached/ps boundary moves only (fused rides the cached side here)
        to_cached = sorted(
            s for s, (src, dst) in plan.migrations.items()
            if src == TIER_PS and dst in (TIER_CACHED, TIER_FUSED)
        )
        to_ps = sorted(
            s for s, (src, dst) in plan.migrations.items() if dst == TIER_PS
        )
        if to_cached or to_ps:
            def _apply() -> Dict:
                with span(
                    "tiering.migration", step=gstep,
                    to_cached=len(to_cached), to_ps=len(to_ps),
                ):
                    ctx.apply_migration(to_cached=to_cached, to_ps=to_ps)
                return {}

            if self.arbiter is not None:
                from persia_tpu.autopilot import arbiter as arbitration

                self.arbiter.run(arbitration.Intent(
                    arbitration.INTENT_TIER, "tiering",
                    lambda _abort_check: _apply(),
                    label=f"{len(to_cached)}->cached {len(to_ps)}->ps",
                ))
            else:
                _apply()
        self._m_migrations.inc(len(plan.migrations))
        record_event(
            "tiering.migrate", step=gstep,
            moves={s: f"{src}->{dst}" for s, (src, dst) in plan.migrations.items()},
        )
        logger.info(
            "auto-tier fence %d: migrated %s (suppressed %d)",
            gstep, dict(plan.migrations), plan.suppressed,
        )
        self.placements = dict(plan.placements)
        return dict(plan.migrations)

    # ------------------------------------------------- snapshot / resume

    def export_state(self) -> Dict:
        return {
            "placements": dict(self.placements),
            "profiler": self.profiler.export_state(),
        }

    def load_state(self, state: Dict) -> None:
        self.placements = dict(state["placements"])
        self.profiler.load_state(state["profiler"])


def enable_auto_tier(
    ctx,
    cached_min_reuse: float = 2.0,
    hysteresis: float = 0.25,
    min_dwell: int = 1,
    decay: float = 0.5,
    fused_row_budget: int = 0,
    vocabs: Optional[Mapping[str, int]] = None,
    profiler_kwargs: Optional[Dict] = None,
    arbiter=None,
) -> AutoTierController:
    """Wire auto-tiering onto a ``CachedTrainCtx``: build the profiler over
    every slot (cached groups in group order — their sketch indices stay
    contiguous for the strided observe — then the ps slots), a planner
    budgeted by the tier's cache pools, and attach the controller so the
    stream's fences drive it."""
    tier = ctx.tier
    slot_order = [s for g in tier.groups for s in g.slots] + sorted(
        s for s in tier.ps_slots
    )
    kwargs = dict(profiler_kwargs or {})
    # sharded tier -> sharded profiler (one sub-sketch per directory
    # shard, routed by each slot's group salt) so the observe can fuse
    # into the sharded feed walk; explicit profiler_kwargs still win
    if getattr(tier, "feed_shards", None) and "shards" not in kwargs:
        kwargs["shards"] = tier.feed_shards
        kwargs.setdefault("slot_salts", tier.profiler_slot_salts())
    profiler = AccessProfiler(slot_order, **kwargs)
    lockstep = [
        list(members)
        for members in ctx.embedding_config.feature_groups.values()
        if len(members) > 1
    ]
    planner = PlacementPlanner(
        cached_row_budget=sum(g.rows for g in tier.groups),
        fused_row_budget=fused_row_budget,
        vocabs=vocabs,
        cached_min_reuse=cached_min_reuse,
        hysteresis=hysteresis,
        min_dwell=min_dwell,
        lockstep_groups=lockstep,
    )
    placements = {s: TIER_CACHED for g in tier.groups for s in g.slots}
    placements.update({s: TIER_PS for s in tier.ps_slots})
    ctrl = AutoTierController(profiler, planner, placements, decay=decay,
                              arbiter=arbiter)
    ctx.attach_auto_tier(ctrl)
    return ctrl

"""Sparsity-aware PS shard planning: load-weighted ring split points.

Hash-uniform sharding (``hashing.uniform_splits``) balances *key counts*,
not *traffic*: under production zipf skew a handful of heavy-hitter signs
concentrates lookup/update mass on whichever shard their hashes land in
(Parallax's motivating observation — sparse variables need size- and
access-aware partitioning, arxiv 1808.02621). The tiering access sketch
already measures exactly that mass (``AccessProfiler.slot_tops`` heavy
hitters + per-slot decayed totals), so the elastic tier can place ring
boundaries where the *load* CDF crosses k/n rather than where the hash
space does.

Model: each merged heavy hitter is a point mass at its ring position
``splitmix64(sign)`` (the position ``sign_to_range_shard`` routes by); the
un-tracked remainder of each slot's mass is spread uniformly over the ring
(sketch tails are hash-uniform to first order). Splits come from inverting
that piecewise-linear CDF at the n-1 equal-mass targets; a point mass
heavier than a whole target gets the boundary placed just past it, so one
pathological sign never straddles two shards.

Hysteresis follows :class:`..planner.PlacementPlanner`'s discipline: a
same-count rebalance is adopted only when the candidate's modeled skew
beats the incumbent's by a ``(1 + hysteresis)`` margin AND the incumbent
has dwelled ``min_dwell`` planning rounds — two shards trading a hot range
every round would otherwise thrash the handoff machinery. A *different*
shard count always adopts (the reshard was explicitly requested).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from persia_tpu.embedding.hashing import splitmix64, uniform_splits

_RING = float(1 << 64)


@dataclass
class ShardPlan:
    """One planning round's outcome."""

    splits: np.ndarray  # (n-1,) ascending u64 ring boundaries
    loads: np.ndarray  # (n,) modeled load fraction per shard (sums to 1)
    skew: float  # max(loads) / mean(loads) — 1.0 is perfect balance
    adopted: bool  # False = hysteresis kept the incumbent
    suppressed: int  # cumulative rebalances suppressed by hysteresis


class ShardPlanner:
    """Load-weighted ring splits from the tiering access sketch."""

    def __init__(self, hysteresis: float = 0.1, min_dwell: int = 2):
        self.hysteresis = float(hysteresis)
        self.min_dwell = int(min_dwell)
        self._current: Optional[np.ndarray] = None
        self._dwell = 0  # rounds the incumbent has been stable
        self.suppressed = 0

    # ----------------------------------------------------------- load model

    @staticmethod
    def mass_from_profiler(profiler) -> Tuple[np.ndarray, np.ndarray, float]:
        """(positions u64, point masses, uniform residual mass) summed over
        every slot the profiler tracks: heavy hitters become point masses
        at their ring positions; each slot's remaining (total - tracked)
        mass joins the uniform residual."""
        pos_l: List[int] = []
        w_l: List[float] = []
        residual = 0.0
        for name, st in profiler.stats().items():
            tracked = 0.0
            for sign, est in profiler.slot_tops(name):
                pos_l.append(sign)
                w_l.append(float(est))
                tracked += float(est)
            residual += max(float(st.total) - tracked, 0.0)
        if not pos_l:
            return (np.empty(0, np.uint64), np.empty(0, np.float64), residual)
        pos = splitmix64(np.array(pos_l, dtype=np.uint64))
        w = np.array(w_l, dtype=np.float64)
        # same sign may be hot in several slots → one combined point mass
        pos, inv = np.unique(pos, return_inverse=True)
        combined = np.zeros(len(pos), dtype=np.float64)
        np.add.at(combined, inv, w)
        return pos, combined, residual

    @staticmethod
    def shard_loads(
        splits: np.ndarray, pos: np.ndarray, w: np.ndarray, residual: float,
    ) -> np.ndarray:
        """Modeled load fraction per shard for a given ring: uniform
        residual proportional to arc length + point masses routed by
        ``searchsorted(side="right")`` (the router's own rule)."""
        splits = np.asarray(splits, dtype=np.uint64)
        n = len(splits) + 1
        edges = np.concatenate([[0.0], splits.astype(np.float64), [_RING]])
        loads = residual * np.diff(edges) / _RING
        if len(pos):
            shard = np.searchsorted(splits, np.asarray(pos, np.uint64),
                                    side="right")
            np.add.at(loads, shard, np.asarray(w, np.float64))
        total = loads.sum()
        return loads / total if total > 0 else np.full(n, 1.0 / n)

    @staticmethod
    def skew_of(loads: np.ndarray) -> float:
        loads = np.asarray(loads, dtype=np.float64)
        mean = loads.mean()
        return float(loads.max() / mean) if mean > 0 else 1.0

    # ------------------------------------------------------------ inversion

    @staticmethod
    def _invert_cdf(
        num_shards: int, pos: np.ndarray, w: np.ndarray, residual: float,
    ) -> np.ndarray:
        """Place n-1 boundaries at the equal-mass crossings of the
        piecewise-linear load CDF. A target landing inside a point mass's
        jump puts the boundary just past it (the hot sign stays whole on
        the left shard). Degenerate inputs (no mass at all) fall back to
        hash-uniform splits."""
        n = int(num_shards)
        if n < 1:
            raise ValueError(f"num_shards must be >= 1, got {n}")
        total = float(np.sum(w)) + residual
        if n == 1:
            return np.empty(0, dtype=np.uint64)
        if total <= 0.0:
            return uniform_splits(n)
        order = np.argsort(pos)
        pos_u = np.asarray(pos, np.uint64)[order]
        pos_s = pos_u.astype(np.float64)
        w_s = np.asarray(w, np.float64)[order]
        u = residual / _RING  # uniform density per ring unit
        cum_w = np.concatenate([[0.0], np.cumsum(w_s)])  # before hotspot j
        splits = np.empty(n - 1, dtype=np.uint64)
        j = 0
        for k in range(1, n):
            t = total * k / n
            while j < len(pos_s) and cum_w[j + 1] + u * pos_s[j] < t:
                j += 1
            if j < len(pos_s) and cum_w[j] + u * pos_s[j] >= t:
                # the target lies in the linear segment BEFORE hotspot j is
                # even reached — solve the uniform part alone
                x = (t - cum_w[j]) / u if u > 0 else pos_s[j]
            elif j < len(pos_s):
                # inside hotspot j's jump: boundary just past the hot sign,
                # in EXACT u64 arithmetic — float64 spacing at 2^61+ ring
                # positions exceeds 1, so ``pos + 1.0`` would round back
                # onto (or below) the hot position and drop the mass on the
                # wrong side of the split
                splits[k - 1] = np.uint64(min(int(pos_u[j]) + 1,
                                              (1 << 64) - 1))
                j += 1
                continue
            else:
                x = (t - cum_w[-1]) / u if u > 0 else _RING - 1.0
            # clamp to the largest float64 BELOW 2^64: ``_RING - 1.0``
            # rounds up to 2^64 itself, which overflows the u64 cast
            splits[k - 1] = np.uint64(min(max(x, 0.0), 18446744073709549568.0))
        # float inversion can collapse neighbours; ring splits must be
        # strictly ascending — nudge forward deterministically
        for i in range(1, n - 1):
            if splits[i] <= splits[i - 1]:
                splits[i] = splits[i - 1] + np.uint64(1)
        return splits

    # ----------------------------------------------------------------- plan

    def plan(
        self,
        num_shards: int,
        profiler=None,
        pos: Optional[np.ndarray] = None,
        w: Optional[np.ndarray] = None,
        residual: Optional[float] = None,
    ) -> ShardPlan:
        """One planning round. Load either from ``profiler`` or from raw
        ``(pos, w, residual)`` point masses (tests / offline benches)."""
        if profiler is not None:
            pos, w, residual = self.mass_from_profiler(profiler)
        if pos is None:
            pos, w, residual = (np.empty(0, np.uint64),
                                np.empty(0, np.float64), 1.0)
        residual = 1.0 if residual is None else float(residual)
        cand = self._invert_cdf(num_shards, pos, w, residual)
        cand_loads = self.shard_loads(cand, pos, w, residual)
        cand_skew = self.skew_of(cand_loads)
        incumbent = self._current
        if incumbent is not None and len(incumbent) == len(cand):
            inc_skew = self.skew_of(
                self.shard_loads(incumbent, pos, w, residual)
            )
            clears = cand_skew * (1.0 + self.hysteresis) < inc_skew
            if not (clears and self._dwell >= self.min_dwell):
                if clears:  # margin met but still dwelling — a flap
                    self.suppressed += 1
                self._dwell += 1
                inc_loads = self.shard_loads(incumbent, pos, w, residual)
                return ShardPlan(incumbent, inc_loads, inc_skew,
                                 adopted=False, suppressed=self.suppressed)
        self._current = cand
        self._dwell = 0
        return ShardPlan(cand, cand_loads, cand_skew, adopted=True,
                         suppressed=self.suppressed)

"""ctypes surface of the native access-stats sketch (``native/cache.cpp``
``sketch_*`` — it lives in the cache library because the feeder's admit
walk is where the signs stream past).

Registered in ``persia_tpu.analysis.common.BINDING_FILES`` so persia-lint's
ABI drift checker (ABI000-ABI008) cross-checks every binding here against
the ``extern "C"`` surface, exactly like the cache-directory bindings.
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from persia_tpu.embedding.hbm_cache.directory import build_native

# the lib this file binds — persia-lint's ABI pass resolves the CDLL
# handle below through this constant (build_native() returns a variant
# path the AST tracker cannot evaluate)
_SO = "libpersia_cache.so"

_LIB: Optional[ctypes.CDLL] = None

_u8p = ctypes.POINTER(ctypes.c_uint8)
_u64p = ctypes.POINTER(ctypes.c_uint64)
_f64p = ctypes.POINTER(ctypes.c_double)


def _load_lib() -> ctypes.CDLL:
    global _LIB
    if _LIB is None:
        # same .so as the cache directory (build_native is variant-aware);
        # a separate CDLL keeps this module importable without dragging the
        # directory's staging machinery into scope
        lib = ctypes.CDLL(build_native())
        i64, p, f64 = ctypes.c_int64, ctypes.c_void_p, ctypes.c_double
        # every binding declares BOTH restype and argtypes (restype = None
        # for void) — persia-lint ABI003/ABI007 enforce it mechanically
        lib.sketch_create.restype = p
        lib.sketch_create.argtypes = [i64, i64, i64, i64, i64]
        lib.sketch_destroy.restype = None
        lib.sketch_destroy.argtypes = [p]
        lib.sketch_n_slots.restype = i64
        lib.sketch_n_slots.argtypes = [p]
        lib.sketch_observe.restype = i64
        lib.sketch_observe.argtypes = [p, _u64p, i64, i64, i64]
        lib.sketch_decay.restype = None
        lib.sketch_decay.argtypes = [p, f64]
        lib.sketch_slot_stats.restype = i64
        lib.sketch_slot_stats.argtypes = [p, i64, _f64p]
        lib.sketch_estimate.restype = f64
        lib.sketch_estimate.argtypes = [p, i64, ctypes.c_uint64]
        lib.sketch_export_size.restype = i64
        lib.sketch_export_size.argtypes = [p]
        lib.sketch_export.restype = i64
        lib.sketch_export.argtypes = [p, _u8p, i64]
        lib.sketch_import.restype = i64
        lib.sketch_import.argtypes = [p, _u8p, i64]
        lib.sketch_set_sample.restype = None
        lib.sketch_set_sample.argtypes = [p, i64]
        lib.sketch_slot_tops.restype = i64
        lib.sketch_slot_tops.argtypes = [p, i64, _u64p, _f64p]
        lib.sketch_observe_routed.restype = i64
        lib.sketch_observe_routed.argtypes = [
            ctypes.POINTER(p), i64, ctypes.c_uint64, _u64p, i64, i64, i64,
        ]
        _LIB = lib
    return _LIB


_M64 = (1 << 64) - 1


def splitmix64(x: int) -> int:
    """Python mirror of the native ``splitmix64`` (native/cache.cpp)."""
    x = (x + 0x9E3779B97F4A7C15) & _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


def shard_route(sign: int, part_salt: int, n_shards: int) -> int:
    """Python mirror of the native ``shard_route`` — the mulhi partition
    the sharded feed directory and the sub-sketch family share. Must stay
    bit-identical to the C++ side (pinned by tests)."""
    return (splitmix64((int(sign) ^ int(part_salt)) & _M64) * n_shards) >> 64


class NativeSketch:
    """Thin RAII handle over one native AccessSketch."""

    def __init__(
        self,
        n_slots: int,
        width_log2: int = 16,
        depth: int = 4,
        bitmap_bits: int = 1 << 15,
        topk: int = 8,
    ):
        self._lib = _load_lib()
        self._h = self._lib.sketch_create(
            n_slots, width_log2, depth, bitmap_bits, topk
        )
        if not self._h:
            raise ValueError(
                f"sketch_create rejected geometry (n_slots={n_slots}, "
                f"width_log2={width_log2}, depth={depth}, "
                f"bitmap_bits={bitmap_bits}, topk={topk})"
            )
        self.n_slots = int(n_slots)
        self.topk = int(topk)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.sketch_destroy(h)
            self._h = None

    def observe(
        self, signs: np.ndarray, samples_per_slot: int, slot_base: int
    ) -> int:
        """Strided attribution: position i -> slot_base + i//samples_per_slot
        (a group's flattened (S, B) sign matrix); samples_per_slot <= 0
        attributes everything to slot_base."""
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        return int(self._lib.sketch_observe(
            self._h, signs.ctypes.data_as(_u64p), signs.size,
            int(samples_per_slot), int(slot_base),
        ))

    def decay(self, factor: float) -> None:
        self._lib.sketch_decay(self._h, float(factor))

    def set_sample(self, k: int) -> None:
        """``PERSIA_SKETCH_SAMPLE=1/k`` observe sampling: only signs with
        ``hash(sign) % k == 0`` touch the count-min, every increment scaled
        by k — totals/cm/unique stay unbiased in expectation while the
        unfused observe walk costs 1/k of its DRAM traffic. The hash gate
        is sign-deterministic, so repeated observes of a hot sign are
        consistently kept or consistently skipped (no per-call jitter in
        its estimate). Native clamps k to [1, 2**20]."""
        self._lib.sketch_set_sample(self._h, int(k))

    def slot_tops(self, slot: int) -> tuple:
        """(signs (topk,) u64, ests (topk,) f64) heavy-hitter list for one
        slot; unfilled entries are zero. Used to merge per-shard sub-sketch
        lists deterministically in the sharded profiler."""
        signs = np.zeros(self.topk, dtype=np.uint64)
        ests = np.zeros(self.topk, dtype=np.float64)
        rc = self._lib.sketch_slot_tops(
            self._h, int(slot), signs.ctypes.data_as(_u64p),
            ests.ctypes.data_as(_f64p),
        )
        if rc < 0:
            raise IndexError(f"slot {slot} out of range [0, {self.n_slots})")
        return signs, ests

    def slot_stats(self, slot: int) -> tuple:
        """(total, unique_est, hot_frac, top1_frac) for one slot index."""
        out = np.empty(4, dtype=np.float64)
        rc = self._lib.sketch_slot_stats(
            self._h, int(slot), out.ctypes.data_as(_f64p)
        )
        if rc != 0:
            raise IndexError(f"slot {slot} out of range [0, {self.n_slots})")
        return float(out[0]), float(out[1]), float(out[2]), float(out[3])

    def estimate(self, slot: int, sign: int) -> float:
        return float(self._lib.sketch_estimate(
            self._h, int(slot), ctypes.c_uint64(int(sign) & (2**64 - 1))
        ))

    def export_bytes(self) -> bytes:
        size = int(self._lib.sketch_export_size(self._h))
        buf = np.empty(size, dtype=np.uint8)
        n = int(self._lib.sketch_export(
            self._h, buf.ctypes.data_as(_u8p), size
        ))
        if n < 0:
            raise RuntimeError("sketch_export: buffer undersized")
        return buf[:n].tobytes()

    def import_bytes(self, blob: bytes) -> None:
        buf = np.frombuffer(blob, dtype=np.uint8)
        rc = int(self._lib.sketch_import(
            self._h, buf.ctypes.data_as(_u8p), buf.size
        ))
        if rc != 0:
            raise ValueError(
                "sketch_import: blob geometry does not match this sketch "
                "(profiler config changed across the snapshot?)"
            )


def observe_routed(
    sketches, part_salt: int, signs: np.ndarray,
    samples_per_slot: int, slot_base: int,
) -> int:
    """Observe a sign stream into a per-shard sub-sketch family, routing
    each sign with the SAME ``shard_route(sign ^ part_salt)`` the sharded
    feed directory uses — the unfused paths (ServiceCtx, PS-tier slots)
    land updates in the same sub-sketch the fused walk would, so the two
    observe paths share state instead of forking it."""
    signs = np.ascontiguousarray(signs, dtype=np.uint64)
    handles = [s._h for s in sketches]
    arr = (ctypes.c_void_p * len(handles))(*handles)
    return int(_load_lib().sketch_observe_routed(
        arr, len(handles), ctypes.c_uint64(int(part_salt) & (2**64 - 1)),
        signs.ctypes.data_as(_u64p), signs.size,
        int(samples_per_slot), int(slot_base),
    ))

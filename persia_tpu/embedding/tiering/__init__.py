"""Sparsity-aware auto-tiering (ROADMAP direction 4; Parallax-style
per-variable placement, arxiv 1808.02621).

Three parts, one pipeline:

- :mod:`profiler` — per-slot frequency / working-set sketch fed by the
  native admit walk (``native/cache.cpp sketch_*``): decayed access
  totals, a count-min over signs, two-window linear-counting working-set
  estimates, top-K heavy hitters.
- :mod:`planner` — scores each slot (reuse = total/unique, traffic
  density = total/vocab) against tier capacity budgets and assigns
  fused / cached / ps, with hysteresis + dwell so placement cannot flap.
- :mod:`controller` — applies the plan at stream snapshot fences
  (the PR 5 jobstate machinery): feeder parked, ledger drained,
  manifest committed, then ``CachedTrainCtx.apply_migration``
  re-registers the moving slots and the stream resumes.
"""

from persia_tpu.embedding.tiering.controller import (  # noqa: F401
    AUTO_TIER_ENV,
    AutoTierController,
    auto_tier_enabled,
    enable_auto_tier,
)
from persia_tpu.embedding.tiering.planner import (  # noqa: F401
    TIER_CACHED,
    TIER_FUSED,
    TIER_PS,
    TIERS,
    PlacementPlanner,
    TierPlan,
)
from persia_tpu.embedding.tiering.profiler import (  # noqa: F401
    AccessProfiler,
    SlotStats,
    publish_sketch_metrics,
)

"""The fused cached-tier train/eval step builders (one jitted XLA
program per step: gather -> model fwd/bwd -> dense update -> on-device
sparse update -> eviction payload)."""


from __future__ import annotations

import ctypes
import os
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from persia_tpu.config import EmbeddingConfig
from persia_tpu.data import PersiaBatch
from persia_tpu.embedding.optim import OPTIMIZER_ADAM, OptimizerConfig
from persia_tpu.embedding.worker import (
    ProcessedBatch,
    ProcessedSlot,
    ShardedLookup,
    preprocess_batch,
)
from persia_tpu.logger import get_default_logger
from persia_tpu.utils import round_up_pow2 as _round_up_pow2
from persia_tpu.metrics import get_metrics
from persia_tpu.ops.sparse_update import sparse_update
from persia_tpu.tracing import span

logger = get_default_logger("persia_tpu.hbm_cache")

# ------------------------------------------------------------------ ctypes


from persia_tpu.embedding.hbm_cache.groups import (  # noqa: F401
    CacheGroup,
    CacheLayout,
    CachedTrainState,
    _apply_aux,
    _entry_to_state_cols,
    _gather_entry_rows,
    _model_emb_from_gathered,
    _restore_rows,
    _scatter_entry_block,
    _slot_group_of,
    _state_init_consts,
    _bucket,
)

def build_cached_train_step(
    model,
    dense_optimizer,
    sparse_cfg: OptimizerConfig,
    groups: Sequence[CacheGroup],
    loss_fn=None,
    donate: bool = True,
    ps_grad_dtype=jnp.float32,
    ps_grad_wire: Optional[str] = None,
    dynamic_loss_scale: bool = False,
    growth_interval: int = 2000,
    growth_factor: float = 2.0,
    backoff_factor: float = 0.5,
    max_scale: float = float(2 ** 24),
    sentinel_probe: bool = False,
    guard_clip_norm: Optional[float] = None,
):
    """Jitted ``step(state, batch, layout) -> (state, header)``.

    batch = {
      "dense": [(B,F) f32], "labels": [(B,1) f32],
      "stacked_rows": {group: (S, B, L) int32 cache rows for the group's
                       pooled slots (stack order = layout.stacked), pad = C
                       (the zero row)},
      "stacked_scale": {group: (S, B) f32} — omitted when no slot scales,
      "raw_rows": {slot: (B, L) int32} for sequence slots,
      "ps_emb": [ {"pooled": (B,D)} | {"distinct","index","mask"} ... ] —
                mixed-tier slots served by the worker/PS path
                (layout.ps names them, in order),
    }
    Miss scatters and the evict-payload read run as a separate fused tiny
    jit (``_apply_aux``) dispatched by the ctx around this step, so this —
    the expensive compile — sees only fixed-shape inputs. Returns
    ``(state, header, ps_gpacked)``: header = [loss, preds...]; ps_gpacked
    = flat f32 gradients of the ps_emb entries (empty when none) for the
    worker's gradient return.

    ``dynamic_loss_scale`` (same management as the hybrid path's
    build_train_step; ref GradScaler, persia/ctx.py:926-1005): the loss is
    scaled before backward, an on-device finite check over EVERY gradient
    (dense + cached + ps) gates the update — overflow skips the dense
    update AND the cached-row sparse update (scale *= backoff), a finite
    streak grows the scale. Header becomes [loss | scale | finite | preds],
    and ps_gpacked carries [grads... | scale | finite] so the write-back
    thread can unscale/skip without any extra device fetch. One documented
    divergence from the reference: the Adam beta powers (device AND PS)
    advance on overflow-skipped steps too — keeping the two tiers' powers
    in lockstep without a per-step device sync; the skipped step itself
    applies no gradient anywhere.

    ``ps_grad_wire``: the gradient-RETURN wire for PS-tier slots —
    "float32" / "bfloat16" (equivalent to ``ps_grad_dtype``, kept for
    callers that pass the dtype directly) or "int8": bytegrad-style
    per-slot absmax quantization with an error-feedback residual
    (``parallel/grad_sync.quantize_int8_ef``) — ~4× fewer d2h bytes than
    f32 on the wire that physically caps the ps-stream regime. The
    residual stays DEVICE-resident: the step reads it from
    ``batch["ps_gres"]`` (flat f32, zeros to reset) and returns the
    updated one, so what int8 could not represent this step re-enters the
    next step's wire instead of being lost. With int8 the step returns
    ``ps_gpacked = (q int8, scales f32 (S[+finite]), new_residual f32)``
    — grads are unscaled ON DEVICE under dynamic loss scaling (the
    scales tail then carries the finite flag), and an overflow step ships
    zeros and carries the residual through unchanged.

    ``sentinel_probe``: numerical-health probe for the stream sentinel
    (persia_tpu/health). Appends a fixed probe tail to the header —
    ``[dense_gnorm, group_gnorm x n_groups, ps_gnorm, finite, clipped]``
    (norms unscaled, pre-clip) — and arms the finite gate even without
    dynamic loss scaling: a non-finite gradient skips the dense update,
    masks every cached row, and ships a flagged/zeroed ps wire, exactly
    like an overflow step (device-side "skip-batch" rung; the ps wire
    then carries the ``[scale|finite]`` tail so the write-back thread can
    honor the skip). Healthy unclipped steps multiply by exactly 1.0
    everywhere, so arming the probe is bit-transparent. ``guard_clip_norm``
    (requires ``sentinel_probe``) rescales the whole update on device when
    the total grad norm exceeds it — the sentinel's "clip" rung.
    """
    from functools import partial

    from persia_tpu.parallel.train_step import default_loss_fn

    loss_fn = loss_fn or default_loss_fn
    by_name = {g.name: g for g in groups}
    if ps_grad_wire is not None:
        if ps_grad_wire not in ("float32", "bfloat16", "int8"):
            raise ValueError(
                f"ps_grad_wire must be float32/bfloat16/int8, got {ps_grad_wire!r}"
            )
        if ps_grad_wire == "bfloat16":
            ps_grad_dtype = jnp.bfloat16
    ps_int8 = ps_grad_wire == "int8"

    @partial(jax.jit, static_argnums=(2,), donate_argnums=(0,) if donate else ())
    def step(state: CachedTrainState, batch: Dict, layout: CacheLayout):
        tables, emb_state = dict(state.tables), dict(state.emb_state)

        # ONE gather per group for all its stacked pooled slots, plus one
        # per raw slot; differentiate w.r.t. the GATHERED arrays (like the
        # fused path) so cotangents stay gather-shaped instead of dense
        # table-shaped scatters
        stacked_gathered = {
            gname: tables[gname][rows]  # (S, B, L, dim)
            for gname, rows in batch["stacked_rows"].items()
        }
        raw_gathered = {
            name: tables[_slot_group_of(groups, name)][rows]
            for name, rows in batch["raw_rows"].items()
        }
        from persia_tpu.parallel.train_step import (
            _embedding_model_inputs, _split_emb,
        )

        ps_diff, ps_static = _split_emb(batch.get("ps_emb", []))

        scale = (
            state.loss_scale.scale
            if dynamic_loss_scale
            else jnp.asarray(1.0, jnp.float32)
        )

        def loss_wrapper(params, stacked_in, raw_in, ps_in):
            model_emb = _model_emb_from_gathered(
                groups, batch, layout, stacked_in, raw_in,
                pad_row=lambda gname: by_name[gname].rows,
                ps_model_inputs=_embedding_model_inputs(ps_in, ps_static),
            )
            variables = {"params": params}
            if state.batch_stats:
                variables["batch_stats"] = state.batch_stats
                logits, updates = model.apply(
                    variables, batch["dense"], model_emb, train=True,
                    mutable=["batch_stats"],
                )
                new_stats = updates["batch_stats"]
            else:
                logits = model.apply(variables, batch["dense"], model_emb, train=True)
                new_stats = state.batch_stats
            loss = loss_fn(logits, batch["labels"][0])
            return loss * scale.astype(loss.dtype), (loss, logits, new_stats)

        (_, (loss, logits, new_stats)), (param_grads, stacked_g, raw_g, ps_g) = (
            jax.value_and_grad(
                loss_wrapper, argnums=(0, 1, 2, 3), has_aux=True
            )(state.params, stacked_gathered, raw_gathered, ps_diff)
        )

        need_guard = dynamic_loss_scale or sentinel_probe
        if need_guard:
            leaves = (
                jax.tree.leaves(param_grads)
                + jax.tree.leaves(stacked_g) + jax.tree.leaves(raw_g)
                + jax.tree.leaves(ps_g)
            )
            finite = jnp.all(
                jnp.stack([jnp.all(jnp.isfinite(g)) for g in leaves])
            )
            inv = jnp.where(finite, 1.0 / scale, 0.0).astype(jnp.float32)
        else:
            finite = jnp.asarray(True)
            inv = jnp.asarray(1.0, jnp.float32)

        clip_f = jnp.asarray(1.0, jnp.float32)
        probe_tail = None
        if sentinel_probe:
            # Norms of the UNSCALED gradients (inv divides the loss scale
            # out; overflow steps report 0 and carry the finite flag).
            def _gnorm(parts):
                parts = list(parts)
                if not parts:
                    return jnp.asarray(0.0, jnp.float32)
                return jnp.sqrt(
                    sum(jnp.sum(jnp.square(p.astype(jnp.float32)))
                        for p in parts)
                )

            dense_gnorm = _gnorm(jax.tree.leaves(param_grads)) * inv
            group_gnorms = []
            for g in groups:
                parts = []
                if g.name in batch["stacked_rows"]:
                    parts.append(stacked_g[g.name])
                for name in g.raw_slots:
                    if name in batch["raw_rows"]:
                        parts.append(raw_g[name])
                group_gnorms.append(_gnorm(parts) * inv)
            ps_gnorm = _gnorm(jax.tree.leaves(ps_g)) * inv
            if guard_clip_norm is not None:
                total = jnp.sqrt(
                    jnp.square(dense_gnorm) + jnp.square(ps_gnorm)
                    + sum(jnp.square(n) for n in group_gnorms)
                )
                clip_f = jnp.where(
                    total > guard_clip_norm,
                    guard_clip_norm / jnp.maximum(total, 1e-12),
                    1.0,
                ).astype(jnp.float32)
            probe_tail = jnp.stack(
                [dense_gnorm] + group_gnorms + [
                    ps_gnorm,
                    finite.astype(jnp.float32),
                    (clip_f < 1.0).astype(jnp.float32),
                ]
            )
            inv = inv * clip_f

        if need_guard:
            param_grads = jax.tree.map(
                lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype),
                param_grads,
            )

        import optax as _optax

        updates, new_opt_state = dense_optimizer.update(
            param_grads, state.opt_state, state.params
        )
        new_params = _optax.apply_updates(state.params, updates)
        if need_guard:
            # overflow / non-finite grads: dense update skipped entirely
            new_params = jax.tree.map(
                lambda new, old: jnp.where(finite, new, old),
                new_params, state.params,
            )
            new_opt_state = jax.tree.map(
                lambda new, old: jnp.where(finite, new, old),
                new_opt_state, state.opt_state,
            )

        # on-device sparse update of the cached rows — ONE duplicate-safe
        # scatter per group (dedup inside sparse_update merges the same row
        # appearing in several slots)
        batch_state = state.emb_batch_state * jnp.array(
            [sparse_cfg.beta1, sparse_cfg.beta2], dtype=jnp.float32
        )
        for g in groups:
            idp, gp, mp = [], [], []
            if g.name in batch["stacked_rows"]:
                rows = batch["stacked_rows"][g.name]
                idp.append(rows.reshape(-1))
                # unscale under dynamic loss scaling; on overflow every row
                # is MASKED OUT below (sparse_update touches no row at all —
                # exact skip for every optimizer incl. weight decay and
                # Adam's state decay, at O(touched rows)); the grads are
                # also selected to zero so inf*0 NaNs never enter the math
                sg = stacked_g[g.name].astype(jnp.float32).reshape(-1, g.dim)
                gp.append(jnp.where(finite, sg * inv, 0.0))
                mp.append(((rows < g.rows) & finite).reshape(-1))
            for name in g.raw_slots:
                if name not in batch["raw_rows"]:
                    continue
                rows = batch["raw_rows"][name]
                idp.append(rows.reshape(-1))
                rg = raw_g[name].astype(jnp.float32).reshape(-1, g.dim)
                gp.append(jnp.where(finite, rg * inv, 0.0))
                mp.append(((rows < g.rows) & finite).reshape(-1))
            if not idp:
                continue
            tables[g.name], emb_state[g.name] = sparse_update(
                sparse_cfg,
                tables[g.name],
                emb_state[g.name],
                jnp.concatenate(idp) if len(idp) > 1 else idp[0],
                jnp.concatenate(gp) if len(gp) > 1 else gp[0],
                batch_state,
                mask=jnp.concatenate(mp) if len(mp) > 1 else mp[0],
            )

        new_ls = state.loss_scale
        if dynamic_loss_scale:
            from persia_tpu.parallel.train_step import LossScaleState

            good = jnp.where(finite, state.loss_scale.good_steps + 1, 0)
            grown = good >= growth_interval
            new_scale = jnp.where(
                finite,
                jnp.where(grown, scale * growth_factor, scale),
                scale * backoff_factor,
            )
            new_scale = jnp.clip(new_scale, 1.0, max_scale)
            new_ls = LossScaleState(
                scale=new_scale, good_steps=jnp.where(grown, 0, good)
            )
        new_state = CachedTrainState(
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt_state,
            tables=tables,
            emb_state=emb_state,
            emb_batch_state=batch_state,
            step=state.step + 1,
            loss_scale=new_ls,
        )
        head = [jnp.reshape(loss, (1,)).astype(jnp.float32)]
        if dynamic_loss_scale:
            head.append(jnp.reshape(scale, (1,)).astype(jnp.float32))
            head.append(jnp.reshape(finite, (1,)).astype(jnp.float32))
        head.append(jnp.reshape(jax.nn.sigmoid(logits), (-1,)).astype(jnp.float32))
        if probe_tail is not None:
            head.append(probe_tail)
        header = jnp.concatenate(head)
        # ps-tier gradients are an inherent d2h; a bf16 wire halves the
        # bytes on the return path (the reference ships scaled-f16 grad
        # wires, lib.rs:157-180) — the host casts back to f32 before the
        # worker's unscale/update. Under dynamic scaling the buffer's last
        # two entries are [scale | finite] (both exact in bf16: scale is a
        # power of two), so the write-back thread needs no extra fetch.
        # The int8 wire quarter-widths the same bytes: per-slot absmax
        # quantization with a device-resident error-feedback residual.
        if ps_int8:
            from persia_tpu.parallel.grad_sync import quantize_int8_ef

            flats = [jnp.reshape(g, (-1,)).astype(jnp.float32) for g in ps_g]
            total = sum(f.shape[0] for f in flats)
            res = batch.get("ps_gres")
            if res is None:
                res = jnp.zeros((total,), jnp.float32)
            qs, scs, new_res = [], [], []
            off = 0
            for f in flats:
                r = jax.lax.slice(res, (off,), (off + f.shape[0],))
                off += f.shape[0]
                # unscale ON the device (inv = 0 on overflow): the residual
                # must accumulate true-gradient error, not scaled error
                q, sc, _deq, nr = quantize_int8_ef(f * inv, r)
                if need_guard:
                    q = jnp.where(finite, q, jnp.zeros_like(q))
                    nr = jnp.where(finite, nr, r)
                qs.append(q)
                scs.append(sc)
                new_res.append(nr)
            q_packed = (
                jnp.concatenate(qs) if qs else jnp.zeros((0,), jnp.int8)
            )
            sc_parts = [jnp.stack(scs)] if scs else []
            if need_guard:
                sc_parts.append(
                    jnp.reshape(finite.astype(jnp.float32), (1,))
                )
            sc_packed = (
                jnp.concatenate(sc_parts) if sc_parts
                else jnp.zeros((0,), jnp.float32)
            )
            res_packed = (
                jnp.concatenate(new_res) if new_res
                else jnp.zeros((0,), jnp.float32)
            )
            return new_state, header, (q_packed, sc_packed, res_packed)
        ps_flat = [
            (jnp.reshape(g, (-1,)).astype(jnp.float32) * clip_f).astype(
                ps_grad_dtype
            )
            for g in ps_g
        ]
        if need_guard and ps_flat:
            ps_flat.append(
                jnp.stack([scale, finite.astype(jnp.float32)]).astype(ps_grad_dtype)
            )
        ps_gpacked = (
            jnp.concatenate(ps_flat) if ps_flat
            else jnp.zeros((0,), ps_grad_dtype)
        )
        return new_state, header, ps_gpacked

    return step


def build_cached_eval_step(model, groups: Sequence[CacheGroup]):
    """Jitted ``eval_step(state, batch, layout) -> preds``.

    Eval must not mutate the cache (no admits, no evictions, no directory
    churn — the ADVICE round-1 corruption bug): resident signs gather from
    the live cache tables; misses arrive as a host-side PS lookup
    (``miss_tables``: {group: (Mp, dim)}) with rows pre-assigned to C+1+j.
    Values come from a two-gather select (no table concat — concatenating
    would copy the multi-GB pool per eval batch). Mask rule here is
    ``rows != C`` (pad) since miss rows legitimately exceed C."""
    from functools import partial

    by_name = {g.name: g for g in groups}

    def _gather_ext(table, miss_table, rows, C):
        from_cache = table[jnp.minimum(rows, C)]
        miss_idx = jnp.maximum(rows - (C + 1), 0)
        from_miss = miss_table[miss_idx].astype(table.dtype)
        return jnp.where((rows > C)[..., None], from_miss, from_cache)

    @partial(jax.jit, static_argnums=(2,))
    def eval_step(state: CachedTrainState, batch: Dict, layout: CacheLayout):
        stacked_gathered = {}
        for gname, rows in batch["stacked_rows"].items():
            C = by_name[gname].rows
            stacked_gathered[gname] = _gather_ext(
                state.tables[gname], batch["miss_tables"][gname], rows, C
            )
        raw_gathered = {}
        for name, rows in batch["raw_rows"].items():
            gname = _slot_group_of(groups, name)
            C = by_name[gname].rows
            raw_gathered[name] = _gather_ext(
                state.tables[gname], batch["miss_tables"][gname], rows, C
            )
        from persia_tpu.parallel.train_step import (
            _embedding_model_inputs, _split_emb,
        )

        ps_diff, ps_static = _split_emb(batch.get("ps_emb", []))
        model_emb = _model_emb_from_gathered(
            groups, batch, layout, stacked_gathered, raw_gathered,
            pad_row=lambda gname: by_name[gname].rows,
            ps_model_inputs=_embedding_model_inputs(ps_diff, ps_static),
        )
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        logits = model.apply(variables, batch["dense"], model_emb, train=False)
        return jax.nn.sigmoid(logits)

    return eval_step


# -------------------------------------------------------------- host tier



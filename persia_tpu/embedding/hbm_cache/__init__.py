"""Write-back HBM embedding cache over the host parameter-server tier.

The TPU answer to the reference's beyond-GPU-memory regime
(`README.md:29` — 100T parameters on CPU parameter servers): keep the
authoritative, unbounded-vocab store on the host PS tier
(`persia_tpu.embedding.store` / `native_store`), but keep the *working set*
resident in HBM as a fixed-size row pool, so

- **hits** never cross the host↔device boundary at all: the step receives
  int32 cache-row indices (4 B/id instead of ``4·dim`` B/id), gathers from
  HBM, and applies the sparse optimizer **on device** to the cached rows —
  gradients never leave the chip;
- **misses** check full ``[emb | optimizer state]`` rows out of the PS
  (`checkout_entries`) and scatter them into the cache inside the same
  jitted step;
- **evictions** (LRU, decided by the native C++ directory `native/cache.cpp`)
  read the victim rows back out of the step (they ride the step's output)
  and write them to the PS — the write-back.

With a skewed (production-like) id distribution the steady-state miss rate
is small, so per-step host↔device traffic approaches the fused HBM path's
(ids only) while vocabulary stays unbounded like the reference's PS. This
replaces the reference's *bounded-staleness* asynchrony with *bounded
residency*: cached rows train fully synchronously (stronger than the
reference's staleness>0 mode); only tier migration is asynchronous-ish.

Pipelining: ``CachedTrainCtx.train_step`` defers the previous step's
eviction write-back (and metric fetch) until after the current step is
dispatched, so host-side preprocessing and PS traffic overlap the device
step — the TPU analogue of the reference's latency-hiding lookup workers
(`rust/persia-core/src/forward.rs:640-779`). A same-sign
evict-then-re-miss across adjacent steps is detected on the host (the
directory reports evictions synchronously) and forces the pending
write-back to land before the fresh checkout reads the PS.

Limitations (v1): hash-stack slots are not cacheable (their table keys are
many-to-one per distinct id). Adam matches the pure-PS path to fp
tolerance: the device's shared batch-level beta powers advance once per
step and are mirrored to the PS each gradient batch — the reference's
batch-level semantics (persia-common/src/optim.rs:99-221; parity-tested in
tests/test_hbm_cache.py::test_cached_adam_matches_pure_ps_adam, like the
Adagrad/SGD exactness tests). The one documented wrinkle: under
dynamic_loss_scale, powers also advance on overflow-skipped steps (see
build_cached_train_step).
"""

from persia_tpu.embedding.hbm_cache.directory import (  # noqa: F401
    CacheDirectory,
    _BufRing,
    build_native,
    native_uniform_init,
)
from persia_tpu.embedding.hbm_cache.groups import (  # noqa: F401
    CacheGroup,
    CacheLayout,
    CachedTrainState,
    init_cached_tables,
    make_cache_groups,
)
from persia_tpu.embedding.hbm_cache.step import (  # noqa: F401
    build_cached_eval_step,
    build_cached_train_step,
)
from persia_tpu.embedding.hbm_cache.tier import CachedEmbeddingTier  # noqa: F401
from persia_tpu.embedding.hbm_cache.ctx import CachedTrainCtx  # noqa: F401
from persia_tpu.embedding.hbm_cache.stream import run_train_stream  # noqa: F401

"""Native cache-directory bindings + host staging rings (split from the
round-3 monolith; see package __init__ for the design overview)."""


from __future__ import annotations

import ctypes
import os
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from persia_tpu.config import EmbeddingConfig
from persia_tpu.data import PersiaBatch
from persia_tpu.embedding.optim import OPTIMIZER_ADAM, OptimizerConfig
from persia_tpu.embedding.worker import (
    ProcessedBatch,
    ProcessedSlot,
    ShardedLookup,
    preprocess_batch,
)
from persia_tpu.logger import get_default_logger
from persia_tpu.utils import round_up_pow2 as _round_up_pow2
from persia_tpu.embedding.hbm_cache.common import _bucket  # noqa: F401
from persia_tpu.metrics import get_metrics
from persia_tpu.ops.sparse_update import sparse_update
from persia_tpu.tracing import span

logger = get_default_logger("persia_tpu.hbm_cache")

# ------------------------------------------------------------------ ctypes


# one extra level: this file lives in the hbm_cache PACKAGE
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
_SRC = os.path.join(_REPO_ROOT, "native", "cache.cpp")
_SO = os.path.join(_REPO_ROOT, "native", "libpersia_cache.so")
_LIB: Optional[ctypes.CDLL] = None

_i64p = ctypes.POINTER(ctypes.c_int64)
_u64p = ctypes.POINTER(ctypes.c_uint64)


def build_native(force: bool = False) -> str:
    from persia_tpu.embedding._native_build import build_so

    return build_so(
        # -pthread: the sharded feeder runs its shard walks on a native pool
        _SRC, _SO, ["-O3", "-std=c++17", "-fPIC", "-shared", "-Wall",
                    "-pthread"],
        logger, force=force,
    )


def _load_lib() -> ctypes.CDLL:
    global _LIB
    if _LIB is None:
        # CDLL the path build_native RETURNS (sanitizer-variant aware)
        so_path = build_native()
        lib = ctypes.CDLL(so_path)
        i64, p = ctypes.c_int64, ctypes.c_void_p
        # every binding declares BOTH restype and argtypes (restype = None
        # for void) — persia-lint ABI003/ABI007 enforce it mechanically
        lib.cache_create.restype = p
        lib.cache_create.argtypes = [i64]
        lib.cache_destroy.restype = None
        lib.cache_destroy.argtypes = [p]
        lib.cache_len.restype = i64
        lib.cache_len.argtypes = [p]
        lib.cache_capacity.restype = i64
        lib.cache_capacity.argtypes = [p]
        lib.cache_admit.restype = i64
        lib.cache_admit.argtypes = [p, _u64p, i64, _i64p, _i64p, _u64p, _i64p, _i64p]
        lib.cache_probe.restype = None
        lib.cache_probe.argtypes = [p, _u64p, i64, _i64p]
        lib.cache_drain.restype = i64
        lib.cache_drain.argtypes = [p, _u64p, _i64p]
        lib.cache_snapshot.restype = i64
        lib.cache_snapshot.argtypes = [p, _u64p, _i64p]
        lib.cache_set_admit_touches.restype = None
        lib.cache_set_admit_touches.argtypes = [p, i64]
        # probe layout selector (round 17): 1 = SIMD tag probe, 0 = scalar
        lib.cache_set_probe_mode.restype = None
        lib.cache_set_probe_mode.argtypes = [p, i64]
        lib.cache_probe_mode.restype = i64
        lib.cache_probe_mode.argtypes = [p]
        _i32p = ctypes.POINTER(ctypes.c_int32)
        lib.cache_admit_positions.restype = i64
        lib.cache_admit_positions.argtypes = [
            p, _u64p, i64, _i32p, _u64p, _i64p, _u64p, _i64p,
            ctypes.POINTER(i64), ctypes.POINTER(i64),
        ]
        lib.cache_uniform_init.restype = None
        lib.cache_uniform_init.argtypes = [
            _u64p, i64, i64, ctypes.c_uint64, ctypes.c_double,
            ctypes.c_double, ctypes.POINTER(ctypes.c_float),
        ]
        lib.cache_init_rows.restype = None
        lib.cache_init_rows.argtypes = [
            _u64p, i64, i64, ctypes.c_uint64, ctypes.c_int,
            ctypes.c_double, ctypes.c_double, ctypes.POINTER(ctypes.c_float),
        ]
        u32 = ctypes.c_uint32
        u32p = ctypes.POINTER(u32)
        lib.pending_map_create.restype = p
        lib.pending_map_create.argtypes = []
        lib.pending_map_destroy.restype = None
        lib.pending_map_destroy.argtypes = [p]
        lib.pending_map_size.restype = i64
        lib.pending_map_size.argtypes = [p]
        lib.pending_map_insert.restype = None
        lib.pending_map_insert.argtypes = [p, _u64p, _i64p, i64, u32]
        lib.pending_map_insert_range.restype = None
        lib.pending_map_insert_range.argtypes = [p, _u64p, i64, i64, u32]
        lib.pending_map_query.restype = i64
        lib.pending_map_query.argtypes = [p, _u64p, i64, u32p, _i64p]
        lib.pending_map_remove.restype = None
        lib.pending_map_remove.argtypes = [p, _u64p, i64, u32]
        lib.cache_feed_batch.restype = i64
        lib.cache_feed_batch.argtypes = [
            p, p, _u64p, i64, _i32p, _u64p, _i64p, _u64p, _i64p,
            ctypes.POINTER(i64), ctypes.POINTER(i64),
            _i64p, _i64p, ctypes.POINTER(i64), ctypes.c_uint64,
        ]
        # ---- sharded feeder directory (round 14) ----
        pp = ctypes.POINTER(p)  # void** — the per-shard sketch array
        lib.cache_create_sharded.restype = p
        lib.cache_create_sharded.argtypes = [i64, i64, ctypes.c_uint64, i64]
        lib.cache_sharded_destroy.restype = None
        lib.cache_sharded_destroy.argtypes = [p]
        lib.cache_sharded_len.restype = i64
        lib.cache_sharded_len.argtypes = [p]
        lib.cache_sharded_capacity.restype = i64
        lib.cache_sharded_capacity.argtypes = [p]
        lib.cache_sharded_n_shards.restype = i64
        lib.cache_sharded_n_shards.argtypes = [p]
        lib.cache_sharded_threads.restype = i64
        lib.cache_sharded_threads.argtypes = [p]
        lib.cache_sharded_set_threads.restype = None
        lib.cache_sharded_set_threads.argtypes = [p, i64]
        lib.cache_sharded_set_admit_touches.restype = None
        lib.cache_sharded_set_admit_touches.argtypes = [p, i64]
        lib.cache_sharded_shard_sizes.restype = None
        lib.cache_sharded_shard_sizes.argtypes = [p, _i64p]
        lib.cache_sharded_shard_busy_ns.restype = None
        lib.cache_sharded_shard_busy_ns.argtypes = [p, _i64p]
        # ---- probe layout + walker affinity (round 17) ----
        lib.cache_sharded_shard_stall_ns.restype = None
        lib.cache_sharded_shard_stall_ns.argtypes = [p, _i64p]
        lib.cache_sharded_set_probe_mode.restype = None
        lib.cache_sharded_set_probe_mode.argtypes = [p, i64]
        lib.cache_sharded_probe_mode.restype = i64
        lib.cache_sharded_probe_mode.argtypes = [p]
        lib.cache_sharded_set_affinity.restype = None
        lib.cache_sharded_set_affinity.argtypes = [p, i64]
        lib.cache_sharded_affinity.restype = i64
        lib.cache_sharded_affinity.argtypes = [p]
        lib.cache_sharded_probe.restype = None
        lib.cache_sharded_probe.argtypes = [p, _u64p, i64, _i64p]
        lib.cache_sharded_admit.restype = i64
        lib.cache_sharded_admit.argtypes = [
            p, _u64p, i64, _i64p, _i64p, _u64p, _i64p, ctypes.POINTER(i64),
        ]
        lib.cache_sharded_snapshot.restype = i64
        lib.cache_sharded_snapshot.argtypes = [p, _u64p, _i64p]
        lib.cache_sharded_drain.restype = i64
        lib.cache_sharded_drain.argtypes = [p, _u64p, _i64p]
        lib.cache_feed_batch_sharded.restype = i64
        lib.cache_feed_batch_sharded.argtypes = [
            p, p, _u64p, i64, _i32p, _u64p, _i64p, _u64p, _i64p,
            ctypes.POINTER(i64), ctypes.POINTER(i64),
            _i64p, _i64p, ctypes.POINTER(i64), ctypes.c_uint64,
            pp, i64, i64, i64,
        ]
        _LIB = lib
    return _LIB


#: PERSIA_FEED_AFFINITY policy names → native mode codes. ``none`` leaves
#: walkers unpinned; ``compact`` packs worker i onto cpu ``i % ncpu``
#: (shared-LLC locality); ``spread`` stripes workers across the cpu range
#: (one walker per NUMA node's worth of cores on big hosts).
AFFINITY_MODES = {"none": 0, "compact": 1, "spread": 2}


def feed_affinity_from_env() -> int:
    """Resolve PERSIA_FEED_AFFINITY to a native pinning mode (default 0 =
    none). Unknown values fall back to none — placement is best-effort."""
    return AFFINITY_MODES.get(
        os.environ.get("PERSIA_FEED_AFFINITY", "none").strip().lower(), 0)


def feed_probe_from_env() -> int:
    """Resolve PERSIA_FEED_PROBE to a probe-layout mode: ``scalar`` → 0,
    anything else (including unset) → 1, the SIMD tag probe. Mirrors the
    native ``default_probe_mode`` so Python-side introspection agrees with
    directories created before the first setter call."""
    return 0 if os.environ.get("PERSIA_FEED_PROBE", "").strip() == "scalar" else 1


def native_uniform_init(
    signs: np.ndarray, seed: int, dim: int, lo: float, hi: float,
    out: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Seeded cold-miss embedding init in C++ — bit-identical to
    ``hashing.uniform_init_for_signs`` (tested). ``out`` (M, dim) f32
    C-contiguous is filled in place when given."""
    lib = _load_lib()
    signs = np.ascontiguousarray(signs, dtype=np.uint64)
    m = len(signs)
    if out is None:
        out = np.empty((m, dim), dtype=np.float32)
    assert out.flags["C_CONTIGUOUS"] and out.dtype == np.float32
    lib.cache_uniform_init(
        signs.ctypes.data_as(_u64p), m, dim, ctypes.c_uint64(seed),
        lo, hi, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
    )
    return out


def native_init_rows(
    signs: np.ndarray, seed: int, dim: int, method,
    out: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Seeded cold-miss init for any ``config.InitializationMethod`` —
    bit-identical to ``hashing.init_for_signs`` and to the PS cores
    (tests/test_init_methods.py), so a row born in the cache matches one
    born on the PS (ref: emb_entry.rs:28-60 seeded init)."""
    lib = _load_lib()
    signs = np.ascontiguousarray(signs, dtype=np.uint64)
    m = len(signs)
    if out is None:
        out = np.empty((m, dim), dtype=np.float32)
    assert out.flags["C_CONTIGUOUS"] and out.dtype == np.float32
    lib.cache_init_rows(
        signs.ctypes.data_as(_u64p), m, dim, ctypes.c_uint64(seed),
        method.code, method.p0, method.p1,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
    )
    return out


def _retain_allocator_pages() -> None:
    """Tell glibc to satisfy MB-scale allocations from retained heap pages.

    The per-step staging buffers (~0.5-1 MB each) historically crossed
    malloc's default mmap threshold, so every step paid mmap +
    first-touch page faults + munmap TLB churn — profiled at ~20 ms/step
    of pure allocator cost on a single-core host. The old answer was a
    fixed-depth buffer-reuse ring, which turned out to hand a
    still-in-flight buffer back to the feeder whenever the pipeline ran
    deeper than the depth — measured as run-to-run NONDETERMINISTIC
    training (torn staging bytes). Raising M_MMAP_THRESHOLD keeps fresh
    allocations cheap (glibc free-lists, no page churn) so every step can
    own brand-new buffers: correctness by construction, same speed.
    Called once, lazily, when the first cache tier is constructed — a
    process that merely imports this package (fused-tier users, test
    collection) keeps its default allocator behavior. Opt out with
    PERSIA_NO_MALLOPT=1. No-op where mallopt is unavailable (non-glibc)."""
    global _MALLOPT_DONE
    if _MALLOPT_DONE or os.environ.get("PERSIA_NO_MALLOPT") == "1":
        return
    _MALLOPT_DONE = True
    try:
        libc = ctypes.CDLL(None)
        libc.mallopt.restype = ctypes.c_int
        libc.mallopt.argtypes = [ctypes.c_int, ctypes.c_int]
        M_MMAP_THRESHOLD = -3
        libc.mallopt(M_MMAP_THRESHOLD, 64 * 1024 * 1024)
    except Exception:  # noqa: BLE001 — allocator tuning is best-effort
        pass


_MALLOPT_DONE = False


class _BufRing:
    """Per-step host staging buffer source.

    Every ``get`` returns a FRESH array: the per-step buffers escape into
    an asynchronously consumed pipeline (device_put serialization, jit
    argument lifetimes), and no rotation depth or release protocol proved
    robust against every consumer — a reused buffer whose bytes change
    while any in-flight reader still needs them silently corrupts
    training (observed as bimodal per-step losses at deep prefetch).
    Allocation stays cheap because ``_retain_allocator_pages`` keeps
    glibc from mmap-ing these MB-scale buffers. The class keeps its
    pooling-era ``key`` argument so call sites stay unchanged."""

    def get(self, key, shape, dtype) -> np.ndarray:
        return np.empty(shape, dtype)

    def full(self, key, shape, dtype, fill) -> np.ndarray:
        arr = np.empty(shape, dtype)
        arr.fill(fill)
        return arr


class CacheDirectory:
    """LRU map sign → device cache row (native C++, O(1) per op).

    ``admit_touches`` — touch-gated admission (the reference's
    ``admit_probability`` analogue, reference
    `persia-embedding-config/src/lib.rs` HyperParameters): a non-resident
    sign is admitted only on its Nth distinct-batch touch; earlier touches
    map to the pad row ``capacity`` (zero forward contribution, gradient
    dropped — the reference's non-admitted-sign semantics). Default 1 =
    admit on first touch (exact parity with the ungated tier).

    ``shards`` — when set, the directory is partitioned into that many
    independent shards (own mutex + LRU chain + row range) keyed by
    ``shard_route(sign ^ part_salt)``; the feed walk can then run on the
    native thread pool (``feed_threads``) and fuse the tiering sketch
    observe into the same pass (``feed_batch(..., sketches=)``). Outputs
    are merged in shard order, so they are bit-identical at ANY thread
    count (but differ from the unsharded directory's LRU order for
    ``shards > 1`` — ``shards`` must therefore be a jobstate-stable
    choice, not derived from the host). ``shards=1`` is bit-identical to
    the legacy directory. ``part_salt`` is the per-group ledger salt
    (:func:`group_salt`) so partitioning rides the same namespace the
    hazard ledger already uses."""

    def __init__(self, capacity: int, admit_touches: int = 1,
                 shards: Optional[int] = None, feed_threads: int = 1,
                 part_salt: int = 0, probe: Optional[int] = None,
                 affinity: Optional[int] = None):
        self._lib = _load_lib()
        self.part_salt = int(part_salt) & (2**64 - 1)
        self._sharded = shards is not None
        if self._sharded:
            self._h = self._lib.cache_create_sharded(
                capacity, max(1, int(shards)), self.part_salt,
                max(1, int(feed_threads)))
            # the native side clamps shards to [1, min(64, capacity)]
            self.shards: Optional[int] = int(
                self._lib.cache_sharded_n_shards(self._h))
        else:
            self._h = self._lib.cache_create(capacity)
            self.shards = None
        # probe layout (round 17): the native side already defaulted from
        # PERSIA_FEED_PROBE at load; an explicit arg overrides per handle.
        # Bit-identical either way — a profiling/parity knob, never a
        # jobstate-stable choice.
        if probe is not None:
            self.set_probe_mode(probe)
        aff = feed_affinity_from_env() if affinity is None else int(affinity)
        if self._sharded and aff:
            self._lib.cache_sharded_set_affinity(self._h, aff)
        self.capacity = capacity
        self.admit_touches = int(admit_touches)
        if self.admit_touches > 1:
            if self._sharded:
                self._lib.cache_sharded_set_admit_touches(
                    self._h, self.admit_touches)
            else:
                self._lib.cache_set_admit_touches(self._h, self.admit_touches)
        # reusable admit_positions outputs: 5 scratch arrays (miss/evict
        # results are .copy()'d out, so a single reused buffer each is safe)
        # plus a ring for the per-position rows (which ESCAPE to the async
        # device staging path as views)
        self._scratch_n = 0
        self._rows_ring = _BufRing()

    @property
    def feed_threads(self) -> int:
        return (int(self._lib.cache_sharded_threads(self._h))
                if self._sharded else 1)

    def set_feed_threads(self, threads: int) -> None:
        """Resize the native walker pool (sharded mode only; clamped to
        [1, shards]). Output bits never depend on this — it is purely a
        throughput knob, safe to change between feeds."""
        if self._sharded:
            self._lib.cache_sharded_set_threads(self._h, max(1, int(threads)))

    def shard_sizes(self) -> np.ndarray:
        """Resident count per shard (sharded mode; (shards,) i64) — the
        per-shard occupancy surfaced in stream stats and fence logs."""
        if not self._sharded:
            return np.array([len(self)], dtype=np.int64)
        out = np.empty(self.shards, dtype=np.int64)
        self._lib.cache_sharded_shard_sizes(
            self._h, out.ctypes.data_as(_i64p))
        return out

    def shard_busy_ns(self) -> np.ndarray:
        """Per-shard walk time of the LAST feed in ns (sharded mode) —
        feeds the ``persia_tpu_feeder_shard_busy`` gauges + ``feed.shard``
        spans."""
        if not self._sharded:
            return np.zeros(1, dtype=np.int64)
        out = np.empty(self.shards, dtype=np.int64)
        self._lib.cache_sharded_shard_busy_ns(
            self._h, out.ctypes.data_as(_i64p))
        return out

    def shard_stall_ns(self) -> np.ndarray:
        """Per-shard pool-queue wait of the LAST feed in ns (sharded mode):
        dispatch-to-walk-start, summed over both walk phases. Busy says how
        long a shard's walk ran; stall says how long it waited for a core
        first — together they separate shard imbalance from core starvation
        on the ``persia_tpu_feeder_shard_stall`` gauge."""
        if not self._sharded:
            return np.zeros(1, dtype=np.int64)
        out = np.empty(self.shards, dtype=np.int64)
        self._lib.cache_sharded_shard_stall_ns(
            self._h, out.ctypes.data_as(_i64p))
        return out

    @property
    def probe_mode(self) -> int:
        """Active probe layout: 1 = SIMD tag probe, 0 = scalar slot walk."""
        if self._sharded:
            return int(self._lib.cache_sharded_probe_mode(self._h))
        return int(self._lib.cache_probe_mode(self._h))

    def set_probe_mode(self, mode: int) -> None:
        """Select the probe layout (1 = SIMD tag probe, 0 = scalar).
        Output bits never depend on this — it exists for the golden parity
        suite and A/B profiling; safe to flip between feeds."""
        mode = 1 if int(mode) else 0
        if self._sharded:
            self._lib.cache_sharded_set_probe_mode(self._h, mode)
        else:
            self._lib.cache_set_probe_mode(self._h, mode)

    @property
    def feed_affinity(self) -> int:
        """Walker pinning policy (sharded mode): 0 none, 1 compact,
        2 spread — see ``PERSIA_FEED_AFFINITY``."""
        if not self._sharded:
            return 0
        return int(self._lib.cache_sharded_affinity(self._h))

    def set_feed_affinity(self, mode: int) -> None:
        """Re-pin the walker pool (sharded mode only; best-effort, Linux
        only). Purely a placement knob — output bits never depend on it."""
        if self._sharded:
            self._lib.cache_sharded_set_affinity(self._h, int(mode))

    def _ensure_scratch(self, n: int) -> None:
        if n <= self._scratch_n:
            return
        self._scratch_n = n
        self._s_miss_signs = np.empty(n, dtype=np.uint64)
        self._s_miss_rows = np.empty(n, dtype=np.int64)
        self._s_ev_signs = np.empty(n, dtype=np.uint64)
        self._s_ev_rows = np.empty(n, dtype=np.int64)
        self._s_miss_idx = np.empty(n, dtype=np.int64)
        self._s_rst_src = np.empty(n, dtype=np.int64)
        self._s_rst_pos = np.empty(n, dtype=np.int64)

    def __del__(self):
        if getattr(self, "_h", None) is not None:
            if self._sharded:
                self._lib.cache_sharded_destroy(self._h)
            else:
                self._lib.cache_destroy(self._h)
            self._h = None

    def __len__(self) -> int:
        if self._sharded:
            return self._lib.cache_sharded_len(self._h)
        return self._lib.cache_len(self._h)

    def admit(self, signs: np.ndarray):
        """signs must be deduplicated. Returns (rows (n,), miss_idx (M,),
        evict_signs (K,), evict_rows (K,)). Raises if the batch's distinct
        count exceeds capacity (the C call returns -1 *before* writing
        rows_out, so the outputs are uninitialized in that case)."""
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        n = len(signs)
        self._ensure_scratch(n)
        # bucketed ring shape (n varies per batch; exact shapes would
        # reallocate every call), result is the [:n] slice
        rows = self._rows_ring.get("rows64", (_bucket(max(n, 1)),), np.int64)[:n]
        miss_idx = self._s_miss_idx
        ev_signs = self._s_ev_signs
        ev_rows = self._s_ev_rows
        n_evict = ctypes.c_int64(0)
        admit_fn = (self._lib.cache_sharded_admit if self._sharded
                    else self._lib.cache_admit)
        n_miss = admit_fn(
            self._h, signs.ctypes.data_as(_u64p), n,
            rows.ctypes.data_as(_i64p), miss_idx.ctypes.data_as(_i64p),
            ev_signs.ctypes.data_as(_u64p), ev_rows.ctypes.data_as(_i64p),
            ctypes.byref(n_evict),
        )
        if n_miss < 0:
            raise RuntimeError(
                f"batch distinct-sign count {n} exceeds cache capacity "
                f"{self.capacity} — raise cache rows or shrink the batch"
            )
        k = n_evict.value
        return rows, miss_idx[:n_miss].copy(), ev_signs[:k].copy(), ev_rows[:k].copy()

    def admit_positions(self, signs: np.ndarray):
        """Admit a RAW (duplicated) position-level sign stream — the dedup
        happens natively. Returns (rows (n,) int32 per position,
        miss_signs (M,), miss_rows (M,), evict_signs (K,), evict_rows (K,),
        n_unique). One call replaces per-slot dedup + cross-slot dedup +
        admit + row LUT for the single-id fast path."""
        if self._sharded:
            out = self.feed_batch(signs, None)
            return out[:6]
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        n = signs.size
        self._ensure_scratch(n)
        rows = self._rows_ring.get("rows", (_bucket(max(n, 1)),), np.int32)[:n]
        miss_signs = self._s_miss_signs
        miss_rows = self._s_miss_rows
        ev_signs = self._s_ev_signs
        ev_rows = self._s_ev_rows
        n_unique = ctypes.c_int64(0)
        n_evict = ctypes.c_int64(0)
        i32p = ctypes.POINTER(ctypes.c_int32)
        n_miss = self._lib.cache_admit_positions(
            self._h, signs.ctypes.data_as(_u64p), n,
            rows.ctypes.data_as(i32p),
            miss_signs.ctypes.data_as(_u64p), miss_rows.ctypes.data_as(_i64p),
            ev_signs.ctypes.data_as(_u64p), ev_rows.ctypes.data_as(_i64p),
            ctypes.byref(n_unique), ctypes.byref(n_evict),
        )
        if n_miss < 0:
            raise RuntimeError(
                f"batch distinct-sign count exceeds cache capacity "
                f"{self.capacity} — raise cache rows or shrink the batch"
            )
        k = n_evict.value
        return (
            rows, miss_signs[:n_miss].copy(), miss_rows[:n_miss].copy(),
            ev_signs[:k].copy(), ev_rows[:k].copy(), n_unique.value,
        )

    def feed_batch(
        self, signs: np.ndarray, pending_map: "PendingSignMap | None",
        salt: int = 0,
        sketches: Optional[Sequence] = None,
        samples_per_slot: int = 0, slot_base: int = 0,
    ):
        """The feeder hot-loop fused call (``native/cache.cpp``
        ``cache_feed_batch``): everything ``admit_positions`` does PLUS the
        write-back hazard-ledger probe of the resulting misses, in ONE
        native round-trip. Returns ``admit_positions``'s 6-tuple extended
        with ``(restore_src (R,), restore_pos (R,))`` — the in-flight ring
        row and miss ordinal of every miss whose freshest entry is still
        riding an un-landed eviction write-back. The probe runs before the
        caller's ring-span reservation, so restore hits must be
        REVALIDATED against the map after reserving (see the C comment);
        a hit that died in between is safe to route through the PS.

        ``salt`` namespaces the ledger probe per cache group (the native
        side applies the SAME ``sign ^ salt`` the Python map methods do —
        see :func:`group_salt`).

        Sharded mode only: ``sketches`` (one per shard — native sketch
        handles or objects carrying ``_h``) fuses the tiering observe into
        the admit walk itself, one traversal of the sign matrix instead of
        two. ``samples_per_slot``/``slot_base`` give the position → slot
        map (position ``i`` → ``slot_base + i // samples_per_slot``). The
        fused observe attributes a sign to the slot of its FIRST position
        in the batch — callers must only fuse when sign → slot is
        injective (``feature_index_prefix_bit > 0``) and keep the routed
        unfused observe otherwise."""
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        n = signs.size
        self._ensure_scratch(n)
        rows = self._rows_ring.get("rows", (_bucket(max(n, 1)),), np.int32)[:n]
        n_unique = ctypes.c_int64(0)
        n_evict = ctypes.c_int64(0)
        n_restore = ctypes.c_int64(0)
        i32p = ctypes.POINTER(ctypes.c_int32)
        pending_h = pending_map._h if pending_map is not None else None
        common = (
            signs.ctypes.data_as(_u64p), n,
            rows.ctypes.data_as(i32p),
            self._s_miss_signs.ctypes.data_as(_u64p),
            self._s_miss_rows.ctypes.data_as(_i64p),
            self._s_ev_signs.ctypes.data_as(_u64p),
            self._s_ev_rows.ctypes.data_as(_i64p),
            ctypes.byref(n_unique), ctypes.byref(n_evict),
            self._s_rst_src.ctypes.data_as(_i64p),
            self._s_rst_pos.ctypes.data_as(_i64p),
            ctypes.byref(n_restore), ctypes.c_uint64(salt & (2**64 - 1)),
        )
        if self._sharded:
            sk_arr, n_sk = None, 0
            if sketches is not None:
                handles = [getattr(s, "_h", s) for s in sketches]
                if len(handles) != self.shards:
                    raise ValueError(
                        f"fused observe needs one sketch per shard "
                        f"({self.shards}), got {len(handles)}")
                sk_arr = (ctypes.c_void_p * len(handles))(*handles)
                n_sk = len(handles)
            n_miss = self._lib.cache_feed_batch_sharded(
                self._h, pending_h, *common,
                sk_arr, n_sk, int(samples_per_slot), int(slot_base),
            )
        else:
            if sketches is not None:
                raise ValueError("fused sketch observe needs shards= set")
            n_miss = self._lib.cache_feed_batch(self._h, pending_h, *common)
        if n_miss < 0:
            raise RuntimeError(
                f"batch distinct-sign count exceeds cache capacity "
                f"{self.capacity} — raise cache rows or shrink the batch"
            )
        k = n_evict.value
        r = n_restore.value
        return (
            rows,
            self._s_miss_signs[:n_miss].copy(),
            self._s_miss_rows[:n_miss].copy(),
            self._s_ev_signs[:k].copy(), self._s_ev_rows[:k].copy(),
            n_unique.value,
            self._s_rst_src[:r].copy(), self._s_rst_pos[:r].copy(),
        )

    def probe(self, signs: np.ndarray) -> np.ndarray:
        """Read-only residency check: row per sign, -1 on miss. No admit, no
        LRU touch — safe for eval/infer batches."""
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        rows = np.empty(len(signs), dtype=np.int64)
        probe_fn = (self._lib.cache_sharded_probe if self._sharded
                    else self._lib.cache_probe)
        probe_fn(self._h, signs.ctypes.data_as(_u64p), len(signs),
                 rows.ctypes.data_as(_i64p))
        return rows

    def drain(self) -> Tuple[np.ndarray, np.ndarray]:
        """Empty the directory; returns (signs, rows) of everything resident."""
        cap = self.capacity
        signs = np.empty(cap, dtype=np.uint64)
        rows = np.empty(cap, dtype=np.int64)
        drain_fn = (self._lib.cache_sharded_drain if self._sharded
                    else self._lib.cache_drain)
        k = drain_fn(self._h, signs.ctypes.data_as(_u64p),
                     rows.ctypes.data_as(_i64p))
        return signs[:k].copy(), rows[:k].copy()

    def snapshot(self) -> Tuple[np.ndarray, np.ndarray]:
        """Non-destructive (signs, rows) of everything resident — no LRU
        churn, no eviction, directory unchanged."""
        cap = self.capacity
        signs = np.empty(cap, dtype=np.uint64)
        rows = np.empty(cap, dtype=np.int64)
        snap_fn = (self._lib.cache_sharded_snapshot if self._sharded
                   else self._lib.cache_snapshot)
        k = snap_fn(self._h, signs.ctypes.data_as(_u64p),
                    rows.ctypes.data_as(_i64p))
        return signs[:k].copy(), rows[:k].copy()


# ------------------------------------------------------------ device state


def group_salt(name: str) -> int:
    """64-bit namespace salt for a cache group's pending-ledger keys.

    The ``PendingSignMap`` is GLOBAL to the stream but its entries are
    per-group ring rows, while the gate runs per group — with
    ``feature_index_prefix_bit=0`` two groups can carry the SAME raw sign,
    and an unsalted probe in group B would resolve group A's in-flight
    eviction (restoring A's ring rows into B's cache: silent corruption;
    round-5 advisor finding). Both the Python map methods and the native
    fused probe (``cache_feed_batch``) key on ``sign ^ group_salt(name)``,
    so the namespaces cannot collide. Deterministic by group name."""
    import hashlib

    h = hashlib.blake2b(name.encode(), digest_size=8).digest()
    return int.from_bytes(h, "little") or 1


class PendingSignMap:
    """Native sign → (token, src) map for the stream's write-back hazard
    gate (`native/cache.cpp` pending_map_*): one query call per step
    replaces a per-pending-record searchsorted scan. Internally
    mutex-protected, so the fused feeder probe (``cache_feed_batch``) and
    the write-back thread's removals need no shared Python lock; the
    stream's condvar still orders removals against ring-tail advances.

    ``salt`` (see :func:`group_salt`) namespaces keys per cache group:
    every method XORs it into the signs before they touch the native map,
    and the fused native probe applies the SAME xor (``cache_feed_batch``'s
    ``salt`` argument) — the two sides must agree or the fused path would
    silently probe the wrong namespace."""

    def __init__(self):
        self._lib = _load_lib()
        self._h = self._lib.pending_map_create()
        if not self._h:
            raise MemoryError("pending_map_create failed")

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.pending_map_destroy(h)
            self._h = None

    def __len__(self) -> int:
        return int(self._lib.pending_map_size(self._h))

    @staticmethod
    def _salted(signs: np.ndarray, salt: int) -> np.ndarray:
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        if salt:
            signs = signs ^ np.uint64(salt)
        return signs

    def insert(
        self, signs: np.ndarray, srcs: np.ndarray, token: int, salt: int = 0
    ) -> None:
        signs = self._salted(signs, salt)
        srcs = np.ascontiguousarray(srcs, dtype=np.int64)
        assert len(signs) == len(srcs)
        self._lib.pending_map_insert(
            self._h, signs.ctypes.data_as(_u64p),
            srcs.ctypes.data_as(_i64p), len(signs),
            ctypes.c_uint32(token & 0xFFFFFFFF),
        )

    def insert_range(
        self, signs: np.ndarray, base_src: int, token: int, salt: int = 0
    ) -> None:
        """Insert ``signs[i] -> (base_src + i, token)`` — the contiguous
        ring-span form every eviction record takes, without the host-side
        arange temporary."""
        signs = self._salted(signs, salt)
        self._lib.pending_map_insert_range(
            self._h, signs.ctypes.data_as(_u64p), len(signs),
            int(base_src), ctypes.c_uint32(token & 0xFFFFFFFF),
        )

    def query(self, signs: np.ndarray, salt: int = 0):
        """(hits, tokens (n,) u32, srcs (n,) i64 with -1 = not pending)."""
        signs = self._salted(signs, salt)
        n = len(signs)
        tokens = np.empty(n, dtype=np.uint32)
        srcs = np.empty(n, dtype=np.int64)
        hits = self._lib.pending_map_query(
            self._h, signs.ctypes.data_as(_u64p), n,
            tokens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            srcs.ctypes.data_as(_i64p),
        )
        return int(hits), tokens, srcs

    def remove(self, signs: np.ndarray, token: int, salt: int = 0) -> None:
        signs = self._salted(signs, salt)
        self._lib.pending_map_remove(
            self._h, signs.ctypes.data_as(_u64p), len(signs),
            ctypes.c_uint32(token & 0xFFFFFFFF),
        )

"""The asynchronous streaming train loop of the cached tier (feeder ->
stager -> dispatch -> write-back pipeline), split out of CachedTrainCtx
-- ``CachedTrainCtx.train_stream`` delegates here."""


from __future__ import annotations

import ctypes
import os
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from persia_tpu.config import EmbeddingConfig
from persia_tpu.data import PersiaBatch
from persia_tpu.embedding.optim import OPTIMIZER_ADAM, OptimizerConfig
from persia_tpu.embedding.worker import (
    ProcessedBatch,
    ProcessedSlot,
    ShardedLookup,
    preprocess_batch,
)
from persia_tpu.logger import get_default_logger
from persia_tpu.utils import round_up_pow2 as _round_up_pow2
from persia_tpu.metrics import get_metrics
from persia_tpu.ops.sparse_update import sparse_update
from persia_tpu.tracing import record_event, span, stage_span

logger = get_default_logger("persia_tpu.hbm_cache")

# ------------------------------------------------------------------ ctypes


from persia_tpu.embedding.hbm_cache.groups import (  # noqa: F401
    CacheLayout,
    _bucket,
)
from persia_tpu.embedding.hbm_cache.directory import (  # noqa: F401
    PendingSignMap,
    _BufRing,
)

def run_train_stream(
    self,
    batches,
    prefetch: int = 3,
    on_metrics: Optional[Callable[[Dict], None]] = None,
    wb_flush_steps: int = 8,
    fetch_final: bool = True,
    psgrad_batch: int = 8,
    dispatch_k: int = 4,
    pipeline_depth: int = 1,
    snapshot_every: Optional[int] = None,
    job_state=None,
    start_step: int = 0,
    sentinel=None,
    skip_steps=None,
    fence_callback: Optional[Callable[[int], None]] = None,
) -> Optional[Dict]:
    """Fully-pipelined training over an iterable of ``PersiaBatch``.

    Three concurrent stages (the TPU analogue of the reference's
    latency-hiding forward/backward engines, forward.rs:640-779 /
    backward.rs:304-354):

    - a **feeder thread** runs host preprocessing, the directory admit,
      the PS checkout, and kicks off the async host→device staging for
      batch N+k while the device executes batch N;
    - the **caller's thread** only dispatches the (tiny) device programs
      in order;
    - a **write-back thread** materializes each step's eviction payload
      (the device→host transfer) and persists it to the PS.

    Correctness across threads: the directory is only touched by the
    feeder (serial admits), and the feeder's hazard gate blocks a PS
    checkout while an overlapping eviction write-back is in flight.
    Returns the final step's metrics; ``on_metrics`` (if given) receives
    every step's metrics at the cost of a per-step device sync.

    Mixed-tier configs stream too: PS-tier slots forward in the feeder
    thread and their gradients return through the write-back thread, so
    they train under BOUNDED staleness (a forward may read entries
    whose previous-step gradients are in flight, the window set by the
    prefetch depth) — the reference's async mode; cached slots stay
    fully synchronous.

    ``psgrad_batch``: PS-tier gradient returns are device→host fetches;
    on a high-latency link a serial per-step fetch caps the whole
    pipeline at 1/latency. The write-back thread therefore accumulates
    up to ``psgrad_batch`` consecutive steps' gradient outputs and
    fetches them CONCURRENTLY (parallel transfers share the latency),
    then applies them to the worker in step order — the staleness
    window grows to ``prefetch + psgrad_batch`` steps, the same
    throughput/staleness trade the reference's lookup-worker count
    sets (forward.rs:640-779).

    ``fetch_final=False`` keeps the loop COMPLETELY free of
    device→host transfers: the final header is only
    ``block_until_ready``-synced (completion without a fetch) and
    stashed device-side; ``last_metrics()`` materializes it on demand.
    On a remote-attached chip a d2h fetch costs tens of ms and can
    permanently degrade the runtime's dispatch latency (measured ~200×
    on the axon tunnel), so throughput-critical loops should defer every
    fetch past the region they care about.

    ``dispatch_k``: multi-step fused dispatch. Up to ``dispatch_k``
    consecutive HAZARD-FREE staged steps (no in-flight-eviction restore,
    no PS-tier forward — exactly the windows where the hazard ledger
    shows no overlap) are packed and run as ONE jitted K-step program
    (``ctx._dispatch_packed``), cutting Python dispatch and header
    traffic by K×. A step that restores from the standing ring, carries a
    PS-tier forward, or changes shape signature flushes the pack first,
    so packing NEVER reorders a restore against the eviction write that
    produced its ring rows, and the write-back FIFO keeps step order.
    Packing adds NO staleness to cached slots (every packed step still
    sees its predecessor's updates inside the program); it only defers
    the per-step header materialization by < K steps. ``on_metrics``
    forces ``dispatch_k=1`` (it needs a header sync per step). Partial
    packs (stream tail, or a 50 ms idle wait while the feeder is parked
    on ring back-pressure) dispatch through the already-compiled
    single-step path — only exactly-K uniform windows pay a (one-time)
    K-step compile.

    ``snapshot_every`` + ``job_state``: step-fenced consistent snapshots
    (persia_tpu.jobstate). Every ``snapshot_every`` global steps the
    FEEDER pauses before preparing the next batch and a fence marker
    rides the pipeline's own FIFO: by the time the dispatcher sees it,
    every earlier step has dispatched; a drain marker then flushes the
    write-back thread (eviction landings + PS-tier gradient applies), the
    hazard ledger and eviction rings are verified empty (tails caught up
    to heads — the same accounting the in-flight gate uses), and
    ``ctx._fence_capture`` flushes the resident cache to the PS and
    commits one manifest epoch: PS shards, dense params + optimizer
    state + (now cold) cache pools, directory/ring occupancy, the loader
    cursor, and the RNG streams. ``start_step`` offsets the fence cadence
    and journal ids for a resumed stream
    (``train_stream(batches_from_F, start_step=F, ...)``).

    ``pipeline_depth``: MPMD stage-graph pipelining
    (persia_tpu/parallel/stage_graph.py). At depth >= 2 the step's FEED
    stage (the fused aux scatters of ``_apply_feed``) dispatches from the
    STAGER thread up to ``depth - 1`` steps ahead of its own dense stage,
    so batch N+k's embedding feed rides under batch N's dense compute —
    the source paper's bounded-staleness overlap expressed in the
    dispatch layer, with the depth as the staleness knob. Bit-parity is
    preserved (not approximated): a feed only hoists when its rows are
    disjoint from every in-flight dense stage's trained rows (disjoint
    scatters commute bitwise); a conflict stalls the feed
    (``pipeline.stall``) until the dense stages retire. Steps the hazard
    ledger already serializes — in-flight-eviction restores, PS-tier
    forwards — enter the window as BARRIERS: they dispatch through the
    full in-order path and no later feed hoists across them. Feed-done
    steps pack into dense-only K-step windows (``min(dispatch_k, depth)``
    wide, so a full pack never overruns the window); fences drain the
    window before capture (``pipeline.drain``) so jobstate bit-parity
    holds unchanged, and a post-migration fence fires the stage graph's
    ``rebuild()`` hooks. ``on_metrics`` forces depth 1 (per-step header
    sync), like ``dispatch_k``.

    ``fence_callback``: a hook invoked at EVERY fence with the global
    step, after the manifest commit (when ``job_state`` is armed) and the
    migration point, while the feeder is still parked and the write-back
    drained — the one window where topology may change under the stream
    (the autopilot controller's reshard/replication actuation point;
    persia_tpu/autopilot). Park → callback → resume: the drained-fence
    invariants are identical to snapshot fences, and a no-op callback is
    bit-transparent to the stream (tests/test_autopilot.py pins this).
    With ``fence_callback`` set the fence cadence runs even without
    ``job_state`` (no manifest is committed then). A callback exception is
    ISOLATED: the fence's own invariants already held before the callback
    ran, so the error is counted
    (``persia_tpu_stream_fence_callback_errors``), recorded as a
    ``stream.fence_callback_error`` flight event, and training continues —
    the callback's own journal (e.g. the autopilot's planned manifest)
    keeps its interrupted work resumable. Fence-internal failures (drain,
    ledger, manifest commit) still abort the stream.

    ``sentinel`` + ``skip_steps`` (persia_tpu/health): an armed
    :class:`~persia_tpu.health.sentinel.StreamSentinel` digests each
    step's header one dispatch behind the newest in-flight step (the
    probe tail rides the header the step already emits; disabled cost is
    one ``is None`` check) and raises ``SentinelRollback`` through the
    caller's thread for the fence auto-rollback driver
    (``health.run_guarded_stream``). ``skip_steps`` is the quarantined
    global-step set: the feeder consumes those batches WITHOUT preparing
    or training them — seq/fence cadence and journal ids stay aligned
    with the unquarantined run.
    """
    import queue as _queue
    import time as _time

    if prefetch < 1:
        raise ValueError(f"prefetch must be >= 1, got {prefetch}")
    if pipeline_depth < 1:
        raise ValueError(f"pipeline_depth must be >= 1, got {pipeline_depth}")
    from persia_tpu.parallel.stage_graph import StageGraph, feed_hazard_info

    # on_metrics needs a per-step header sync, which serializes the
    # stages anyway — force the in-order pipeline (same rule as dispatch_k)
    PIPE = pipeline_depth > 1 and on_metrics is None
    graph = StageGraph(pipeline_depth if PIPE else 1)
    self._stage_graph = graph
    for _hook in self._stage_rebuild_hooks:
        graph.on_rebuild(_hook)
    job_mgr = None
    if job_state is not None:
        from persia_tpu.jobstate import coerce_manager

        job_mgr = coerce_manager(job_state)
        if self._job_epoch is None:
            self._job_epoch = 0  # journal from the first step; see jobstate
    fence_done = threading.Event()
    # Host staging buffers are FRESH per step (_BufRing hands out new
    # arrays; its docstring records the reuse-race history), so nothing
    # needs sizing against the prefetch depth here.
    self._land_pending()  # do not mix with a sync-path deferred step
    cv = threading.Condition()
    stop = threading.Event()
    # a pipelined stream needs the staged queue at least window-deep or
    # the queue cap (not the depth knob) would bound the feed look-ahead
    qcap = max(prefetch, graph.depth)
    staged_q: "_queue.Queue" = _queue.Queue(maxsize=qcap)
    # bounds device-memory retention: at most ~(queue + one flush batch)
    # steps of eviction payloads (+ one psgrad batch) stay pinned in HBM
    # while the PS lags
    wb_q: "_queue.Queue" = _queue.Queue(
        maxsize=max(1, wb_flush_steps) + qcap + max(1, psgrad_batch)
    )
    SENTINEL = object()
    errors: List[BaseException] = []

    # Standing-ring accounting. Eviction payloads land in each group's
    # DEVICE ring (ctx._ev_rings, written inside _apply_aux_ring); the
    # allocator below reserves PADDED row spans at prepare time and
    # back-pressures when the in-flight window would overrun the ring. The
    # write-back thread advances the tail after landing a span in the PS.
    # All shared state (heads/tails/alloc_q/sign_map) is guarded by `cv`.
    heads: Dict[str, int] = {}  # monotonic, unwrapped
    tails: Dict[str, int] = {}
    # per-group FIFO of reserved span sizes (skip + kp) — allocations and
    # flushes are both in seq order per group, so tail advance is a pop
    alloc_q: Dict[str, List[int]] = {}
    flush_now = threading.Event()  # feeder → wb: ring full, flush early

    def ring_alloc(gname: str, kp: int) -> int:
        W = self.ring_rows(gname)
        if kp > W:
            raise RuntimeError(
                f"one step evicts {kp} (padded) rows > the {W}-row "
                f"eviction ring of group {gname!r}; raise wb_ring_rows or "
                "lower the eviction volume (admit_touches / cache_rows)"
            )
        with cv:
            while not (stop.is_set() or errors):
                head = heads.get(gname, 0)
                tail = tails.get(gname, 0)
                # a span never wraps mid-region: skip to 0 if it would
                skip = (W - head % W) if (head % W) + kp > W else 0
                if head + skip + kp - tail <= W:
                    heads[gname] = head + skip + kp
                    alloc_q.setdefault(gname, []).append(skip + kp)
                    return (head + skip) % W
                if tail == head and head % W:
                    # ring fully drained, only the wrap waste doesn't fit
                    # the circular invariant (waste counts as allocated
                    # until a flush passes it, but there is nothing left
                    # to flush) — jump both pointers to the next ring
                    # boundary; no live span exists to overlap
                    heads[gname] = tails[gname] = -(-head // W) * W
                    continue
                # ring full: ask the write-back thread to flush early and
                # wait for the tail to advance
                flush_now.set()
                with span("stream.ring_wait", group=gname):
                    cv.wait(timeout=0.5)
            return -1  # unwinding — the step never dispatches

    # sign → (token=seq, ring row) for every in-flight eviction: ONE native
    # query per gate call (native/cache.cpp pending_map_*), ONE restore
    # program per group per step (all hits gather from the standing ring,
    # regardless of how many producing steps are referenced). Keys are
    # namespaced per group (directory.group_salt): with
    # feature_index_prefix_bit=0 the same raw sign can live in two groups,
    # and an unsalted probe would restore the OTHER group's ring rows.
    sign_map = PendingSignMap()
    # a COPY, refreshed in place after a fence-point tier migration (the
    # migration replaces self.tier, and the feeder/gate closures hold this
    # dict): group names usually survive a move (cache_d{dim}) but a dim
    # appearing/disappearing changes the key set
    salts = dict(self.tier._group_salt)

    def gate(gname: str, miss_signs: np.ndarray):
        """Resolve re-missed pending-evicted signs against the in-flight
        DEVICE ring: returns at most one restore descriptor, whose payload
        is ``None`` (= the group's standing ring, resolved by the main
        thread at dispatch). Correctness is dispatch ordering: the steps
        that wrote the referenced ring rows dispatch before this one, and
        a span is only reallocated after its write-back lands (tail
        advance), which also removes its map entries."""
        with cv:
            if stop.is_set() or errors:
                return None
            hits, _tokens, srcs = sign_map.query(miss_signs, salt=salts[gname])
            if not hits:
                return None
            pos = np.nonzero(srcs >= 0)[0]
            return [(None, srcs[pos], pos)]

    prep_q: "_queue.Queue" = _queue.Queue(maxsize=prefetch)

    def _put(q, item) -> bool:
        while not (stop.is_set() or errors):
            try:
                q.put(item, timeout=0.5)
                return True
            except _queue.Full:
                continue
        return False

    # dispatch/feeder accounting for the bench artifact (ctx.stream_stats):
    # regressions in the hot loop must be visible from the JSON alone
    stats = {
        "dispatch_k": max(1, int(dispatch_k)) if on_metrics is None else 1,
        "packs": 0, "packed_steps": 0, "single_steps": 0,
        "pipelined_feeds": 0,
        "feeder_busy_s": 0.0, "wall_s": 0.0,
        "degraded_steps": 0, "degraded_lookup_frac_max": 0.0,
        "fences": 0, "quarantine_skips": 0,
    }
    # health sentinel: headers queued at dispatch, digested one window
    # behind (sentinel.py); both hooks are no-ops when sentinel is None
    from persia_tpu.health.sentinel import sentinel_drain, sentinel_note

    sent_pending: List = []
    t_start = _time.perf_counter()
    # per-seq degraded-lookup fraction (written by the feeder BEFORE the
    # item enters prep_q, popped by the dispatcher — queue ordering is the
    # happens-before edge); the router's window counters are exclusive to
    # the feeder thread inside one stream
    deg_fracs: Dict[int, float] = {}
    _router = self.tier.router
    _deg_tracking = (
        hasattr(_router, "take_degraded_window")
        and getattr(_router, "policy", None) is not None
    )
    _m_step_deg = get_metrics().gauge(
        "persia_tpu_stream_degraded_lookup_frac",
        "per-step degraded lookup fraction of the cached stream",
    )
    _m_feeder_util = get_metrics().gauge(
        "persia_tpu_stream_feeder_util",
        "fraction of stream wall time the feeder thread was busy",
    )
    _m_packed_frac = get_metrics().gauge(
        "persia_tpu_stream_packed_step_frac",
        "fraction of dispatched steps that rode a K-step pack",
    )

    def _publish_live_stats() -> None:
        """Export the stream's headline ratios as live gauges so the
        telemetry collector sees them mid-run, not just in the final
        stats dict."""
        elapsed = _time.perf_counter() - t_start
        if elapsed > 0.0:
            _m_feeder_util.set(stats["feeder_busy_s"] / elapsed)
        done = stats["packed_steps"] + stats["single_steps"]
        if done:
            _m_packed_frac.set(stats["packed_steps"] / done)

    def _note_degraded(seq: int) -> None:
        """Per-step degraded accounting + the configurable abort: a step
        that had to synthesize more than ``max_degraded_frac`` of its
        lookups kills the stream instead of silently training on mostly-
        degraded embeddings."""
        if not _deg_tracking:
            return
        d, t = _router.take_degraded_window()
        frac = (d / t) if t else 0.0
        deg_fracs[seq] = frac
        _m_step_deg.set(frac)
        if frac > 0.0:
            stats["degraded_steps"] += 1
            stats["degraded_lookup_frac_max"] = max(
                stats["degraded_lookup_frac_max"], frac
            )
        if frac > _router.policy.max_degraded_frac:
            raise RuntimeError(
                f"step {seq}: degraded_lookup_frac {frac:.3f} exceeds the "
                f"abort threshold {_router.policy.max_degraded_frac:.3f}"
            )

    def feeder_prep():
        """Stage 1: host preprocessing + directory admit (fused with the
        native hazard-ledger probe) + PS probe."""
        seq = 0
        try:
            for batch in batches:
                if stop.is_set() or errors:
                    break
                if (
                    (job_mgr is not None or fence_callback is not None)
                    and snapshot_every
                    and seq > 0 and (start_step + seq) % snapshot_every == 0
                ):
                    # snapshot fence: pause BEFORE this step's prepare — a
                    # prepare would touch the directory and the PS (admits,
                    # checkout LRU) and the capture must see exactly the
                    # post-step-(seq-1) state. The marker rides the FIFO so
                    # the dispatcher reaches it only after every earlier
                    # step dispatched; fence_done unparks us post-capture.
                    fence_done.clear()
                    if not _put(prep_q, ("fence", start_step + seq)):
                        return
                    while not fence_done.wait(0.25):
                        if stop.is_set() or errors:
                            return
                if skip_steps and (start_step + seq) in skip_steps:
                    # quarantined step: consume the batch but never touch
                    # the directory/PS/device with it — seq still advances
                    # so fence cadence + journal ids match a run where the
                    # step never existed
                    record_event(
                        "health.quarantine_skip", step=start_step + seq
                    )
                    stats["quarantine_skips"] += 1
                    seq += 1
                    continue
                t_prep = _time.perf_counter()
                with stage_span("stream.prep"):
                    item = self.tier.prepare_batch(
                        batch, hazard_gate=gate, ring_alloc=ring_alloc,
                        pending_map=sign_map,
                    )
                with span("stream.ps_forward"):
                    ps_item = self._ps_forward(batch)
                try:
                    _note_degraded(seq)
                except BaseException:
                    # abort threshold tripped with a PS forward in hand:
                    # release its staleness slot before unwinding
                    if ps_item is not None:
                        self.worker.abort_gradient(ps_item[0])
                    raise
                if ps_item is not None:
                    _ref, embs, _counts, entries = ps_item
                    di0 = item[0]
                    di0["ps_emb"] = entries
                    layout0 = CacheLayout(
                        stacked=item[1].stacked,
                        ps=tuple(eb.name for eb in embs),
                    )
                    item = (di0, layout0) + item[2:]
                evict_meta = item[6]
                # evicted signs become hazard-gated HERE (admit time): a
                # later batch's probe must not trust the PS for them
                # until the write-back lands their rows. Map srcs are the
                # STANDING-RING rows reserved by ring_alloc above.
                if evict_meta:
                    with cv:
                        for gn, (ev, k, ring_pos) in evict_meta.items():
                            if ring_pos < 0:  # unwinding ring_alloc
                                continue
                            sign_map.insert_range(
                                ev[:k], ring_pos, seq, salt=salts[gn]
                            )
                stats["feeder_busy_s"] += _time.perf_counter() - t_prep
                if not _put(prep_q, (seq, item, ps_item)):
                    if ps_item is not None:
                        self.worker.abort_gradient(ps_item[0])
                    return
                seq += 1
        except BaseException as e:  # noqa: BLE001 — propagate to caller
            errors.append(e)
            with cv:
                cv.notify_all()
        finally:
            prep_q.put(SENTINEL)

    def _pipe_abort() -> bool:
        return stop.is_set() or bool(errors)

    def feeder_dp():
        """Stage 2 — the FEED stage of the stage graph: async host→device
        staging, and (pipeline_depth > 1) the feed-program dispatch
        itself, hoisted above the not-yet-dispatched dense stages of
        earlier steps. ``reserve_feed`` holds a feed back while its rows
        collide with an in-flight dense stage (bit-parity by row
        disjointness; stage_graph module docstring) or while the window
        is at depth (the staleness bound). Restore/PS/pre-init steps
        forward un-fed as window BARRIERS and keep the full in-order
        dispatch path."""
        try:
            while True:
                got = prep_q.get()
                if got is SENTINEL:
                    break
                if isinstance(got, tuple) and got[0] == "fence":
                    if not _put(staged_q, got):  # FIFO keeps fence ordering
                        return
                    continue
                seq, item, ps_item = got
                (di, layout, miss_aux, cold_aux, restore_aux, evict_aux,
                 evict_meta) = item
                # self.state races only benignly here: the main thread
                # sets it once (init_state at step 0); a stale None read
                # just routes this step through the in-order barrier path
                pipelinable = (
                    PIPE and not restore_aux and ps_item is None
                    and self.state is not None
                )
                hazard = None
                if pipelinable:
                    # hazard sets come from the HOST arrays, before the
                    # staging below turns them into device buffers
                    hazard = feed_hazard_info(
                        di, miss_aux, cold_aux, evict_aux,
                        {n: g.name for n, g in self.tier._slot_group.items()},
                    )
                with graph.lane("feed"):
                    with stage_span("stream.stage"):
                        di, miss_aux, cold_aux, evict_aux = self._stage(
                            di, miss_aux, cold_aux, evict_aux
                        )
                    # restore index arrays must commit like every other aux
                    # input: on a mesh an uncommitted put lands on one
                    # device and _restore_rows would see incompatible
                    # devices against the replicated tables. Payloads stay
                    # untouched — None means "the group's standing eviction
                    # ring", resolved by the main thread at dispatch.
                    rep = self._replicated()
                    put = (
                        jax.device_put if rep is None
                        else (lambda a: jax.device_put(a, rep))
                    )
                    restore_aux = {
                        gn: [(p, put(src), put(dst)) for (p, src, dst) in lst]
                        for gn, lst in restore_aux.items()
                    }
                feed_done = False
                feed_payload = None
                if pipelinable:
                    # stall time (reserve_feed) stays OUTSIDE the feed
                    # lane so stage_overlap_frac measures work, not waits
                    if not graph.reserve_feed(
                        seq, hazard[0], hazard[1], should_abort=_pipe_abort
                    ):
                        return
                    with graph.lane("feed"):
                        with span("stream.feed_dispatch", step=seq):
                            with self._state_lock:
                                feed_payload = self._apply_feed(
                                    miss_aux, cold_aux, evict_aux, evict_meta
                                )
                    feed_done = True
                elif PIPE:
                    if not graph.reserve_feed(
                        seq, None, None, should_abort=_pipe_abort,
                        barrier=True,
                    ):
                        if ps_item is not None:
                            self.worker.abort_gradient(ps_item[0])
                        return
                if not _put(
                    staged_q,
                    (seq, di, layout, miss_aux, cold_aux, restore_aux,
                     evict_aux, evict_meta, ps_item, feed_done, feed_payload),
                ):
                    if ps_item is not None:
                        self.worker.abort_gradient(ps_item[0])
                    return
        except BaseException as e:  # noqa: BLE001
            errors.append(e)
            with cv:
                cv.notify_all()
        finally:
            staged_q.put(SENTINEL)  # main's shutdown drain guarantees room

    # device→host transfers cost ~60 ms latency each regardless of size,
    # so the write-back batches many steps' payloads and fetches them
    # CONCURRENTLY (parallel transfers share the latency), then persists
    # to the PS. The gate never needs host data (device-side restore).
    FLUSH_STEPS = max(1, wb_flush_steps)

    def _flush_acc(acc) -> None:
        if not acc:
            return
        # the d2h return lane is the stage graph's third stage: eviction
        # write-backs and PS gradient returns ride it
        with graph.lane("psgrad", steps=len(acc)):
            with stage_span("stream.wb_flush", steps=len(acc)):
                _flush_acc_inner(acc)

    def _release_acc(acc) -> None:
        """ONE owner for the write-back accumulator's bookkeeping — used by
        the success path after the rows land AND by every failure path
        (round-5 finding: the queue-timeout early-flush failure leaked all
        three): token-conditionally remove the steps' hazard-ledger
        entries (a later re-evict of the same sign under a newer seq
        survives an older flush), advance the ring tails so the reserved
        spans free for reallocation, clear the accumulator, and wake the
        feeder (which may be parked on ring back-pressure)."""
        with cv:
            for seq, evict_meta, _p in acc:
                for gn, (ev, k, _ring_pos) in evict_meta.items():
                    sign_map.remove(ev[:k], seq, salt=salts[gn])
                    q = alloc_q.get(gn)
                    if q:  # tail advance frees the span for reallocation
                        tails[gn] = tails.get(gn, 0) + q.pop(0)
            cv.notify_all()
        acc.clear()

    def _flush_acc_inner(acc) -> None:
        pool = self._fetch_pool()
        fetches = []  # (seq, gname, k, device payload)
        for seq, evict_meta, evict_payload in acc:
            for gn, (ev, k, _ring_pos) in evict_meta.items():
                fetches.append((seq, gn, ev, k, evict_payload[gn]))

        def fetch(f):
            return np.asarray(f[4])[:f[3]].astype(np.float32)

        hosts = list(pool.map(fetch, fetches)) if pool else [fetch(f) for f in fetches]
        for (seq, gn, ev, k, _p), host in zip(fetches, hosts):
            g = next(gr for gr in self.tier.groups if gr.name == gn)
            self.tier._set_embedding(ev[:k], host[:k], dim=g.dim)
        _release_acc(acc)

    PS_BATCH = max(1, psgrad_batch)

    def _abort_ps_refs(items) -> None:
        """Best-effort staleness-slot release for queued psgrad items
        (shutdown paths): one place owns which tuple element holds the
        ref and the swallow-exceptions policy."""
        for it in items:
            try:
                self.worker.abort_gradient(it[1][0])
            except Exception:  # noqa: BLE001 — shutdown best-effort
                pass
        if isinstance(items, list):
            items.clear()

    def _flush_ps(ps_acc) -> None:
        if not ps_acc:
            return
        with graph.lane("psgrad", steps=len(ps_acc)):
            _flush_ps_inner(ps_acc)

    def _flush_ps_inner(ps_acc) -> None:
        """Fetch the accumulated steps' packed ps-grad outputs
        CONCURRENTLY (d2h latency is shared), then apply to the worker
        in step order. On an apply failure, not-yet-applied refs are
        aborted (the failing apply aborts its own ref itself).

        Ordering vs eviction write-backs: NONE needed — the constructor
        rejects configs where a feature group spans both tiers, so a PS
        gradient can never touch a sign an eviction wrote back; psgrad
        batches and eviction flushes proceed independently, each keeping
        its own concurrent-fetch batching."""
        pool = self._fetch_pool()

        def fetch(it):
            g = it[2]
            if isinstance(g, tuple):  # int8 wire: (q, scales)
                return tuple(np.asarray(x) for x in g)
            return np.asarray(g)

        hosts = (
            list(pool.map(fetch, ps_acc)) if pool
            else [fetch(it) for it in ps_acc]
        )
        k = 0
        try:
            for k, ((_tag, ps_item, _g, gstep), host) in enumerate(
                zip(ps_acc, hosts)
            ):
                self._apply_ps_grads(ps_item, host, journal_step=gstep)
        except BaseException:
            _abort_ps_refs(ps_acc[k + 1:])
            ps_acc.clear()
            raise
        ps_acc.clear()

    def writeback():
        acc: List = []
        ps_acc: List = []
        while True:
            try:
                item = wb_q.get(timeout=0.25)
            except _queue.Empty:
                # ring-full back-pressure: the feeder is parked waiting for
                # tail advance, and no new wb items can arrive until it
                # resumes — flush whatever is accumulated, however small
                if flush_now.is_set() and acc:
                    try:
                        flush_now.clear()
                        _flush_acc(acc)
                    except BaseException as e:  # noqa: BLE001
                        errors.append(e)
                        # same cleanup contract as the main-loop failure:
                        # ledger entries out, ring spans released, acc
                        # cleared — or the parked feeder deadlocks on
                        # spans nobody will ever free
                        _release_acc(acc)
                continue
            try:
                if item is SENTINEL:
                    _flush_acc(acc)
                    _flush_ps(ps_acc)
                    return
                if isinstance(item, tuple) and item[0] == "fence":
                    # drain marker: everything queued before it (FIFO) must
                    # land — eviction write-backs AND PS-tier gradient
                    # applies — before the capture reads the PS. The event
                    # is set even on failure (the error unwinds the main
                    # loop; an unset event would deadlock it instead).
                    try:
                        _flush_acc(acc)
                        _flush_ps(ps_acc)
                    finally:
                        item[1].set()
                    continue
                if isinstance(item, tuple) and item[0] == "psgrad":
                    ps_acc.append(item)
                    if len(ps_acc) >= PS_BATCH:
                        _flush_ps(ps_acc)
                    continue
                acc.append(item)
                if len(acc) >= FLUSH_STEPS or flush_now.is_set():
                    flush_now.clear()
                    _flush_acc(acc)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
                _abort_ps_refs(ps_acc)
                _release_acc(acc)
                if item is SENTINEL:
                    return

    feeder_t = threading.Thread(target=feeder_prep, daemon=True, name="cache-feeder")
    dp_t = threading.Thread(target=feeder_dp, daemon=True, name="cache-stager")
    wb_t = threading.Thread(target=writeback, daemon=True, name="cache-writeback")
    feeder_t.start()
    dp_t.start()
    wb_t.start()
    header = None
    label_shape = None

    def _abort_drained(got) -> None:
        # a drained-but-never-applied item may carry a PS-tier forward
        # ref: release its staleness slot + stashed layout. prep_q items
        # are (seq, item, ps_item) 3-tuples; staged items carry ps_item
        # at index 8 (the pipelined fields ride behind it)
        if not (isinstance(got, tuple) and len(got) >= 3):
            return
        ps_item = got[8] if len(got) >= 9 else got[-1]
        if (
            ps_item is not None
            and isinstance(ps_item, tuple) and len(ps_item) == 4
        ):
            try:
                self.worker.abort_gradient(ps_item[0])
            except Exception:  # noqa: BLE001 — shutdown best-effort
                pass

    K = stats["dispatch_k"]
    # a full pack retires as ONE dense stage: cap it at the window depth
    # so pack assembly never waits on feeds the window cannot admit
    K_eff = min(K, graph.depth) if PIPE else K
    pack: List = []  # staged hazard-free items awaiting a K-step dispatch
    pack_sig: List = [None]

    def _run_fence(gstep: int) -> None:
        """Snapshot fence, main-thread side: every step < gstep has
        dispatched (the marker rode the FIFO); drain the write-back
        thread, verify the hazard accounting empty, capture, unpark the
        feeder."""
        ev = threading.Event()
        wb_q.put(("fence", ev))
        while not ev.wait(0.25):
            if errors:
                break
        if not errors:
            with cv:
                undrained = {
                    gn: (heads.get(gn, 0), tails.get(gn, 0))
                    for gn in set(heads) | set(tails)
                    if heads.get(gn, 0) != tails.get(gn, 0)
                }
                occupancy = {
                    "resident_rows": {
                        g.name: len(self.tier.dirs[g.name])
                        for g in self.tier.groups
                    },
                    "ring": {
                        gn: {
                            "head": heads.get(gn, 0),
                            "tail": tails.get(gn, 0),
                            "rows": self.ring_rows(gn),
                        }
                        for gn in set(heads) | set(tails)
                    },
                    "pending_ledger_entries": len(sign_map),
                }
                if self.tier.feed_shards is not None:
                    # per-shard directory occupancy + cumulative walk time:
                    # a skewed shard here means the partition salt is
                    # fighting the key distribution
                    occupancy["feeder_shards"] = self.tier.feeder_shard_stats()
            if undrained:
                errors.append(RuntimeError(
                    f"fence at step {gstep}: eviction ring spans still in "
                    f"flight after the write-back drain: {undrained}"
                ))
            else:
                try:
                    if job_mgr is not None:
                        with span("stream.fence", step=gstep):
                            self._fence_capture(job_mgr, gstep, occupancy)
                    stats["fences"] = stats.get("fences", 0) + 1
                    record_event("stream.fence_commit", step=gstep)
                    n_mig = stats.get("migrations", 0)
                    _fence_migrate(gstep)
                    if stats.get("migrations", 0) != n_mig:
                        # the tier swap re-registered groups under the
                        # stage programs: fire the fence-point stage-graph
                        # rebuild hooks (window drained, feeder parked)
                        graph.rebuild(gstep)
                    if fence_callback is not None:
                        # topology-change window: feeder parked, write-back
                        # drained, rings verified empty, manifest (if any)
                        # committed — the callback may reshard the PS tier
                        # or swap routing before the stream resumes
                        try:
                            with span("stream.fence_callback", step=gstep):
                                fence_callback(gstep)
                        except Exception as cb_err:  # noqa: BLE001
                            # a control-plane failure must not take the
                            # training plane down with it: the fence's own
                            # invariants (drain, ledger, manifest) already
                            # held above, the callback's two-phase journal
                            # keeps ITS work resumable, and nothing here
                            # holds cv or leaves the ledger dirty — count
                            # loudly and resume the stream. BaseException
                            # (SimulatedCrash) still aborts like a kill.
                            stats["fence_callback_errors"] = (
                                stats.get("fence_callback_errors", 0) + 1
                            )
                            get_metrics().counter(
                                "persia_tpu_stream_fence_callback_errors",
                                "fence callbacks that raised (stream "
                                "continued; callback journal holds the "
                                "resume token)",
                            ).inc()
                            record_event(
                                "stream.fence_callback_error", step=gstep,
                                error=repr(cb_err),
                            )
                            logger.warning(
                                "fence callback failed at step %d (stream "
                                "continues): %s", gstep, cb_err,
                            )
                except BaseException as e:  # noqa: BLE001
                    errors.append(e)
        fence_done.set()

    def _fence_migrate(gstep: int) -> None:
        """Tier migration point: runs right after the fence's manifest
        commit, with the feeder parked and the write-back drained — the PS
        holds the only copy of every cached row, so a re-registration moves
        pure metadata. The hazard ledger (PendingSignMap) SURVIVES the
        re-registration (same native map; the ring-drain check above
        already proved heads == tails) — it must read empty here or an
        in-flight eviction would dangle across the tier swap."""
        if self._pending_migration is None and self._auto_tier is None:
            return
        with cv:
            n_pending = len(sign_map)
        if n_pending:
            raise RuntimeError(
                f"migration fence at step {gstep}: hazard ledger still "
                f"holds {n_pending} entries after the write-back drain"
            )
        if not self._maybe_migrate_at_fence(gstep):
            return
        with cv:
            # re-registration sanity: the drained ledger survived the tier
            # swap untouched
            if len(sign_map):
                raise RuntimeError(
                    "hazard ledger grew during a parked-feeder migration"
                )
            # fresh device rings were installed (ctx._ev_rings cleared):
            # restart the ring accounting so spans allocate against the
            # NEW ring heights from position 0
            heads.clear()
            tails.clear()
            alloc_q.clear()
            salts.clear()
            salts.update(self.tier._group_salt)
        stats["migrations"] = stats.get("migrations", 0) + 1

    def _post_step(seq, di, evict_meta, evict_payload):
        """Per-step bookkeeping shared by the single and packed paths."""
        nonlocal label_shape
        label_shape = di["labels"][0].shape
        self._global_step = start_step + seq + 1  # fences/journal continue here
        if evict_meta:
            # the ring rows were written device-side inside this step's
            # _apply_aux_ring; the wb thread only needs the per-step
            # payload array for its bounded d2h fetch
            wb_q.put((seq, evict_meta, evict_payload))
        if self.sparse_cfg.kind == OPTIMIZER_ADAM:
            # mirror the device's beta-power advance on the PS every
            # gradient batch (same contract as the sync train_step)
            for grp in self._cached_groups:
                self.tier.router.advance_batch_state(grp)
        _publish_live_stats()

    def _dispatch_one(item):
        nonlocal header
        (seq, di, layout, miss_aux, cold_aux, restore_aux, evict_aux,
         evict_meta, ps_item, feed_done, feed_payload) = item
        try:
            if self.state is None:
                self.init_state(jax.random.PRNGKey(0), di, layout)
            if feed_done:
                # FEED already dispatched from the stager thread: dense
                # stage only (the payload came back with the feed)
                with graph.lane("dense"):
                    with stage_span("stream.dispatch"):
                        with self._state_lock:
                            header = self._dispatch_dense(di, layout)
                evict_payload, ps_gpacked = feed_payload, None
                stats["pipelined_feeds"] += 1
            else:
                with graph.lane("dense"):
                    with stage_span("stream.dispatch"):
                        with self._state_lock:
                            header, evict_payload, ps_gpacked = self._dispatch(
                                di, layout, miss_aux, cold_aux, restore_aux,
                                evict_aux, evict_meta,
                            )
        except BaseException:
            # the in-hand item is already off the queue: the shutdown
            # drain in finally can't see it, so its staleness ref must
            # be released HERE or it leaks
            if ps_item is not None:
                try:
                    self.worker.abort_gradient(ps_item[0])
                except Exception:  # noqa: BLE001 — shutdown best-effort
                    pass
            raise
        if PIPE:
            graph.note_dense(seq)
        stats["single_steps"] += 1
        if ps_item is not None:
            # gradient return for PS-tier slots rides the write-back
            # thread (its d2h is off the dispatch path); FIFO order
            # keeps the worker's per-batch Adam advance in step order.
            # The global step rides along as the apply-journal step id.
            wb_q.put(("psgrad", ps_item, ps_gpacked, start_step + seq))
        _post_step(seq, di, evict_meta, evict_payload)
        sentinel_note(
            sentinel, sent_pending, start_step + seq, header,
            int(np.prod(di["labels"][0].shape)),
        )
        if on_metrics is not None:
            self._last_metrics = self._parse_header(
                np.asarray(header), label_shape
            )
            if _deg_tracking:
                # per-step degraded fraction rides the metrics dict (the
                # chaos suite asserts it is reported every step)
                self._last_metrics["degraded_lookup_frac"] = deg_fracs.pop(
                    seq, 0.0
                )
            on_metrics(self._last_metrics)

    def _item_sig(item):
        """Shape signature of a staged step. Packs are UNIFORM (every
        member shares one signature) so the K-step jit cache is keyed on
        a single step's shapes × K — the same cardinality as the
        single-step cache, not its K-th power."""
        (_seq, di, layout, miss_aux, cold_aux, _restore, evict_aux,
         evict_meta, _ps, _fd, _fp) = item

        def aux_sig(d):
            return tuple(sorted(
                (k, tuple(np.shape(x) for x in (v if isinstance(v, tuple) else (v,))))
                for k, v in d.items()
            ))

        return (
            layout,
            tuple(sorted((k, tuple(np.shape(v))) for k, v in di["stacked_rows"].items())),
            tuple(np.shape(x) for x in di["labels"]),
            aux_sig(miss_aux), aux_sig(cold_aux), aux_sig(evict_aux),
            tuple(sorted((gn, evict_meta[gn][2] >= 0) for gn in evict_meta)),
        )

    def _packable(item) -> bool:
        # hazard-free: no in-flight-eviction restore, no PS-tier forward
        # (its gradient return is per-step), and the state must exist
        return (
            self.state is not None
            and not item[5]          # restore_aux
            and item[8] is None      # ps_item
        )

    def _dense_sig(item):
        """Signature of a feed-done step's DENSE stage: the feed's aux is
        out of the program, so only the model-input shapes key the
        dense-only K-step jit cache."""
        (_seq, di, layout) = item[:3]
        return (
            layout,
            tuple(sorted(
                (k, tuple(np.shape(v)))
                for k, v in di["stacked_rows"].items()
            )),
            tuple(sorted(
                (k, tuple(np.shape(v)))
                for k, v in di.get("raw_rows", {}).items()
            )),
            tuple(np.shape(x) for x in di["labels"]),
            "stacked_scale" in di,
        )

    def _flush_pack_single():
        """Dispatch buffered items through the single-step path (partial
        pack, signature change, or shutdown): reuses already-compiled
        programs and preserves seq order."""
        for it in pack:
            _dispatch_one(it)
        pack.clear()

    def _dispatch_pack():
        nonlocal header
        with graph.lane("dense"):
            with stage_span("stream.dispatch_pack", k=len(pack)):
                headers, payloads = self._dispatch_packed(
                    [(it[1], it[2], it[3], it[4], it[6], it[7]) for it in pack]
                )
        header = headers[-1]
        stats["packs"] += 1
        stats["packed_steps"] += len(pack)
        for it, payload in zip(pack, payloads):
            _post_step(it[0], it[1], it[7], payload)
        for it, h in zip(pack, headers):
            sentinel_note(
                sentinel, sent_pending, start_step + it[0], h,
                int(np.prod(it[1]["labels"][0].shape)),
            )
        pack.clear()

    def _dispatch_pack_dense():
        """One dense-only K-step dispatch over feed-done items — a packed
        window is ONE dense stage of the graph."""
        nonlocal header
        with graph.lane("dense"):
            with stage_span("stream.dispatch_pack", k=len(pack)):
                with self._state_lock:
                    headers = self._dispatch_packed_dense(
                        [(it[1], it[2]) for it in pack]
                    )
        header = headers[-1]
        stats["packs"] += 1
        stats["packed_steps"] += len(pack)
        stats["pipelined_feeds"] += len(pack)
        graph.note_dense(pack[-1][0])
        for it in pack:
            _post_step(it[0], it[1], it[7], it[10])
        for it, h in zip(pack, headers):
            sentinel_note(
                sentinel, sent_pending, start_step + it[0], h,
                int(np.prod(it[1]["labels"][0].shape)),
            )
        pack.clear()

    try:
        while True:
            if pack:
                # never hold a partial pack while the pipeline idles: the
                # feeder may be parked on ring back-pressure waiting for
                # write-backs that only exist once these steps dispatch
                try:
                    item = staged_q.get(timeout=0.05)
                except _queue.Empty:
                    _flush_pack_single()
                    continue
            else:
                item = staged_q.get()
            if item is SENTINEL:
                _flush_pack_single()
                sentinel_drain(sentinel, sent_pending)
                if not errors:
                    # end-of-stream drain: every feed's dense retired
                    graph.drain_for_fence(self._global_step, reason="end")
                break
            if errors:
                # buffered pack items carry no PS refs (_packable) — drop
                pack.clear()
                _abort_drained(item)
                break
            if isinstance(item, tuple) and len(item) == 2 and item[0] == "fence":
                _flush_pack_single()
                # the sentinel must digest every pre-fence header BEFORE
                # the capture: a poisoned step must never become LAST_GOOD
                sentinel_drain(sentinel, sent_pending)
                # feeder parked + FIFO => the window is empty here; the
                # drain is asserted + recorded before the capture reads
                graph.drain_for_fence(item[1])
                _run_fence(item[1])
                continue
            if PIPE and K_eff > 1 and item[9]:  # feed_done: dense-only pack
                sig = _dense_sig(item)
                if pack and sig != pack_sig[0]:
                    _flush_pack_single()
                if not pack:
                    pack_sig[0] = sig
                pack.append(item)
                if len(pack) == K_eff:
                    _dispatch_pack_dense()
                continue
            if K > 1 and not PIPE and _packable(item):
                sig = _item_sig(item)
                if pack and sig != pack_sig[0]:
                    _flush_pack_single()
                if not pack:
                    pack_sig[0] = sig
                pack.append(item)
                if len(pack) == K:
                    _dispatch_pack()
                continue
            _flush_pack_single()
            _dispatch_one(item)
    finally:
        stats["wall_s"] = _time.perf_counter() - t_start
        _publish_live_stats()
        # per-tier layout + occupancy ride the stats dict so bench stream
        # records report EVERY tier, not just the active one's cache stats
        try:
            stats["tiers"] = {
                "cached_slots": sorted(
                    s for g in self.tier.groups for s in g.slots
                ),
                "ps_slots": sorted(self.tier.ps_slots),
                "resident_rows": {
                    g.name: len(self.tier.dirs[g.name])
                    for g in self.tier.groups
                },
                "capacity_rows": {
                    g.name: g.rows for g in self.tier.groups
                },
            }
            if self.tier.feed_shards is not None:
                stats["feeder"] = {
                    "feed_threads": self.tier.feed_threads,
                    "feed_shards": self.tier.feed_shards,
                    "shards": self.tier.feeder_shard_stats(),
                }
        except Exception:  # noqa: BLE001 — stats are best-effort at teardown
            pass
        # dense-plane sync accounting (grad_sync.dense_sync_wire_bytes):
        # the cached tier's dense half rides XLA's implicit psum, so the
        # record carries the modeled f32-allreduce cost — the honest
        # baseline the explicit block-int8 ring modes are priced against
        stats["sync_mode"] = self.sync_mode
        stats["dense_wire_bytes_per_step"] = self.dense_wire_bytes_per_step()
        stats.update(graph.stats(stats["wall_s"]))
        self._stream_stats = stats
        stop.set()
        graph.abort()  # unparks a stager blocked in reserve_feed
        with cv:
            cv.notify_all()

        # unblock stages stuck on full queues, then reap all threads
        while feeder_t.is_alive() or dp_t.is_alive():
            try:
                _abort_drained(prep_q.get_nowait())
            except _queue.Empty:
                pass
            try:
                _abort_drained(staged_q.get(timeout=0.1))
            except _queue.Empty:
                pass
        # final sweep AFTER the feeders died: on an error shutdown they
        # exit on their own, leaving queued items whose PS forward refs
        # would otherwise leak staleness slots
        for q in (prep_q, staged_q):
            while True:
                try:
                    _abort_drained(q.get_nowait())
                except _queue.Empty:
                    break
        wb_q.put(SENTINEL)
        feeder_t.join(timeout=300)
        dp_t.join(timeout=300)
        wb_t.join(timeout=300)
    if errors:
        raise RuntimeError("cached train pipeline failed") from errors[0]
    if header is not None:
        if on_metrics is not None or fetch_final:
            if on_metrics is None:
                self._last_metrics = self._parse_header(
                    np.asarray(header), label_shape
                )
            self._last_header_dev = None  # this stream is the freshest
        else:
            jax.block_until_ready(header)  # completion, no transfer
            self._last_header_dev = (header, label_shape)
            return None
    return self._last_metrics

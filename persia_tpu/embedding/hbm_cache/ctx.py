"""CachedTrainCtx: the TrainCtx-shaped user API of the HBM cache tier
(sync pipelined steps; the async stream lives in stream.py)."""


from __future__ import annotations

import ctypes
import os
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from persia_tpu.config import EmbeddingConfig
from persia_tpu.data import PersiaBatch
from persia_tpu.embedding.optim import OPTIMIZER_ADAM, OptimizerConfig
from persia_tpu.embedding.worker import (
    ProcessedBatch,
    ProcessedSlot,
    ShardedLookup,
    preprocess_batch,
)
from persia_tpu.logger import get_default_logger
from persia_tpu.utils import round_up_pow2 as _round_up_pow2
from persia_tpu.metrics import get_metrics
from persia_tpu.ops.sparse_update import sparse_update
from persia_tpu.tracing import span

logger = get_default_logger("persia_tpu.hbm_cache")

# ------------------------------------------------------------------ ctypes


from persia_tpu.embedding.hbm_cache.directory import CacheDirectory  # noqa: F401
from persia_tpu.embedding.hbm_cache.groups import (  # noqa: F401
    CacheLayout,
    CachedTrainState,
    _apply_aux,
    _apply_aux_ring,
    _bucket,
    _lazy_pool,
    _model_emb_from_gathered,
    _restore_rows,
    _state_init_consts,
    init_cached_tables,
)
from persia_tpu.embedding.hbm_cache.step import (  # noqa: F401
    build_cached_eval_step,
    build_cached_train_step,
)
from persia_tpu.embedding.hbm_cache.tier import (  # noqa: F401
    CachedEmbeddingTier,
    _position_index,
)
from persia_tpu.embedding.hbm_cache.stream import run_train_stream

class CachedTrainCtx:
    """Training context for the HBM-cached hybrid tier — the TrainCtx-shaped
    API (train_step / eval_batch / dump_checkpoint / load_checkpoint) with
    on-device sparse updates and write-back tier migration.

    Pipelined by default: ``train_step`` dispatches the jitted step and
    defers the previous step's eviction write-back + metric fetch, so host
    preprocessing for step N+1 overlaps device compute of step N (the
    reference hides PS latency the same way with concurrent lookup workers,
    forward.rs:640-779). Call with ``fetch_metrics=False`` to keep the
    loop free of device syncs; ``drain()``/``last_metrics()`` at the end.
    """

    def __init__(
        self,
        model,
        dense_optimizer,
        embedding_optimizer,
        worker,
        embedding_config: EmbeddingConfig,
        cache_rows: "int | Dict[int, int]" = 1 << 20,
        loss_fn=None,
        table_dtype=jnp.float32,
        init_seed: Optional[int] = None,
        mesh=None,
        wb_wire_dtype: str = "float32",
        ps_slots: Sequence[str] = (),
        admit_touches: int = 1,
        aux_wire_dtype: str = "float32",
        ps_wire_dtype: str = "float32",
        dynamic_loss_scale: bool = False,
        loss_scale_init: float = float(2 ** 15),
        loss_scale_growth_interval: int = 2000,
        loss_scale_max: float = float(2 ** 24),
        wb_ring_rows: int = 1 << 20,
        health_probe: Optional[bool] = None,
        health_clip_norm: Optional[float] = None,
        health_scrub_at_fence: Optional[bool] = None,
        feed_threads: Optional[int] = None,
        feed_shards: Optional[int] = None,
    ):
        self.model = model
        self.dense_optimizer = dense_optimizer
        self.sparse_cfg = embedding_optimizer.config
        self.worker = worker
        self.embedding_config = embedding_config
        # DP mesh: batch-dim inputs shard over "data", cache pools + aux
        # scatters replicate; XLA reduces the sparse scatter deltas across
        # replicas exactly like replicated dense params (the capacity tier's
        # multi-chip story — the PS side is already sharded host-side)
        self.mesh = mesh
        if wb_wire_dtype not in ("float32", "bfloat16"):
            raise ValueError(f"wb_wire_dtype must be float32/bfloat16, got {wb_wire_dtype!r}")
        # bf16 eviction wire halves the d2h bytes that bound the eviction
        # steady state (the reference ships f16 wires); default stays f32
        # because the cached tier is otherwise bit-exact vs the pure-PS path
        self._wb_bf16 = wb_wire_dtype == "bfloat16"
        # standing per-group DEVICE eviction rings (stream restores gather
        # from here in ONE program per group; see _apply_aux_ring). Sized in
        # PADDED rows; the stream's allocator back-pressures when the
        # in-flight window would overrun.
        self.wb_ring_rows = int(wb_ring_rows)
        self._ev_rings: Dict[str, jnp.ndarray] = {}
        # live-migration bookkeeping (tiering): the constructor args a
        # fence-point re-registration rebuilds the tier/step from, the
        # explicit ps exclude set as it evolves, and the migration hooks
        self.cache_rows = cache_rows
        self._admit_touches = int(admit_touches)
        self._aux_wire_dtype = aux_wire_dtype
        self._loss_fn = loss_fn
        self._ps_wire_dtype = ps_wire_dtype
        self._ls_growth_interval = loss_scale_growth_interval
        self._ls_max = loss_scale_max
        self._ps_exclude: Set[str] = set(ps_slots)
        self._auto_tier = None
        self._pending_migration: Optional[Dict] = None
        # sharded feeder (round 14): feed_threads sizes the native walker
        # pool (None -> PERSIA_FEED_THREADS, pure throughput knob);
        # feed_shards pins the directory partition count (None ->
        # PERSIA_FEED_SHARDS, else 8 when threads > 1). The tier resolves
        # the defaults; the RESOLVED values are remembered here so the
        # fence-point migration rebuild reconstructs the same partition.
        self.tier = CachedEmbeddingTier(
            worker, self.sparse_cfg, cache_rows, embedding_config,
            init_seed=init_seed, ps_slots=ps_slots,
            admit_touches=admit_touches, aux_wire_dtype=aux_wire_dtype,
            feed_threads=feed_threads, feed_shards=feed_shards,
        )
        self._feed_threads = self.tier.feed_threads
        self._feed_shards = self.tier.feed_shards
        # feature groups containing cached slots: the PS-side Adam beta
        # powers of EVERY one of them mirror the device's per-step advance
        self._cached_groups = tuple(sorted({
            embedding_config.group_of(s)
            for g in self.tier.groups for s in g.slots
        }))
        self._state_consts = _state_init_consts(self.sparse_cfg)
        if ps_wire_dtype not in ("float32", "bfloat16", "int8"):
            raise ValueError(
                f"ps_wire_dtype must be float32/bfloat16/int8, got {ps_wire_dtype!r}"
            )
        self.dynamic_loss_scale = dynamic_loss_scale
        self._loss_scale_init = loss_scale_init
        # "int8" = bytegrad-style absmax quantization of the GRADIENT-RETURN
        # wire with a device-resident error-feedback residual (see
        # build_cached_train_step); the forward checkout wire stays bf16
        # (embedding VALUES do not tolerate int8 the way EF'd gradients do)
        self._ps_int8 = ps_wire_dtype == "int8"
        self._ps_residual: Dict[int, jnp.ndarray] = {}
        # numerical-health layer (persia_tpu/health): the on-device probe
        # tail + finite gate and the fence-point PS row scrubber. Defaults
        # follow PERSIA_HEALTH=1; explicit flags override the env.
        from persia_tpu.health import health_enabled

        self._health_probe = (
            health_enabled() if health_probe is None else bool(health_probe)
        )
        self._health_clip_norm = health_clip_norm
        self._health_scrub = (
            self._health_probe
            if health_scrub_at_fence is None
            else bool(health_scrub_at_fence)
        )
        self._step = build_cached_train_step(
            model, dense_optimizer, self.sparse_cfg, self.tier.groups,
            loss_fn=loss_fn,
            ps_grad_wire=ps_wire_dtype,
            dynamic_loss_scale=dynamic_loss_scale,
            growth_interval=loss_scale_growth_interval,
            max_scale=loss_scale_max,
            sentinel_probe=self._health_probe,
            guard_clip_norm=health_clip_norm,
        )
        self._eval = build_cached_eval_step(model, self.tier.groups)
        # forward-side ps wire: stage PS-tier entries in the same reduced
        # dtype the gradients return in (host->device rows are the other
        # half of the PS tier's link bill); int8 grad wire keeps bf16 here
        self._ps_stage_dtype = (
            np.dtype("bfloat16")
            if ps_wire_dtype in ("bfloat16", "int8") else None
        )
        self.table_dtype = table_dtype
        self.state: Optional[CachedTrainState] = None
        # concurrent device->host gradient/eviction fetch pool for the
        # stream's write-back thread: each fetch pays the full link
        # round-trip, so batched fetches MUST overlap (a serial loop is
        # latency x count)
        self._fetch_pool_obj = None
        # deferred write-back: (evict_meta, device payload, device header,
        # label shape) of the most recent dispatched step
        self._pending = None
        self._pending_signs: Set[int] = set()
        self._last_metrics: Optional[Dict] = None
        # (device header, label shape) of a fetch_final=False stream's last
        # step — materialized lazily by last_metrics()
        self._last_header_dev = None
        # per-group 0-row stand-ins for absent aux pieces (_group_empties)
        self._empties: Dict[str, Dict[str, jnp.ndarray]] = {}
        # K-step fused dispatch program (lazy; see _dispatch_packed) and
        # the most recent train_stream's dispatch/feeder accounting
        self._kstep_jit = None
        self._stream_stats: Optional[Dict] = None
        # stage-graph pipelining (parallel/stage_graph.py): every
        # read-modify-replace of ``state``/``_ev_rings`` holds _state_lock
        # once train_stream dispatches feed programs from its stager
        # thread (pipeline_depth > 1); the sync path is single-threaded
        # and pays only an uncontended acquire. _stage_rebuild_hooks are
        # copied onto each stream's StageGraph and fire at a drained
        # fence after a tier migration (StageGraph.rebuild).
        self._state_lock = threading.Lock()
        self._stage_graph = None
        self._stage_rebuild_hooks: List[Callable[[int], None]] = []
        # crash-consistent job state (persia_tpu.jobstate): manifest epoch
        # of the last committed fence (journal-id namespace), the global
        # step counter fences/journal ids run on, and a deferred resume
        # blob applied when init_state builds the state template
        self._job_epoch: Optional[int] = None
        self._global_step: int = 0
        self._resume_state_bytes: Optional[bytes] = None
        self.last_resume_info: Optional[Dict] = None

    def __enter__(self):
        self.worker.register_optimizer(self.sparse_cfg)
        return self

    def __exit__(self, *exc):
        self.drain()
        return False

    # ------------------------------------------------------------- lifecycle

    def init_state(self, rng, sample_inputs: Dict, layout: CacheLayout) -> CachedTrainState:
        import optax

        tables, emb_state = init_cached_tables(
            self.tier.groups, self.sparse_cfg, dtype=self.table_dtype
        )
        by_name = {g.name: g for g in self.tier.groups}
        stacked_gathered = {
            gname: tables[gname][jnp.asarray(rows)]
            for gname, rows in sample_inputs["stacked_rows"].items()
        }
        raw_gathered = {
            name: tables[self.tier._slot_group[name].name][jnp.asarray(rows)]
            for name, rows in sample_inputs["raw_rows"].items()
        }
        ps_model_inputs = None
        if sample_inputs.get("ps_emb"):
            from persia_tpu.parallel.train_step import (
                _embedding_model_inputs, _split_emb,
            )

            ps_diff, ps_static = _split_emb(sample_inputs["ps_emb"])
            ps_model_inputs = _embedding_model_inputs(
                [jnp.asarray(d) for d in ps_diff], ps_static
            )
        model_emb = _model_emb_from_gathered(
            self.tier.groups,
            {
                k: (
                    {kk: jnp.asarray(vv) for kk, vv in v.items()}
                    if isinstance(v, dict) else v
                )
                for k, v in sample_inputs.items()
            },
            layout,
            stacked_gathered,
            raw_gathered,
            pad_row=lambda gname: by_name[gname].rows,
            ps_model_inputs=ps_model_inputs,
        )
        variables = self.model.init(
            rng, sample_inputs["dense"], model_emb, train=False
        )
        params = variables["params"]
        ls = None
        if self.dynamic_loss_scale:
            from persia_tpu.parallel.train_step import LossScaleState

            ls = LossScaleState(
                scale=jnp.asarray(self._loss_scale_init, jnp.float32),
                good_steps=jnp.zeros((), jnp.int32),
            )
        self.state = CachedTrainState(
            params=params,
            batch_stats=variables.get("batch_stats", {}),
            opt_state=self.dense_optimizer.init(params),
            tables=tables,
            emb_state=emb_state,
            emb_batch_state=jnp.ones((2,), dtype=jnp.float32),
            step=jnp.zeros((), dtype=jnp.int32),
            loss_scale=ls,
        )
        if self._resume_state_bytes is not None:
            # deferred resume (persia_tpu.jobstate): the manifest captured
            # the state at a post-flush fence (cold pools), so overlaying
            # it on the fresh template reproduces the fence exactly
            import flax.serialization

            self.state = flax.serialization.from_bytes(
                self.state, self._resume_state_bytes
            )
            self._resume_state_bytes = None
        rep = self._replicated()
        if rep is not None:
            self.state = jax.tree.map(
                lambda x: jax.device_put(x, rep), self.state
            )
        return self.state

    # ------------------------------------------------------------ train/eval

    def _sync_hazard_gate(self, gname: str, miss_signs: np.ndarray):
        if self._pending_signs and not self._pending_signs.isdisjoint(
            miss_signs.tolist()
        ):
            self._land_pending()  # after landing, the PS probe sees them warm
        return None

    def _fetch_pool(self):
        """Pool for CONCURRENT device→host fetches in the stream's
        write-back thread (each fetch pays a full link round-trip)."""
        self._fetch_pool_obj = _lazy_pool(self._fetch_pool_obj, "cache-fetch")
        return self._fetch_pool_obj

    def _replicated(self):
        if self.mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P())

    def _stage(self, device_inputs, miss_aux, cold_aux, evict_aux):
        """Host→device staging with mesh shardings when a DP mesh is set:
        batch-dim leaves shard over ``data`` (dense/labels (B,·); stacked
        row/scale matrices on their middle axis), aux scatters replicate
        (they address the replicated cache pools).

        Every input here is a FRESH per-step host buffer (_BufRing hands
        out new arrays; see its docstring for the reuse-race history), so
        the asynchronous ``device_put``s need no completion barrier — the
        buffers stay alive via the queue items until consumed, and nothing
        rewrites them. A barrier here costs ~180 ms/step on a
        remote-attached chip (measured), so do not add one back without
        re-proving the buffers' lifetime story."""
        if self.mesh is None:
            return (
                jax.device_put(device_inputs), jax.device_put(miss_aux),
                jax.device_put(cold_aux), jax.device_put(evict_aux),
            )
        from jax.sharding import NamedSharding, PartitionSpec as P

        bsh = NamedSharding(self.mesh, P("data"))
        mid = NamedSharding(self.mesh, P(None, "data"))
        rep = self._replicated()
        di = {
            "dense": [jax.device_put(x, bsh) for x in device_inputs["dense"]],
            "labels": [jax.device_put(x, bsh) for x in device_inputs["labels"]],
            "stacked_rows": {
                k: jax.device_put(v, mid)
                for k, v in device_inputs["stacked_rows"].items()
            },
            "raw_rows": {
                k: jax.device_put(v, bsh)
                for k, v in device_inputs["raw_rows"].items()
            },
        }
        if "stacked_scale" in device_inputs:
            di["stacked_scale"] = {
                k: jax.device_put(v, mid)
                for k, v in device_inputs["stacked_scale"].items()
            }
        if "ps_emb" in device_inputs:
            ps = []
            for e in device_inputs["ps_emb"]:
                if "pooled" in e:
                    ps.append({"pooled": jax.device_put(e["pooled"], bsh)})
                elif "pool_index" in e:  # device-pooled sum slot
                    entry = {
                        "distinct": jax.device_put(e["distinct"], rep),
                        "pool_index": jax.device_put(e["pool_index"], bsh),
                    }
                    if "pool_counts" in e:
                        entry["pool_counts"] = jax.device_put(e["pool_counts"], bsh)
                    ps.append(entry)
                else:
                    ps.append({
                        "distinct": jax.device_put(e["distinct"], rep),
                        "index": jax.device_put(e["index"], bsh),
                        "mask": jax.device_put(e["mask"], bsh),
                    })
            di["ps_emb"] = ps
        return (
            di,
            jax.device_put(miss_aux, rep),
            jax.device_put(cold_aux, rep),
            jax.device_put(evict_aux, rep),
        )

    def _group_empties(self, gname: str):
        """Cached 0-row device arrays standing in for absent aux pieces, so
        the fused ``_apply_aux`` keeps ONE dispatch per touched group."""
        em = self._empties.get(gname)
        if em is None:
            g = next(gr for gr in self.tier.groups if gr.name == gname)
            rep = self._replicated()
            put = (
                jax.device_put if rep is None
                else (lambda a: jax.device_put(a, rep))
            )
            aux_dt = self.tier.aux_np_dtype
            em = self._empties[gname] = {
                "rows": put(np.empty(0, dtype=np.int32)),
                "entries": put(
                    np.empty((0, g.dim + g.state_dim), dtype=aux_dt)
                ),
                "emb": put(np.empty((0, g.dim), dtype=aux_dt)),
            }
        return em

    def ring_rows(self, gname: str) -> int:
        """Standing-ring height for a group: per-step evictions are bounded
        by the group's own cache rows, so a ring a couple of cache-sizes
        tall covers any realistic in-flight window without allocating the
        global ceiling for tiny caches (a 100-row test cache does not need
        a 2^20-row ring)."""
        g = next(gr for gr in self.tier.groups if gr.name == gname)
        return min(self.wb_ring_rows, max(4096, 2 * g.rows))

    def _ev_ring(self, gname: str) -> jnp.ndarray:
        """The group's standing eviction ring (lazy; replicated on a mesh)."""
        ring = self._ev_rings.get(gname)
        if ring is None:
            g = next(gr for gr in self.tier.groups if gr.name == gname)
            dt = jnp.bfloat16 if self._wb_bf16 else jnp.float32
            ring = jnp.zeros(
                (self.ring_rows(gname), g.dim + g.state_dim), dtype=dt
            )
            rep = self._replicated()
            ring = jax.device_put(ring) if rep is None else jax.device_put(ring, rep)
            self._ev_rings[gname] = ring
        return ring

    def _apply_feed(self, miss_aux, cold_aux, evict_aux, evict_meta=None):
        """The FEED stage: ONE fused aux program per touched group
        (evict-payload read → ring write → warm scatter → cold scatter;
        ``_apply_aux``/``_apply_aux_ring``). Returns the per-group eviction
        payloads for the write-back thread's bounded d2h fetch.

        In the pipelined stream this runs on the STAGER thread under
        ``_state_lock``, up to ``pipeline_depth - 1`` steps ahead of its
        own dense stage — sound because the stream only hoists a feed
        whose rows are disjoint from every in-flight dense stage's trained
        rows (stage_graph.feed_hazard_info), and scatter/gather chains
        over disjoint rows commute bitwise."""
        evict_payload = {}
        touched = set(miss_aux) | set(cold_aux) | set(evict_aux)
        if not touched:
            return evict_payload
        tables = dict(self.state.tables)
        emb_state = dict(self.state.emb_state)
        with span("ctx.apply_aux", groups=len(touched)):
            for gname in sorted(touched):
                em = self._group_empties(gname)
                ev_rows = evict_aux.get(gname, em["rows"])
                m_rows, m_entries = miss_aux.get(
                    gname, (em["rows"], em["entries"])
                )
                c_rows, c_emb = cold_aux.get(gname, (em["rows"], em["emb"]))
                ring_pos = -1
                if evict_meta and gname in evict_meta:
                    ring_pos = evict_meta[gname][2]
                if ring_pos >= 0:
                    (tables[gname], emb_state[gname],
                     self._ev_rings[gname], payload) = _apply_aux_ring(
                        tables[gname], emb_state[gname],
                        self._ev_ring(gname), jnp.int32(ring_pos),
                        ev_rows, m_rows, m_entries, c_rows, c_emb,
                        self._state_consts, self._wb_bf16,
                    )
                else:
                    tables[gname], emb_state[gname], payload = _apply_aux(
                        tables[gname], emb_state[gname], ev_rows,
                        m_rows, m_entries, c_rows, c_emb,
                        self._state_consts, self._wb_bf16,
                    )
                if gname in evict_aux:
                    evict_payload[gname] = payload
        self.state = self.state.replace(tables=tables, emb_state=emb_state)
        return evict_payload

    def _dispatch(
        self, device_inputs, layout, miss_aux, cold_aux, restore_aux,
        evict_aux, evict_meta=None,
    ):
        """Dispatch the per-step device programs in order: the FEED stage
        (``_apply_feed``) + in-flight restores + the main step. Inputs must
        already be device arrays."""
        evict_payload = self._apply_feed(
            miss_aux, cold_aux, evict_aux, evict_meta
        )
        if restore_aux:
            tables = dict(self.state.tables)
            emb_state = dict(self.state.emb_state)
            n_restores = sum(len(r) for r in restore_aux.values())
            with span("ctx.restores", n=n_restores):
                for gname, restores in restore_aux.items():
                    for payload, src_idx, dst_rows in restores:
                        if payload is None:
                            # stream gate: gather from the group's standing
                            # eviction ring — the producing steps dispatch
                            # before this one (seq order), so their
                            # dynamic_update_slice writes precede this read
                            # in device program order
                            payload = self._ev_ring(gname)
                        tables[gname], emb_state[gname] = _restore_rows(
                            tables[gname], emb_state[gname], payload,
                            src_idx, dst_rows,
                        )
            self.state = self.state.replace(tables=tables, emb_state=emb_state)
        if self._ps_int8 and "ps_emb" in device_inputs:
            # thread the device-resident error-feedback residual through
            # the step; keyed by flat length so a bucketed-shape change
            # resets it to zeros (positions mean different signs then)
            total = 0
            for e in device_inputs["ps_emb"]:
                shape = (
                    e["pooled"].shape if "pooled" in e
                    else e["distinct"].shape
                )
                total += int(np.prod(shape))
            res = self._ps_residual.get(total)
            if res is None:
                z = np.zeros((total,), np.float32)
                rep = self._replicated()
                res = (
                    jax.device_put(z) if rep is None
                    else jax.device_put(z, rep)
                )
            device_inputs = dict(device_inputs)
            device_inputs["ps_gres"] = res
        with span("ctx.main_step"):
            self.state, header, ps_gpacked = self._step(
                self.state, device_inputs, layout
            )
        if self._ps_int8 and isinstance(ps_gpacked, tuple):
            q, scales, new_res = ps_gpacked
            if new_res.shape[0]:
                self._ps_residual[new_res.shape[0]] = new_res
            ps_gpacked = (q, scales)
        return header, evict_payload, ps_gpacked

    # ------------------------------------------------- K-step fused dispatch

    def _kstep_fn(self):
        """The jitted K-step program: for each packed step, apply its aux
        scatters (evict-payload read → ring write → warm/cold scatters),
        then run the main train step — K steps, ONE dispatch. Ordering
        inside the trace is exactly the single-step path's: step i's aux
        reads the post-step-(i-1) tables, so packing changes no math
        (tests pin stream-vs-sync bit parity through packs). Restores are
        excluded by the stream's packing predicate, which is what makes
        the unroll safe without any in-window hazard analysis."""
        if self._kstep_jit is None:
            def run(state, rings, steps, layout):
                rings = dict(rings)
                headers, payloads = [], []
                for di, aux in steps:
                    if aux:
                        tables = dict(state.tables)
                        emb_state = dict(state.emb_state)
                    step_payloads = {}
                    for gname in sorted(aux):
                        a = aux[gname]
                        ev_rows = a["ev"]
                        m_rows, m_entries = a["miss"]
                        c_rows, c_emb = a["cold"]
                        if "ring_pos" in a:
                            (tables[gname], emb_state[gname], rings[gname],
                             payload) = _apply_aux_ring(
                                tables[gname], emb_state[gname],
                                rings[gname], a["ring_pos"],
                                ev_rows, m_rows, m_entries, c_rows, c_emb,
                                self._state_consts, self._wb_bf16,
                            )
                        else:
                            tables[gname], emb_state[gname], payload = _apply_aux(
                                tables[gname], emb_state[gname], ev_rows,
                                m_rows, m_entries, c_rows, c_emb,
                                self._state_consts, self._wb_bf16,
                            )
                        step_payloads[gname] = payload
                    if aux:
                        state = state.replace(
                            tables=tables, emb_state=emb_state
                        )
                    state, header, _ps = self._step(state, di, layout)
                    headers.append(header)
                    payloads.append(step_payloads)
                return state, rings, headers, payloads

            self._kstep_jit = jax.jit(
                run, static_argnums=(3,), donate_argnums=(0, 1)
            )
        return self._kstep_jit

    def _dispatch_packed(self, items):
        """Dispatch K staged steps as one fused program. ``items``:
        ``[(di, layout, miss_aux, cold_aux, evict_aux, evict_meta), ...]``
        — already device-staged, hazard-free (no restore_aux, no ps_emb),
        one shared layout. Returns ``(headers, payloads)``: the per-step
        headers and per-step ``{group: eviction payload}`` dicts for the
        write-back thread's bounded d2h fetches."""
        layout = items[0][1]
        steps = []
        ring_names = set()
        for di, _lay, miss_aux, cold_aux, evict_aux, evict_meta in items:
            aux = {}
            for gname in sorted(set(miss_aux) | set(cold_aux) | set(evict_aux)):
                em = self._group_empties(gname)
                entry = {
                    "ev": evict_aux.get(gname, em["rows"]),
                    "miss": miss_aux.get(gname, (em["rows"], em["entries"])),
                    "cold": cold_aux.get(gname, (em["rows"], em["emb"])),
                }
                ring_pos = -1
                if evict_meta and gname in evict_meta:
                    ring_pos = evict_meta[gname][2]
                if ring_pos >= 0:
                    # traced scalar (not static): ring positions change
                    # every step and must not key the jit cache
                    entry["ring_pos"] = np.int32(ring_pos)
                    ring_names.add(gname)
                aux[gname] = entry
            steps.append((di, aux))
        rings = {gn: self._ev_ring(gn) for gn in sorted(ring_names)}
        state, rings_out, headers, payloads = self._kstep_fn()(
            self.state, rings, tuple(steps), layout
        )
        self.state = state
        self._ev_rings.update(rings_out)
        return headers, payloads

    # -------------------------------------------- pipelined (dense-only)

    def _dispatch_dense(self, device_inputs, layout):
        """DENSE stage of a pipelined step: the feed was already
        dispatched from the stager thread (``_apply_feed``), so only the
        main train program runs here. Caller holds ``_state_lock``."""
        with span("ctx.main_step"):
            self.state, header, _ps = self._step(
                self.state, device_inputs, layout
            )
        return header

    def _dispatch_packed_dense(self, items):
        """Dispatch K feed-done steps as ONE dense-only K-step program.
        Reuses ``_kstep_fn`` with empty per-step aux — its ``if aux:``
        branch folds away at trace time, so the packed window carries no
        aux leaves in the call pytree and no new program shape beyond the
        (one-time) dense-only trace. ``items``: ``[(di, layout), ...]``
        with one shared layout. Caller holds ``_state_lock``."""
        layout = items[0][1]
        steps = tuple((di, {}) for di, _lay in items)
        state, _rings, headers, _payloads = self._kstep_fn()(
            self.state, {}, steps, layout
        )
        self.state = state
        return headers

    def stream_stats(self) -> Optional[Dict]:
        """Dispatch/feeder accounting of the most recent ``train_stream``:
        ``dispatch_k``, ``packs``, ``packed_steps``, ``single_steps``,
        ``feeder_busy_s``, ``wall_s``, plus the dense-plane sync record
        (``sync_mode``, ``dense_wire_bytes_per_step``) — the artifact
        fields bench.py commits so hot-loop regressions are visible from
        the JSON alone."""
        return self._stream_stats

    @property
    def sync_mode(self) -> str:
        """Dense-plane sync label for records: the cached tier's dense half
        rides XLA's implicit psum on a DP mesh ("implicit-psum"), or no
        collective at all on one device ("local"). The explicit quantized /
        sharded modes live on the hybrid TrainCtx (``dense_sync=``); this
        property keeps the vocabulary shared so bench rows compare."""
        if self.mesh is not None and int(self.mesh.shape["data"]) > 1:
            return "implicit-psum"
        return "local"

    def dense_wire_bytes_per_step(self) -> int:
        """Modeled per-replica dense collective bytes/step
        (grad_sync.dense_sync_wire_bytes over the live dense param count);
        0 before state init or off-mesh."""
        if self.state is None or self.mesh is None:
            return 0
        from persia_tpu.parallel.grad_sync import (
            dense_param_count,
            dense_sync_wire_bytes,
        )

        return dense_sync_wire_bytes(
            self.sync_mode,
            dense_param_count(self.state.params),
            int(self.mesh.shape["data"]),
        )

    def _ps_forward(self, batch: PersiaBatch):
        """Forward the PS-tier slot subset through the worker's forward-ref
        machinery. Returns (ref, emb_batches, counts, entries) or None when
        the batch carries no ps slots. The ref's staleness slot is ALWAYS
        released on failure after the forward — any exception past
        put_forward_ids aborts before propagating."""
        if not self.tier.ps_slots:
            return None
        ps_feats = [
            f for f in batch.id_type_features if f.name in self.tier.ps_slots
        ]
        if not ps_feats:
            return None
        from persia_tpu.ctx import stage_embeddings

        ref = self.worker.put_forward_ids(PersiaBatch(ps_feats, requires_grad=False))
        try:
            embs = self.worker.forward_batch_id(ref, train=True)
            entries, counts = stage_embeddings(embs, dtype=self._ps_stage_dtype)
        except BaseException:
            self.worker.abort_gradient(ref)
            raise
        return ref, embs, counts, entries

    def _apply_ps_grads(self, ps_item, ps_gpacked, journal_step=None) -> None:
        """Unpack the step's packed ps-slot gradients (one layout
        convention: unpack_step_grads) and return them to the worker; the
        ref is released either by the update or by an abort on failure.
        ``journal_step`` tags the apply for the PS apply-journal when the
        ctx runs under a job-state manager (exactly-once resume)."""
        from persia_tpu.parallel.train_step import unpack_step_grads

        jid = None
        if journal_step is not None and self._job_epoch is not None:
            from persia_tpu.jobstate import make_journal_id

            jid = make_journal_id(self._job_epoch, journal_step)
        ref, embs, counts, entries = ps_item
        try:
            if isinstance(ps_gpacked, tuple):
                # int8 wire: (q int8, scales f32 per slot [+finite]); grads
                # were unscaled on device, so scale_factor stays 1.0
                from persia_tpu.parallel.grad_sync import dequantize_int8_np

                q = np.asarray(ps_gpacked[0])
                scales = np.asarray(ps_gpacked[1]).astype(np.float32)
                scale_factor = 1.0
                if self.dynamic_loss_scale or self._health_probe:
                    if not scales[-1] > 0.5:  # overflow/non-finite: skip-step
                        self.worker.abort_gradient(ref)
                        return
                    scales = scales[:-1]
                grads = [
                    dequantize_int8_np(g, s)
                    for g, s in zip(
                        unpack_step_grads(q, {"emb": entries}), scales
                    )
                ]
            else:
                gp = np.asarray(ps_gpacked)
                if gp.dtype != np.float32:  # bf16 ps-grad wire
                    gp = gp.astype(np.float32)
                scale_factor = 1.0
                if self.dynamic_loss_scale or self._health_probe:
                    # buffer tail = [scale | finite] (build_cached_train_step)
                    scale_factor = float(gp[-2])
                    if not gp[-1] > 0.5:  # overflow/non-finite: skip-step
                        self.worker.abort_gradient(ref)
                        return
                    gp = gp[:-2]
                grads = unpack_step_grads(gp, {"emb": entries})
            slot_grads = {
                eb.name: (g if d is None else g[:d])
                for eb, g, d in zip(embs, grads, counts)
            }
            if jid is not None:
                self.worker.update_gradient_batched(
                    ref, slot_grads, scale_factor=scale_factor, journal_id=jid
                )
            else:
                self.worker.update_gradient_batched(
                    ref, slot_grads, scale_factor=scale_factor
                )
        except BaseException:
            self.worker.abort_gradient(ref)
            raise

    def train_step(self, batch: PersiaBatch, fetch_metrics: bool = True):
        (device_inputs, layout, miss_aux, cold_aux, restore_aux, evict_aux,
         evict_meta) = self.tier.prepare_batch(
            batch, hazard_gate=self._sync_hazard_gate
        )
        # mixed-tier: worker/PS-served slots (hash-stack or excluded) flow
        # through the same forward-ref machinery the hybrid ctx uses; their
        # gradients come back as a step output
        ps_item = self._ps_forward(batch)
        try:
            if ps_item is not None:
                _ref, embs, _counts, entries = ps_item
                device_inputs["ps_emb"] = entries
                layout = CacheLayout(
                    stacked=layout.stacked,
                    ps=tuple(eb.name for eb in embs),
                )
            if self.state is None:
                self.init_state(jax.random.PRNGKey(0), device_inputs, layout)
            # explicit async host→device staging: passing numpy leaves
            # straight into jit makes the arg conversion a synchronous
            # per-leaf round-trip on remote-attached chips (measured 84 ms
            # vs 1 ms for the same data)
            device_inputs, miss_aux, cold_aux, evict_aux = self._stage(
                device_inputs, miss_aux, cold_aux, evict_aux
            )
            header, evict_payload, ps_gpacked = self._dispatch(
                device_inputs, layout, miss_aux, cold_aux, restore_aux,
                evict_aux, evict_meta,
            )
        except Exception:
            # any failure after the forward must release the staleness slot
            # + stashed layout, or the worker buffers leak (same contract as
            # TrainCtx.train_step)
            if ps_item is not None:
                self.worker.abort_gradient(ps_item[0])
            raise
        if ps_item is not None:
            # the PS-tier gradient return is an inherent d2h (same as the
            # hybrid path); the helper aborts the ref itself on failure.
            # Ordering vs the deferred eviction write-back below is a
            # non-issue: the constructor rejects feature groups spanning
            # both tiers, so these gradients can never touch a sign an
            # eviction wrote back (same invariant the stream path's
            # _flush_ps documents).
            self._apply_ps_grads(
                ps_item, ps_gpacked, journal_step=self._global_step
            )
        prev = self._pending
        self._pending = (
            evict_meta, evict_payload, header, device_inputs["labels"][0].shape
        )
        self._pending_signs = {
            int(s)
            for ev_signs, k, _rp in evict_meta.values()
            for s in ev_signs[:k]
        }
        if prev is not None:
            self._write_back_only(prev)
        if self.sparse_cfg.kind == OPTIMIZER_ADAM:
            # PS-side Adam beta powers advance once per gradient batch,
            # mirroring the device's shared emb_batch_state for EVERY
            # feature group holding cached slots, so write-backs land in a
            # store whose future updates use consistent powers. PS-tier
            # slots' groups advance inside the worker's gradient batch
            # instead — the constructor guarantees the two tier's feature
            # groups are disjoint, so no group can be advanced twice.
            for grp in self._cached_groups:
                self.tier.router.advance_batch_state(grp)
        self._global_step += 1  # the job-state fence/journal step counter
        if fetch_metrics:
            return self._fetch_metrics()
        return None

    def _write_back_only(self, pending) -> None:
        evict_meta, evict_payload, _header, _shape = pending
        self.tier.write_back(evict_meta, evict_payload)

    def _land_pending(self) -> None:
        """Force the deferred write-back to the PS (hazard or boundary)."""
        if self._pending is not None:
            self._fetch_metrics()  # also materializes header once
            self._write_back_only(self._pending)
            self._pending = None
            self._pending_signs = set()

    def _parse_header(self, h: np.ndarray, label_shape) -> Dict:
        """Host view of the step header — the layout is owned by ONE pair
        of decoders (parallel/train_step.py unpack_step_header[_dynamic]);
        this adapter only supplies the label shape."""
        from types import SimpleNamespace

        from persia_tpu.parallel.train_step import (
            unpack_step_header,
            unpack_step_header_dynamic,
        )

        shaped = {"labels": [SimpleNamespace(shape=label_shape)]}
        if self.dynamic_loss_scale:
            loss, preds, scale, finite = unpack_step_header_dynamic(h, shaped)
            return {
                "loss": loss, "preds": preds,
                "loss_scale": scale, "grads_finite": finite,
            }
        loss, preds = unpack_step_header(h, shaped)
        return {"loss": loss, "preds": preds}

    def _fetch_metrics(self) -> Dict:
        if self._pending is None:
            return self._last_metrics or {}
        _meta, _payload, header, label_shape = self._pending
        self._last_metrics = self._parse_header(np.asarray(header), label_shape)
        self._last_header_dev = None  # fresher than any stashed stream header
        return self._last_metrics

    def drain(self) -> Optional[Dict]:
        """Land any deferred write-back and return the last step's metrics
        (materializing a ``fetch_final=False`` stream's stashed header if
        that is the freshest result)."""
        if self._pending is not None:
            self._fetch_metrics()
            self._land_pending()
        return self.last_metrics()

    # -------------------------------------------------------------- pipeline

    def last_metrics(self) -> Optional[Dict]:
        if self._pending:
            return self._fetch_metrics()
        if self._last_header_dev is not None:
            header, label_shape = self._last_header_dev
            self._last_metrics = self._parse_header(
                np.asarray(header), label_shape
            )
            self._last_header_dev = None
        return self._last_metrics


    def sentinel_spec(self) -> Dict:
        """Shape the health sentinel needs to decode the probe tail —
        ``StreamSentinel.from_ctx(ctx)`` consumes this."""
        return {
            "n_groups": len(self.tier.groups),
            "dynamic_loss_scale": self.dynamic_loss_scale,
            "probe": self._health_probe,
        }

    def train_stream(self, *args, **kwargs):
        """Asynchronous pipelined stream training — see
        ``persia_tpu.embedding.hbm_cache.stream.run_train_stream``."""
        return run_train_stream(self, *args, **kwargs)

    def register_stage_rebuild(self, fn) -> None:
        """Register a fence-point stage-graph rebuild hook: ``fn(step)``
        fires inside every subsequent stream's drained fence right after a
        tier migration re-registered the groups (StageGraph.rebuild) —
        the extension point for promoting a migrated group into
        ``FusedTrainCtx`` proper (ROADMAP direction 1)."""
        self._stage_rebuild_hooks.append(fn)

    def eval_batch(self, batch: PersiaBatch) -> np.ndarray:
        # eval misses consult the PS, so a deferred eviction must land first
        self._land_pending()
        inputs, layout = self.tier.prepare_eval_batch(batch)
        if self.tier.ps_slots:
            from persia_tpu.ctx import stage_embeddings

            ps_feats = [
                f for f in batch.id_type_features
                if f.name in self.tier.ps_slots
            ]
            if ps_feats:
                ps_sub = PersiaBatch(ps_feats, requires_grad=False)
                emb_batches = self.worker.forward_directly(ps_sub, train=False)
                entries, _ = stage_embeddings(emb_batches)
                inputs["ps_emb"] = entries
                layout = CacheLayout(
                    stacked=layout.stacked,
                    ps=tuple(eb.name for eb in emb_batches),
                )
        if self.state is None:
            raise RuntimeError("eval before any train_step/init_state")
        # eval stays simple under a mesh: everything replicated is correct
        # (no gradient reduction to get right) and eval is off the hot path
        rep = self._replicated()
        inputs = jax.device_put(inputs) if rep is None else jax.device_put(inputs, rep)
        return np.asarray(self._eval(self.state, inputs, layout))

    # ------------------------------------------------------------ checkpoint

    def publish(self) -> int:
        """Serving-freshness valve: write every resident row to the PS (and
        its incremental-update manager) WITHOUT evicting — hot signs that
        never leave the cache would otherwise ship no online-serving deltas
        between checkpoints. Call on the serving cadence; costs one
        device→host read of the resident rows. Returns rows published."""
        self._land_pending()
        if self.state is None:
            return 0
        return self.tier.publish(self.state.tables, self.state.emb_state)

    def flush(self) -> None:
        """Write every cached row back to the PS (checkpoint boundary); the
        cache restarts cold."""
        self._land_pending()
        if self.state is None:
            return
        self.tier.flush(self.state.tables, self.state.emb_state)
        # the directory is drained; zero the pools so stale rows can never be
        # mistaken for fresh checkouts
        tables, emb_state = init_cached_tables(
            self.tier.groups, self.sparse_cfg, dtype=self.table_dtype
        )
        self.state = self.state.replace(tables=tables, emb_state=emb_state)

    def dump_checkpoint(self, dst: str, blocking: bool = True) -> None:
        self.flush()
        self.worker.dump(dst, blocking=blocking)

    def load_checkpoint(self, src: str) -> None:
        self.flush()
        self.worker.load(src)

    # ------------------------------------------------------- live migration

    def attach_auto_tier(self, controller) -> None:
        """Attach a ``tiering.AutoTierController``: its profiler taps the
        tier's admit walk from the next batch on, and the stream's fences
        drive planning/migration (``_maybe_migrate_at_fence``)."""
        self._auto_tier = controller
        self.tier.profiler = controller.profiler

    def set_feed_threads(self, threads: int) -> None:
        """Resize the sharded feeder's native walker pool (no-op on an
        unsharded tier). Thread count never affects output bits."""
        self._feed_threads = max(1, int(threads))
        self.tier.set_feed_threads(self._feed_threads)

    @property
    def auto_tier(self):
        return self._auto_tier

    def request_migration(
        self,
        to_cached: Sequence[str] = (),
        to_ps: Sequence[str] = (),
        cache_rows: "int | Dict[int, int] | None" = None,
        feed_shards: "int | None" = None,
    ) -> None:
        """Queue a manual tier migration; it applies at the NEXT stream
        snapshot fence (feeder parked, hazard ledger drained, manifest
        committed) — the only point where the PS provably holds the single
        authoritative copy of every moving slot. ``feed_shards`` reshards
        the feed partition in the same rebuild (0 forces unsharded); the
        drained fence is the only safe point, since resident rows cannot
        survive a change of their shard row-ranges."""
        self._pending_migration = {
            "to_cached": tuple(to_cached), "to_ps": tuple(to_ps),
            "cache_rows": cache_rows, "feed_shards": feed_shards,
        }

    def apply_migration(
        self,
        to_cached: Sequence[str] = (),
        to_ps: Sequence[str] = (),
        cache_rows: "int | Dict[int, int] | None" = None,
        feed_shards: "int | None" = None,
    ) -> None:
        """Re-register slots between the cached and ps tiers. The cache
        MUST be cold (every directory drained — i.e. immediately after
        ``flush``/``_fence_capture``): with all rows flushed, the PS holds
        the only copy of every embedding and the move is pure metadata —
        rebuild the tier (directories, salts, groups), the step programs
        (their traces close over the group list), and the device pools.

        Bit-parity contract: a run migrated at fence F matches a run
        RESUMED from F's manifest directly into the final placement — both
        start from the identical flushed PS state and run identical device
        programs from F on (tests/test_tiering.py pins it)."""
        to_cached, to_ps = set(to_cached), set(to_ps)
        if to_cached & to_ps:
            raise ValueError(
                f"slots in both directions: {sorted(to_cached & to_ps)}"
            )
        slots_cfg = self.embedding_config.slots_config
        for s in to_cached | to_ps:
            if s not in slots_cfg:
                raise KeyError(f"unknown slot {s!r} (not in embedding config)")
        for s in to_cached:
            if slots_cfg[s].hash_stack_config.enabled:
                raise ValueError(
                    f"slot {s!r} is hash-stacked: it is served by the "
                    "worker/PS path and cannot move into the cache tier"
                )
        cached_now = {s for g in self.tier.groups for s in g.slots}
        to_cached &= set(self.tier.ps_slots)  # drop no-op moves
        to_ps &= cached_now
        if (not (to_cached or to_ps) and cache_rows is None
                and feed_shards is None):
            return
        self._land_pending()
        for g in self.tier.groups:
            n = len(self.tier.dirs[g.name])
            if n:
                raise RuntimeError(
                    f"apply_migration with a warm cache: group {g.name!r} "
                    f"still holds {n} resident rows — flush first (the "
                    "stream applies migrations only at drained fences)"
                )
        init_seed = self.tier.init_seed
        profiler = self.tier.profiler
        new_exclude = (self._ps_exclude | to_ps) - to_cached
        rows = self.cache_rows if cache_rows is None else cache_rows
        # the drained fence is the ONLY safe point to change the feed
        # partition (reshard): every directory is cold, so new shard
        # row-ranges cannot orphan resident rows
        if feed_shards is not None:
            self._feed_shards = feed_shards if feed_shards >= 1 else None
        # the tier constructor re-validates the mixed-tier invariants
        # (feature-group disjointness, prefix-bit partitioning) against the
        # NEW placement — an invalid plan fails loudly here, pre-mutation
        self.tier = CachedEmbeddingTier(
            self.worker, self.sparse_cfg, rows, self.embedding_config,
            init_seed=init_seed, ps_slots=sorted(new_exclude),
            admit_touches=self._admit_touches,
            aux_wire_dtype=self._aux_wire_dtype,
            feed_threads=self._feed_threads,
            feed_shards=self._feed_shards if self._feed_shards else 0,
        )
        self._feed_shards = self.tier.feed_shards
        self.tier.profiler = profiler
        # regrouping can move slots between group salts — keep the sharded
        # profiler's routing consistent with the NEW directories
        if profiler is not None and getattr(profiler, "shards", None):
            profiler.set_slot_salts(self.tier.profiler_slot_salts())
        self.cache_rows = rows
        self._ps_exclude = new_exclude
        self._cached_groups = tuple(sorted({
            self.embedding_config.group_of(s)
            for g in self.tier.groups for s in g.slots
        }))
        # step/eval traces close over the group list — rebuild them, and
        # drop every group-shaped device cache (rings, empties, K-step jit,
        # int8 residuals); all are rebuilt lazily against the new groups
        self._step = build_cached_train_step(
            self.model, self.dense_optimizer, self.sparse_cfg,
            self.tier.groups,
            loss_fn=self._loss_fn,
            ps_grad_wire=self._ps_wire_dtype,
            dynamic_loss_scale=self.dynamic_loss_scale,
            growth_interval=self._ls_growth_interval,
            max_scale=self._ls_max,
            sentinel_probe=self._health_probe,
            guard_clip_norm=self._health_clip_norm,
        )
        self._eval = build_cached_eval_step(self.model, self.tier.groups)
        self._kstep_jit = None
        self._empties = {}
        self._ev_rings = {}
        self._ps_residual = {}
        if self.state is not None:
            tables, emb_state = init_cached_tables(
                self.tier.groups, self.sparse_cfg, dtype=self.table_dtype
            )
            rep = self._replicated()
            if rep is not None:
                tables = {
                    k: jax.device_put(v, rep) for k, v in tables.items()
                }
                emb_state = {
                    k: jax.device_put(v, rep) for k, v in emb_state.items()
                }
            self.state = self.state.replace(tables=tables, emb_state=emb_state)
        logger.info(
            "tier migration applied: -> cached %s, -> ps %s (ps tier now %s)",
            sorted(to_cached), sorted(to_ps), sorted(self.tier.ps_slots),
        )

    def _maybe_migrate_at_fence(self, gstep: int) -> bool:
        """Stream fence hook (feeder parked, write-back drained, ledger
        empty, manifest committed): apply a queued ``request_migration``
        and/or run the auto-tier controller's planning round. Returns True
        when the tier was re-registered — the stream then resets its ring
        accounting and re-reads the group salts."""
        from persia_tpu.tracing import record_event
        migrated = False
        req = self._pending_migration
        if req is not None:
            self._pending_migration = None
            n = len(req["to_cached"]) + len(req["to_ps"])
            with span(
                "tiering.migration", step=gstep,
                to_cached=len(req["to_cached"]), to_ps=len(req["to_ps"]),
            ):
                self.apply_migration(**req)
            get_metrics().counter(
                "persia_tpu_tiering_migrations",
                "slots live-migrated between sparse tiers at a fence",
            ).inc(n)
            record_event(
                "tiering.migrate", step=gstep,
                moves={
                    **{s: "->cached" for s in req["to_cached"]},
                    **{s: "->ps" for s in req["to_ps"]},
                },
            )
            migrated = True
        if self._auto_tier is not None:
            migrated = bool(self._auto_tier.on_fence(self, gstep)) or migrated
        return migrated

    # ------------------------------------------------- crash-consistent jobs

    def _fence_capture(self, job_mgr, step: int, occupancy: Dict):
        """Commit one job-state epoch at a drained stream fence (or from
        ``snapshot_job`` on the sync path): flush every resident cached row
        to the PS (the pools restart cold — checkout round-trips full
        [emb | state] entries, so the training math is unchanged), then
        capture PS shards + the full CachedTrainState (dense params,
        optimizer state, the now-cold pools, Adam emb_batch_state) + the
        pre-flush directory/ring occupancy + loader cursor + RNG streams
        under one manifest (persia_tpu.jobstate)."""
        import flax.serialization

        from persia_tpu import jobstate

        if self.state is not None:
            self.tier.flush(self.state.tables, self.state.emb_state)
            tables, emb_state = init_cached_tables(
                self.tier.groups, self.sparse_cfg, dtype=self.table_dtype
            )
            self.state = self.state.replace(tables=tables, emb_state=emb_state)
        router = self.tier.router
        if self._health_scrub:
            # repair any non-finite PS rows (flushed cache rows included)
            # BEFORE they are captured into the manifest; journaled so a
            # retried fence is exactly-once per (epoch, step, replica)
            from persia_tpu.health.scrub import scrub_router

            scrub_router(router, self._job_epoch or 0, step)
        components = {
            "cache.json": occupancy,
            "loader.json": {"consumed_batches": step},
        }
        if self._auto_tier is not None:
            # profiler sketch + current placements ride the manifest so a
            # resumed job keeps its access history (and its tier layout)
            components["tiering.json"] = self._auto_tier.export_state()
        manifest = jobstate.snapshot_job(
            job_mgr, step,
            state_bytes=(
                flax.serialization.to_bytes(self.state)
                if self.state is not None else None
            ),
            replicas=router.replicas,
            batch_advances=dict(getattr(router, "batch_advances", {})),
            components=components,
            meta={"kind": "cached_ctx"},
        )
        self._job_epoch = manifest.job_epoch
        self._global_step = step
        return manifest

    def snapshot_job(self, job_state, extra_occupancy: Optional[Dict] = None):
        """Sync-path step-fenced snapshot: land the deferred write-back,
        then fence-capture at the current global step. (The stream path
        fences itself — ``train_stream(snapshot_every=, job_state=)``.)"""
        from persia_tpu import jobstate

        self._land_pending()
        occupancy = {
            "resident_rows": {
                g.name: len(self.tier.dirs[g.name]) for g in self.tier.groups
            },
            "pending_ledger_entries": 0,
        }
        occupancy.update(extra_occupancy or {})
        return self._fence_capture(
            jobstate.coerce_manager(job_state), self._global_step, occupancy
        )

    def resume(self, job_state, restore_ps: bool = True, generators=None):
        """Rebuild the exact mid-epoch fence state from the newest good
        manifest: PS shards rewound (default — bit-identical replay) or
        kept with journal dedupe (``restore_ps=False``, exactly-once), the
        CachedTrainState overlaid when ``init_state`` runs, Adam batch
        advances re-applied, RNG streams restored. Returns the Manifest
        (resume the stream with ``train_stream(batches_from(manifest.step),
        start_step=manifest.step, ...)``) or None on a cold start."""
        from persia_tpu import jobstate

        mgr = jobstate.coerce_manager(job_state)
        router = self.tier.router
        manifest, info = jobstate.resume_job(
            mgr,
            replicas=router.replicas,
            rewind_ps=restore_ps,
            optimizer=self.sparse_cfg,
            generators=generators,
        )
        self.last_resume_info = info
        if manifest is None:
            self._job_epoch = 0
            self._global_step = 0
            return None
        if self._auto_tier is not None and manifest.has("tiering.json"):
            from persia_tpu.embedding.tiering.planner import TIER_PS

            self._auto_tier.load_state(manifest.read_json("tiering.json"))
            # re-register to the SAVED placement BEFORE touching dense.state:
            # the manifest's cache pools (and the state template the bytes
            # deserialize against) were captured under it, and the profiler's
            # history only makes sense against the layout it scored
            want_ps = {
                s for s, t in self._auto_tier.placements.items()
                if t == TIER_PS
            }
            tracked = set(self._auto_tier.placements)
            have_ps = set(self.tier.ps_slots) & tracked
            cached_now = {s for g in self.tier.groups for s in g.slots}
            self.apply_migration(
                to_cached=sorted((have_ps - want_ps) & tracked),
                to_ps=sorted(want_ps & cached_now),
            )
        if manifest.has("dense.state"):
            self._resume_state_bytes = manifest.read_blob("dense.state")
            if self.state is not None:
                import flax.serialization

                state = flax.serialization.from_bytes(
                    self.state, self._resume_state_bytes
                )
                rep = self._replicated()
                if rep is not None:
                    state = jax.tree.map(
                        lambda x: jax.device_put(x, rep), state
                    )
                self.state = state
                self._resume_state_bytes = None
        router.batch_advances = dict(info.get("batch_advances", {}))
        self._job_epoch = manifest.job_epoch
        self._global_step = manifest.step
        return manifest

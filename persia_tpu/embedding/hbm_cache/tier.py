"""CachedEmbeddingTier: host-side cache directory + PS traffic
(probe/checkout/write-back) + per-batch staging."""


from __future__ import annotations

import ctypes
import os
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from persia_tpu.config import EmbeddingConfig
from persia_tpu.data import PersiaBatch
from persia_tpu.embedding.optim import OPTIMIZER_ADAM, OptimizerConfig
from persia_tpu.embedding.worker import (
    ProcessedBatch,
    ProcessedSlot,
    ShardedLookup,
    preprocess_batch,
)
from persia_tpu.logger import get_default_logger
from persia_tpu.utils import round_up_pow2 as _round_up_pow2
from persia_tpu.metrics import get_metrics
from persia_tpu.ops.sparse_update import sparse_update
from persia_tpu.tracing import record_span, span

logger = get_default_logger("persia_tpu.hbm_cache")

# ------------------------------------------------------------------ ctypes


from persia_tpu.embedding.hbm_cache.directory import (  # noqa: F401
    CacheDirectory,
    _BufRing,
    _retain_allocator_pages,
    group_salt,
    native_init_rows,
    native_uniform_init,
)
from persia_tpu.embedding.hbm_cache.groups import (  # noqa: F401
    CacheGroup,
    CacheLayout,
    _bucket,
    _gather_entry_rows,
    _lazy_pool,
    _slot_group_of,
    _state_init_consts,
    init_cached_tables,
    make_cache_groups,
)

class CachedEmbeddingTier:
    """Host orchestration: directory admits, PS checkouts, write-backs.

    ``worker`` is an ``EmbeddingWorker`` (its ``lookup_router`` fans checkout
    and write-back out to the sharded PS replicas; its dump/load provide the
    checkpoint path for the authoritative store)."""

    def __init__(
        self,
        worker,
        sparse_cfg: OptimizerConfig,
        rows: "int | Dict[int, int]",
        embedding_config: Optional[EmbeddingConfig] = None,
        init_seed: Optional[int] = None,
        ps_slots: Sequence[str] = (),
        admit_touches: int = 1,
        aux_wire_dtype: str = "float32",
        feed_threads: Optional[int] = None,
        feed_shards: Optional[int] = None,
    ):
        self.worker = worker
        self.cfg = embedding_config or worker.embedding_config
        self.sparse_cfg = sparse_cfg
        if aux_wire_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"aux_wire_dtype must be float32/bfloat16, got {aux_wire_dtype!r}"
            )
        # host→device wire dtype for the per-step miss/cold aux matrices
        # (the largest per-step transfers). bf16 halves the bytes on a
        # bandwidth-starved link; the device scatter casts to the table
        # dtype, so only the checked-out entries/seeds are quantized (the
        # reference ships f16 lookup wires the same way, lib.rs:157-180).
        import ml_dtypes

        self.aux_np_dtype = (
            np.dtype(ml_dtypes.bfloat16)
            if aux_wire_dtype == "bfloat16" else np.dtype(np.float32)
        )
        # cold misses are seeded-init ON THE HOST (bit-identical to the PS's
        # init) and never touch the PS until eviction — the tier must know
        # the PS seed + init bounds (all replicas share them by convention)
        if init_seed is None:
            init_seed = getattr(worker.lookup_router.replicas[0], "seed", None)
            if init_seed is None:
                raise ValueError(
                    "init_seed not given and PS replicas expose no .seed "
                    "(pass init_seed= to CachedEmbeddingTier/CachedTrainCtx)"
                )
        self.init_seed = int(init_seed)
        dims = {
            slot.dim
            for name, slot in self.cfg.slots_config.items()
            if not slot.hash_stack_config.enabled and name not in ps_slots
        }
        rows_per_group = rows if isinstance(rows, dict) else {d: rows for d in dims}
        self.groups, self.ps_slots = make_cache_groups(
            self.cfg, rows_per_group, sparse_cfg, exclude=ps_slots
        )
        # a feature group is ONE shared key space (members share an index
        # prefix): a cached slot and a ps-tier slot in the same group would
        # be two incoherent writers to the same PS entries (cache copies go
        # stale against direct PS updates) — reject the arrangement
        cached_names = {s for g in self.groups for s in g.slots}
        for fg_name, members in self.cfg.feature_groups.items():
            ms = set(members)
            if ms & cached_names and ms & set(self.ps_slots):
                raise ValueError(
                    f"feature group {fg_name!r} mixes cached slots "
                    f"{sorted(ms & cached_names)} with PS-tier slots "
                    f"{sorted(ms & set(self.ps_slots))}: one key space "
                    "cannot span both tiers"
                )
        # The tier-disjointness above only partitions the PS key space when
        # groups carry distinct sign prefixes. With feature_index_prefix_bit
        # == 0 every slot hashes into one raw u64 space, so a PS-tier sign
        # can collide with a cached-tier sign across groups and eviction
        # flushes vs ps-grad applies would become unordered writers to the
        # same PS entry.
        if self.groups and self.ps_slots and self.cfg.feature_index_prefix_bit == 0:
            raise ValueError(
                "mixed-tier config (cached groups + PS-tier slots "
                f"{sorted(self.ps_slots)}) requires feature_index_prefix_bit "
                "> 0 so per-group sign prefixes partition the PS key space; "
                "with prefix bit 0 a cached-tier sign can collide with a "
                "PS-tier sign and the two tiers would race on one PS entry"
            )
        # per-group pending-ledger namespace salts (see directory.group_salt:
        # with feature_index_prefix_bit=0 raw signs can collide ACROSS
        # groups, and an unsalted hazard probe would restore the wrong
        # group's in-flight ring rows)
        self._group_salt = {g.name: group_salt(g.name) for g in self.groups}
        # sharded feeder (round 14): feed_threads sizes the native walker
        # pool (pure throughput knob — sharded outputs are bit-identical at
        # any thread count); feed_shards partitions each group's directory
        # by its group salt. The shard COUNT is numerics-affecting (row
        # assignment differs from the unsharded walk for S > 1), so it is
        # pinned independently of the thread count: enabling threads
        # defaults S to 8, and a jobstate-resumed run must keep its S.
        if feed_threads is None:
            feed_threads = int(os.environ.get("PERSIA_FEED_THREADS", "1") or 1)
        self.feed_threads = max(1, int(feed_threads))
        if feed_shards is None:
            env = os.environ.get("PERSIA_FEED_SHARDS", "")
            if env:
                feed_shards = int(env)
            elif self.feed_threads > 1:
                feed_shards = 8
        if feed_shards is not None and int(feed_shards) < 1:
            feed_shards = None  # PERSIA_FEED_SHARDS=0 forces unsharded
        self.feed_shards = None if feed_shards is None else int(feed_shards)
        self.dirs = {
            g.name: CacheDirectory(
                g.rows, admit_touches=admit_touches,
                shards=self.feed_shards, feed_threads=self.feed_threads,
                part_salt=self._group_salt[g.name],
            )
            for g in self.groups
        }
        if self.feed_shards is not None and self.dirs:
            # the native side clamps shards to [1, min(64, capacity)]
            self.feed_shards = next(iter(self.dirs.values())).shards
        # signs whose CURRENT cache row was born from a degraded (shard-
        # down) lookup: their eviction write-back must be DROPPED — the
        # row's lineage is a synthetic init vector, and persisting it would
        # clobber whatever the restored shard actually holds. Cleared when
        # the sign is next admitted from live PS data.
        self._deg_lock = threading.Lock()
        self._degraded_born: set = set()
        # per-step host staging buffers (fresh per step; see _BufRing).
        # Allocator tuning keeps the fresh MB-scale buffers off the mmap
        # path — applied here, not at import, so fused-tier-only processes
        # keep default malloc behavior
        _retain_allocator_pages()
        self._ring = _BufRing()
        self._slot_group = {s: g for g in self.groups for s in g.slots}
        # optional auto-tiering access profiler (tiering.AccessProfiler):
        # when attached, the prepare paths feed it every slot's sign stream
        # — one strided native observe per group on the fast path
        self.profiler = None
        # static fast-path eligibility per slot (config is immutable): the
        # per-batch check reduces to "every feature single-id" (the only
        # data-dependent part)
        self._fast_prefix: Dict[str, np.uint64] = {}
        self._fast_eligible: Dict[str, bool] = {}
        for name, slot in self.cfg.slots_config.items():
            self._fast_eligible[name] = (
                slot.embedding_summation
                and not slot.sqrt_scaling
                and not slot.hash_stack_config.enabled
            )
            self._fast_prefix[name] = slot.index_prefix
        m = get_metrics()
        self._m_hit = m.counter(
            "persia_tpu_cache_hit_count", "batch distinct signs resident in HBM"
        )
        self._m_miss = m.counter(
            "persia_tpu_cache_miss_count", "batch distinct signs checked out of the PS"
        )
        self._m_evict = m.counter(
            "persia_tpu_cache_evict_count", "rows written back to the PS on eviction"
        )
        self._m_wb_deg_dropped = m.counter(
            "persia_tpu_degraded_born_wb_rows_dropped",
            "cache write-back rows dropped because the row was born from a degraded lookup",
        )
        self._m_shard_busy = m.gauge(
            "persia_tpu_feeder_shard_busy",
            "per-shard walk seconds of the last sharded feed (labels: group, shard)",
        )
        self._m_shard_stall = m.gauge(
            "persia_tpu_feeder_shard_stall",
            "per-shard pool-queue wait seconds of the last sharded feed "
            "(labels: group, shard) — busy high = shard imbalance, stall "
            "high = not enough cores",
        )

    def set_feed_threads(self, threads: int) -> None:
        """Resize every group directory's native walker pool. Output bits
        never depend on the thread count — safe to change mid-job."""
        self.feed_threads = max(1, int(threads))
        for d in self.dirs.values():
            d.set_feed_threads(self.feed_threads)

    def profiler_slot_salts(self) -> Dict[str, int]:
        """Partition salt per cached slot (its group's salt): the sharded
        profiler must route a slot's unfused observes with the SAME salt
        its group's directory partitions by, or the fused and unfused
        observe paths would land the same sign in different sub-sketches."""
        return {
            s: self._group_salt[g.name] for g in self.groups for s in g.slots
        }

    def _note_shard_walk(self, gname: str, d: CacheDirectory) -> None:
        """Publish the last feed's native-measured per-shard walk times:
        one ``feed.shard`` span + one ``persia_tpu_feeder_shard_busy`` and
        one ``persia_tpu_feeder_shard_stall`` gauge sample per shard."""
        stall = d.shard_stall_ns().tolist()
        for s, ns in enumerate(d.shard_busy_ns().tolist()):
            self._m_shard_busy.set(ns * 1e-9, group=gname, shard=str(s))
            self._m_shard_stall.set(stall[s] * 1e-9, group=gname, shard=str(s))
            record_span("feed.shard", ns * 1e-9, group=gname, shard=s,
                        stall_ns=stall[s])

    def feeder_shard_stats(self) -> Dict[str, Dict[str, List[int]]]:
        """Per-group per-shard occupancy + last-feed walk/queue-wait ns
        (sharded mode; empty when unsharded) — surfaced in stream stats and
        fence logs."""
        if self.feed_shards is None:
            return {}
        return {
            g.name: {
                "sizes": self.dirs[g.name].shard_sizes().tolist(),
                "busy_ns": self.dirs[g.name].shard_busy_ns().tolist(),
                "stall_ns": self.dirs[g.name].shard_stall_ns().tolist(),
            }
            for g in self.groups
        }

    @property
    def router(self) -> ShardedLookup:
        return self.worker.lookup_router

    @property
    def init_method(self):
        """Read LIVE from the worker's hyperparams (not a construction-time
        snapshot): a configure() pushed after ctx creation reaches the PS
        replicas immediately, and cold rows born here must stay bit-identical
        to rows born there."""
        return self.worker.hyperparams.resolved_init_method()

    # PS traffic helpers: big checkout/write-back calls chunk across the
    # worker's thread pool (the native store releases the GIL; its internal
    # shard mutexes make disjoint chunks near-contention-free)
    _PAR_CHUNK = 8192
    _chunk_pool_obj = None

    def _chunk_pool(self):
        """Pool for chunking big host store calls (probe/write-back): ctypes
        store calls release the GIL, so chunks get real parallelism on
        multi-core feeder hosts. Daemon threads; lives with the tier."""
        self._chunk_pool_obj = _lazy_pool(self._chunk_pool_obj, "cache-chunk")
        return self._chunk_pool_obj

    def _probe(self, signs: np.ndarray, dim: int):
        """Chunk-parallel warm/cold probe across the worker's thread pool.
        Results land in ring-reused caller-owned buffers (chunks write
        disjoint slices, so concurrent fills are safe)."""
        n = len(signs)
        entry_len = dim + self.sparse_cfg.state_dim(dim)
        # ring shapes are bucketed (n varies every step; an exact-shape ring
        # would reallocate every call), results are the [:n] slices
        nb = _bucket(max(n, 1))
        vals = self._ring.get(
            ("probe_vals", entry_len), (nb, entry_len), np.float32
        )[:n]
        warm8 = self._ring.get("probe_warm", (nb,), np.uint8)[:n]
        if n <= self._PAR_CHUNK:
            return self.router.probe_entries(
                signs, dim, vals_out=vals, warm_out=warm8
            )
        pool = self._chunk_pool()
        bounds = list(range(0, n, self._PAR_CHUNK)) + [n]

        def chunk(se):
            s, e = se
            self.router.probe_entries(
                signs[s:e], dim, vals_out=vals[s:e], warm_out=warm8[s:e]
            )

        list(pool.map(chunk, zip(bounds[:-1], bounds[1:])))
        return warm8.view(np.bool_), vals

    def _filter_degraded_born(self, signs: np.ndarray, values: np.ndarray):
        """Drop write-back rows whose cache lineage is a degraded lookup
        (never misapply synthetic-init-rooted training onto the restored
        shard's real rows). Counted; no-op while the set is empty."""
        with self._deg_lock:
            if not self._degraded_born:
                return signs, values
            reg = np.fromiter(
                self._degraded_born, dtype=np.uint64,
                count=len(self._degraded_born),
            )
        mask = np.isin(np.asarray(signs, dtype=np.uint64), reg)
        if not mask.any():
            return signs, values
        self._m_wb_deg_dropped.inc(int(mask.sum()))
        keep = ~mask
        return signs[keep], values[keep]

    def _set_embedding(self, signs: np.ndarray, values: np.ndarray, dim: int) -> None:
        signs, values = self._filter_degraded_born(signs, values)
        if not len(signs):
            return
        n = len(signs)
        if n <= self._PAR_CHUNK:
            self.router.set_embedding(
                signs, values, dim=dim, commit_incremental=True
            )
            return
        pool = self._chunk_pool()
        bounds = list(range(0, n, self._PAR_CHUNK)) + [n]
        list(
            pool.map(
                lambda se: self.router.set_embedding(
                    signs[se[0]:se[1]], values[se[0]:se[1]], dim=dim,
                    commit_incremental=True,
                ),
                zip(bounds[:-1], bounds[1:]),
            )
        )

    # ------------------------------------------------------------- helpers

    def _observe_ps_feats(self, batch: PersiaBatch) -> None:
        """Feed PS-tier slots' sign streams to the access profiler: a slot
        that migrated OUT of the cache must keep accruing stats or it could
        never earn its way back (its sketch mass would just decay away).
        Raw (unprefixed) signs are fine — stats are per slot, and a
        constant prefix changes neither totals nor distinct counts."""
        if self.profiler is None or not self.ps_slots:
            return
        with span("cache.sketch_observe", group="__ps__"):
            for f in batch.id_type_features:
                if f.name in self.ps_slots:
                    flat, _counts = f.flat_counts()
                    self.profiler.observe_slot(
                        f.name, np.ascontiguousarray(flat, dtype=np.uint64)
                    )

    def _group_slots(self, pb: ProcessedBatch) -> Dict[str, List[ProcessedSlot]]:
        out: Dict[str, List[ProcessedSlot]] = {}
        for slot in pb.slots:
            out.setdefault(self._slot_group[slot.name].name, []).append(slot)
        for slots in out.values():
            slots.sort(key=lambda s: s.name)
        return out

    @staticmethod
    def _dedup_group_signs(slots: List[ProcessedSlot]):
        """Concatenate the group's per-slot distinct signs and dedup ACROSS
        slots (the directory's contract requires globally distinct signs —
        with feature_index_prefix_bit=0 two slots can carry the same sign)."""
        from persia_tpu.embedding import native_worker

        all_signs = (
            np.concatenate([s.distinct for s in slots])
            if slots else np.empty(0, np.uint64)
        )
        native = native_worker.dedup(all_signs)
        if native is not None:
            uniq, inv = native
        else:
            uniq, inv = np.unique(all_signs, return_inverse=True)
        return all_signs, uniq, inv.astype(np.int64)

    def _stack_layout(self, g: CacheGroup, slots: List[ProcessedSlot]):
        """Common (B, L) layout for the group's pooled slots: L = max count
        across those slots (pow2-bucketed to bound recompiles)."""
        pooled = [s for s in slots if s.config.embedding_summation]
        if not pooled:
            return pooled, 0
        max_c = max((int(s.counts.max()) if len(s.counts) else 1) for s in pooled)
        return pooled, _round_up_pow2(max(max_c, 1), floor=1)

    def _slot_rows(
        self, slot: ProcessedSlot, slot_rows: np.ndarray, L: int, pad_row: int
    ) -> np.ndarray:
        idx = _position_index(slot, L)
        lut = np.append(slot_rows, np.int64(pad_row))
        return lut[idx].astype(np.int32)

    # ------------------------------------------------------------ train path

    def _admit_aux(
        self, g: CacheGroup, miss_signs, rows_miss, ev_signs, ev_rows,
        n_unique, hazard_gate, miss_aux, cold_aux, restore_aux, evict_aux,
        evict_meta, ring_alloc=None,
    ) -> None:
        """Post-admit bookkeeping shared by the general and single-id fast
        paths: metrics, the cross-step write-back hazard gate, the
        warm/cold miss split (WARM = PS holds trained state, full entry
        ships; COLD = brand-new sign, host-seeded emb only, no PS touch
        until eviction), and the eviction read-back bucket."""
        C = g.rows
        self._m_hit.inc(n_unique - len(miss_signs))
        self._m_miss.inc(len(miss_signs))
        self._m_evict.inc(len(ev_signs))

        # Reserve this step's eviction-ring span BEFORE the hazard-gate
        # query: the allocator only hands out spans with no live map
        # entries, so rows the gate is about to reference can never land
        # in THIS step's span — this step's ring write precedes this
        # step's restores in device program order, and a same-step
        # overwrite of a restore source would corrupt the restore.
        k = len(ev_rows)
        ring_pos = -1
        if k:
            kp = _bucket(k)
            if ring_alloc is not None:
                ring_pos = ring_alloc(g.name, kp)
            e_rows = self._ring.full(("e_rows", g.name), (kp,), np.int32, C)
            e_rows[:k] = ev_rows
            evict_aux[g.name] = e_rows
            evict_meta[g.name] = (ev_signs, k, ring_pos)

        resolved = None
        if hazard_gate is not None and len(miss_signs):
            with span("cache.hazard_gate", n=len(miss_signs)):
                resolved = hazard_gate(g.name, miss_signs)

        m = len(miss_signs)
        if m:
            handled = np.zeros(m, dtype=bool)
            if resolved:
                for payload, src_idx, pos in resolved:
                    handled[pos] = True
                    # pow2-bucketed; src pad reads row 0 harmlessly, dst
                    # pad C+1 is dropped by the scatter
                    S = len(pos)
                    sp = _round_up_pow2(S)
                    src = np.zeros(sp, dtype=np.int64)
                    dst = np.full(sp, C + 1, dtype=np.int32)
                    src[:S] = src_idx
                    dst[:S] = rows_miss[pos]
                    restore_aux.setdefault(g.name, []).append(
                        (payload, src, dst)
                    )
            with span("cache.ps_probe", n=m):
                warm, vals = self._probe(miss_signs, g.dim)
            widx = np.nonzero(warm[:m] & ~handled)[0]
            cidx = np.nonzero(~warm[:m] & ~handled)[0]
            # degraded-lineage bookkeeping: misses served while their
            # shard was down (router recorded them) birth rows whose
            # write-back must be dropped; every OTHER admit is live PS
            # data and clears an earlier degraded mark for its sign
            if hasattr(self.router, "degraded_intersection"):
                with self._deg_lock:
                    had_degraded = bool(self._degraded_born)
                deg = (
                    self.router.degraded_intersection(miss_signs[:m])
                    if getattr(self.router, "policy", None) is not None
                    else None
                )
                if deg is not None and deg.any():
                    with self._deg_lock:
                        self._degraded_born.update(
                            int(s) for s in miss_signs[:m][deg]
                        )
                if had_degraded:
                    clean = (
                        miss_signs[:m][~deg] if deg is not None and deg.any()
                        else miss_signs[:m]
                    )
                    with self._deg_lock:
                        self._degraded_born.difference_update(
                            int(s) for s in clean
                        )
            # aux buffers come from the reuse ring and escape to the async
            # staging path; pad regions carry garbage values on purpose —
            # pad rows are C+1, which the scatters drop
            if len(widx):
                with span("cache.warm_fill", n=len(widx)):
                    entry_len = g.dim + g.state_dim
                    wp = _bucket(len(widx))
                    w_rows = self._ring.full(
                        ("w_rows", g.name), (wp,), np.int32, C + 1
                    )
                    w_entries = self._ring.get(
                        ("w_entries", g.name), (wp, entry_len), self.aux_np_dtype
                    )
                    w_rows[:len(widx)] = rows_miss[widx]
                    w_entries[:len(widx)] = vals[widx]  # casts on a bf16 wire
                    miss_aux[g.name] = (w_rows, w_entries)
            if len(cidx):
                with span("cache.cold_fill", n=len(cidx)):
                    cp = _bucket(len(cidx))
                    c_rows = self._ring.full(
                        ("c_rows", g.name), (cp,), np.int32, C + 1
                    )
                    c_f32 = self._ring.get(
                        ("c_emb_f32", g.name), (cp, g.dim), np.float32
                    )
                    c_rows[:len(cidx)] = rows_miss[cidx]
                    native_init_rows(
                        miss_signs[cidx], self.init_seed, g.dim,
                        self.init_method, out=c_f32[:len(cidx)],
                    )
                    if self.aux_np_dtype == np.float32:
                        c_emb = c_f32
                    else:
                        c_emb = self._ring.get(
                            ("c_emb", g.name), (cp, g.dim), self.aux_np_dtype
                        )
                        c_emb[:len(cidx)] = c_f32[:len(cidx)]
                    cold_aux[g.name] = (c_rows, c_emb)
        # (eviction read-back bucket reserved above, before the gate)

    def _single_id_groups(self, batch: PersiaBatch):
        """The fast-path precondition: EVERY group is pooled-only, no
        hash-stack, no sqrt scaling, and every feature carries exactly one
        id per sample. Returns [(group, slot_names, (S, B) prefixed sign
        matrix), ...] or None (→ general path)."""
        from persia_tpu.embedding import native_worker
        from persia_tpu.embedding.hashing import add_index_prefix

        feats = {
            f.name: f for f in batch.id_type_features
            if f.name not in self.ps_slots  # mixed-tier: worker/PS path
        }
        for name in feats:
            if name not in self._slot_group:
                # same loud failure the general path's preprocess raises
                raise KeyError(f"unknown slot {name!r} (not in embedding config)")
            if not self._fast_eligible[name]:  # static per-slot precompute
                return None

        out = []
        prefix_bit = self.cfg.feature_index_prefix_bit
        for g in self.groups:
            names = [n for n in g.pooled_slots if n in feats]
            if not names:
                continue
            flats = []
            for name in names:
                flat, counts = feats[name].flat_counts()
                # exactly one id per sample — a total that merely EQUALS the
                # batch size (counts like [2, 0, 1, ...]) would misalign ids
                # to samples
                if len(flat) != len(counts) or not (counts == 1).all():
                    return None
                flats.append(np.ascontiguousarray(flat, dtype=np.uint64))
            mat = self._ring.get(
                ("sid_mat", g.name), (len(names), len(flats[0])), np.uint64
            )
            # ONE native call builds every prefixed row (the per-slot numpy
            # prefix-OR + copy loop was a measurable share of the feeder)
            prefixes = np.array(
                [self._fast_prefix[n] for n in names], dtype=np.uint64
            )
            if not native_worker.build_sid_matrix(
                flats, prefixes, prefix_bit, mat
            ):
                for i, (name, flat) in enumerate(zip(names, flats)):
                    mat[i] = add_index_prefix(
                        flat, self._fast_prefix[name], prefix_bit
                    )
            out.append((g, tuple(names), mat))
        return out

    def prepare_batch(
        self,
        batch: PersiaBatch,
        hazard_gate: Optional[Callable[[np.ndarray], None]] = None,
        ring_alloc: Optional[Callable[[str, int], int]] = None,
        pending_map=None,
    ):
        """Admit the batch's distinct signs, check misses out of the PS, and
        build the device step inputs. Returns (device_inputs, layout,
        miss_aux, cold_aux, restore_aux, evict_aux, evict_meta) where
        miss_aux/cold_aux hold warm/cold miss scatters, restore_aux holds
        device-side re-admissions resolved by the hazard gate, and
        evict_meta = {group: (evict_signs, true_K, ring_pos)} describes the write-back
        due after the step.

        ``hazard_gate(group_name, miss_signs)``: called before each group's
        PS probe. When a pipelined caller has eviction write-backs still in
        flight, a fresh miss on one of those signs would read stale data
        from the PS. The gate returns a list of ``(payload, src_idx,
        positions)`` restore descriptors — ``payload`` is ``None`` for
        "the group's standing device eviction ring" (the stream gate) or a
        DEVICE-resident payload array, ``src_idx`` rows within it,
        ``positions`` the resolved indices into ``miss_signs`` — and those
        signs are re-admitted by an on-device row restore instead of a
        host checkout. A bare ``None`` return means no overlap.

        ``pending_map``: the stream's native hazard ledger
        (``PendingSignMap``). When given, the single-id fast path fuses the
        ledger probe INTO the admit call (``cache_feed_batch``) instead of
        calling ``hazard_gate`` — one native round-trip for dedup + admit +
        eviction selection + row LUT + hazard probe."""
        fast = self._single_id_groups(batch)
        if fast is not None:
            return self._prepare_batch_single_id(
                batch, fast, hazard_gate, ring_alloc, pending_map
            )
        cached_feats = [
            f for f in batch.id_type_features if f.name not in self.ps_slots
        ]
        self._observe_ps_feats(batch)
        pb = preprocess_batch(cached_feats, self.cfg)
        slots_by_group = self._group_slots(pb)

        stacked_rows: Dict[str, np.ndarray] = {}
        stacked_scale: Dict[str, np.ndarray] = {}
        layout_stacked: List[Tuple[str, Tuple[str, ...]]] = []
        raw_rows: Dict[str, np.ndarray] = {}
        miss_aux: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        cold_aux: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        restore_aux: Dict[str, List] = {}
        evict_aux: Dict[str, np.ndarray] = {}
        evict_meta: Dict[str, Tuple[np.ndarray, int, int]] = {}
        any_scale = False

        for g in self.groups:
            slots = slots_by_group.get(g.name, [])
            if not slots:
                continue
            C = g.rows
            if self.profiler is not None:
                with span("cache.sketch_observe", group=g.name):
                    for slot in slots:
                        # position-level stream: distinct[inverse] rebuilds
                        # the raw (duplicated) sign sequence frequencies need
                        self.profiler.observe_slot(
                            slot.name, slot.distinct[slot.inverse]
                        )
            all_signs, uniq, inv = self._dedup_group_signs(slots)
            rows_u, miss_idx, ev_signs, ev_rows = self.dirs[g.name].admit(uniq)
            rows = rows_u[inv]  # per original (slot-concatenated) position
            miss_signs = uniq[miss_idx]
            self._admit_aux(
                g, miss_signs, rows_u[miss_idx], ev_signs, ev_rows,
                len(uniq), hazard_gate,
                miss_aux, cold_aux, restore_aux, evict_aux, evict_meta,
                ring_alloc,
            )

            # per-slot row matrices: pooled slots stack into (S, B, L)
            pooled, L = self._stack_layout(g, slots)
            off = 0
            stack_mats, scale_mats, stack_names = [], [], []
            for slot in slots:
                d = slot.num_distinct
                srows = rows[off:off + d]
                off += d
                if slot.config.embedding_summation:
                    stack_names.append(slot.name)
                    stack_mats.append(self._slot_rows(slot, srows, L, C))
                    if slot.config.sqrt_scaling:
                        any_scale = True
                        scale_mats.append(
                            (1.0 / np.sqrt(np.maximum(slot.counts, 1))).astype(np.float32)
                        )
                    else:
                        scale_mats.append(
                            np.ones(slot.batch_size, dtype=np.float32)
                        )
                else:
                    raw_rows[slot.name] = self._slot_rows(
                        slot, srows, slot.config.sample_fixed_size, C
                    )
            if stack_mats:
                stacked_rows[g.name] = np.stack(stack_mats)
                stacked_scale[g.name] = np.stack(scale_mats)
                layout_stacked.append((g.name, tuple(stack_names)))

        device_inputs = {
            "dense": [np.asarray(f.data, dtype=np.float32) for f in batch.non_id_type_features],
            "labels": [np.asarray(l.data, dtype=np.float32) for l in batch.labels],
            "stacked_rows": stacked_rows,
            "raw_rows": raw_rows,
        }
        if any_scale:
            device_inputs["stacked_scale"] = stacked_scale
        layout = CacheLayout(stacked=tuple(layout_stacked))
        return (
            device_inputs, layout, miss_aux, cold_aux, restore_aux,
            evict_aux, evict_meta,
        )

    def _prepare_batch_single_id(self, batch: PersiaBatch, fast, hazard_gate,
                                 ring_alloc=None, pending_map=None):
        """Single-id fast path: ONE native call per group
        (``cache_feed_batch``: dedup + admit + per-position rows + hazard
        probe) and the row matrix is its output reshaped — no per-slot
        dedup, no row LUT, no stack copy, no separate ledger round-trip.
        Dominates the 1-core feeder's budget on the Criteo-style
        all-single-id shape. Without a ``pending_map`` (the sync path) the
        admit is ``cache_admit_positions`` and the gate rides
        ``hazard_gate`` exactly as before."""
        stacked_rows: Dict[str, np.ndarray] = {}
        layout_stacked: List[Tuple[str, Tuple[str, ...]]] = []
        miss_aux: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        cold_aux: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        restore_aux: Dict[str, List] = {}
        evict_aux: Dict[str, np.ndarray] = {}
        evict_meta: Dict[str, Tuple[np.ndarray, int, int]] = {}
        self._observe_ps_feats(batch)

        for g, names, mat in fast:
            S, B = mat.shape
            d = self.dirs[g.name]
            # fused sketch observe (round 14): when the directory is
            # sharded and the profiler carries a matching sub-sketch
            # family, the observe rides the admit walk itself — one
            # traversal of the sign matrix instead of two. The fused walk
            # attributes a sign to its first position's slot, exact only
            # when sign -> slot is injective, hence the prefix-bit gate;
            # otherwise (and on the general/ServiceCtx paths) the routed
            # unfused observe keeps the same sub-sketch state.
            fuse_base = None
            if (
                self.profiler is not None
                and d.shards is not None
                and getattr(self.profiler, "shards", None) == d.shards
                and self.cfg.feature_index_prefix_bit > 0
            ):
                fuse_base = self.profiler.group_contiguous_base(names)
            if self.profiler is not None and fuse_base is None:
                # the (S, B) matrix attributes positions to slots by stride
                # — ONE native observe for the whole group
                with span("cache.sketch_observe", group=g.name, n=mat.size):
                    self.profiler.observe_group(names, mat.reshape(-1), B)
            sketches = self.profiler.sketches if fuse_base is not None else None
            gate = hazard_gate
            if pending_map is not None:
                salt = self._group_salt[g.name]
                with span("cache.admit", group=g.name, n=mat.size,
                          fused_observe=fuse_base is not None):
                    (rows, miss_signs, miss_rows, ev_signs, ev_rows, n_unique,
                     rst_src, rst_pos) = d.feed_batch(
                        mat.reshape(-1), pending_map, salt=salt,
                        sketches=sketches, samples_per_slot=B,
                        slot_base=fuse_base or 0,
                    )
                gate = _make_reval_gate(pending_map, rst_pos, salt)
            elif sketches is not None:
                with span("cache.admit", group=g.name, n=mat.size,
                          fused_observe=True):
                    (rows, miss_signs, miss_rows, ev_signs, ev_rows,
                     n_unique) = d.feed_batch(
                        mat.reshape(-1), None,
                        sketches=sketches, samples_per_slot=B,
                        slot_base=fuse_base,
                    )[:6]
            else:
                with span("cache.admit", group=g.name, n=mat.size):
                    (rows, miss_signs, miss_rows, ev_signs, ev_rows,
                     n_unique) = d.admit_positions(mat.reshape(-1))
            if d.shards is not None:
                self._note_shard_walk(g.name, d)
            with span("cache.admit_aux", group=g.name, misses=len(miss_signs)):
                self._admit_aux(
                    g, miss_signs, miss_rows, ev_signs, ev_rows, n_unique,
                    gate, miss_aux, cold_aux, restore_aux, evict_aux,
                    evict_meta, ring_alloc,
                )
            stacked_rows[g.name] = rows.reshape(S, B, 1)
            layout_stacked.append((g.name, names))

        device_inputs = {
            "dense": [np.asarray(f.data, dtype=np.float32) for f in batch.non_id_type_features],
            "labels": [np.asarray(l.data, dtype=np.float32) for l in batch.labels],
            "stacked_rows": stacked_rows,
            "raw_rows": {},
        }
        layout = CacheLayout(stacked=tuple(layout_stacked))
        return (
            device_inputs, layout, miss_aux, cold_aux, restore_aux,
            evict_aux, evict_meta,
        )

    # ------------------------------------------------------------- eval path

    def prepare_eval_batch(self, batch: PersiaBatch):
        """Build eval-step inputs with ZERO cache mutation: resident signs
        map to their cache rows via a read-only probe; misses get a plain
        infer PS lookup (zeros for never-trained signs, no admission) and
        ride as an appended miss table with rows C+1+j."""
        cached_feats = [
            f for f in batch.id_type_features if f.name not in self.ps_slots
        ]
        pb = preprocess_batch(cached_feats, self.cfg)
        slots_by_group = self._group_slots(pb)

        stacked_rows: Dict[str, np.ndarray] = {}
        stacked_scale: Dict[str, np.ndarray] = {}
        layout_stacked: List[Tuple[str, Tuple[str, ...]]] = []
        raw_rows: Dict[str, np.ndarray] = {}
        miss_tables: Dict[str, np.ndarray] = {}
        any_scale = False

        for g in self.groups:
            slots = slots_by_group.get(g.name, [])
            if not slots:
                continue
            C = g.rows
            all_signs, uniq, inv = self._dedup_group_signs(slots)
            rows_u = self.dirs[g.name].probe(uniq)
            miss_mask = rows_u < 0
            miss_signs = uniq[miss_mask]
            m = len(miss_signs)
            mp = _round_up_pow2(max(m, 1))
            mt = np.zeros((mp, g.dim), dtype=np.float32)
            if m:
                mt[:m] = self.router.lookup(miss_signs, g.dim, train=False)
                rows_u = rows_u.copy()
                rows_u[miss_mask] = C + 1 + np.arange(m)
            miss_tables[g.name] = mt
            rows = rows_u[inv]

            pooled, L = self._stack_layout(g, slots)
            off = 0
            stack_mats, scale_mats, stack_names = [], [], []
            for slot in slots:
                d = slot.num_distinct
                srows = rows[off:off + d]
                off += d
                if slot.config.embedding_summation:
                    stack_names.append(slot.name)
                    stack_mats.append(self._slot_rows(slot, srows, L, C))
                    if slot.config.sqrt_scaling:
                        any_scale = True
                        scale_mats.append(
                            (1.0 / np.sqrt(np.maximum(slot.counts, 1))).astype(np.float32)
                        )
                    else:
                        scale_mats.append(np.ones(slot.batch_size, dtype=np.float32))
                else:
                    raw_rows[slot.name] = self._slot_rows(
                        slot, srows, slot.config.sample_fixed_size, C
                    )
            if stack_mats:
                stacked_rows[g.name] = np.stack(stack_mats)
                stacked_scale[g.name] = np.stack(scale_mats)
                layout_stacked.append((g.name, tuple(stack_names)))

        inputs = {
            "dense": [np.asarray(f.data, dtype=np.float32) for f in batch.non_id_type_features],
            "labels": [np.asarray(l.data, dtype=np.float32) for l in batch.labels],
            "stacked_rows": stacked_rows,
            "raw_rows": raw_rows,
            "miss_tables": miss_tables,
        }
        if any_scale:
            inputs["stacked_scale"] = stacked_scale
        return inputs, CacheLayout(stacked=tuple(layout_stacked))

    # ------------------------------------------------------------ write-back

    def write_back(self, evict_meta, evict_payload) -> None:
        """Persist evicted rows to the PS (full [emb | state] entries)."""
        for gname, (ev_signs, k, _ring_pos) in evict_meta.items():
            if not k:
                continue
            g = next(gr for gr in self.groups if gr.name == gname)
            payload = np.asarray(evict_payload[gname])[:k].astype(np.float32)
            self._set_embedding(ev_signs[:k], payload, dim=g.dim)

    def _write_rows(self, g: CacheGroup, signs, rows, tables, emb_state) -> None:
        """Shared flush/publish body: gather ``[emb | state]`` for the given
        rows ON DEVICE (one d2h transfer of only those entries — fetching
        the full pool arrays would cost the whole table per call on a
        bandwidth-starved link) and persist to the PS as training updates."""
        kp = _round_up_pow2(len(rows))
        rpad = np.zeros(kp, dtype=np.int64)  # pad rows re-read row 0, sliced off
        rpad[:len(rows)] = rows
        payload = _gather_entry_rows(
            tables[g.name], emb_state[g.name], jax.device_put(rpad)
        )
        # this d2h IS the operation (bounded entry fetch to persist to the
        # PS) and runs on the flush/publish path, not the per-step hot path
        host = np.asarray(payload)[:len(rows)].astype(np.float32)  # persia-lint: disable=JAX001
        self._set_embedding(signs, host, dim=g.dim)

    def flush(self, tables, emb_state) -> None:
        """Drain every cached row back to the PS (checkpoint/eval boundary).
        ``tables``/``emb_state`` are the CURRENT device arrays."""
        for g in self.groups:
            signs, rows = self.dirs[g.name].drain()
            if len(signs):
                self._write_rows(g, signs, rows, tables, emb_state)

    def publish(self, tables, emb_state) -> int:
        """Write every RESIDENT row to the PS without evicting anything —
        the serving-freshness valve. Eviction write-backs only cover rows
        that LEAVE the cache, so a hot sign trained every step would ship no
        incremental update while it stays resident; publishing on the
        serving cadence closes that gap (the reference needs no equivalent —
        its PS sees every gradient). Returns the number of rows published."""
        total = 0
        for g in self.groups:
            signs, rows = self.dirs[g.name].snapshot()  # no directory churn
            if len(signs):
                self._write_rows(g, signs, rows, tables, emb_state)
                total += len(signs)
        return total


def _make_reval_gate(pending_map, rst_pos: np.ndarray, salt: int = 0):
    """Hazard gate for the fused feed path: the candidates were already
    found by ``cache_feed_batch``, but that probe ran BEFORE this step's
    eviction-ring span was reserved — a write-back landing in between can
    free a referenced span for reuse by this very step. ``_admit_aux``
    calls the gate AFTER the reservation, so re-querying the (few)
    candidates here closes the race: entries still live reference spans
    the allocator cannot have handed out; entries that died have landed in
    the PS, and dropping them routes those misses through the ordinary
    warm-probe path. ``salt`` is the group's ledger namespace — it must
    match the salt the fused probe used."""
    if not len(rst_pos):
        return None

    def gate(gname: str, miss_signs: np.ndarray):
        _hits, _tokens, srcs = pending_map.query(miss_signs[rst_pos], salt=salt)
        live = srcs >= 0
        if not live.any():
            return None
        return [(None, srcs[live], rst_pos[live])]

    return gate


def _position_index(slot: ProcessedSlot, L: int) -> np.ndarray:
    """(B, L) matrix of positions into the slot's distinct array (pad == D),
    reusing the native raw-index builder."""
    from persia_tpu.embedding import native_worker

    idx = native_worker.raw_index(slot.counts, slot.inverse, L, slot.num_distinct)
    if idx is None:
        idx = np.full((slot.batch_size, L), slot.num_distinct, dtype=np.int32)
        pos = 0
        for b, c in enumerate(slot.counts.tolist()):
            take = min(c, L)
            idx[b, :take] = slot.inverse[pos:pos + take]
            pos += c
    return idx


# ------------------------------------------------------------------- ctx



"""Cache groups, device state layout, and the scatter/gather/aux device
helpers of the HBM cache tier."""


from __future__ import annotations

import ctypes
import os
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from persia_tpu.config import EmbeddingConfig
from persia_tpu.data import PersiaBatch
from persia_tpu.embedding.optim import OPTIMIZER_ADAM, OptimizerConfig
from persia_tpu.embedding.worker import (
    ProcessedBatch,
    ProcessedSlot,
    ShardedLookup,
    preprocess_batch,
)
from persia_tpu.logger import get_default_logger
from persia_tpu.utils import round_up_pow2 as _round_up_pow2
from persia_tpu.metrics import get_metrics
from persia_tpu.ops.sparse_update import sparse_update
from persia_tpu.tracing import span

logger = get_default_logger("persia_tpu.hbm_cache")

# ------------------------------------------------------------------ ctypes


from persia_tpu.embedding.hbm_cache.common import _bucket  # noqa: F401
from persia_tpu.embedding.hbm_cache.directory import (  # noqa: F401
    native_uniform_init,
)

@flax.struct.dataclass
class CachedTrainState:
    params: object
    batch_stats: object
    opt_state: object
    tables: Dict[str, jnp.ndarray]  # group → (C+1, dim); row C is the zero pad row
    emb_state: Dict[str, Dict[str, jnp.ndarray]]  # group → optimizer state (C+1, ·)
    emb_batch_state: jnp.ndarray
    step: jnp.ndarray
    # dynamic mixed-precision loss scaling (None = static); same state the
    # hybrid TrainCtx carries (parallel/train_step.py LossScaleState)
    loss_scale: Optional[object] = None


@dataclass(frozen=True)
class CacheGroup:
    """One HBM row pool shared by all slots of one embedding dim."""

    name: str
    dim: int
    rows: int  # cache capacity C (the table itself has C+1 rows)
    state_dim: int
    pooled_slots: Tuple[str, ...]  # stacked: one gather/update for all of them
    raw_slots: Tuple[str, ...]  # sequence slots, per-slot (B, L) rows

    @property
    def slots(self) -> Tuple[str, ...]:
        return self.pooled_slots + self.raw_slots


def _lazy_pool(existing, prefix: str, workers: int = 8):
    """Idempotent daemon ThreadPoolExecutor creation (shared by the tier's
    chunking pool and the stream's fetch pool)."""
    if existing is None:
        from concurrent.futures import ThreadPoolExecutor

        existing = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix=prefix
        )
    return existing


def make_cache_groups(
    cfg: EmbeddingConfig, rows_per_group: Dict[int, int],
    sparse_cfg: OptimizerConfig, exclude: Sequence[str] = (),
) -> Tuple[List[CacheGroup], Tuple[str, ...]]:
    """Group slots by dim (all same-dim slots share one row pool; cross-slot
    sign collisions are handled by the group-level dedup in
    ``CachedEmbeddingTier.prepare_batch``, so a prefix-bit-0 config cannot
    violate the directory's distinct-signs contract).

    Returns ``(groups, ps_slots)``: hash-stack slots (many table keys per
    id — uncacheable by construction) and any ``exclude``d names ride the
    pure worker/PS path inside the same ctx (the mixed-tier arrangement)."""
    unknown = set(exclude) - set(cfg.slots_config)
    if unknown:
        raise KeyError(
            f"exclude names not in embedding config: {sorted(unknown)}"
        )
    by_dim: Dict[int, Tuple[List[str], List[str]]] = {}
    ps_slots: List[str] = []
    for name, slot in cfg.slots_config.items():
        if slot.hash_stack_config.enabled or name in exclude:
            ps_slots.append(name)
            continue
        pooled, raw = by_dim.setdefault(slot.dim, ([], []))
        (pooled if slot.embedding_summation else raw).append(name)
    groups = []
    for dim in sorted(by_dim):
        pooled, raw = by_dim[dim]
        groups.append(
            CacheGroup(
                name=f"cache_d{dim}",
                dim=dim,
                rows=rows_per_group[dim],
                state_dim=sparse_cfg.state_dim(dim),
                pooled_slots=tuple(sorted(pooled)),
                raw_slots=tuple(sorted(raw)),
            )
        )
    return groups, tuple(sorted(ps_slots))


def init_cached_tables(
    groups: Sequence[CacheGroup], sparse_cfg: OptimizerConfig, dtype=jnp.float32
):
    """Zeroed row pools (+1 pad row at index C whose zeros absorb padding
    gathers). Content arrives via checkout scatters; initial values are
    irrelevant except the pad row, which the masked sparse update never
    touches."""
    from persia_tpu.ops.sparse_update import init_sparse_state

    tables, emb_state = {}, {}
    for g in groups:
        tables[g.name] = jnp.zeros((g.rows + 1, g.dim), dtype=dtype)
        emb_state[g.name] = init_sparse_state(sparse_cfg, g.rows + 1, g.dim)
    return tables, emb_state


def _entry_to_state_cols(state: Dict[str, jnp.ndarray], entry_tail):
    """Split the PS entry's state tail (M, state_dim) into sparse_update's
    per-key columns — PS entry layout is [emb | acc] (adagrad) or
    [emb | m | v] (adam), `persia_tpu/embedding/optim.py` init_state /
    update_dense."""
    out = {}
    off = 0
    for key in ("acc", "m", "v"):
        if key in state:
            w = state[key].shape[1]
            out[key] = entry_tail[:, off:off + w]
            off += w
    return out


# ----------------------------------------------------------- device step


def _model_emb_from_gathered(
    groups: Sequence[CacheGroup],
    batch: Dict,
    layout: "CacheLayout",
    stacked_gathered: Dict[str, jnp.ndarray],
    raw_gathered: Dict[str, jnp.ndarray],
    pad_row: Callable[[str], int],
    ps_model_inputs: Optional[List] = None,
):
    """Build the per-slot model input list (global sorted slot order) from
    the per-group stacked gather and per-slot raw gathers. ``pad_row(gname)``
    returns the row index whose gather must be masked out (the zero pad)."""
    slot_emb: Dict[str, object] = {}
    stacked_names = dict(layout.stacked)
    for gname, got in stacked_gathered.items():
        rows = batch["stacked_rows"][gname]  # (S, B, L)
        mask = rows != pad_row(gname)
        m = mask[..., None].astype(got.dtype)
        pooled = (got * m).sum(axis=2)  # (S, B, dim)
        scale = batch.get("stacked_scale", {}).get(gname)
        if scale is not None:
            pooled = pooled * scale[..., None].astype(pooled.dtype)
        for i, name in enumerate(stacked_names[gname]):
            slot_emb[name] = pooled[i]
    for name, got in raw_gathered.items():
        gname = _slot_group_of(groups, name)
        rows = batch["raw_rows"][name]
        slot_emb[name] = (got, rows != pad_row(gname))
    if ps_model_inputs is not None:
        # mixed-tier: worker/PS-served slots join the cached ones in the
        # same globally-sorted slot order the model expects
        for name, emb in zip(layout.ps, ps_model_inputs):
            slot_emb[name] = emb
    return [slot_emb[n] for n in sorted(slot_emb)]


def _slot_group_of(groups: Sequence[CacheGroup], slot: str) -> str:
    for g in groups:
        if slot in g.slots:
            return g.name
    raise KeyError(slot)


@dataclass(frozen=True)
class CacheLayout:
    """Static (hashable) description of which slots a batch carries —
    ``stacked``: ((group, (slot, ...)), ...) in stack order. Passed as a
    static jit argument so slot membership never rides in the traced pytree
    (it changes at most a handful of times per run)."""

    stacked: Tuple[Tuple[str, Tuple[str, ...]], ...]
    # mixed-tier: slot names served by the worker/PS path (hash-stack or
    # explicitly excluded), in the order their entries ride batch["ps_emb"]
    ps: Tuple[str, ...] = ()


# Tiny per-group device ops kept OUT of the main train step so that the
# variable miss/evict counts (pow2-bucketed) only ever recompile these
# trivial programs, never the model fwd/bwd. The main step's shapes are
# fixed per (B, L, slot-layout) and compile exactly once.


from functools import partial as _partial


def _scatter_entry_block(table, state: Dict[str, jnp.ndarray], rows, entries):
    """Shared body: scatter ``[emb | state]`` rows into the cache pools
    (out-of-range pad rows drop)."""
    dim = table.shape[1]
    table = table.at[rows].set(entries[:, :dim].astype(table.dtype), mode="drop")
    out_state = dict(state)
    cols = _entry_to_state_cols(out_state, entries[:, dim:])
    for key, vals in cols.items():
        out_state[key] = out_state[key].at[rows].set(
            vals.astype(out_state[key].dtype), mode="drop"
        )
    return table, out_state


@jax.jit
def _gather_entry_rows(table, state: Dict[str, jnp.ndarray], rows):
    """(K, dim + state_dim) ``[emb | state]`` of the given rows — the
    flush/publish read path (device gather, then ONE bounded d2h)."""
    parts = [table[rows]]
    for key in ("acc", "m", "v"):
        if key in state:
            parts.append(state[key][rows])
    return jnp.concatenate(parts, axis=1)


@_partial(jax.jit, donate_argnums=(0, 1))
def _restore_rows(table, state: Dict[str, jnp.ndarray], payload, src_idx, dst_rows):
    """Re-admit rows whose write-back is still in flight straight from the
    DEVICE-resident eviction payload (device→host transfers on a
    remote-attached chip cost ~60 ms latency each — the hazard path must
    never wait on one)."""
    return _scatter_entry_block(table, state, dst_rows, payload[src_idx])


@_partial(jax.jit, donate_argnums=(0, 1), static_argnums=(7, 8))
def _apply_aux(table, state: Dict[str, jnp.ndarray], ev_rows, m_rows,
               m_entries, c_rows, c_emb, state_consts, wb_bf16=False):
    """Fused per-group per-step aux program: read the eviction payload (from
    the PRE-scatter table — a missed row may reuse an evicted one), then
    scatter warm entries and cold seeds. One dispatch instead of three:
    after the first write-back d2h the runtime's per-dispatch latency
    degrades ~200× (see ``train_stream``), so the steady-state eviction
    regime pays per CALL, not per byte. Absent pieces ride as 0-row arrays.

    Compile-cache tradeoff: fusing keys the jit on the COMBINATION of the
    three piece-size buckets (worst case the cross-product, vs the per-piece
    sum for split jits). In practice the regimes are disjoint — fill phase
    is cold-only, steady state is (warm, evict) in one or two stable buckets
    each with cold decaying — so observed combinations stay within a few
    dozen tiny programs; the per-call dispatch saving dominates once the
    runtime is in the degraded-dispatch mode."""
    parts = [table[ev_rows]]
    for key in ("acc", "m", "v"):
        if key in state:
            parts.append(state[key][ev_rows])
    payload = jnp.concatenate(parts, axis=1)
    if wb_bf16:
        # bf16 write-back wire (the reference ships f16 lookup/grad wires,
        # lib.rs:157-180): halves the d2h bytes that bound the eviction
        # steady state; opt-in because the default tier is bit-exact
        payload = payload.astype(jnp.bfloat16)
    table, out_state = _scatter_entry_block(table, state, m_rows, m_entries)
    table = table.at[c_rows].set(c_emb.astype(table.dtype), mode="drop")
    for key, val in state_consts:
        st = out_state[key]
        fill = jnp.full((c_rows.shape[0], st.shape[1]), val, dtype=st.dtype)
        out_state[key] = st.at[c_rows].set(fill, mode="drop")
    return table, out_state, payload


@_partial(jax.jit, donate_argnums=(0, 1, 2), static_argnums=(9, 10))
def _apply_aux_ring(table, state: Dict[str, jnp.ndarray], ring, ring_pos,
                    ev_rows, m_rows, m_entries, c_rows, c_emb, state_consts,
                    wb_bf16=False):
    """``_apply_aux`` + one extra fused write: the eviction payload also
    lands in the group's standing DEVICE ring at ``ring_pos``. The stream's
    hazard restores then gather straight from the ring — ONE
    ``_restore_rows`` per group per step regardless of how many in-flight
    steps' payloads are referenced, where per-payload restores cost one
    degraded-latency dispatch EACH (measured 35 ms/step of a 129 ms wall at
    saturation). The per-step payload array is still returned for the
    write-back thread's bounded d2h fetch."""
    table, out_state, payload = _apply_aux(
        table, state, ev_rows, m_rows, m_entries, c_rows, c_emb,
        state_consts, wb_bf16,
    )
    ring = jax.lax.dynamic_update_slice(
        ring, payload.astype(ring.dtype), (ring_pos, 0)
    )
    return table, out_state, ring, payload


def _state_init_consts(cfg: OptimizerConfig):
    """(key, scalar) pairs for a fresh entry's optimizer-state tail —
    mirrors ``init_sparse_state`` / the PS's ``init_state``."""
    from persia_tpu.embedding.optim import OPTIMIZER_ADAGRAD

    if cfg.kind == OPTIMIZER_ADAGRAD:
        return (("acc", float(cfg.initialization)),)
    if cfg.kind == OPTIMIZER_ADAM:
        return (("m", 0.0), ("v", 0.0))
    return ()


# _bucket lives in hbm_cache.common (leaf module) — re-exported above for
# the step/stream/tier/ctx imports that predate the package split.



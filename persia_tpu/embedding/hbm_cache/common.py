"""Leaf helpers shared across the hbm_cache package.

This module must stay import-free of the package's other modules —
``directory`` and ``groups`` both depend on it, so anything here that
imported back from them would recreate the cycle the round-4 package
split tripped over.
"""

from __future__ import annotations

from persia_tpu.utils import round_up_pow2 as _round_up_pow2


def _bucket(m: int) -> int:
    """Padded size: pow2 below 4096, then 4096-multiples (the miss arrays are
    the dominant per-step transfer — pow2 padding would waste up to 2×)."""
    return _round_up_pow2(m) if m < 4096 else -(-m // 4096) * 4096

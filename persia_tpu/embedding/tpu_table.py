"""On-TPU sharded embedding tables (the HBM fast path).

The reference keeps ALL embedding tables on CPU parameter servers
(`rust/persia-embedding-server/src/embedding_parameter_service/mod.rs`) because
GPU memory can't hold them. A TPU pod has a different sweet spot: tables up to
a few hundred GB fit in pooled HBM when sharded over a mesh axis, and lookups
become on-device gathers + an ICI ``psum`` — no host round-trip, no staleness,
trained synchronously by the same optimizer step as the dense half.

persia_tpu therefore has two embedding tiers:

- **Host PS tier** (`persia_tpu.embedding.store` / `native_store`): unbounded
  vocab, LRU eviction, async bounded-staleness updates — parity with the
  reference, for the 100T-scale tail.
- **This module**: medium tables resident in HBM, rows sharded over the ``ep``
  mesh axis, lookup = local gather masked to the shard's row range + ``psum``
  over ``ep``. Gradients flow through plain autodiff: the local gather's
  transpose is a scatter-add into the local shard, so the update is exact and
  synchronous.

Everything is functional: tables are pytree leaves you put in the optax param
tree. ``EmbeddingSpec``/``create_tables``/``embedding_lookup``/``embedding_bag``.
"""

from __future__ import annotations

import functools

from persia_tpu.parallel.mesh import shard_map_compat
from dataclasses import dataclass
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class EmbeddingSpec:
    """Declares one HBM-resident table (ref capability: SlotConfig dim/init,
    `rust/persia-embedding-config/src/lib.rs:528-560`, minus LRU)."""

    vocab: int
    dim: int
    init_scale: float = 0.01


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def table_sharding(mesh: Mesh, axis: str = "ep") -> NamedSharding:
    """Rows over ``axis``, embedding dim replicated."""
    return NamedSharding(mesh, P(axis, None))


def create_table(
    key: jax.Array,
    spec: EmbeddingSpec,
    mesh: Mesh,
    axis: str = "ep",
    dtype=jnp.float32,
) -> jax.Array:
    """Uniform(-init_scale, init_scale) table, padded to the shard count
    (padding rows zeroed) and placed with rows sharded over ``axis``."""
    n = mesh.shape[axis]
    vpad = _round_up(spec.vocab, n)
    tbl = jax.random.uniform(
        key, (vpad, spec.dim), dtype=dtype, minval=-spec.init_scale, maxval=spec.init_scale
    )
    if vpad > spec.vocab:
        tbl = tbl.at[spec.vocab :].set(0.0)
    return jax.device_put(tbl, table_sharding(mesh, axis))


def create_tables(
    key: jax.Array,
    specs: Dict[str, EmbeddingSpec],
    mesh: Mesh,
    axis: str = "ep",
    dtype=jnp.float32,
) -> Dict[str, jax.Array]:
    keys = jax.random.split(key, len(specs))
    return {
        name: create_table(k, spec, mesh, axis, dtype)
        for k, (name, spec) in zip(keys, sorted(specs.items()))
    }


def _local_lookup(tbl, ids, axis: str):
    """Per-shard gather: rows outside this shard contribute zeros; psum over
    ``axis`` assembles the full embedding. ids may be any integer shape."""
    rows = tbl.shape[0]
    start = lax.axis_index(axis) * rows
    loc = ids.astype(jnp.int32) - start
    valid = (loc >= 0) & (loc < rows)
    emb = jnp.take(tbl, jnp.clip(loc, 0, rows - 1), axis=0)
    emb = jnp.where(valid[..., None], emb, jnp.zeros((), emb.dtype))
    return lax.psum(emb, axis)


def embedding_lookup(
    table: jax.Array,
    ids: jax.Array,
    mesh: Mesh,
    axis: str = "ep",
    data_axis: Optional[str] = None,
) -> jax.Array:
    """ids [...] int → embeddings [..., dim].

    ``data_axis``: if given, the leading axis of ``ids`` is sharded over that
    mesh axis (composing DP with embedding parallelism); output is sharded the
    same way. Ids must lie in [0, vocab); ids in [vocab, padded_rows) hit the
    zero-initialized padding rows, ids >= padded_rows return zeros.
    """
    ids_spec = P(data_axis) if data_axis else P()
    fn = shard_map_compat(
        functools.partial(_local_lookup, axis=axis),
        mesh=mesh,
        in_specs=(P(axis, None), ids_spec),
        out_specs=ids_spec,
        check_vma=False,
    )
    return fn(table, ids)


def embedding_bag(
    table: jax.Array,
    ids: jax.Array,
    mesh: Mesh,
    axis: str = "ep",
    data_axis: Optional[str] = None,
    mode: str = "sum",
    sqrt_scaling: bool = False,
) -> jax.Array:
    """Pooled lookup over the last ids axis (ref: sum-pooling postprocess,
    `embedding_worker_service/mod.rs:537-584`).

    ids [..., L] with negative entries masked out (padding). mode: "sum" |
    "mean". ``sqrt_scaling`` divides the sum by sqrt(count) like the
    reference's optional scaling (sum mode only).
    """
    if mode not in ("sum", "mean"):
        raise ValueError(f"mode must be sum|mean, got {mode}")
    if mode == "mean" and sqrt_scaling:
        raise ValueError("sqrt_scaling only applies to mode='sum'")
    mask = ids >= 0
    safe_ids = jnp.where(mask, ids, 0)
    emb = embedding_lookup(table, safe_ids, mesh, axis, data_axis)
    emb = emb * mask[..., None].astype(emb.dtype)
    pooled = jnp.sum(emb, axis=-2)
    count = jnp.maximum(jnp.sum(mask, axis=-1), 1).astype(pooled.dtype)
    if mode == "mean":
        pooled = pooled / count[..., None]
    elif sqrt_scaling:
        pooled = pooled / jnp.sqrt(count)[..., None]
    return pooled


def lookup_all(
    tables: Dict[str, jax.Array],
    ids: Dict[str, jax.Array],
    mesh: Mesh,
    axis: str = "ep",
    data_axis: Optional[str] = None,
) -> Dict[str, jax.Array]:
    """Batched convenience: per-slot pooled (2-D ids) or single-id lookup."""
    out = {}
    for name, tbl in tables.items():
        i = ids[name]
        if i.ndim >= 2:
            out[name] = embedding_bag(tbl, i, mesh, axis, data_axis)
        else:
            out[name] = embedding_lookup(tbl, i, mesh, axis, data_axis)
    return out

"""Sparse checkpoint subsystem: per-shard files, done markers, re-shard on
load, async status machine.

Parity target: `rust/persia-model-manager/src/lib.rs`:
- status machine {Dumping(progress), Loading(progress), Idle, Failed}
  (lib.rs:63-69)
- per-internal-shard files ``replica_{r}_shard_{i}.emb`` (lib.rs:242-343)
- done-marker file ``embedding_dump_done`` with model info (lib.rs:152-198);
  master waits for all replicas (lib.rs:200-240)
- load = parallel file reads → insert (lib.rs:375-425); replica-count change
  re-shards by sign routing (ref: emb_worker:1150-1259)

All IO goes through :mod:`persia_tpu.storage` (the ``persia-storage``
equivalent), so checkpoint directories can live on local disk, ``hdfs://``
or ``gs://`` transparently.

File payloads use the store's shard wire format (u32 count, then per entry
u64 sign / u32 dim / u32 len / f32 data) — identical for the numpy and C++
backends."""

from __future__ import annotations

import io
import json
import struct
import threading
import time
import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Union

import numpy as np

from persia_tpu.embedding.hashing import sign_to_shard
from persia_tpu.logger import get_default_logger
from persia_tpu.storage import StoragePath, storage_path

logger = get_default_logger("persia_tpu.checkpoint")

DONE_MARKER = "embedding_dump_done"

# integrity trailer on every shard file: crc32 (LE u32) + magic. Legacy
# files (no magic) still load; a file carrying the magic with a mismatched
# crc — or a truncated/garbled payload — raises CorruptCheckpointError
# instead of silently loading a torn shard.
_CRC_MAGIC = b"PCK1"


class CorruptCheckpointError(RuntimeError):
    """A checkpoint shard file is torn or corrupt (crc/format mismatch)."""


def _wrap_shard_blob(data: bytes) -> bytes:
    return data + struct.pack("<I", zlib.crc32(data) & 0xFFFFFFFF) + _CRC_MAGIC


def _unwrap_shard_blob(blob: bytes, name: str) -> bytes:
    """Strip + verify the crc trailer; legacy (magic-less) blobs pass
    through for the format check in the store's loader."""
    if len(blob) >= 8 and blob[-4:] == _CRC_MAGIC:
        data, (crc,) = blob[:-8], struct.unpack("<I", blob[-8:-4])
        if (zlib.crc32(data) & 0xFFFFFFFF) != crc:
            raise CorruptCheckpointError(
                f"shard file {name} failed its crc32 check — the checkpoint "
                "is corrupt (torn write or bit rot); fall back to an older "
                "checkpoint"
            )
        return data
    return blob


class ModelManagerStatus:
    """Thread-safe status machine (ref: lib.rs:63-69)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._state = "idle"
        self._progress = 0.0
        self._error: Optional[str] = None

    def set(self, state: str, progress: float = 0.0, error: Optional[str] = None):
        with self._lock:
            self._state, self._progress, self._error = state, progress, error

    def get(self) -> Dict:
        with self._lock:
            return {"status": self._state, "progress": self._progress, "error": self._error}


def _shard_name(replica: int, shard: int) -> str:
    return f"replica_{replica}_shard_{shard}.emb"


def _marker_name(replica: int) -> str:
    return f"replica_{replica}_done"


def _read_json(path: StoragePath) -> Optional[Dict]:
    from persia_tpu.storage import StorageError

    try:
        return json.loads(path.read_text())
    except (OSError, ValueError, StorageError):
        return None


def dump_store(
    store,
    dst_dir: Union[str, StoragePath],
    replica_index: int = 0,
    replica_size: int = 1,
    status: Optional[ModelManagerStatus] = None,
    num_io_threads: int = 4,
    session: Optional[str] = None,
) -> None:
    """Dump one PS replica's shards in parallel + markers. The last replica to
    finish writes the master done-marker (ref: lib.rs:200-240).

    ``session`` tags this dump across replicas: stale markers from a previous
    dump into the same directory cannot prematurely complete this one. The
    caller fanning out to replicas passes one shared session id; a lone
    replica can leave it None (a fresh one is derived from the start time).
    """
    status = status or ModelManagerStatus()
    status.set("dumping", 0.0)
    session = session or f"s{time.time_ns()}"
    root = storage_path(dst_dir)
    try:
        root.makedirs()
        # invalidate any previous dump in this directory before writing
        done_path = root.join(DONE_MARKER)
        if done_path.exists():
            done_path.remove()
        my_marker = root.join(_marker_name(replica_index))
        if my_marker.exists():
            my_marker.remove()
        n = store.num_internal_shards
        for old in root.list():
            if old.startswith(f"replica_{replica_index}_shard_"):
                idx = old.split("_shard_")[1].split(".")[0]
                if idx.isdigit() and int(idx) >= n:
                    root.join(old).remove()
        done = 0
        lock = threading.Lock()

        def dump_one(i: int):
            nonlocal done
            blob = store.dump_shard(i)
            # write_bytes is temp + fsync + atomic rename (storage.DiskPath),
            # so a crash mid-dump can never leave a torn shard under the
            # final name; the crc trailer catches everything else on load
            root.join(_shard_name(replica_index, i)).write_bytes(
                _wrap_shard_blob(blob)
            )
            with lock:
                done += 1
                status.set("dumping", done / n)

        with ThreadPoolExecutor(max_workers=num_io_threads) as pool:
            list(pool.map(dump_one, range(n)))

        my_marker.write_text(
            json.dumps({"num_internal_shards": n, "session": session, "time": time.time()})
        )

        # master marker once every replica's marker exists FOR THIS SESSION
        markers = [
            _read_json(root.join(_marker_name(r))) for r in range(replica_size)
        ]
        if all(m is not None and m.get("session") == session for m in markers):
            info = {
                "num_replicas": replica_size,
                "session": session,
                "datetime": time.strftime("%Y-%m-%dT%H:%M:%S"),
                # serving replicas use this to skip incremental packets that
                # predate the checkpoint (persia_tpu.incremental)
                "time_us": time.time_ns() // 1000,
            }
            done_path.write_text(json.dumps(info))
        status.set("idle", 1.0)
    except Exception as e:
        status.set("failed", error=repr(e))
        raise


def checkpoint_info(src_dir: Union[str, StoragePath]) -> Dict:
    return json.loads(storage_path(src_dir).join(DONE_MARKER).read_text())


def _iter_entries(blob: bytes):
    buf = io.BytesIO(blob)
    (n,) = struct.unpack("<I", buf.read(4))
    for _ in range(n):
        header = buf.read(16)
        sign, dim, ln = struct.unpack("<QII", header)
        data = buf.read(4 * ln)
        yield sign, header, data


def _filter_blob_for_replica(blob: bytes, replica_index: int, replica_size: int) -> bytes:
    """Keep only entries this replica owns under the current sign routing
    (the cross-replica re-shard path, ref: emb_worker:1192-1259)."""
    if replica_size <= 1:
        return blob
    kept: List[bytes] = []
    count = 0
    signs: List[int] = []
    parts: List[bytes] = []
    for sign, header, data in _iter_entries(blob):
        signs.append(sign)
        parts.append(header + data)
    if not signs:
        return struct.pack("<I", 0)
    owner = sign_to_shard(np.array(signs, dtype=np.uint64), replica_size)
    for i, own in enumerate(owner.tolist()):
        if own == replica_index:
            kept.append(parts[i])
            count += 1
    return struct.pack("<I", count) + b"".join(kept)


def load_store(
    store,
    src_dir: Union[str, StoragePath],
    replica_index: int = 0,
    replica_size: int = 1,
    status: Optional[ModelManagerStatus] = None,
    num_io_threads: int = 4,
    require_marker: bool = True,
) -> int:
    """Load every shard file in the checkpoint into this replica, filtering by
    current sign routing (works across replica- AND internal-shard-count
    changes — entries re-route on insert). Returns entries loaded."""
    status = status or ModelManagerStatus()
    status.set("loading", 0.0)
    root = storage_path(src_dir)
    try:
        info = _read_json(root.join(DONE_MARKER))
        if info is None:
            if require_marker:
                raise FileNotFoundError(
                    f"no valid {DONE_MARKER} in {root} (incomplete dump?)"
                )
            # markerless fallback: load every .emb file, filtered
            files = sorted(f for f in root.list() if f.endswith(".emb"))
            need_filter = replica_size > 1
        else:
            # marker-driven: only files the recorded topology actually wrote
            dumped_replicas = int(info["num_replicas"])
            files = []
            for r in range(dumped_replicas):
                if dumped_replicas == replica_size and r != replica_index:
                    continue  # same topology → only our own replica's files
                marker = _read_json(root.join(_marker_name(r)))
                shards = int(marker["num_internal_shards"]) if marker else 0
                files += [_shard_name(r, i) for i in range(shards)]
            # same topology: our own files hold exactly our signs — no filter
            need_filter = dumped_replicas != replica_size
        total = len(files)
        loaded = 0
        done = 0
        lock = threading.Lock()

        def load_one(fname: str) -> int:
            nonlocal done
            blob = _unwrap_shard_blob(root.join(fname).read_bytes(), fname)
            try:
                if need_filter:
                    blob = _filter_blob_for_replica(
                        blob, replica_index, replica_size
                    )
                n = store.load_shard_bytes(blob)
            except (struct.error, ValueError, IndexError) as e:
                # a magic-less blob that fails the wire-format parse is a
                # torn legacy file (or garbage) — surface it as corruption,
                # never as a partial load
                raise CorruptCheckpointError(
                    f"shard file {fname} does not parse as a checkpoint "
                    f"shard ({e!r}) — torn or corrupt"
                ) from e
            with lock:
                done += 1
                status.set("loading", done / max(total, 1))
            return n

        with ThreadPoolExecutor(max_workers=num_io_threads) as pool:
            loaded = sum(pool.map(load_one, files))
        status.set("idle", 1.0)
        return loaded
    except Exception as e:
        status.set("failed", error=repr(e))
        raise


def dump_dense(state_bytes: bytes, dst_dir: Union[str, StoragePath], name: str = "dense.ckpt") -> None:
    root = storage_path(dst_dir)
    root.makedirs()
    root.join(name).write_bytes(state_bytes)


def load_dense(
    src_dir: Union[str, StoragePath], name: str = "dense.ckpt",
    missing_ok: bool = False,
):
    """Read the dense blob; ``missing_ok`` returns None instead of raising
    when the checkpoint has no dense half (works on every storage backend —
    remote backends raise StorageError, not FileNotFoundError)."""
    p = storage_path(src_dir).join(name)
    if missing_ok and not p.exists():
        return None
    return p.read_bytes()

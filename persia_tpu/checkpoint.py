"""Sparse checkpoint subsystem: per-shard files, done markers, re-shard on
load, async status machine.

Parity target: `rust/persia-model-manager/src/lib.rs`:
- status machine {Dumping(progress), Loading(progress), Idle, Failed}
  (lib.rs:63-69)
- per-internal-shard files ``replica_{r}_shard_{i}.emb`` (lib.rs:242-343)
- done-marker file ``embedding_dump_done`` with model info (lib.rs:152-198);
  master waits for all replicas (lib.rs:200-240)
- load = parallel file reads → insert (lib.rs:375-425); replica-count change
  re-shards by sign routing (ref: emb_worker:1150-1259)

File payloads use the store's shard wire format (u32 count, then per entry
u64 sign / u32 dim / u32 len / f32 data) — identical for the numpy and C++
backends."""

from __future__ import annotations

import io
import json
import os
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional

import numpy as np

from persia_tpu.embedding.hashing import sign_to_shard
from persia_tpu.logger import get_default_logger

logger = get_default_logger("persia_tpu.checkpoint")

DONE_MARKER = "embedding_dump_done"


class ModelManagerStatus:
    """Thread-safe status machine (ref: lib.rs:63-69)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._state = "idle"
        self._progress = 0.0
        self._error: Optional[str] = None

    def set(self, state: str, progress: float = 0.0, error: Optional[str] = None):
        with self._lock:
            self._state, self._progress, self._error = state, progress, error

    def get(self) -> Dict:
        with self._lock:
            return {"status": self._state, "progress": self._progress, "error": self._error}


def _shard_file(dst_dir: str, replica: int, shard: int) -> str:
    return os.path.join(dst_dir, f"replica_{replica}_shard_{shard}.emb")


def _replica_marker(dst_dir: str, replica: int) -> str:
    return os.path.join(dst_dir, f"replica_{replica}_done")


def _read_json(path: str) -> Optional[Dict]:
    try:
        with open(path) as f:
            return json.loads(f.read())
    except (OSError, ValueError):
        return None


def dump_store(
    store,
    dst_dir: str,
    replica_index: int = 0,
    replica_size: int = 1,
    status: Optional[ModelManagerStatus] = None,
    num_io_threads: int = 4,
    session: Optional[str] = None,
) -> None:
    """Dump one PS replica's shards in parallel + markers. The last replica to
    finish writes the master done-marker (ref: lib.rs:200-240).

    ``session`` tags this dump across replicas: stale markers from a previous
    dump into the same directory cannot prematurely complete this one. The
    caller fanning out to replicas passes one shared session id; a lone
    replica can leave it None (a fresh one is derived from the start time).
    """
    status = status or ModelManagerStatus()
    status.set("dumping", 0.0)
    session = session or f"s{time.time_ns()}"
    try:
        os.makedirs(dst_dir, exist_ok=True)
        # invalidate any previous dump in this directory before writing
        done_path = os.path.join(dst_dir, DONE_MARKER)
        if os.path.exists(done_path):
            os.remove(done_path)
        my_marker = _replica_marker(dst_dir, replica_index)
        if os.path.exists(my_marker):
            os.remove(my_marker)
        n = store.num_internal_shards
        for old in os.listdir(dst_dir):
            if old.startswith(f"replica_{replica_index}_shard_"):
                idx = old.split("_shard_")[1].split(".")[0]
                if idx.isdigit() and int(idx) >= n:
                    os.remove(os.path.join(dst_dir, old))
        done = 0
        lock = threading.Lock()

        def dump_one(i: int):
            nonlocal done
            blob = store.dump_shard(i)
            tmp = _shard_file(dst_dir, replica_index, i) + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, _shard_file(dst_dir, replica_index, i))
            with lock:
                done += 1
                status.set("dumping", done / n)

        with ThreadPoolExecutor(max_workers=num_io_threads) as pool:
            list(pool.map(dump_one, range(n)))

        with open(my_marker + ".tmp", "w") as f:
            f.write(
                json.dumps(
                    {"num_internal_shards": n, "session": session, "time": time.time()}
                )
            )
        os.replace(my_marker + ".tmp", my_marker)

        # master marker once every replica's marker exists FOR THIS SESSION
        markers = [
            _read_json(_replica_marker(dst_dir, r)) for r in range(replica_size)
        ]
        if all(m is not None and m.get("session") == session for m in markers):
            info = {
                "num_replicas": replica_size,
                "session": session,
                "datetime": time.strftime("%Y-%m-%dT%H:%M:%S"),
            }
            with open(done_path + ".tmp", "w") as f:
                f.write(json.dumps(info))
            os.replace(done_path + ".tmp", done_path)
        status.set("idle", 1.0)
    except Exception as e:
        status.set("failed", error=repr(e))
        raise


def checkpoint_info(src_dir: str) -> Dict:
    with open(os.path.join(src_dir, DONE_MARKER)) as f:
        return json.loads(f.read())


def _iter_entries(blob: bytes):
    buf = io.BytesIO(blob)
    (n,) = struct.unpack("<I", buf.read(4))
    for _ in range(n):
        header = buf.read(16)
        sign, dim, ln = struct.unpack("<QII", header)
        data = buf.read(4 * ln)
        yield sign, header, data


def _filter_blob_for_replica(blob: bytes, replica_index: int, replica_size: int) -> bytes:
    """Keep only entries this replica owns under the current sign routing
    (the cross-replica re-shard path, ref: emb_worker:1192-1259)."""
    if replica_size <= 1:
        return blob
    kept: List[bytes] = []
    count = 0
    signs: List[int] = []
    parts: List[bytes] = []
    for sign, header, data in _iter_entries(blob):
        signs.append(sign)
        parts.append(header + data)
    if not signs:
        return struct.pack("<I", 0)
    owner = sign_to_shard(np.array(signs, dtype=np.uint64), replica_size)
    for i, own in enumerate(owner.tolist()):
        if own == replica_index:
            kept.append(parts[i])
            count += 1
    return struct.pack("<I", count) + b"".join(kept)


def load_store(
    store,
    src_dir: str,
    replica_index: int = 0,
    replica_size: int = 1,
    status: Optional[ModelManagerStatus] = None,
    num_io_threads: int = 4,
    require_marker: bool = True,
) -> int:
    """Load every shard file in the checkpoint into this replica, filtering by
    current sign routing (works across replica- AND internal-shard-count
    changes — entries re-route on insert). Returns entries loaded."""
    status = status or ModelManagerStatus()
    status.set("loading", 0.0)
    try:
        info = _read_json(os.path.join(src_dir, DONE_MARKER))
        if info is None:
            if require_marker:
                raise FileNotFoundError(
                    f"no valid {DONE_MARKER} in {src_dir} (incomplete dump?)"
                )
            # markerless fallback: load every .emb file, filtered
            files = sorted(f for f in os.listdir(src_dir) if f.endswith(".emb"))
            need_filter = replica_size > 1
        else:
            # marker-driven: only files the recorded topology actually wrote
            dumped_replicas = int(info["num_replicas"])
            files = []
            for r in range(dumped_replicas):
                if dumped_replicas == replica_size and r != replica_index:
                    continue  # same topology → only our own replica's files
                marker = _read_json(_replica_marker(src_dir, r))
                shards = int(marker["num_internal_shards"]) if marker else 0
                files += [
                    os.path.basename(_shard_file(src_dir, r, i)) for i in range(shards)
                ]
            # same topology: our own files hold exactly our signs — no filter
            need_filter = dumped_replicas != replica_size
        total = len(files)
        loaded = 0
        done = 0
        lock = threading.Lock()

        def load_one(fname: str) -> int:
            nonlocal done
            with open(os.path.join(src_dir, fname), "rb") as f:
                blob = f.read()
            if need_filter:
                blob = _filter_blob_for_replica(blob, replica_index, replica_size)
            n = store.load_shard_bytes(blob)
            with lock:
                done += 1
                status.set("loading", done / max(total, 1))
            return n

        with ThreadPoolExecutor(max_workers=num_io_threads) as pool:
            loaded = sum(pool.map(load_one, files))
        status.set("idle", 1.0)
        return loaded
    except Exception as e:
        status.set("failed", error=repr(e))
        raise


def dump_dense(state_bytes: bytes, dst_dir: str, name: str = "dense.ckpt") -> None:
    os.makedirs(dst_dir, exist_ok=True)
    tmp = os.path.join(dst_dir, name + ".tmp")
    with open(tmp, "wb") as f:
        f.write(state_bytes)
    os.replace(tmp, os.path.join(dst_dir, name))


def load_dense(src_dir: str, name: str = "dense.ckpt") -> bytes:
    with open(os.path.join(src_dir, name), "rb") as f:
        return f.read()

"""Flash attention as a Pallas TPU kernel.

Tiled online-softmax attention: the [L, L] score matrix is never
materialized in HBM. Grid = (B*H, q_blocks, k_blocks); the innermost grid
dimension is sequential on TPU, so VMEM scratch carries the (m, l, acc)
online-softmax state across k blocks and the output block is written once on
the last k step. fp32 accumulation regardless of input dtype; MXU matmuls via
``preferred_element_type``.

Off-TPU (tests, CPU dry runs) the kernel runs in interpret mode. The backward
pass recomputes attention densely under XLA (``@jax.custom_vjp``) — exact
gradients, O(L^2) memory on the backward only.

Used by the model zoo for long user-behavior sequences (DIN-style attention)
and usable as the local block of ring attention for L/n still too large for
dense scores.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_BIG = -1e30


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
               *, scale: float, causal: bool, block_q: int, block_k: int,
               seq_len: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_BIG)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Causal: blocks entirely above the diagonal contribute nothing — skip
    # their compute (their DMA is already pipelined; compute is the cost).
    block_live = True
    if causal:
        block_live = ki * block_k <= qi * block_q + block_q - 1

    @pl.when(block_live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)  # [block_q, d]
        k = k_ref[0].astype(jnp.float32)  # [block_k, d]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [block_q, block_k]

        q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = k_pos < seq_len  # padded keys never attend
        if causal:
            mask = mask & (k_pos <= q_pos)
        s = jnp.where(mask, s, _NEG_BIG)

        m_prev = m_ref[:]                       # [block_q, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m_prev - m_new)          # [block_q, 1]
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[:] = m_new

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _fa_forward(q, k, v, scale, causal, block_q, block_k, interpret):
    b, l, h, d = q.shape
    # Snap the block cap to a power of two so clamping can't produce a block
    # that fails to divide the padded length; pad to lcm(bq, bk) so BOTH
    # grids cover every row/column.
    cap = 8
    while cap < _round_up(l, 8):
        cap *= 2
    bq = min(block_q, cap)
    bk = min(block_k, cap)
    lp = _round_up(l, math.lcm(bq, bk))

    def prep(x):
        x = jnp.moveaxis(x, 2, 1).reshape(b * h, l, d)
        return jnp.pad(x, ((0, 0), (0, lp - l), (0, 0)))

    qf, kf, vf = prep(q), prep(k), prep(v)
    grid = (b * h, lp // bq, lp // bk)
    out = pl.pallas_call(
        functools.partial(
            _fa_kernel, scale=scale, causal=causal,
            block_q=bq, block_k=bk, seq_len=l,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, lp, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),   # m
            pltpu.VMEM((bq, 1), jnp.float32),   # l
            pltpu.VMEM((bq, d), jnp.float32),   # acc
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return jnp.moveaxis(out[:, :l, :].reshape(b, h, l, d), 1, 2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret):
    return _fa_forward(q, k, v, scale, causal, block_q, block_k, interpret)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    return _fa_forward(q, k, v, scale, causal, block_q, block_k, interpret), (q, k, v)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, g):
    from persia_tpu.parallel.sequence import reference_attention

    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: reference_attention(q, k, v, causal=causal, scale=scale), q, k, v
    )
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 256,
    block_k: int = 512,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Tiled attention: q, k, v [B, L, H, D] → [B, L, H, D].

    ``interpret=None`` auto-selects interpret mode off-TPU so the same call
    sites work in CPU tests and on hardware.
    """
    if q.ndim != 4:
        raise ValueError(f"expected [B, L, H, D], got shape {q.shape}")
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash(q, k, v, scale, causal, block_q, block_k, interpret)

"""Pallas TPU kernels for the hot ops (VMEM-tiled, MXU-shaped).

The reference's hand-written hot loops are CPU AVX2 kernels
(`rust/persia-simd/src/lib.rs`) — those stay on the host-PS side (see
``native/ps.cpp``). This package is the device-side counterpart: Pallas
kernels for ops where XLA's default fusion leaves performance on the table.
"""

from persia_tpu.ops.flash_attention import flash_attention  # noqa: F401

"""Device-side sparse optimizer updates for HBM-resident embedding tables.

The reference applies sparse optimizers on the CPU parameter server with AVX2
kernels after the embedding worker has *accumulated gradients per sign*
(`embedding_worker_service/mod.rs:703-872` sums duplicate-id gradients, then
`embedding_parameter_service/mod.rs:359-427` runs `Optimizable::update` per
row). This module is the TPU counterpart for tables that live in HBM: the
same per-unique-row math (`persia_tpu/embedding/optim.py` — SGD / Adagrad
(±vectorwise-shared) / Adam), expressed as static-shape XLA:

1. sort ids, segment-sum duplicate gradients (the worker's per-sign
   accumulation),
2. gather the touched rows + optimizer state,
3. apply the optimizer math on the (N, dim) block,
4. scatter-add the deltas back (invalid tail rows contribute exact zeros).

Everything is functional and jit/grad/shard friendly; no dynamic shapes.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from persia_tpu.embedding.optim import (
    OPTIMIZER_ADAGRAD,
    OPTIMIZER_ADAM,
    OPTIMIZER_SGD,
    OptimizerConfig,
)


def init_sparse_state(cfg: OptimizerConfig, vocab: int, dim: int) -> Dict[str, jnp.ndarray]:
    """Per-table optimizer state arrays (the HBM layout of the reference's
    trailing `[emb | state]` block, `persia-embedding-holder/src/emb_entry.rs:16-76`)."""
    if cfg.kind == OPTIMIZER_SGD:
        return {}
    if cfg.kind == OPTIMIZER_ADAGRAD:
        width = 1 if cfg.vectorwise_shared else dim
        return {"acc": jnp.full((vocab, width), cfg.initialization, dtype=jnp.float32)}
    if cfg.kind == OPTIMIZER_ADAM:
        return {
            "m": jnp.zeros((vocab, dim), dtype=jnp.float32),
            "v": jnp.zeros((vocab, dim), dtype=jnp.float32),
        }
    raise ValueError(f"unknown optimizer kind {cfg.kind}")


_PAD_SENTINEL = np.iinfo(np.int32).max


def dedup_gradients(
    ids: jnp.ndarray, grads: jnp.ndarray, mask: jnp.ndarray = None
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Per-sign gradient accumulation with static shapes.

    ids (N,) int, grads (N, D) → (uid (N,), gsum (N, D), valid (N,) bool).
    Row k < num_unique holds the k-th distinct id (ascending) and the sum of
    its gradients; rows past num_unique are garbage flagged invalid.
    ``mask`` (N,) bool marks live entries: masked-out entries (batch padding)
    are routed to an out-of-vocab sentinel that sorts last and is flagged
    invalid, so padding can never touch a real row — not even through
    weight decay, which applies to every *touched* row.
    """
    n = ids.shape[0]
    if mask is not None:
        ids = jnp.where(mask, ids, _PAD_SENTINEL)
        grads = grads * mask[..., None].astype(grads.dtype)
    order = jnp.argsort(ids)
    sids = ids[order]
    sg = grads[order]
    is_new = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), sids[1:] != sids[:-1]]
    )
    seg = jnp.cumsum(is_new) - 1  # (N,) segment index per sorted element
    gsum = jax.ops.segment_sum(sg, seg, num_segments=n)
    uid = jnp.zeros((n,), dtype=ids.dtype).at[seg].set(sids)
    valid = (jnp.arange(n) <= seg[-1]) & (uid != _PAD_SENTINEL)
    return uid, gsum, valid


def _apply_rows(
    cfg: OptimizerConfig,
    w: jnp.ndarray,
    st: Dict[str, jnp.ndarray],
    g: jnp.ndarray,
    batch_state: jnp.ndarray,
):
    """Optimizer math on a dense (N, D) block of touched rows — mirrors
    ``OptimizerConfig.update_dense`` bit-for-bit in f32."""
    w = w.astype(jnp.float32)
    g = g.astype(jnp.float32)
    # weight decay applies to SGD/Adagrad only — the reference's Adam branch
    # has no decay term (persia_tpu/embedding/optim.py update_dense,
    # mirroring persia-common/src/optim.rs adam_avx2)
    if cfg.weight_decay and cfg.kind in (OPTIMIZER_SGD, OPTIMIZER_ADAGRAD):
        g = g + cfg.weight_decay * w
    if cfg.kind == OPTIMIZER_SGD:
        return w - cfg.lr * g, {}
    if cfg.kind == OPTIMIZER_ADAGRAD:
        if cfg.vectorwise_shared:
            g2 = jnp.mean(g * g, axis=-1, keepdims=True)  # (N, 1)
            acc = st["acc"] * cfg.g_square_momentum + g2
            new_w = w - cfg.lr * g / jnp.sqrt(acc + cfg.eps)
        else:
            acc = st["acc"] * cfg.g_square_momentum + g * g
            new_w = w - cfg.lr * g / jnp.sqrt(acc + cfg.eps)
        return new_w, {"acc": acc}
    if cfg.kind == OPTIMIZER_ADAM:
        m = st["m"] * cfg.beta1 + (1.0 - cfg.beta1) * g
        v = st["v"] * cfg.beta2 + (1.0 - cfg.beta2) * g * g
        beta1_pow, beta2_pow = batch_state[0], batch_state[1]
        m_hat = m / (1.0 - beta1_pow)
        v_hat = v / (1.0 - beta2_pow)
        new_w = w - cfg.lr * m_hat / (jnp.sqrt(v_hat) + cfg.eps)
        return new_w, {"m": m, "v": v}
    raise ValueError(f"unknown optimizer kind {cfg.kind}")


def sparse_update(
    cfg: OptimizerConfig,
    table: jnp.ndarray,
    state: Dict[str, jnp.ndarray],
    ids: jnp.ndarray,
    grads: jnp.ndarray,
    batch_state: jnp.ndarray = None,
    mask: jnp.ndarray = None,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Apply one sparse optimizer step for the rows named by ``ids``.

    table (V, D) f32, state from ``init_sparse_state``, ids (N,) int,
    grads (N, D). Duplicate ids have their gradients summed first (reference
    worker semantics). ``batch_state`` = (beta1^t, beta2^t) f32[2] for Adam
    (the reference's per-feature-group accumulated beta powers,
    `persia-common/src/optim.rs:99-221`). ``mask`` (N,) bool marks live
    entries; masked-out (padding) entries touch no row at all.
    Rows only touched with zero effective delta are bit-identical unchanged.
    """
    if batch_state is None:
        batch_state = jnp.ones((2,), dtype=jnp.float32)
    ids = ids.astype(jnp.int32)
    uid, gsum, valid = dedup_gradients(ids, grads, mask)
    w = table[uid]  # OOB sentinel rows clamp-gather; their deltas are dropped
    st_rows = {k: v[uid] for k, v in state.items()}
    new_w, new_st = _apply_rows(cfg, w, st_rows, gsum, batch_state)
    vcol = valid[:, None]
    table = table.at[uid].add(
        jnp.where(vcol, new_w - w.astype(jnp.float32), 0.0).astype(table.dtype),
        mode="drop",
    )
    out_state = {}
    for k, full in state.items():
        delta = jnp.where(vcol, new_st[k] - st_rows[k], 0.0)
        out_state[k] = full.at[uid].add(delta.astype(full.dtype), mode="drop")
    return table, out_state


def masked_flat_ids_grads(
    ids: jnp.ndarray, grads: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Flatten bag/single-id slots for ``sparse_update``: ids (B,) or (B, L)
    with -1 padding + per-position grads → (flat_ids, flat_grads (N, D),
    flat_mask). Padding keeps its -1 id but is masked out, so it touches no
    table row (not even through weight decay)."""
    mask = (ids >= 0).reshape(-1)
    flat_ids = ids.reshape(-1)
    flat_g = grads.reshape(-1, grads.shape[-1])
    return flat_ids, flat_g, mask

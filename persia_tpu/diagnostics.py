"""Stall/deadlock detection.

Parity target: the reference's opt-in deadlock detector — a background
thread scanning parking_lot lock graphs every 60 s, enabled by
`PERSIA_DEADLOCK_DETECTION` (`rust/persia-common/src/utils.rs:21-48`),
started by every binary and the Python extension
(`rust/persia-core/src/lib.rs:494`).

Python threads can't introspect a lock graph, so the TPU-native equivalent
watches *progress*: components register heartbeats
(``heartbeat("forward_worker")``); if any registered component goes silent
longer than the threshold, the detector logs every thread's stack (the
information a deadlocked pipeline actually needs). Enabled by
``PERSIA_DEADLOCK_DETECTION=1`` like the reference, or explicitly via
``start_stall_detector``.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from contextlib import contextmanager
from typing import Dict, Optional, Tuple

from persia_tpu.logger import get_default_logger

logger = get_default_logger("persia_tpu.diagnostics")

_lock = threading.Lock()
_beats: Dict[str, float] = {}
_inflight: Dict[int, Tuple[str, float, Optional[float]]] = {}
_inflight_seq = 0
_detector: Optional["StallDetector"] = None


def heartbeat(component: str) -> None:
    """Mark ``component`` as alive now. Cheap; call from loop bodies."""
    with _lock:
        _beats[component] = time.monotonic()


def unregister(component: str) -> None:
    with _lock:
        _beats.pop(component, None)


@contextmanager
def inflight(task: str, stall_after_s: Optional[float] = None):
    """Track one in-flight operation (e.g. an RPC handler). The detector
    flags operations still running past the threshold — the server-side
    analog of a heartbeat, since a healthy server may be idle but a request
    must finish. ``stall_after_s`` overrides the detector's default for
    legitimately slow operations (checkpoint dump/load)."""
    global _inflight_seq
    with _lock:
        _inflight_seq += 1
        key = _inflight_seq
        _inflight[key] = (task, time.monotonic(), stall_after_s)
    try:
        yield
    finally:
        with _lock:
            _inflight.pop(key, None)


def dump_all_stacks(reason: str = "") -> str:
    """All thread stacks as one string (also logged at warning level)."""
    lines = [f"=== thread dump{': ' + reason if reason else ''} ==="]
    names = {t.ident: t.name for t in threading.enumerate()}
    for ident, frame in sys._current_frames().items():
        lines.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        lines.extend(l.rstrip() for l in traceback.format_stack(frame))
    text = "\n".join(lines)
    logger.warning("%s", text)
    return text


class StallDetector:
    """Background scanner (ref cadence: every 60 s)."""

    def __init__(self, stall_after_s: float = 60.0, interval_s: float = 10.0):
        self.stall_after_s = stall_after_s
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stall_count = 0

    def start(self) -> "StallDetector":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="persia-stall-detector")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    def check_once(self) -> list:
        """One scan; returns stalled component/operation names."""
        now = time.monotonic()
        with _lock:
            stalled = [c for c, t in _beats.items()
                       if now - t > self.stall_after_s]
            stalled += [
                f"inflight:{task}" for task, t, limit in _inflight.values()
                if now - t > (limit if limit is not None else self.stall_after_s)
            ]
        if stalled:
            self.stall_count += 1
            dump_all_stacks(f"components stalled >{self.stall_after_s}s: {stalled}")
        self._surface(stalled)
        return stalled

    def _surface(self, stalled: list) -> None:
        # silent-component detection feeds the observability plane, not
        # just the log: a counter + gauge for alerting, and a flight
        # event so the recorder's ring carries WHICH components went
        # quiet. Lazy imports keep diagnostics importable before the
        # metrics registry exists (it is started by binaries' main()).
        try:
            from persia_tpu.metrics import get_metrics

            m = get_metrics()
            m.gauge(
                "persia_tpu_stalled_components",
                "components currently silent past the stall threshold",
            ).set(float(len(stalled)))
            if stalled:
                m.counter(
                    "persia_tpu_stall_events",
                    "stall-detector scans that found silent components",
                ).inc()
        except Exception:  # pragma: no cover - metrics plane optional
            pass
        if stalled:
            try:
                from persia_tpu.tracing import record_event

                record_event(
                    "diagnostics.stall",
                    components=",".join(sorted(stalled)),
                    stall_after_s=self.stall_after_s,
                )
            except Exception:  # pragma: no cover - tracing plane optional
                pass

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.check_once()


def start_stall_detector(stall_after_s: float = 60.0,
                         interval_s: float = 10.0) -> StallDetector:
    global _detector
    if _detector is None:
        _detector = StallDetector(stall_after_s, interval_s).start()
    return _detector


def maybe_start_from_env() -> Optional[StallDetector]:
    """Opt-in via env, like the reference's PERSIA_DEADLOCK_DETECTION."""
    if os.environ.get("PERSIA_DEADLOCK_DETECTION", "0") in ("1", "true"):
        return start_stall_detector(
            stall_after_s=float(os.environ.get("PERSIA_STALL_AFTER_SEC", "60")))
    return None

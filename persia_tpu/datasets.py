"""File-backed dataset readers producing ``PersiaBatch`` streams.

Parity target: the reference example's file-driven data source
(`/root/reference/examples/src/adult-income/data_source.py` — a real
on-disk dataset parsed into id-type features + dense tensors + labels).
The framework-level reader here covers the Criteo display-advertising
schema (the north-star bench config, BASELINE.json): streaming TSV —
optionally gzip'd, optionally parquet when pyarrow exists — into LIL
``PersiaBatch``es without materializing the file.

Criteo-Kaggle row format (tab-separated)::

    label \t I1..I13 (ints, may be empty) \t C1..C26 (hex ids, may be empty)

Dense integers go through the standard ``log(x+1)`` transform (negatives
clamp to 0 first); categorical hex ids become raw u64 signs — the PS tier
is a hash table over the full u64 space, so no vocabulary capping is
needed; empty categorical fields map to a per-slot out-of-band sentinel
sign so "missing" learns its own embedding.
"""

from __future__ import annotations

import gzip
import os
from typing import Iterator, List, Optional, Sequence

import numpy as np

from persia_tpu.data import (
    IDTypeFeatureWithSingleID,
    Label,
    NonIDTypeFeature,
    PersiaBatch,
)

N_CRITEO_DENSE = 13
N_CRITEO_SPARSE = 26

# "missing categorical" sentinel base: far above the 32-bit hex-id space the
# Kaggle dataset uses, one sentinel per slot
_MISSING_BASE = np.uint64(1) << np.uint64(60)


class CriteoTSV:
    """Streaming Criteo TSV/parquet reader.

    ``batches(batch_size)`` yields ``PersiaBatch``es until the file ends;
    the final short batch is dropped by default (static device shapes),
    keep it with ``drop_remainder=False``. ``limit_batches`` bounds the
    stream (epoch budget control).
    """

    def __init__(
        self,
        path: str,
        slot_names: Optional[Sequence[str]] = None,
        requires_grad: bool = True,
    ):
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        self.path = path
        self.slot_names = (
            list(slot_names)
            if slot_names is not None
            else [f"cat_{i}" for i in range(N_CRITEO_SPARSE)]
        )
        if len(self.slot_names) != N_CRITEO_SPARSE:
            raise ValueError(
                f"Criteo schema has {N_CRITEO_SPARSE} categorical slots, "
                f"got {len(self.slot_names)} names"
            )
        self.requires_grad = requires_grad

    # ----------------------------------------------------------- row source

    def _rows(self) -> Iterator[List[str]]:
        if self.path.endswith(".parquet"):
            yield from self._parquet_rows()
            return
        opener = gzip.open if self.path.endswith(".gz") else open
        with opener(self.path, "rt") as f:
            for line in f:
                line = line.rstrip("\n")
                if line:
                    yield line.split("\t")

    def _parquet_rows(self) -> Iterator[List[str]]:
        try:
            import pyarrow.parquet as pq
        except ImportError as e:  # pragma: no cover - env-dependent
            raise RuntimeError(
                "parquet input needs pyarrow, which is not installed"
            ) from e
        table = pq.read_table(self.path)
        cols = [table.column(i).to_pylist() for i in range(table.num_columns)]
        for row in zip(*cols):
            yield ["" if v is None else str(v) for v in row]

    # -------------------------------------------------------------- batching

    def batches(
        self,
        batch_size: int,
        drop_remainder: bool = True,
        limit_batches: Optional[int] = None,
    ) -> Iterator[PersiaBatch]:
        labels: List[float] = []
        dense: List[List[float]] = []
        sparse: List[List[np.uint64]] = [[] for _ in range(N_CRITEO_SPARSE)]
        emitted = 0

        def flush() -> PersiaBatch:
            ids = [
                IDTypeFeatureWithSingleID(
                    self.slot_names[i], np.asarray(sparse[i], dtype=np.uint64)
                )
                for i in range(N_CRITEO_SPARSE)
            ]
            batch = PersiaBatch(
                ids,
                non_id_type_features=[
                    NonIDTypeFeature(np.asarray(dense, dtype=np.float32))
                ],
                labels=[
                    Label(np.asarray(labels, dtype=np.float32).reshape(-1, 1))
                ],
                requires_grad=self.requires_grad,
            )
            labels.clear()
            dense.clear()
            for s in sparse:
                s.clear()
            return batch

        for row in self._rows():
            if len(row) < 1 + N_CRITEO_DENSE + N_CRITEO_SPARSE:
                row = row + [""] * (
                    1 + N_CRITEO_DENSE + N_CRITEO_SPARSE - len(row)
                )
            labels.append(float(row[0]) if row[0] else 0.0)
            drow = []
            for i in range(N_CRITEO_DENSE):
                v = row[1 + i]
                x = float(v) if v else 0.0
                drow.append(float(np.log1p(max(x, 0.0))))
            dense.append(drow)
            for i in range(N_CRITEO_SPARSE):
                v = row[1 + N_CRITEO_DENSE + i]
                sparse[i].append(
                    np.uint64(int(v, 16)) if v
                    else _MISSING_BASE + np.uint64(i)
                )
            if len(labels) == batch_size:
                yield flush()
                emitted += 1
                if limit_batches is not None and emitted >= limit_batches:
                    return
        if labels and not drop_remainder:
            yield flush()

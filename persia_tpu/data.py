"""Batch datatypes + binary wire format.

Parity target: ``persia/embedding/data.py`` (numpy-side batch construction and
validation; LIL sparse id lists; ``PersiaBatch.to_bytes``) and the Rust wire
types in ``rust/persia-common/src/lib.rs:30-155``. The wire format here is a
custom little-endian binary layout shared by Python and the C++ services
(replacing the reference's speedy serialization).
"""

from __future__ import annotations

import io
import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from persia_tpu.config import MAX_BATCH_SIZE
from persia_tpu.env import skip_check_data

_MAGIC = b"PTB1"

_DTYPE_CODES: Dict[str, int] = {
    "float32": 0,
    "float64": 1,
    "float16": 2,
    "int8": 3,
    "int16": 4,
    "int32": 5,
    "int64": 6,
    "uint8": 7,
    "uint16": 8,
    "uint32": 9,
    "uint64": 10,
    "bool": 11,
}
_CODE_DTYPES = {v: np.dtype(k) for k, v in _DTYPE_CODES.items()}


def _check_dtype(array: np.ndarray, who: str) -> None:
    if array.dtype.name not in _DTYPE_CODES:
        raise TypeError(f"{who}: unsupported dtype {array.dtype}")


class IDTypeFeature:
    """One sparse slot: a list-of-lists of u64 signs, one variable-length list
    per sample (ref: persia/embedding/data.py:69-114).

    Internally the canonical form is CSR (``flat`` ids + per-sample
    ``counts``) because every downstream consumer — preprocessing dedup,
    wire serialization — wants it flat; per-sample Python iteration over
    65k-element lists was the round-1 hot-loop cost. The list-of-arrays
    ``data`` view is materialized lazily."""

    def __init__(self, name: str, data: Optional[Sequence[np.ndarray]]):
        self.name = name
        self._flat: Optional[np.ndarray] = None
        self._counts: Optional[np.ndarray] = None
        if data is None:  # from_flat path fills _flat/_counts
            self._data: Optional[List[np.ndarray]] = None
            return
        data = list(data)
        if len(data) > MAX_BATCH_SIZE:
            raise ValueError(f"batch_size {len(data)} exceeds MAX_BATCH_SIZE {MAX_BATCH_SIZE}")
        if not skip_check_data():
            for sample in data:
                if not isinstance(sample, np.ndarray) or sample.dtype != np.uint64:
                    raise TypeError(
                        f"IDTypeFeature {name!r}: every sample must be a np.uint64 ndarray"
                    )
                if sample.ndim != 1:
                    raise TypeError(f"IDTypeFeature {name!r}: samples must be 1-D")
        self._data = data

    @classmethod
    def from_flat(
        cls, name: str, flat: np.ndarray, counts: np.ndarray
    ) -> "IDTypeFeature":
        """Construct directly from the CSR form (no per-sample Python lists).
        ``flat``: all ids concatenated (u64); ``counts``: ids per sample."""
        if flat.dtype != np.uint64 or flat.ndim != 1:
            raise TypeError(f"IDTypeFeature {name!r}: flat must be 1-D np.uint64")
        counts = np.ascontiguousarray(counts, dtype=np.int64)
        if len(counts) > MAX_BATCH_SIZE:
            raise ValueError(
                f"batch_size {len(counts)} exceeds MAX_BATCH_SIZE {MAX_BATCH_SIZE}"
            )
        if int(counts.sum()) != len(flat):
            raise ValueError(f"IDTypeFeature {name!r}: counts sum != len(flat)")
        f = cls(name, None)
        f._flat = np.ascontiguousarray(flat)
        f._counts = counts
        return f

    def flat_counts(self) -> "tuple[np.ndarray, np.ndarray]":
        """(flat ids (n,), counts (B,)) — computed once and cached."""
        if self._flat is None:
            data = self._data
            self._counts = np.fromiter(
                (len(s) for s in data), count=len(data), dtype=np.int64
            )
            self._flat = (
                np.concatenate(data) if self._counts.sum() else np.empty(0, np.uint64)
            )
        return self._flat, self._counts

    @property
    def data(self) -> List[np.ndarray]:
        if self._data is None:
            if len(self._counts) == 0:
                self._data = []
            else:
                self._data = np.split(self._flat, np.cumsum(self._counts[:-1]))
        return self._data

    @property
    def batch_size(self) -> int:
        return len(self._counts) if self._counts is not None else len(self._data)

    def __len__(self) -> int:
        return self.batch_size


class IDTypeFeatureWithSingleID:
    """One sparse slot where each sample has exactly one id
    (ref: persia/embedding/data.py:116-157). Converts to the LIL form."""

    def __init__(self, name: str, data: np.ndarray):
        if not isinstance(data, np.ndarray) or data.dtype != np.uint64 or data.ndim != 1:
            raise TypeError(
                f"IDTypeFeatureWithSingleID {name!r}: data must be a 1-D np.uint64 ndarray"
            )
        if len(data) > MAX_BATCH_SIZE:
            raise ValueError(f"batch_size {len(data)} exceeds MAX_BATCH_SIZE {MAX_BATCH_SIZE}")
        self.name = name
        self.data = data

    @property
    def batch_size(self) -> int:
        return len(self.data)

    def to_lil(self) -> IDTypeFeature:
        return IDTypeFeature.from_flat(
            self.name, self.data, np.ones(len(self.data), dtype=np.int64)
        )


class NdarrayDataBase:
    """Dense ndarray payload with name + dtype validation
    (ref: persia/embedding/data.py:160-276)."""

    DEFAULT_NAME = "ndarray_base"

    def __init__(self, data: np.ndarray, name: Optional[str] = None):
        if not isinstance(data, np.ndarray):
            raise TypeError(f"{self.DEFAULT_NAME}: data must be an ndarray")
        _check_dtype(data, self.DEFAULT_NAME)
        if data.ndim < 1:
            raise TypeError(f"{self.DEFAULT_NAME}: data must have at least 1 dim")
        if len(data) > MAX_BATCH_SIZE:
            raise ValueError(f"batch_size {len(data)} exceeds MAX_BATCH_SIZE {MAX_BATCH_SIZE}")
        self.data = np.ascontiguousarray(data)
        self._name = name

    @property
    def name(self) -> str:
        return self._name if self._name is not None else self.DEFAULT_NAME

    @property
    def batch_size(self) -> int:
        return len(self.data)

    def __len__(self) -> int:
        return len(self.data)


class NonIDTypeFeature(NdarrayDataBase):
    DEFAULT_NAME = "non_id_type_feature"


class Label(NdarrayDataBase):
    DEFAULT_NAME = "label"


def _write_ndarray(buf: io.BytesIO, name: str, arr: np.ndarray) -> None:
    name_b = name.encode()
    buf.write(struct.pack("<H", len(name_b)))
    buf.write(name_b)
    buf.write(struct.pack("<BB", _DTYPE_CODES[arr.dtype.name], arr.ndim))
    buf.write(struct.pack(f"<{arr.ndim}q", *arr.shape))
    buf.write(arr.tobytes())


def _read_ndarray(buf: io.BytesIO) -> Tuple[str, np.ndarray]:
    (name_len,) = struct.unpack("<H", buf.read(2))
    name = buf.read(name_len).decode()
    code, ndim = struct.unpack("<BB", buf.read(2))
    shape = struct.unpack(f"<{ndim}q", buf.read(8 * ndim))
    dtype = _CODE_DTYPES[code]
    n = int(np.prod(shape)) if shape else 1
    # copy: frombuffer views are read-only; deserialized batches must behave
    # like locally-constructed (writable) ones
    arr = np.frombuffer(buf.read(n * dtype.itemsize), dtype=dtype).reshape(shape).copy()
    return name, arr


class PersiaBatch:
    """One training batch: sparse id slots + dense features + labels + meta
    (ref: persia/embedding/data.py:279-411, rust/persia-core/src/data.rs:34-52).

    ``requires_grad=True`` batches must carry labels (the training path needs
    them on the NN worker; ref data.rs:228-248).
    """

    def __init__(
        self,
        id_type_features: Sequence[IDTypeFeature | IDTypeFeatureWithSingleID],
        non_id_type_features: Optional[Sequence[NonIDTypeFeature]] = None,
        labels: Optional[Sequence[Label]] = None,
        requires_grad: bool = True,
        batch_id: Optional[int] = None,
        meta: Optional[bytes] = None,
    ):
        if len(id_type_features) == 0:
            raise ValueError("id_type_features must be non-empty")
        converted: List[IDTypeFeature] = []
        for f in id_type_features:
            if isinstance(f, IDTypeFeatureWithSingleID):
                f = f.to_lil()
            elif not isinstance(f, IDTypeFeature):
                raise TypeError(f"unsupported id feature type {type(f)}")
            converted.append(f)
        batch_size = converted[0].batch_size
        for f in converted:
            if f.batch_size != batch_size:
                raise ValueError(
                    f"id feature {f.name!r} batch_size {f.batch_size} != {batch_size}"
                )
        non_id_type_features = list(non_id_type_features or [])
        labels_list = list(labels or [])
        for x in non_id_type_features + labels_list:
            if x.batch_size != batch_size:
                raise ValueError(f"{x.name!r} batch_size {x.batch_size} != {batch_size}")
        if requires_grad and not labels_list:
            raise ValueError("requires_grad=True batch must carry labels")
        if batch_id is not None and batch_id < 0:
            raise ValueError("batch_id must be non-negative")

        self.id_type_features = converted
        self.non_id_type_features = non_id_type_features
        self.labels = labels_list
        self.requires_grad = requires_grad
        self.batch_id = batch_id
        self.meta = meta
        # set by the dataflow tier when the id features were already buffered
        # at an embedding worker: (worker_index, forward ref) — the trainer's
        # lookup uses the ref instead of re-sending ids (ref:
        # IDTypeFeatureRemoteRef, persia-common/src/lib.rs:115-155)
        self.remote_ref: Optional[Tuple[int, int]] = None

    @property
    def batch_size(self) -> int:
        return self.id_type_features[0].batch_size

    def to_bytes(self) -> bytes:
        """Serialize to the shared wire format (ref: data.py:409-411 / data.rs:256)."""
        buf = io.BytesIO()
        buf.write(_MAGIC)
        flags = 1 if self.requires_grad else 0
        if self.meta is not None:
            flags |= 2
        batch_id = self.batch_id if self.batch_id is not None else -1
        meta = self.meta or b""
        buf.write(
            struct.pack(
                "<BqIHHH",
                flags,
                batch_id,
                len(meta),
                len(self.id_type_features),
                len(self.non_id_type_features),
                len(self.labels),
            )
        )
        buf.write(meta)
        for f in self.id_type_features:
            name_b = f.name.encode()
            buf.write(struct.pack("<H", len(name_b)))
            buf.write(name_b)
            values, counts = f.flat_counts()
            offsets = np.zeros(len(counts) + 1, dtype=np.int64)
            np.cumsum(counts, out=offsets[1:])
            if offsets[-1] > 0xFFFFFFFF:
                raise ValueError(
                    f"id feature {f.name!r}: {offsets[-1]} total ids exceeds the "
                    f"u32 wire offset limit"
                )
            buf.write(struct.pack("<I", len(counts)))
            buf.write(offsets.astype(np.uint32).tobytes())
            if len(counts):
                buf.write(values.astype(np.uint64, copy=False).tobytes())
        for x in self.non_id_type_features:
            _write_ndarray(buf, x.name, x.data)
        for x in self.labels:
            _write_ndarray(buf, x.name, x.data)
        return buf.getvalue()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "PersiaBatch":
        buf = io.BytesIO(raw)
        if buf.read(4) != _MAGIC:
            raise ValueError("bad magic: not a PersiaBatch payload")
        flags, batch_id, meta_len, n_id, n_dense, n_label = struct.unpack(
            "<BqIHHH", buf.read(struct.calcsize("<BqIHHH"))
        )
        meta = buf.read(meta_len) if flags & 2 else None
        id_feats = []
        for _ in range(n_id):
            (name_len,) = struct.unpack("<H", buf.read(2))
            name = buf.read(name_len).decode()
            (bs,) = struct.unpack("<I", buf.read(4))
            offsets = np.frombuffer(buf.read(4 * (bs + 1)), dtype=np.uint32)
            # copy once → per-sample slices are writable views of writable memory
            values = np.frombuffer(buf.read(8 * int(offsets[-1])), dtype=np.uint64).copy()
            counts = np.diff(offsets.astype(np.int64))
            id_feats.append(IDTypeFeature.from_flat(name, values, counts))
        dense = []
        for _ in range(n_dense):
            name, arr = _read_ndarray(buf)
            dense.append(NonIDTypeFeature(arr, name=name))
        labels = []
        for _ in range(n_label):
            name, arr = _read_ndarray(buf)
            labels.append(Label(arr, name=name))
        return cls(
            id_feats,
            non_id_type_features=dense,
            labels=labels,
            requires_grad=bool(flags & 1),
            batch_id=None if batch_id == -1 else batch_id,
            meta=meta,
        )
